"""``metric-registry`` — the canonical ``rmt_*`` instrument set
(``core/metrics_defs.py``) and its call sites must agree.

Three sub-invariants, all from the DEFS dict parsed out of
metrics_defs.py (pure data, so the checker reads the same source of
truth the runtime does):

  * every emit site names a DECLARED series: ``mdefs.<accessor>()``
    must name a real accessor, ``get("rmt_...")`` /
    ``Counter("rmt_...")``-style constructions must name a declared
    metric;
  * literal ``tags={...}`` dicts at ``.inc()/.observe()/.set()`` call
    sites (on a direct ``mdefs.<accessor>()`` chain or a variable
    assigned from one) only use the series' DECLARED tag keys — an
    undeclared key raises at runtime, but only when that branch runs,
    which is exactly how PR 7's counter races hid;
  * every declared series has at least one call site somewhere in the
    package (a declared-but-never-emitted series is registry drift:
    wire it or remove it).

Indirection through accessor-name strings (``_count("transfer_pool_hits")``
in core/transfer.py) counts as a reference — string literals equal to an
accessor name are tracked.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .engine import Project, Violation, const_str, register

_METRICS_DEFS_SUFFIX = "core/metrics_defs.py"
# module-level names of metrics_defs that are legal attribute accesses
_MODULE_PUBLIC = {"get", "DEFS", "LATENCY_BOUNDARIES", "BYTES_BOUNDARIES"}
_EMIT_METHODS = {"inc", "observe", "set"}
_METRIC_CLASSES = {"Counter", "Gauge", "Histogram"}


def parse_registry(project: Project
                   ) -> Tuple[Dict[str, Tuple[str, Tuple[str, ...]]],
                              Dict[str, str]]:
    """(metrics, accessors): ``metrics[name] = (cls, tag_keys)`` from the
    DEFS literal; ``accessors[fn_name] = metric_name`` from the
    ``def x(): return get("...")`` accessor bodies."""
    sf = project.get(_METRICS_DEFS_SUFFIX)
    metrics: Dict[str, Tuple[str, Tuple[str, ...]]] = {}
    accessors: Dict[str, str] = {}
    if sf is None or sf.tree is None:
        return metrics, accessors
    for node in ast.walk(sf.tree):
        targets = node.targets if isinstance(node, ast.Assign) else (
            [node.target] if isinstance(node, ast.AnnAssign) else [])
        if targets and \
                any(isinstance(t, ast.Name) and t.id == "DEFS"
                    for t in targets) and \
                isinstance(node.value, ast.Dict):
            for k, v in zip(node.value.keys, node.value.values):
                name = const_str(k)
                if name is None or not isinstance(v, ast.Tuple) or \
                        len(v.elts) != 2:
                    continue
                cls = v.elts[0].id if isinstance(v.elts[0], ast.Name) \
                    else "?"
                tag_keys: Tuple[str, ...] = ()
                kwargs = v.elts[1]
                if isinstance(kwargs, ast.Call):
                    for kw in kwargs.keywords:
                        if kw.arg == "tag_keys" and \
                                isinstance(kw.value, ast.Tuple):
                            tag_keys = tuple(
                                s for s in (const_str(e)
                                            for e in kw.value.elts)
                                if s is not None)
                metrics[name] = (cls, tag_keys)
        if isinstance(node, ast.FunctionDef) and node.name != "get":
            for stmt in node.body:
                if isinstance(stmt, ast.Return) and \
                        isinstance(stmt.value, ast.Call) and \
                        isinstance(stmt.value.func, ast.Name) and \
                        stmt.value.func.id == "get" and stmt.value.args:
                    mname = const_str(stmt.value.args[0])
                    if mname:
                        accessors[node.name] = mname
    return metrics, accessors


def _mdefs_aliases(tree: ast.AST) -> Tuple[Set[str], Dict[str, str]]:
    """(module_aliases, imported_accessors): names this module binds to
    the metrics_defs module itself, and accessor names imported from it
    (``from .metrics_defs import scheduler_placements as _sp`` maps
    ``_sp -> scheduler_placements``)."""
    aliases: Set[str] = set()
    imported: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""   # "" for ``from . import x``
            if mod.split(".")[-1] == "metrics_defs":
                for a in node.names:
                    if a.name != "*":
                        imported[a.asname or a.name] = a.name
            else:
                for a in node.names:
                    if a.name == "metrics_defs":
                        aliases.add(a.asname or a.name)
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name.split(".")[-1] == "metrics_defs":
                    aliases.add(a.asname or a.name)
    return aliases, imported


def _accessor_of_call(call: ast.AST, aliases: Set[str],
                      imported: Dict[str, str],
                      accessors: Dict[str, str]) -> Optional[str]:
    """Accessor name when ``call`` is ``mdefs.<acc>()`` or an imported
    ``<acc>()``."""
    if not isinstance(call, ast.Call):
        return None
    f = call.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
            and f.value.id in aliases and f.attr in accessors:
        return f.attr
    if isinstance(f, ast.Name) and f.id in imported and \
            imported[f.id] in accessors:
        return imported[f.id]
    return None


@register("metric-registry")
def check_metric_registry(project: Project, options: dict
                          ) -> List[Violation]:
    metrics, accessors = parse_registry(project)
    defs_sf = project.get(_METRICS_DEFS_SUFFIX)
    defs_rel = defs_sf.rel if defs_sf else _METRICS_DEFS_SUFFIX
    out: List[Violation] = []
    if not metrics:
        out.append(Violation(
            "metric-registry", defs_rel, 1,
            "could not parse the DEFS registry out of metrics_defs.py"))
        return out
    accessor_names = set(accessors)
    referenced: Set[str] = set()   # metric names with >= 1 call site

    for sf in project.files:
        if sf.tree is None or sf.rel.endswith(_METRICS_DEFS_SUFFIX):
            continue
        aliases, imported = _mdefs_aliases(sf.tree)
        # variables assigned from an accessor call anywhere in the file:
        # ``self._m_submitted = mdefs.tasks_submitted()`` or
        # ``hist = task_stage_seconds()`` — tracked so tags checks reach
        # the hoisted hot-path instruments
        var_metric: Dict[str, str] = {}
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                acc = _accessor_of_call(node.value, aliases, imported,
                                        accessors)
                if acc:
                    t = node.targets[0]
                    key = None
                    if isinstance(t, ast.Name):
                        key = t.id
                    elif isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self":
                        key = f"self.{t.attr}"
                    if key:
                        var_metric[key] = accessors[acc]

        for node in ast.walk(sf.tree):
            # unknown accessor: mdefs.<not-an-accessor>
            if isinstance(node, ast.Attribute) and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id in aliases:
                if node.attr in accessor_names:
                    referenced.add(accessors[node.attr])
                elif node.attr not in _MODULE_PUBLIC and \
                        not node.attr.startswith("__"):
                    out.append(Violation(
                        "metric-registry", sf.rel, node.lineno,
                        f"metrics_defs.{node.attr} is not a declared "
                        f"accessor (typo? declare the series in DEFS)"))
            if isinstance(node, ast.Name) and node.id in imported and \
                    imported[node.id] in accessor_names:
                referenced.add(accessors[imported[node.id]])
            # string-literal references: get("rmt_x"), Counter("rmt_x"),
            # and accessor-name strings (the _count("...") indirection)
            if isinstance(node, ast.Call):
                fname = None
                if isinstance(node.func, ast.Name):
                    fname = node.func.id
                elif isinstance(node.func, ast.Attribute):
                    fname = node.func.attr
                if fname in _METRIC_CLASSES | {"get"} and node.args:
                    lit = const_str(node.args[0])
                    if lit and lit.startswith("rmt_"):
                        if lit in metrics:
                            referenced.add(lit)
                        else:
                            out.append(Violation(
                                "metric-registry", sf.rel, node.lineno,
                                f"metric {lit!r} is not declared in "
                                f"metrics_defs.DEFS"))
            if isinstance(node, ast.Constant) and \
                    isinstance(node.value, str) and \
                    node.value in accessor_names:
                referenced.add(accessors[node.value])
            # tags= literal keys at emit sites
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _EMIT_METHODS:
                base = node.func.value
                mname = None
                acc = _accessor_of_call(base, aliases, imported, accessors)
                if acc:
                    mname = accessors[acc]
                elif isinstance(base, ast.Name):
                    mname = var_metric.get(base.id)
                elif isinstance(base, ast.Attribute) and \
                        isinstance(base.value, ast.Name) and \
                        base.value.id == "self":
                    mname = var_metric.get(f"self.{base.attr}")
                if mname is None or mname not in metrics:
                    continue
                referenced.add(mname)
                declared = set(metrics[mname][1])
                for kw in node.keywords:
                    if kw.arg != "tags" or not isinstance(kw.value,
                                                          ast.Dict):
                        continue
                    for k in kw.value.keys:
                        key = const_str(k)
                        if key is not None and key not in declared:
                            out.append(Violation(
                                "metric-registry", sf.rel, node.lineno,
                                f"tag key {key!r} is not declared for "
                                f"{mname} (declared: "
                                f"{sorted(declared) or 'none'})"))

    for name in sorted(metrics):
        if name not in referenced:
            out.append(Violation(
                "metric-registry", defs_rel, 1,
                f"declared series {name} has no call site anywhere in "
                f"the package (registry drift: wire it or remove it)"))
    return out
