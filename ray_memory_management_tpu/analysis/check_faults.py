"""``fault-site`` — the string-keyed fault plane (``utils/faults.py``)
and its fire points must agree.

  * every ``faults.fire("<site>")`` / ``fire("<site>")`` literal in the
    package names a site registered in ``SITES`` (a typo'd site silently
    never fires — the injection test passes while injecting nothing);
  * every registered site has >= 1 fire point in the package (a
    registered-but-never-fired site is drift: chaos plans list it, but
    no fault can ever materialize there);
  * every registered site is referenced by >= 1 test (substring match in
    the test tree) so the chaos suite actually exercises it.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from .engine import Project, Violation, const_str, register

_FAULTS_SUFFIX = "utils/faults.py"


def parse_sites(project: Project) -> Tuple[str, ...]:
    sf = project.get(_FAULTS_SUFFIX)
    if sf is None or sf.tree is None:
        return ()
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Assign) and \
                any(isinstance(t, ast.Name) and t.id == "SITES"
                    for t in node.targets) and \
                isinstance(node.value, (ast.Tuple, ast.List)):
            return tuple(s for s in (const_str(e)
                                     for e in node.value.elts)
                         if s is not None)
    return ()


def _fire_literals(project: Project) -> List[Tuple[str, str, int]]:
    """(site, rel, lineno) for every ``fire("<lit>")`` /
    ``faults.fire("<lit>")`` call in the package, excluding faults.py
    itself (its own fire() definition and docstrings are not call
    sites)."""
    out: List[Tuple[str, str, int]] = []
    for sf in project.files:
        if sf.tree is None or sf.rel.endswith(_FAULTS_SUFFIX):
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            f = node.func
            name = f.id if isinstance(f, ast.Name) else (
                f.attr if isinstance(f, ast.Attribute) else None)
            if name != "fire":
                continue
            lit = const_str(node.args[0])
            if lit is not None:
                out.append((lit, sf.rel, node.lineno))
    return out


@register("fault-site")
def check_fault_sites(project: Project, options: dict) -> List[Violation]:
    sites = parse_sites(project)
    faults_sf = project.get(_FAULTS_SUFFIX)
    faults_rel = faults_sf.rel if faults_sf else _FAULTS_SUFFIX
    out: List[Violation] = []
    if not sites:
        out.append(Violation(
            "fault-site", faults_rel, 1,
            "could not parse the SITES tuple out of utils/faults.py"))
        return out
    site_set: Set[str] = set(sites)
    fired: Dict[str, int] = {}
    for lit, rel, lineno in _fire_literals(project):
        if lit in site_set:
            fired[lit] = fired.get(lit, 0) + 1
        else:
            out.append(Violation(
                "fault-site", rel, lineno,
                f"fire({lit!r}) names an unregistered fault site "
                f"(registered: {', '.join(sites)})"))

    tested: Set[str] = set()
    for tf in project.test_files:
        for site in sites:
            if site in tf.text:
                tested.add(site)

    for site in sites:
        if site not in fired:
            out.append(Violation(
                "fault-site", faults_rel, 1,
                f"registered site {site!r} has no fire() point in the "
                f"package (drift: wire it or remove it)"))
        if site not in tested and project.test_files:
            out.append(Violation(
                "fault-site", faults_rel, 1,
                f"registered site {site!r} is never referenced by any "
                f"test (chaos coverage gap)"))
    return out
