"""CLI for the rmtcheck static-analysis suite.

``python -m ray_memory_management_tpu.analysis [--json] [--frozen]
[--rule RULE ...] [--root DIR]`` — exits non-zero when any violation is
found, printing ``file:line: rule: message`` lines (or a machine-
readable JSON report with ``--json``). ``rmt check`` delegates here.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from .engine import all_rules, run_checks

REPORT_VERSION = 1


def build_report(violations, rules: List[str], files_scanned: int,
                 frozen: bool) -> dict:
    counts: dict = {}
    for v in violations:
        counts[v.rule] = counts.get(v.rule, 0) + 1
    return {
        "version": REPORT_VERSION,
        "frozen": frozen,
        "rules": rules,
        "files_scanned": files_scanned,
        "violation_count": len(violations),
        "counts_by_rule": counts,
        "violations": [v.as_dict() for v in violations],
    }


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="rmt check",
        description="rmtcheck: static analysis for the runtime's "
                    "concurrency and registry conventions")
    ap.add_argument("--json", action="store_true",
                    help="emit a machine-readable JSON report")
    ap.add_argument("--frozen", action="store_true",
                    help="treat new wire-protocol keys as violations "
                         "instead of auto-registering (CI mode)")
    ap.add_argument("--rule", action="append", dest="rules",
                    metavar="RULE",
                    help="run only this rule (repeatable); default all")
    ap.add_argument("--root", default=None,
                    help="repo root to analyze (default: the installed "
                         "package's own tree)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the registered rules and exit")
    args = ap.parse_args(argv)

    # importing the checkers registers the rules
    from . import (  # noqa: F401
        check_faults, check_locks, check_logs, check_metrics,
        check_protocol, check_trace,
    )
    if args.list_rules:
        for r in all_rules():
            print(r)
        return 0

    if args.root:
        repo = os.path.abspath(args.root)
        pkg = os.path.join(repo, "ray_memory_management_tpu")
        if not os.path.isdir(pkg):
            pkg = repo  # analyze an arbitrary tree (fixtures)
    else:
        pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        repo = os.path.dirname(pkg)
    tests = os.path.join(repo, "tests")

    options = {"frozen": args.frozen}
    violations = run_checks(pkg, tests if os.path.isdir(tests) else None,
                            rules=args.rules, options=options)

    rules = args.rules or all_rules()
    from .engine import Project
    files_scanned = len(Project(pkg, None).files)

    try:
        if args.json:
            print(json.dumps(build_report(violations, rules,
                                          files_scanned,
                                          args.frozen), indent=2))
        else:
            for v in violations:
                print(v.format())
            for line in options.get("schema_diff", ()):
                print(f"protocol_schema.py updated: {line}",
                      file=sys.stderr)
            if violations:
                print(f"\nrmt check: {len(violations)} violation(s) "
                      f"across {files_scanned} files", file=sys.stderr)
            else:
                print(f"rmt check: clean ({files_scanned} files, "
                      f"{len(rules)} rules)", file=sys.stderr)
    except BrokenPipeError:
        # downstream closed the pipe (e.g. `rmt check --json | head`):
        # swap stdout for devnull so the interpreter's exit flush
        # doesn't raise again, and keep the violation exit code
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
