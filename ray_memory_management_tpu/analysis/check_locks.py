"""Lock-discipline checkers.

``lock-discipline`` — a field annotated ``# guarded-by: <lock>`` at its
``__init__`` assignment may only be MUTATED (assigned, subscript-stored,
or hit with a mutating method like ``.append``/``.pop``/``.update``)
inside a lexical ``with self.<lock>:`` block of the same class, or in a
function carrying ``# rmtcheck: holds=<lock>`` (caller-held contract).
``__init__`` itself is construction-before-threads and exempt. Reads are
not checked (too many benign racy reads are by design: monotonic
counters, snapshot loops); aliased mutation (``x = self.f; x.pop()``)
is out of scope — annotate the hot path, not every alias.

``blocking-under-lock`` — inside any held lock-like region (a ``with``
whose subject's last name segment looks like a lock: ``*lock``, ``*mu``,
``*mutex``, ``*cond``, ``*sem``), flag calls that can block the thread:
``time.sleep``/``.sleep()``, ``subprocess.*``, ``os.system``,
``socket.create_connection``, ``select.select``, ``.accept()``,
``.recv*()``, and ``.wait()``/``.wait_for()`` on anything OTHER than the
held subject itself (``cond.wait()`` under ``with cond:`` is the
condition-variable protocol and legal). This is the PR 2/PR 7 race
class: a sleep or socket read under a hot lock turns every other thread
into a convoy.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from .engine import (
    GUARDED_BY_RE, Project, SourceFile, Violation, register, self_attr,
    unparse,
)

MUTATOR_METHODS = {
    "append", "appendleft", "extend", "insert", "pop", "popleft",
    "remove", "clear", "update", "add", "discard", "setdefault",
    "__setitem__", "popitem",
}

_LOCKISH_RE = re.compile(r"(lock|mutex|cond|sem)$|(^|_)mu$")

BLOCKING_SIMPLE = {
    "time.sleep", "os.system", "socket.create_connection",
    "select.select", "subprocess.run", "subprocess.call",
    "subprocess.check_call", "subprocess.check_output",
    "subprocess.Popen",
}
BLOCKING_METHOD_ATTRS = {
    "sleep", "accept", "recv", "recv_bytes", "recv_bytes_into",
}
WAIT_ATTRS = {"wait", "wait_for"}


def _lockish(expr: ast.AST) -> bool:
    """Does a with-subject look like a lock/condition/semaphore?"""
    if isinstance(expr, ast.Attribute):
        name = expr.attr
    elif isinstance(expr, ast.Name):
        name = expr.id
    elif isinstance(expr, ast.Subscript):
        # self._conn_send_locks[conn] — a lock table entry
        return _lockish(expr.value)
    elif isinstance(expr, ast.Call):
        return False  # ``with self._applied(...)`` etc: not a lock
    else:
        return False
    return bool(_LOCKISH_RE.search(name.lower()))


def _guarded_fields(sf: SourceFile, cls: ast.ClassDef) -> Dict[str, Tuple[str, int]]:
    """{field: (lock_attr, annotation_line)} from ``# guarded-by:``
    comments on ``self.<field> =`` lines inside the class body."""
    out: Dict[str, Tuple[str, int]] = {}
    end = max(getattr(cls, "end_lineno", cls.lineno), cls.lineno)
    assign_re = re.compile(r"^\s*self\.(\w+)\s*[:=]")
    for lineno in range(cls.lineno, end + 1):
        line = sf.line_text(lineno)
        m = GUARDED_BY_RE.search(line)
        if not m:
            continue
        am = assign_re.match(line)
        if am:
            lock = m.group(1)
            if lock.startswith("self."):
                lock = lock[len("self."):]
            out[am.group(1)] = (lock, lineno)
    return out


def _with_held_attrs(node: ast.With) -> Set[str]:
    """self.<attr> lock attributes acquired by a with statement."""
    out: Set[str] = set()
    for item in node.items:
        attr = self_attr(item.context_expr)
        if attr is not None:
            out.add(attr)
    return out


def _mutated_field(stmt: ast.AST) -> List[Tuple[str, int]]:
    """(field, lineno) for every self.<field> mutation in ONE statement
    node (non-recursive over child statements)."""
    found: List[Tuple[str, int]] = []

    def target_fields(t: ast.AST) -> None:
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                target_fields(e)
            return
        a = self_attr(t)
        if a is not None:
            found.append((a, t.lineno))
            return
        if isinstance(t, ast.Subscript):
            a = self_attr(t.value)
            if a is not None:
                found.append((a, t.lineno))
        if isinstance(t, ast.Starred):
            target_fields(t.value)

    if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
        targets = stmt.targets if isinstance(stmt, ast.Assign) else \
            [stmt.target]
        for t in targets:
            target_fields(t)
    elif isinstance(stmt, ast.Delete):
        for t in stmt.targets:
            target_fields(t)
    elif isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
        call = stmt.value
        if isinstance(call.func, ast.Attribute) and \
                call.func.attr in MUTATOR_METHODS:
            a = self_attr(call.func.value)
            if a is not None:
                found.append((a, call.lineno))
    return found


@register("lock-discipline")
def check_lock_discipline(project: Project, options: dict
                          ) -> List[Violation]:
    out: List[Violation] = []
    for sf in project.files:
        if sf.tree is None:
            continue
        for cls in [n for n in ast.walk(sf.tree)
                    if isinstance(n, ast.ClassDef)]:
            guarded = _guarded_fields(sf, cls)
            if not guarded:
                continue
            for fn in cls.body:
                if not isinstance(fn, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    continue
                if fn.name == "__init__":
                    continue
                held0 = set(sf.holds_annotation(fn))
                _walk_guarded(sf, fn.body, held0, guarded, out, sf.rel)
    return out


def _walk_guarded(sf: SourceFile, body: List[ast.stmt], held: Set[str],
                  guarded: Dict[str, Tuple[str, int]],
                  out: List[Violation], rel: str) -> None:
    for stmt in body:
        for field, lineno in _mutated_field(stmt):
            info = guarded.get(field)
            if info is None:
                continue
            lock, _ = info
            if lock not in held:
                out.append(Violation(
                    "lock-discipline", rel, lineno,
                    f"self.{field} is guarded-by {lock} but mutated "
                    f"without holding it (held: "
                    f"{sorted(held) if held else 'nothing'})"))
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            _walk_guarded(sf, stmt.body, held | _with_held_attrs(stmt),
                          guarded, out, rel)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested def runs LATER (thread target, callback): only its
            # own holds= contract applies
            _walk_guarded(sf, stmt.body, set(sf.holds_annotation(stmt)),
                          guarded, out, rel)
        else:
            for child in _child_bodies(stmt):
                _walk_guarded(sf, child, held, guarded, out, rel)


def _child_bodies(stmt: ast.stmt) -> List[List[ast.stmt]]:
    bodies = []
    for name in ("body", "orelse", "finalbody"):
        b = getattr(stmt, name, None)
        if b:
            bodies.append(b)
    for h in getattr(stmt, "handlers", ()) or ():
        bodies.append(h.body)
    return bodies


# ------------------------------------------------------- blocking-under-lock
def _blocking_call(call: ast.Call, held_exprs: Set[str]
                   ) -> Optional[str]:
    """A human-readable description when ``call`` can block, else None.
    ``held_exprs``: unparsed with-subjects currently held (so waiting on
    the held condition itself is allowed)."""
    func = call.func
    dotted = unparse(func)
    if dotted in BLOCKING_SIMPLE:
        return dotted
    if isinstance(func, ast.Attribute):
        if func.attr in BLOCKING_METHOD_ATTRS:
            return f"{dotted}()"
        if func.attr in WAIT_ATTRS:
            subject = unparse(func.value)
            if subject not in held_exprs:
                return f"{dotted}() (not the held condition)"
    return None


@register("blocking-under-lock")
def check_blocking_under_lock(project: Project, options: dict
                              ) -> List[Violation]:
    out: List[Violation] = []
    for sf in project.files:
        if sf.tree is None:
            continue
        _walk_blocking(sf, sf.tree.body, frozenset(), out)
    return out


def _stmt_exprs(stmt: ast.stmt) -> List[ast.AST]:
    """The expression fields that execute as part of THIS statement
    (child statement lists are walked separately so lock regions nest
    correctly)."""
    exprs: List[ast.AST] = []
    for name in ("test", "value", "iter", "exc", "cause", "msg"):
        e = getattr(stmt, name, None)
        if isinstance(e, ast.AST):
            exprs.append(e)
    if isinstance(stmt, ast.Assign):
        exprs.extend(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        exprs.append(stmt.target)
    elif isinstance(stmt, ast.Delete):
        exprs.extend(stmt.targets)
    elif isinstance(stmt, ast.Return) and stmt.value is None:
        pass
    return exprs


def _calls_in(expr: ast.AST):
    """Call nodes in an expression, NOT descending into lambdas (their
    bodies run later, outside the current lock region)."""
    stack = [expr]
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Lambda):
            continue
        if isinstance(node, ast.Call):
            yield node
        stack.extend(ast.iter_child_nodes(node))


def _walk_blocking(sf: SourceFile, body: List[ast.stmt],
                   held_exprs: frozenset, out: List[Violation]) -> None:
    for stmt in body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested/top-level def starts a fresh region (plus any
            # caller-held contract from its holds= annotation, which for
            # blocking purposes names self.<lock> subjects)
            held0 = frozenset(f"self.{a}"
                              for a in sf.holds_annotation(stmt))
            _walk_blocking(sf, stmt.body, held0, out)
            continue
        if held_exprs:
            for expr in _stmt_exprs(stmt):
                for call in _calls_in(expr):
                    desc = _blocking_call(call, set(held_exprs))
                    if desc:
                        out.append(Violation(
                            "blocking-under-lock", sf.rel, call.lineno,
                            f"blocking call {desc} while holding "
                            f"{sorted(held_exprs)}"))
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            new_exprs = set(held_exprs)
            for item in stmt.items:
                if held_exprs:
                    for call in _calls_in(item.context_expr):
                        desc = _blocking_call(call, set(held_exprs))
                        if desc:
                            out.append(Violation(
                                "blocking-under-lock", sf.rel,
                                call.lineno,
                                f"blocking call {desc} while holding "
                                f"{sorted(held_exprs)}"))
                if _lockish(item.context_expr):
                    new_exprs.add(unparse(item.context_expr))
            _walk_blocking(sf, stmt.body, frozenset(new_exprs), out)
        elif isinstance(stmt, ast.ClassDef):
            _walk_blocking(sf, stmt.body, frozenset(), out)
        else:
            for child in _child_bodies(stmt):
                _walk_blocking(sf, child, held_exprs, out)
