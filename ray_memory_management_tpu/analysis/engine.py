"""rmtcheck engine: file discovery, pragma handling, checker registry.

The runtime grew a set of CONVENTION-based invariants — ``# guarded-by``
lock discipline, the canonical ``rmt_*`` metric registry, the named
fault-site plane, additive-only wire protocol v2, ContextVar trace
propagation — that nothing machine-checked (Ray itself lints exactly
this class of invariant in CI). Each convention gets one AST checker
here; the suite runs as ``python -m ray_memory_management_tpu.analysis``
(CLI ``rmt check``) and as the tier-1 test
``tests/test_static_analysis.py`` asserting zero violations on the tree.

Suppression grammar (audited exceptions only — every pragma carries its
reason in the trailing comment text)::

    some_code()  # rmtcheck: disable=<rule>[,<rule>] — <reason>

A pragma suppresses its own line and, when it sits alone on a line, the
line below. ``# rmtcheck: disable-file=<rule>`` within the first 20
lines suppresses a rule for the whole file. ``# rmtcheck: holds=<lock>``
on a ``def`` line asserts the function runs with ``self.<lock>`` held by
its caller (the lock checkers treat the body as a held-lock region).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Callable, Dict, List, Optional, Tuple

PRAGMA_RE = re.compile(r"#\s*rmtcheck:\s*disable=([\w,\-]+)")
FILE_PRAGMA_RE = re.compile(r"#\s*rmtcheck:\s*disable-file=([\w,\-]+)")
HOLDS_RE = re.compile(r"#\s*rmtcheck:\s*holds=([\w,]+)")
GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*([\w.]+)")


class Violation:
    """One invariant breach at a file:line."""

    __slots__ = ("rule", "path", "line", "message")

    def __init__(self, rule: str, path: str, line: int, message: str):
        self.rule = rule
        self.path = path
        self.line = line
        self.message = message

    def format(self) -> str:
        return f"{self.path}:{self.line}: {self.rule}: {self.message}"

    def as_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message}

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"Violation({self.format()})"


class SourceFile:
    """One parsed module: text, per-line pragmas, AST (None on syntax
    error — reported as its own violation by run_checks)."""

    def __init__(self, path: str, rel: str, text: str):
        self.path = path
        self.rel = rel
        self.text = text
        self.lines = text.splitlines()
        self.tree: Optional[ast.AST] = None
        self.syntax_error: Optional[str] = None
        try:
            self.tree = ast.parse(text, filename=rel)
        except SyntaxError as e:  # pragma: no cover - tree always parses
            self.syntax_error = f"syntax error: {e.msg} (line {e.lineno})"
        # line -> set(rules) disabled there
        self._disabled: Dict[int, set] = {}
        self._file_disabled: set = set()
        for i, line in enumerate(self.lines, start=1):
            m = PRAGMA_RE.search(line)
            if m:
                rules = set(m.group(1).split(","))
                self._disabled.setdefault(i, set()).update(rules)
                # a standalone pragma line covers the statement below it
                if line.strip().startswith("#"):
                    self._disabled.setdefault(i + 1, set()).update(rules)
            if i <= 20:
                fm = FILE_PRAGMA_RE.search(line)
                if fm:
                    self._file_disabled.update(fm.group(1).split(","))

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def suppressed(self, rule: str, lineno: int) -> bool:
        if rule in self._file_disabled:
            return True
        return rule in self._disabled.get(lineno, ())

    def holds_annotation(self, node: ast.AST) -> List[str]:
        """Locks asserted held for a function via ``# rmtcheck: holds=``
        on (or directly above) its ``def`` line."""
        locks: List[str] = []
        for lineno in (getattr(node, "lineno", 0),
                       getattr(node, "lineno", 0) - 1):
            m = HOLDS_RE.search(self.line_text(lineno))
            if m:
                locks.extend(m.group(1).split(","))
        return locks


class Project:
    """The file sets the checkers see: the package tree (checked) and
    the test tree (scanned only for references, never checked)."""

    def __init__(self, package_root: str, test_root: Optional[str] = None,
                 repo_root: Optional[str] = None):
        self.package_root = package_root
        self.test_root = test_root
        self.repo_root = repo_root or os.path.dirname(package_root)
        self.files: List[SourceFile] = self._load(package_root)
        self.test_files: List[SourceFile] = (
            self._load(test_root, skip_dirs=("analysis_fixtures",))
            if test_root and os.path.isdir(test_root) else [])

    def _load(self, root: str, skip_dirs: Tuple[str, ...] = ()
              ) -> List[SourceFile]:
        out: List[SourceFile] = []
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in ("__pycache__",) + skip_dirs)
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                rel = os.path.relpath(path, self.repo_root)
                try:
                    with open(path, "r", encoding="utf-8") as f:
                        out.append(SourceFile(path, rel, f.read()))
                except OSError:  # pragma: no cover - unreadable file
                    continue
        return out

    def get(self, rel_suffix: str) -> Optional[SourceFile]:
        for sf in self.files:
            if sf.rel.endswith(rel_suffix):
                return sf
        return None


# rule name -> checker(project, options) -> [Violation]
CheckerFn = Callable[[Project, dict], List[Violation]]
_REGISTRY: Dict[str, CheckerFn] = {}


def register(rule: str) -> Callable[[CheckerFn], CheckerFn]:
    def deco(fn: CheckerFn) -> CheckerFn:
        _REGISTRY[rule] = fn
        return fn
    return deco


def all_rules() -> List[str]:
    return sorted(_REGISTRY)


def run_checks(package_root: str, test_root: Optional[str] = None,
               rules: Optional[List[str]] = None,
               options: Optional[dict] = None) -> List[Violation]:
    """Run the (selected) checkers over the tree; returns unsuppressed
    violations sorted by path:line. ``options``: ``frozen`` (bool) makes
    protocol-additivity treat NEW wire keys as violations instead of
    auto-registering them (the CI mode)."""
    # import the checker modules so they register (lazy: the analysis
    # package must stay importable without running anything)
    from . import (  # noqa: F401
        check_faults, check_health, check_locks, check_logs,
        check_metrics, check_protocol, check_trace,
    )

    project = Project(package_root, test_root)
    opts = dict(options or {})
    out: List[Violation] = []
    for sf in project.files:
        if sf.syntax_error:
            out.append(Violation("parse", sf.rel, 1, sf.syntax_error))
    for rule in (rules or all_rules()):
        fn = _REGISTRY.get(rule)
        if fn is None:
            raise ValueError(f"unknown rule {rule!r} (want {all_rules()})")
        for v in fn(project, opts):
            sf = next((f for f in project.files if f.rel == v.path), None)
            if sf is not None and sf.suppressed(v.rule, v.line):
                continue
            out.append(v)
    out.sort(key=lambda v: (v.path, v.line, v.rule))
    return out


# --------------------------------------------------------------- AST helpers
def unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - very old ast shapes
        return "<expr>"


def dict_literal_keys(node: ast.Dict) -> List[str]:
    """String keys of a dict literal (non-literal keys skipped)."""
    keys = []
    for k in node.keys:
        if isinstance(k, ast.Constant) and isinstance(k.value, str):
            keys.append(k.value)
    return keys


def const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def self_attr(node: ast.AST) -> Optional[str]:
    """'x' for ``self.x``, else None."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None
