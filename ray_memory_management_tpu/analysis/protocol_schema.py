"""Generated wire-protocol v2 key registry — do not hand-edit key sets.

``rmt check`` (rule ``protocol-additivity``) regenerates this file when
core/transfer.py starts sending a NEW request/reply key (additive
evolution, the diff is printed), and FAILS when a key listed here stops
appearing in the code: removing or renaming a wire key breaks rolling
upgrades where old peers still send/expect it. In ``--frozen`` mode
(CI / tests/test_static_analysis.py) additions fail too, so the schema
diff lands in the same commit as the protocol change.
"""

# v2 fetch request: client -> server header dict
REQUEST_KEYS = (
    "codecs",
    "defer_above",
    "length",
    "offset",
    "oid",
    "proto",
    "trace",
)

# v2 fetch reply: server -> client header dict
REPLY_KEYS = (
    "codec",
    "crc",
    "deferred",
    "error",
    "size",
    "total",
)

# observability piggyback frames: worker flush frame + agent pong
FRAME_KEYS = (
    "dadd",
    "ddel",
    "dfull",
    "events",
    "logs",
    "profile",
    "samples",
    "seq",
    "series",
    "stat",
    "type",
)
