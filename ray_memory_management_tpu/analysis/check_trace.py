"""``trace-propagation`` — every function that serializes a
dispatch/done/transfer frame must carry the trace context.

PR 6 made spans causal by threading ``trace_ctx`` through every hop:
dispatch frames (``{"type": "exec"|"exec_actor"}`` built in
core/runtime.py), done frames (``{"type": "done"}`` in core/worker.py),
and transfer request headers (dicts carrying both ``"oid"`` and
``"proto"`` in core/transfer.py). A new frame constructor that forgets
the trace field doesn't fail anything — the span tree just silently
loses its parent edge. So: any function containing one of those frame
literals must mention a trace identifier (``trace_ctx``, ``trace``,
``_trace...``) somewhere in its body.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from .engine import Project, Violation, dict_literal_keys, register

_FRAME_TYPES = {"exec", "exec_actor", "done"}
_FRAME_FILES = ("core/runtime.py", "core/worker.py",
                "core/node_agent.py", "core/remote_node.py")
_TRANSFER_SUFFIX = "core/transfer.py"


def _is_frame_dict(node: ast.Dict, in_transfer: bool) -> bool:
    keys = dict_literal_keys(node)
    if in_transfer:
        return "oid" in keys and "proto" in keys
    if "type" not in keys:
        return False
    for k, v in zip(node.keys, node.values):
        if isinstance(k, ast.Constant) and k.value == "type" and \
                isinstance(v, ast.Constant) and v.value in _FRAME_TYPES:
            return True
    return False


def _mentions_trace(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and "trace" in node.id.lower():
            return True
        if isinstance(node, ast.Attribute) and \
                "trace" in node.attr.lower():
            return True
        if isinstance(node, ast.Constant) and \
                isinstance(node.value, str) and "trace" in node.value:
            return True
    return False


def _enclosing_function(tree: ast.AST, target: ast.AST
                        ) -> Optional[ast.AST]:
    """Innermost function whose body contains ``target`` (by identity)."""
    def visit(node: ast.AST, current: Optional[ast.AST]
              ) -> Optional[ast.AST]:
        if node is target:
            return current
        nxt = node if isinstance(node, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)) else current
        for child in ast.iter_child_nodes(node):
            hit = visit(child, nxt)
            if hit is not None:
                return hit
        return None

    return visit(tree, None)


@register("trace-propagation")
def check_trace_propagation(project: Project, options: dict
                            ) -> List[Violation]:
    out: List[Violation] = []
    for sf in project.files:
        if sf.tree is None:
            continue
        in_transfer = sf.rel.endswith(_TRANSFER_SUFFIX)
        if not in_transfer and not any(sf.rel.endswith(s)
                                       for s in _FRAME_FILES):
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Dict) or \
                    not _is_frame_dict(node, in_transfer):
                continue
            fn = _enclosing_function(sf.tree, node)
            if fn is None:
                # module-level frame literal (e.g. a constant template):
                # nothing to propagate from — skip
                continue
            if not _mentions_trace(fn):
                kind = "transfer request" if in_transfer else "frame"
                out.append(Violation(
                    "trace-propagation", sf.rel, node.lineno,
                    f"{getattr(fn, 'name', '<fn>')}() serializes a "
                    f"{kind} dict but never touches a trace field — "
                    f"the span tree loses its parent edge here "
                    f"(thread trace_ctx through, see core/trace.py)"))
    return out
