"""``alert-rule-registry`` — the shipped health rule pack
(``core/health.py``) and the metric registry must agree.

A health rule references its series by name inside a plain data tuple
(``("rate", "rmt_tasks_failed_total", 30.0)``), which is invisible to
``metric-registry`` (that rule only tracks ``get()``/constructor args
and accessor-name strings). So rule-pack drift — a rule watching a
series that was renamed or removed from ``metrics_defs.DEFS`` — would
silently evaluate to no-data forever: the alert can never fire, which
is the worst possible failure mode for an alerting system.

This rule closes the gap: every ``rmt_*`` string constant in a
``core/health.py`` module must name a series declared in DEFS. The
probe functions live in the same module and reference series the same
way, so they are covered too.
"""

from __future__ import annotations

import ast
from typing import List

from .check_metrics import parse_registry
from .engine import Project, Violation, register

_HEALTH_SUFFIX = "core/health.py"


@register("alert-rule-registry")
def check_alert_rule_registry(project: Project, options: dict
                              ) -> List[Violation]:
    sf = project.get(_HEALTH_SUFFIX)
    if sf is None or sf.tree is None:
        return []  # no health module in this tree: nothing to drift
    metrics, _accessors = parse_registry(project)
    out: List[Violation] = []
    if not metrics:
        out.append(Violation(
            "alert-rule-registry", sf.rel, 1,
            "could not parse the DEFS registry out of metrics_defs.py "
            "(rule-pack series cannot be validated)"))
        return out
    for node in ast.walk(sf.tree):
        if not (isinstance(node, ast.Constant)
                and isinstance(node.value, str)
                and node.value.startswith("rmt_")):
            continue
        if node.value not in metrics:
            out.append(Violation(
                "alert-rule-registry", sf.rel, node.lineno,
                f"health rule references series {node.value!r} which is "
                "not declared in metrics_defs.DEFS — the rule can never "
                "fire (rename it or declare the series)"))
    return out
