"""``log-discipline`` — package code uses the structured log plane.

PR 10 gave the runtime a cluster log plane (utils/structlog.py): a
record emitted through the package logger carries node/role/task/trace
identity and lands in the head LogStore; a bare ``print()`` yields an
anonymous line on some process's stderr that no query surface can find.
Two conventions keep the plane authoritative:

- no bare ``print()`` in library code. CLI entry points (``scripts/``,
  any ``__main__.py``), bench/microbench modules and the top-level
  ``setup``-style scripts are console programs whose stdout IS the
  interface — they are exempt. Audited exceptions (e.g. a user-facing
  ``Dataset.show()``) carry a pragma with a reason.

- log calls format lazily: ``log.warning("x %s", v)``, never
  ``log.warning(f"x {v}")``. Eager formatting pays string-build cost
  even when the level is filtered, and it destroys the constant message
  template that makes records aggregatable.
"""

from __future__ import annotations

import ast
from typing import List

from .engine import Project, Violation, register

_LOG_METHODS = {"debug", "info", "warning", "error", "exception",
                "critical"}
# receivers that are conventionally loggers; plus any name assigned from
# a get_logger()/getLogger() call in the same file (collected per file)
_LOGGER_NAMES = {"log", "logger", "_log", "_logger", "LOG"}
_LOGGER_FACTORIES = {"get_logger", "getLogger"}


def _is_exempt(rel: str) -> bool:
    base = rel.rsplit("/", 1)[-1]
    return ("/scripts/" in rel
            or base == "__main__.py"
            or base.endswith("_bench.py")
            or base == "microbenchmark.py")


def _logger_vars(tree: ast.AST) -> set:
    names = set(_LOGGER_NAMES)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        call = node.value
        if not (isinstance(call, ast.Call)
                and isinstance(call.func, ast.Attribute)
                and call.func.attr in _LOGGER_FACTORIES):
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                names.add(tgt.id)
            elif isinstance(tgt, ast.Attribute):
                names.add(tgt.attr)
    return names


def _receiver_name(func: ast.Attribute) -> str:
    base = func.value
    if isinstance(base, ast.Name):
        return base.id
    if isinstance(base, ast.Attribute):
        return base.attr
    return ""


def _eager_reason(arg: ast.AST) -> str:
    """Why the first log argument formats eagerly, or '' if it's fine."""
    if isinstance(arg, ast.JoinedStr):
        return "an f-string"
    if isinstance(arg, ast.BinOp) and isinstance(arg.op, ast.Mod):
        return "%-interpolation"
    if isinstance(arg, ast.Call) and \
            isinstance(arg.func, ast.Attribute) and \
            arg.func.attr == "format":
        return "str.format()"
    return ""


@register("log-discipline")
def check_log_discipline(project: Project, options: dict
                         ) -> List[Violation]:
    out: List[Violation] = []
    for sf in project.files:
        if sf.tree is None or _is_exempt(sf.rel):
            continue
        loggers = _logger_vars(sf.tree)
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Name) and func.id == "print":
                out.append(Violation(
                    "log-discipline", sf.rel, node.lineno,
                    "bare print() in package code — use the package "
                    "logger (utils/structlog.get_logger) so the line "
                    "carries node/task/trace identity and reaches the "
                    "head LogStore; scripts/, __main__.py and bench "
                    "modules are exempt"))
                continue
            if isinstance(func, ast.Attribute) and \
                    func.attr in _LOG_METHODS and \
                    _receiver_name(func) in loggers and node.args:
                reason = _eager_reason(node.args[0])
                if reason:
                    out.append(Violation(
                        "log-discipline", sf.rel, node.lineno,
                        f"log call formats its message eagerly with "
                        f"{reason} — pass a %s template and args "
                        f"(log.{func.attr}(\"x %s\", v)) so formatting "
                        f"is skipped when the level is filtered and "
                        f"the template stays aggregatable"))
    return out
