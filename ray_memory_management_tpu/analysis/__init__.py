"""rmtcheck: AST static analysis + runtime race/deadlock detection for
the runtime's concurrency and registry conventions.

Static suite: ``python -m ray_memory_management_tpu.analysis`` or
``rmt check`` — see ``engine.run_checks`` and ``analysis/README.md``.
Runtime detector: ``lockwatch`` (opt-in via ``RMT_LOCK_CHECK=1``).
"""

from .engine import Violation, all_rules, run_checks  # noqa: F401

__all__ = ["Violation", "all_rules", "run_checks", "run_default"]


def run_default(frozen: bool = False, rules=None):
    """Run the suite against the in-tree package + tests (the paths the
    CLI and tier-1 test use)."""
    import os

    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    repo = os.path.dirname(pkg)
    tests = os.path.join(repo, "tests")
    return run_checks(pkg, tests, rules=rules,
                      options={"frozen": frozen})
