"""HTTP ingress proxy.

The reference runs an HTTP proxy per node (serve/_private/http_proxy.py:189,
333) routing ``/<deployment>`` to replicas. Here a single proxy actor runs
a stdlib ThreadingHTTPServer (no aiohttp dependency): request bodies are
passed as the deployment's argument, JSON bodies decoded, responses
JSON-encoded. Enough surface for curl/load-balancer ingress; Python-side
traffic should prefer handles (zero-copy through the object plane).
"""

from __future__ import annotations

import json
import threading
from typing import Dict

from .. import api as core_api

PROXY_NAME = "SERVE_HTTP_PROXY"


class HTTPProxy:
    def __init__(self, controller, port: int):
        self._controller = controller
        self._port = port
        self._handles: Dict[str, object] = {}
        self._server = None
        self._thread = None

    def ready(self) -> int:
        if self._server is not None:  # idempotent: already listening
            return self._port
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        proxy = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _dispatch(self):
                name = self.path.strip("/").split("/")[0]
                if not name:
                    self.send_response(404)
                    self.end_headers()
                    self.wfile.write(b'{"error": "no deployment in path"}')
                    return
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                arg = None
                if body:
                    try:
                        arg = json.loads(body)
                    except json.JSONDecodeError:
                        arg = body.decode("utf-8", "replace")
                try:
                    handle = proxy._handle_for(name)
                    ref = handle.remote(arg) if arg is not None \
                        else handle.remote()
                    result = core_api.get(ref, timeout=60)
                    payload = json.dumps(result).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.end_headers()
                    self.wfile.write(payload)
                except Exception as e:  # noqa: BLE001 — surface to client
                    self.send_response(500)
                    self.end_headers()
                    self.wfile.write(
                        json.dumps({"error": str(e)}).encode())

            do_GET = _dispatch
            do_POST = _dispatch

        self._server = ThreadingHTTPServer(("127.0.0.1", self._port), Handler)
        self._port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="serve-http")
        self._thread.start()
        return self._port

    def _handle_for(self, name: str):
        from .handle import DeploymentHandle

        h = self._handles.get(name)
        if h is None:
            h = DeploymentHandle(self._controller, name)
            self._handles[name] = h
        return h

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()


def start_proxy(controller, port: int) -> int:
    """Start (or reuse) the proxy actor; returns the bound port."""
    try:
        proxy = core_api.get_actor(PROXY_NAME)
    except Exception:
        try:
            proxy = core_api.remote(HTTPProxy).options(
                name=PROXY_NAME, lifetime="detached", num_cpus=0,
                max_concurrency=32,
            ).remote(controller, port)
        except Exception:
            proxy = core_api.get_actor(PROXY_NAME)
    return core_api.get(proxy.ready.remote(), timeout=60)
