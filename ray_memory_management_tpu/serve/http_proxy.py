"""HTTP ingress proxy.

The reference runs an HTTP proxy per node (serve/_private/http_proxy.py:189,
333) routing ``/<deployment>`` to replicas. Here a single proxy actor runs
a stdlib ThreadingHTTPServer (no aiohttp dependency): request bodies are
passed as the deployment's argument, JSON bodies decoded, responses
JSON-encoded. Enough surface for curl/load-balancer ingress; Python-side
traffic should prefer handles (zero-copy through the object plane).

Two data-plane behaviors live here:

  - **Tracing** — every request mints a ROOT trace context before
    dispatch; the runtime's submit path then parents the
    router→replica→engine spans under it, so one trace id (returned in
    the ``x-rmt-trace-id`` response header) walks a p99 outlier
    end-to-end through ``rmt trace`` / ``summarize_critical_path`` /
    the log plane.
  - **Load shedding** — a request arriving while the deployment's known
    queue depth exceeds ``serve_shed_queue_factor x replicas x
    max_concurrent_queries`` is rejected with HTTP 429 up front
    (counted under ``rmt_serve_shed_total{reason="queue_full"}``);
    router-level backpressure timeouts and empty routing tables also
    map to 429 rather than a generic 500 — clients can tell "retry
    later" from "broken".
"""

from __future__ import annotations

import json
import threading
from typing import Dict

from .. import api as core_api
from ..utils import tracing

PROXY_NAME = "SERVE_HTTP_PROXY"


def _count_shed_queue_full() -> None:
    try:
        from ..core import metrics_defs as mdefs

        mdefs.serve_shed().inc(tags={"reason": "queue_full"})
    except Exception:  # noqa: BLE001
        pass


class HTTPProxy:
    def __init__(self, controller, port: int):
        self._controller = controller
        self._port = port
        self._handles: Dict[str, object] = {}
        self._server = None
        self._thread = None

    def ready(self) -> int:
        if self._server is not None:  # idempotent: already listening
            return self._port
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        from .handle import BackpressureTimeout, NoReplicasError

        proxy = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _reply(self, status: int, payload: dict, trace_id=None):
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                if trace_id is not None:
                    self.send_header("x-rmt-trace-id", trace_id)
                self.end_headers()
                self.wfile.write(json.dumps(payload).encode())

            def _dispatch(self):
                # root span for the whole request: submits below inherit
                # it, so proxy->router->replica->engine share one trace id
                ctx = tracing.new_root()
                trace_id = ctx[0]
                token = tracing.set_current(ctx)
                try:
                    self._dispatch_traced(trace_id)
                finally:
                    tracing.reset(token)

            def _dispatch_traced(self, trace_id: str):
                name = self.path.strip("/").split("/")[0]
                if not name:
                    self._reply(404, {"error": "no deployment in path"},
                                trace_id)
                    return
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                arg = None
                if body:
                    try:
                        arg = json.loads(body)
                    except json.JSONDecodeError:
                        arg = body.decode("utf-8", "replace")
                try:
                    handle = proxy._handle_for(name)
                    if handle._router.overloaded():
                        # reject BEFORE routing: a request past the shed
                        # threshold would only wait out its whole
                        # backpressure window and time out anyway
                        _count_shed_queue_full()
                        self._reply(429, {"error": "overloaded: queue "
                                          f"full for {name}"}, trace_id)
                        return
                    ref = handle.remote(arg) if arg is not None \
                        else handle.remote()
                    result = core_api.get(ref, timeout=60)
                    self._reply(200, result, trace_id)
                except (BackpressureTimeout, NoReplicasError) as e:
                    self._reply(429, {"error": str(e)}, trace_id)
                except Exception as e:  # noqa: BLE001 — surface to client
                    self._reply(500, {"error": str(e)}, trace_id)

            do_GET = _dispatch
            do_POST = _dispatch

        self._server = ThreadingHTTPServer(("127.0.0.1", self._port), Handler)
        self._port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="serve-http")
        self._thread.start()
        return self._port

    def _handle_for(self, name: str):
        from .handle import DeploymentHandle

        h = self._handles.get(name)
        if h is None:
            h = DeploymentHandle(self._controller, name)
            self._handles[name] = h
        return h

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()


def start_proxy(controller, port: int) -> int:
    """Start (or reuse) the proxy actor; returns the bound port."""
    try:
        proxy = core_api.get_actor(PROXY_NAME)
    except Exception:
        try:
            proxy = core_api.remote(HTTPProxy).options(
                name=PROXY_NAME, lifetime="detached", num_cpus=0,
                max_concurrency=32,
            ).remote(controller, port)
        except Exception:
            proxy = core_api.get_actor(PROXY_NAME)
    return core_api.get(proxy.ready.remote(), timeout=60)
