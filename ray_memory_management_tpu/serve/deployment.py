"""Deployment definition API: @serve.deployment, .bind(), .deploy().

The reference's Deployment class + decorator (python/ray/serve/deployment.py
— options/num_replicas/user_config/max_concurrent_queries,
``Deployment.bind`` building a deployment graph node, `.deploy()` pushing
to the controller) and AutoscalingConfig
(serve/config.py AutoscalingConfig).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Tuple

from .. import serialization as ser


@dataclass
class AutoscalingConfig:
    min_replicas: int = 1
    max_replicas: int = 1
    target_num_ongoing_requests_per_replica: float = 1.0

    def to_dict(self) -> dict:
        return {
            "min_replicas": self.min_replicas,
            "max_replicas": self.max_replicas,
            "target_num_ongoing_requests_per_replica":
                self.target_num_ongoing_requests_per_replica,
        }


class Application:
    """A bound deployment (the reference's DAGNode from
    ``Deployment.bind``): deployment + init args, possibly referencing
    other bound deployments, resolved to handles at deploy time."""

    def __init__(self, deployment: "Deployment", args: Tuple, kwargs: Dict):
        self.deployment = deployment
        self.args = args
        self.kwargs = kwargs


class Deployment:
    def __init__(self, func_or_class: Any, name: str,
                 num_replicas: int = 1,
                 init_args: Tuple = (),
                 init_kwargs: Optional[Dict] = None,
                 user_config: Any = None,
                 max_concurrent_queries: int = 100,
                 autoscaling_config: Optional[AutoscalingConfig] = None,
                 ray_actor_options: Optional[Dict] = None,
                 placement_hint: Optional[str] = None):
        self._func_or_class = func_or_class
        self.name = name
        self.num_replicas = num_replicas
        self.init_args = init_args
        self.init_kwargs = init_kwargs or {}
        self.user_config = user_config
        self.max_concurrent_queries = max_concurrent_queries
        self.autoscaling_config = autoscaling_config
        self.ray_actor_options = ray_actor_options
        # hex object id whose holding node/tier new replicas should
        # prefer (e.g. shipped weights pinned in a device tier)
        self.placement_hint = placement_hint

    def options(self, *, name: Optional[str] = None,
                num_replicas: Optional[int] = None,
                init_args: Optional[Tuple] = None,
                init_kwargs: Optional[Dict] = None,
                user_config: Any = None,
                max_concurrent_queries: Optional[int] = None,
                autoscaling_config: Optional[AutoscalingConfig] = None,
                ray_actor_options: Optional[Dict] = None,
                placement_hint: Optional[str] = None) -> "Deployment":
        return Deployment(
            self._func_or_class,
            name if name is not None else self.name,
            num_replicas if num_replicas is not None else self.num_replicas,
            init_args if init_args is not None else self.init_args,
            init_kwargs if init_kwargs is not None else self.init_kwargs,
            user_config if user_config is not None else self.user_config,
            max_concurrent_queries if max_concurrent_queries is not None
            else self.max_concurrent_queries,
            autoscaling_config if autoscaling_config is not None
            else self.autoscaling_config,
            ray_actor_options if ray_actor_options is not None
            else self.ray_actor_options,
            placement_hint if placement_hint is not None
            else self.placement_hint,
        )

    def bind(self, *args, **kwargs) -> Application:
        return Application(self, args, kwargs)

    def deploy(self, *init_args, **init_kwargs):
        """Imperative deploy (the reference's 1.x-style API, still present
        at serve/deployment.py deploy)."""
        from . import api as serve_api

        d = self
        if init_args or init_kwargs:
            # only override what was actually passed; deploy(x) must not
            # clobber decorator-supplied init_kwargs with {}
            d = self.options(
                init_args=init_args if init_args else None,
                init_kwargs=init_kwargs if init_kwargs else None)
        return serve_api._deploy(d)

    def get_handle(self):
        from . import api as serve_api

        return serve_api.get_deployment_handle(self.name)

    def to_config(self) -> dict:
        cfg = {
            "func_or_class_blob": ser.dumps_function(self._func_or_class),
            "num_replicas": self.num_replicas,
            "init_args": self.init_args,
            "init_kwargs": self.init_kwargs,
            "user_config": self.user_config,
            "max_concurrent_queries": self.max_concurrent_queries,
            "actor_options": self.ray_actor_options,
            "autoscaling": self.autoscaling_config.to_dict()
            if self.autoscaling_config else None,
            "placement_hint": self.placement_hint,
        }
        if cfg["autoscaling"]:
            # autoscaler owns num_replicas between min and max
            cfg["num_replicas"] = max(
                self.autoscaling_config.min_replicas, 1)
        return cfg


def deployment(_func_or_class: Optional[Callable] = None, *,
               name: Optional[str] = None,
               num_replicas: int = 1,
               init_args: Tuple = (),
               init_kwargs: Optional[Dict] = None,
               user_config: Any = None,
               max_concurrent_queries: int = 100,
               autoscaling_config: Optional[Any] = None,
               ray_actor_options: Optional[Dict] = None,
               placement_hint: Optional[str] = None):
    """``@serve.deployment`` / ``@serve.deployment(num_replicas=...)``."""
    if autoscaling_config is not None and isinstance(
            autoscaling_config, dict):
        autoscaling_config = AutoscalingConfig(**autoscaling_config)

    def wrap(func_or_class):
        return Deployment(
            func_or_class,
            name or getattr(func_or_class, "__name__", "deployment"),
            num_replicas=num_replicas,
            init_args=init_args,
            init_kwargs=init_kwargs,
            user_config=user_config,
            max_concurrent_queries=max_concurrent_queries,
            autoscaling_config=autoscaling_config,
            ray_actor_options=ray_actor_options,
            placement_hint=placement_hint,
        )

    if _func_or_class is not None:
        return wrap(_func_or_class)
    return wrap
