"""Serve library: online model serving over the actor runtime.

The reference's ``ray.serve`` (python/ray/serve/ — controller actor,
deployment/replica reconciler, router with in-flight caps, long-poll
config push, autoscaling, HTTP proxies).
"""

from .api import (  # noqa: F401
    delete,
    get_deployment_handle,
    get_handle,
    list_deployments,
    run,
    shutdown,
    start,
    status,
)
from .deployment import (  # noqa: F401
    Application,
    AutoscalingConfig,
    Deployment,
    deployment,
)
from .handle import DeploymentHandle  # noqa: F401
from .llm import DynamicBatcher, LLMServer, llm_deployment  # noqa: F401
