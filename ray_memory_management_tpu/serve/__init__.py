"""Serve library: online model serving over the actor runtime.

The reference's ``ray.serve`` (python/ray/serve/ — controller actor,
deployment/replica reconciler, router with in-flight caps, long-poll
config push, autoscaling, HTTP proxies).
"""

from .api import (  # noqa: F401
    delete,
    get_deployment_handle,
    get_handle,
    list_deployments,
    run,
    shutdown,
    start,
    status,
)
from .deployment import (  # noqa: F401
    Application,
    AutoscalingConfig,
    Deployment,
    deployment,
)
from .handle import (  # noqa: F401
    BackpressureTimeout,
    DeploymentHandle,
    NoReplicasError,
)
from .kv_cache import KVPagePool  # noqa: F401
from .llm import (  # noqa: F401
    ContinuousBatcher,
    DynamicBatcher,
    LLMServer,
    llm_deployment,
    pack_weights,
    unpack_weights,
)
