"""Replica actor: hosts one copy of a deployment.

The reference's RayServeReplica (serve/_private/replica.py:250,494): wraps
the user's class/function, counts in-flight queries, applies
``reconfigure(user_config)``, and drains before shutdown.
"""

from __future__ import annotations

import inspect
import threading
import time
from typing import Any, Optional


class Replica:
    """Generic replica actor body. The deployment's callable arrives
    cloudpickled (our actor creation path ships it), so replicas never
    import user modules."""

    def __init__(self, deployment_name: str, replica_tag: str,
                 func_or_class_blob: bytes, init_args, init_kwargs,
                 user_config: Optional[dict] = None):
        import cloudpickle

        func_or_class = cloudpickle.loads(func_or_class_blob)
        self.deployment_name = deployment_name
        self.replica_tag = replica_tag
        self._ongoing = 0
        self._total = 0
        self._lock = threading.Lock()
        if inspect.isclass(func_or_class):
            self.callable = func_or_class(*init_args, **(init_kwargs or {}))
        else:
            if init_args or init_kwargs:
                raise ValueError("function deployments take no init args")
            self.callable = func_or_class
        if user_config is not None:
            self.reconfigure(user_config)

    def ready(self) -> str:
        return self.replica_tag

    def reconfigure(self, user_config) -> None:
        """Push a new user_config (serve/_private/replica.py reconfigure)."""
        fn = getattr(self.callable, "reconfigure", None)
        if fn is None:
            if user_config is not None and not callable(self.callable):
                raise ValueError(
                    f"deployment {self.deployment_name} has user_config but "
                    "no reconfigure() method")
            return
        fn(user_config)

    def handle_request(self, method: str, args, kwargs) -> Any:
        from ..core import metrics_defs as mdefs
        from ..utils import faults

        t0 = time.monotonic()
        with self._lock:
            self._ongoing += 1
            self._total += 1
        result = "ok"
        try:
            act = faults.fire("replica.exec")
            if act is not None:
                if act.mode == "stall":  # inflates service time: the
                    act.sleep()          # p99/SLO-attribution test site
                else:  # error/drop surface to the caller's api.get
                    act.raise_()
            if method in ("__call__", None):
                target = self.callable
            else:
                target = getattr(self.callable, method)
            return target(*args, **kwargs)
        except BaseException:
            result = "error"
            raise
        finally:
            with self._lock:
                self._ongoing -= 1
            try:
                mdefs.serve_requests().inc(tags={
                    "deployment": self.deployment_name, "result": result})
                mdefs.serve_request_seconds().observe(
                    time.monotonic() - t0,
                    tags={"deployment": self.deployment_name})
            except Exception:  # noqa: BLE001 — metrics never fail serving
                pass

    def metrics(self) -> dict:
        with self._lock:
            return {
                "replica_tag": self.replica_tag,
                "num_ongoing_requests": self._ongoing,
                "num_total_requests": self._total,
            }

    def drain(self, timeout_s: float = 5.0) -> bool:
        """Wait for in-flight requests to finish (graceful shutdown)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if self._ongoing == 0:
                    return True
            time.sleep(0.02)
        return False
