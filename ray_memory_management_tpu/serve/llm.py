"""LM serving: a deployment wrapping the KV-cached decode path.

The reference serves models through generic deployments plus the
``serve.batch`` request coalescer (python/ray/serve/batching.py:279;
replica loop serve/_private/replica.py:250). Here the same two pieces are
TPU-shaped:

  - :class:`DynamicBatcher` — a thread-based request coalescer: callers
    block, a background thread collects up to ``max_batch_size`` requests
    within ``batch_wait_timeout_s`` and runs them as ONE model call. On a
    TPU the batch dimension is nearly free (MXU width), so coalescing is
    the difference between 1x and Nx decode throughput under load.
  - :class:`LLMServer` — the deployment class: holds params on device,
    pads each batch to a fixed shape bucket (batch -> ``max_batch_size``
    rows, prompt -> multiple of ``pad_multiple``), so XLA compiles ONE
    prefill+decode program per bucket and reuses it forever
    (models/gpt.py generate's compile-once contract).

Requests carry token ids (``{"tokens": [...]}``) or plain text
(``{"text": ...}``, byte-level fallback tokenizer) — the deployment is
model-complete without shipping a tokenizer dependency.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from .deployment import deployment


class _Pending:
    __slots__ = ("item", "event", "result", "error")

    def __init__(self, item):
        self.item = item
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None


class DynamicBatcher:
    """Coalesce concurrent blocking calls into batched ``fn`` invocations.

    ``fn(items: list) -> list`` runs on the batcher thread; callers park
    in :meth:`submit` until their result is ready. The first arrival opens
    a window of ``batch_wait_timeout_s``; the batch launches when the
    window closes or ``max_batch_size`` is reached, whichever is first
    (the reference's @serve.batch semantics, batching.py:279)."""

    def __init__(self, fn, max_batch_size: int = 8,
                 batch_wait_timeout_s: float = 0.01):
        self._fn = fn
        self.max_batch_size = max_batch_size
        self.batch_wait_timeout_s = batch_wait_timeout_s
        self._q: List[_Pending] = []
        self._cond = threading.Condition()
        self._stop = False
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="llm-batcher")
        self._thread.start()

    def submit(self, item, timeout: float = 300.0):
        p = _Pending(item)
        with self._cond:
            if self._stop:
                raise RuntimeError("batcher closed")
            self._q.append(p)
            self._cond.notify()
        if not p.event.wait(timeout):
            raise TimeoutError("batched call timed out")
        if p.error is not None:
            raise p.error
        return p.result

    def _loop(self) -> None:
        while not self._stop:
            with self._cond:
                while not self._q and not self._stop:
                    self._cond.wait(timeout=1.0)
                if self._stop:
                    return
                deadline = time.monotonic() + self.batch_wait_timeout_s
                while (len(self._q) < self.max_batch_size
                       and time.monotonic() < deadline):
                    self._cond.wait(timeout=max(
                        0.0, deadline - time.monotonic()))
                batch = self._q[: self.max_batch_size]
                del self._q[: self.max_batch_size]
            try:
                results = self._fn([p.item for p in batch])
                if len(results) != len(batch):
                    raise ValueError(
                        f"batch fn returned {len(results)} results for "
                        f"{len(batch)} items")
                for p, r in zip(batch, results):
                    p.result = r
                    p.event.set()
            except BaseException as e:  # noqa: BLE001 — deliver to callers
                for p in batch:
                    p.error = e
                    p.event.set()

    def close(self) -> None:
        with self._cond:
            self._stop = True
            drained = list(self._q)
            self._q.clear()
            self._cond.notify_all()
        for p in drained:  # fail parked callers promptly, not by timeout
            p.error = RuntimeError("batcher closed")
            p.event.set()


def _bytes_tokenize(text: str, vocab_size: int) -> List[int]:
    """Byte-level fallback: utf-8 bytes offset past the special range."""
    return [2 + (b % (vocab_size - 2)) for b in text.encode()]


class ContinuousBatcher:
    """Decode-step-granular request scheduler (continuous batching).

    The DynamicBatcher above is a whole-batch barrier: every request in a
    batch decodes the full ``max_new_tokens`` before ANY new request joins,
    so under streaming arrivals the chip idles on retired rows and new
    arrivals queue behind the stragglers. This engine schedules at decode-
    step granularity over a fixed slot table (the vLLM/Orca iteration-level
    scheduling idea, TPU-shaped):

      - a KV cache of ``max_slots`` rows lives across requests; a new
        request is PREFILLED into a free row the moment one exists
        (per-bucket compiled prefill writes its prompt's KV at positions
        [0, len));
      - every engine iteration runs ONE single-token decode step over all
        occupied rows (one compiled program, static [max_slots, 1] shape,
        per-row offsets via models/gpt.forward_with_cache_rows);
      - a row that reaches its request's token budget retires immediately
        and its slot admits the next queued request at the very next step.

    Per-row offsets also make mixed-length batches EXACT: each row attends
    only to its own true history with its own rope phases — the padded-
    batch approximation (a short row conditioning on its repeated final
    token) is gone.
    """

    def __init__(self, params, cfg, max_slots: int = 8,
                 max_new_tokens: int = 32, temperature: float = 0.0,
                 pad_multiple: int = 64, seed: int = 0,
                 steps_per_iter: int = 8):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from ..models import gpt

        self._jax, self._jnp, self._np, self._gpt = jax, jnp, np, gpt
        self.cfg = cfg
        self.params = params
        self.max_slots = max_slots
        self.max_new_tokens = max_new_tokens
        self.temperature = temperature
        self.pad_multiple = pad_multiple
        # scheduling quantum: each engine iteration decodes K tokens for
        # every occupied row inside ONE compiled lax.scan — per-step
        # Python dispatch would otherwise eat the step-granularity win
        # (the barrier mode scans its whole budget in one program; K
        # amortizes dispatch K-fold while arrivals still join within K
        # steps and finished rows retire within K steps)
        self.steps_per_iter = max(1, min(steps_per_iter, max_new_tokens))
        self._key = jax.random.PRNGKey(seed)
        self._cache = gpt.init_kv_cache(cfg, max_slots, cfg.max_seq)
        self._prefill_cache: Dict[int, Any] = {}  # bucket -> compiled fn

        def _sample(logits, key):
            if self.temperature > 0:
                return jax.random.categorical(key, logits / self.temperature)
            return jnp.argmax(logits, axis=-1)

        K = self.steps_per_iter

        def step_fn(params, cache, last, offsets, key):
            def body(carry, t):
                cache, last, key = carry
                key, sub = jax.random.split(key)
                logits, cache = gpt.forward_with_cache_rows(
                    params, last[:, None], cache, offsets + t, cfg)
                nxt = _sample(logits[:, 0], sub)
                return (cache, nxt, key), nxt

            (cache, _, _), toks = jax.lax.scan(
                body, (cache, last, key), jnp.arange(K))
            return cache, toks  # [K, B]

        # donate the cache so each iteration updates it in place on device
        # instead of allocating a fresh multi-hundred-MB copy
        self._step = jax.jit(step_fn, donate_argnums=(1,))
        self._sample = _sample

        # slot state (host side)
        self._slot_pending: List[Optional[_Pending]] = [None] * max_slots
        self._slot_offset = np.zeros(max_slots, np.int32)
        self._slot_last = np.ones(max_slots, np.int32)
        self._slot_out: List[List[int]] = [[] for _ in range(max_slots)]
        self._slot_budget = np.zeros(max_slots, np.int32)

        self._q: List[_Pending] = []
        self._cond = threading.Condition()
        self._stop = False
        self.steps = 0  # decode steps executed (the "batches" analog)
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="llm-engine")
        self._thread.start()

    # -- client side ----------------------------------------------------------
    def submit(self, tokens: List[int], timeout: float = 300.0,
               max_new_tokens: Optional[int] = None):
        """Blocking generate. ``max_new_tokens`` may be set PER REQUEST
        (capped by the engine default): with step-granular scheduling a
        short request retires early and frees its slot — under the old
        whole-batch barrier every request paid the longest budget."""
        budget = self.max_new_tokens if max_new_tokens is None else \
            max(1, min(int(max_new_tokens), self.max_new_tokens))
        p = _Pending((list(tokens), budget))
        with self._cond:
            if self._stop:
                raise RuntimeError("engine closed")
            self._q.append(p)
            self._cond.notify()
        if not p.event.wait(timeout):
            raise TimeoutError("generation timed out")
        if p.error is not None:
            raise p.error
        return p.result

    def close(self) -> None:
        """Stop the engine, failing queued AND slot-resident requests
        promptly with "engine closed" (never leaving a caller to ride out
        its full submit timeout). Slot state belongs to the engine thread,
        so its _stop exit path fails the resident rows; this thread only
        drains the queue."""
        with self._cond:
            self._stop = True
            drained = list(self._q)
            self._q.clear()
            self._cond.notify_all()
        for p in drained:
            p.error = RuntimeError("engine closed")
            p.event.set()

    # -- engine side ----------------------------------------------------------
    def _prefill_fn(self, bucket: int):
        jax, jnp, gpt, cfg = self._jax, self._jnp, self._gpt, self.cfg
        fn = self._prefill_cache.get(bucket)
        if fn is not None:
            return fn

        def prefill(params, cache, tokens, row, true_len, key):
            lax = jax.lax
            row_cache = {
                "k": lax.dynamic_slice_in_dim(cache["k"], row, 1, axis=1),
                "v": lax.dynamic_slice_in_dim(cache["v"], row, 1, axis=1),
            }
            logits, row_cache = gpt.forward_with_cache_rows(
                params, tokens, row_cache, jnp.zeros((1,), jnp.int32), cfg)
            cache = {
                "k": lax.dynamic_update_slice_in_dim(
                    cache["k"], row_cache["k"], row, axis=1),
                "v": lax.dynamic_update_slice_in_dim(
                    cache["v"], row_cache["v"], row, axis=1),
            }
            first = self._sample(logits[0, true_len - 1][None], key)[0]
            return cache, first

        fn = jax.jit(prefill, donate_argnums=(1,))
        self._prefill_cache[bucket] = fn
        return fn

    def _admit(self, p: _Pending, row: int) -> None:
        np, jnp = self._np, self._jnp
        toks, budget = p.item
        limit = self.cfg.max_seq - self.max_new_tokens
        toks = toks[-limit:]
        bucket = max(self.pad_multiple,
                     ((len(toks) + self.pad_multiple - 1)
                      // self.pad_multiple) * self.pad_multiple)
        bucket = min(bucket, limit)
        arr = np.ones((1, bucket), np.int32)
        arr[0, : len(toks)] = toks  # right-pad junk is invisible: the
        # per-row mask stops at true_len and decode overwrites those slots
        self._key, sub = self._jax.random.split(self._key)
        self._cache, first = self._prefill_fn(bucket)(
            self.params, self._cache, jnp.asarray(arr),
            jnp.int32(row), jnp.int32(len(toks)), sub)
        self._slot_pending[row] = p
        self._slot_offset[row] = len(toks)
        self._slot_last[row] = int(first)
        self._slot_out[row] = [int(first)]
        self._slot_budget[row] = budget - 1

    def _retire(self, row: int) -> None:
        p = self._slot_pending[row]
        self._slot_pending[row] = None
        self._slot_offset[row] = 0
        self._slot_last[row] = 1
        if p is not None:
            p.result = self._slot_out[row]
            p.event.set()

    def _loop(self) -> None:
        jnp, np = self._jnp, self._np
        while True:
            with self._cond:
                while (not self._stop and not self._q
                       and all(p is None for p in self._slot_pending)):
                    self._cond.wait(timeout=1.0)
                if self._stop:
                    # fail slot-resident requests too: close() cannot
                    # touch slot state (it races this thread), so the
                    # exit path owns that cleanup
                    victims = [p for p in self._slot_pending
                               if p is not None]
                    self._slot_pending = [None] * self.max_slots
                    for p in victims:
                        p.error = RuntimeError("engine closed")
                        p.event.set()
                    return
                admits = []
                for row in range(self.max_slots):
                    if self._slot_pending[row] is None and self._q:
                        admits.append((self._q.pop(0), row))
            try:
                for p, row in admits:
                    self._admit(p, row)
                    if self._slot_budget[row] <= 0:
                        self._retire(row)  # max_new_tokens == 1
                active = [r for r in range(self.max_slots)
                          if self._slot_pending[r] is not None]
                if not active:
                    continue
                self._key, sub = self._jax.random.split(self._key)
                self._cache, toks = self._step(
                    self.params, self._cache,
                    jnp.asarray(self._slot_last),
                    jnp.asarray(self._slot_offset), sub)
                toks = np.asarray(toks)  # [K, B]
                self.steps += self.steps_per_iter
                for r in active:
                    # a row finishing mid-iteration consumes only what its
                    # budget allows; the surplus decoded junk wrote into
                    # its OWN cache rows beyond its end, which the per-row
                    # mask keeps invisible and the next prefill overwrites
                    take = min(self.steps_per_iter,
                               int(self._slot_budget[r]))
                    self._slot_out[r].extend(
                        int(toks[t, r]) for t in range(take))
                    self._slot_last[r] = int(toks[take - 1, r])
                    self._slot_offset[r] += take
                    self._slot_budget[r] -= take
                    if self._slot_budget[r] <= 0:
                        self._retire(r)
            except BaseException as e:  # noqa: BLE001 — fail loudly to
                with self._cond:        # every parked caller, keep serving
                    victims = ([p for p in self._slot_pending
                                if p is not None] + self._q)
                    self._slot_pending = [None] * self.max_slots
                    self._q.clear()
                for p in victims:
                    p.error = e
                    p.event.set()


class LLMServer:
    """Deployment class: KV-cached batched generation on one chip.

    ``user_config`` (reconfigure) can retune ``max_new_tokens`` /
    ``temperature`` without a redeploy."""

    def __init__(self, preset: str = "gpt2-small",
                 max_batch_size: int = 8,
                 batch_wait_timeout_s: float = 0.01,
                 max_new_tokens: int = 32,
                 temperature: float = 0.0,
                 pad_multiple: int = 64,
                 seed: int = 0,
                 batching: str = "continuous",
                 steps_per_iter: int = 8):
        import jax

        from ..models import gpt

        self.cfg = gpt.PRESETS[preset]
        if max_new_tokens + pad_multiple > self.cfg.max_seq:
            raise ValueError(
                f"max_new_tokens={max_new_tokens} leaves no room for a "
                f"{pad_multiple}-token prompt bucket within the model's "
                f"max_seq={self.cfg.max_seq}")
        self.gpt = gpt
        self.params = gpt.init_params(jax.random.PRNGKey(seed), self.cfg)
        self.max_new_tokens = max_new_tokens
        self.temperature = temperature
        self.pad_multiple = pad_multiple
        self.max_batch_size = max_batch_size
        self.seed = seed
        self._key = jax.random.PRNGKey(seed + 1)
        self._stats = {"requests": 0, "batches": 0, "generated_tokens": 0}
        self.batching = batching
        self.steps_per_iter = steps_per_iter
        if batching == "continuous":
            # decode-step-granular join/leave + exact per-row positions
            self._engine = ContinuousBatcher(
                self.params, self.cfg, max_slots=max_batch_size,
                max_new_tokens=max_new_tokens, temperature=temperature,
                pad_multiple=pad_multiple, seed=seed + 1,
                steps_per_iter=steps_per_iter)
            self._batcher = None
        elif batching == "barrier":
            # legacy whole-batch mode (kept for A/B benchmarking)
            self._engine = None
            self._batcher = DynamicBatcher(
                self._run_batch, max_batch_size=max_batch_size,
                batch_wait_timeout_s=batch_wait_timeout_s)
        else:
            raise ValueError(f"unknown batching mode: {batching!r}")

    # -- config ---------------------------------------------------------------
    def reconfigure(self, user_config: Optional[dict]) -> None:
        if not user_config:
            return
        new_tokens = int(user_config.get(
            "max_new_tokens", self.max_new_tokens))
        if new_tokens + self.pad_multiple > self.cfg.max_seq:
            raise ValueError(
                f"max_new_tokens={new_tokens} leaves no room for a "
                f"{self.pad_multiple}-token prompt bucket within "
                f"max_seq={self.cfg.max_seq}")
        new_temp = float(user_config.get("temperature", self.temperature))
        changed = (new_tokens != self.max_new_tokens
                   or new_temp != self.temperature)
        self.max_new_tokens = new_tokens
        self.temperature = new_temp
        if self._engine is not None and changed:
            # temperature is baked into the engine's compiled sampler at
            # trace time (and the token budget into its slot accounting):
            # swap in a fresh engine rather than mutating a live one
            old = self._engine
            self._engine = ContinuousBatcher(
                self.params, self.cfg, max_slots=self.max_batch_size,
                max_new_tokens=new_tokens, temperature=new_temp,
                pad_multiple=self.pad_multiple, seed=self.seed + 1,
                steps_per_iter=self.steps_per_iter)
            old.close()

    # -- request surface ------------------------------------------------------
    def __call__(self, request: Any = None) -> Dict[str, Any]:
        """HTTP entrypoint: {"tokens": [...]} or {"text": "..."}. Returns
        {"tokens": [...]}. An optional per-request "max_new_tokens"
        (capped by the deployment default) is honored in continuous mode —
        step-granular scheduling makes short requests retire early; in
        barrier mode the whole batch decodes the deployment default."""
        if isinstance(request, str):
            request = {"text": request}
        request = request or {}
        tokens = request.get("tokens")
        if tokens is None:
            tokens = _bytes_tokenize(request.get("text", ""),
                                     self.cfg.vocab_size)
        if not tokens:
            tokens = [1]
        out = self.generate(tokens,
                            max_new_tokens=request.get("max_new_tokens"))
        return {"tokens": out, "prompt_len": len(tokens)}

    def generate(self, tokens: Sequence[int],
                 max_new_tokens: Optional[int] = None) -> List[int]:
        """Generate continuation ids for one prompt (batched under the
        hood with whatever arrives concurrently). ``max_new_tokens`` can
        be set per request in continuous mode (capped by the deployment
        default); barrier mode always decodes the full default."""
        if self._engine is not None:
            out = self._engine.submit(list(tokens),
                                      max_new_tokens=max_new_tokens)
            self._stats["requests"] += 1
            self._stats["generated_tokens"] += len(out)
            self._stats["batches"] = self._engine.steps
            return out
        return self._batcher.submit(list(tokens))

    def stats(self) -> dict:
        return dict(self._stats)

    # -- batched model call ---------------------------------------------------
    def _run_batch(self, prompts: List[List[int]]) -> List[List[int]]:
        """One prefill+decode for a batch of prompts. Shapes are bucketed:
        batch padded to max_batch_size rows, prompt length to the next
        pad_multiple — one compiled program per (bucket, steps), reused
        across calls.

        Rows shorter than the bucket are right-padded by repeating their
        own final token. Equal-length batches (the common serving shape)
        are exact; a shorter row in a mixed batch conditions on those
        repeats — the standard padded-batch approximation (exact handling
        would need per-row position masks through prefill)."""
        import jax.numpy as jnp
        import numpy as np

        import jax

        n = len(prompts)
        lens = [len(p) for p in prompts]
        s0 = max(lens)
        bucket = ((s0 + self.pad_multiple - 1)
                  // self.pad_multiple) * self.pad_multiple
        bucket = min(bucket, self.cfg.max_seq - self.max_new_tokens)
        B = self.max_batch_size
        arr = np.ones((B, bucket), np.int32)  # dummy rows: token 1
        for i, p in enumerate(prompts):
            p = p[-bucket:]  # truncate over-long prompts from the left
            arr[i, : len(p)] = p
            if len(p) < bucket:
                # right-pad with the row's final token: with causal
                # attention the FINAL position's logits (which seed the
                # decode) see the true prompt plus harmless repeats
                arr[i, len(p):] = p[-1]
        self._key, sub = jax.random.split(self._key)
        out = self.gpt.generate(
            self.params, self.cfg, jnp.asarray(arr),
            steps=self.max_new_tokens, temperature=self.temperature,
            key=sub)
        out_np = np.asarray(out)
        self._stats["requests"] += n
        self._stats["batches"] += 1
        self._stats["generated_tokens"] += n * self.max_new_tokens
        return [out_np[i, bucket: bucket + self.max_new_tokens].tolist()
                for i in range(n)]


def llm_deployment(preset: str = "gpt2-small",
                   ray_actor_options: Optional[dict] = None,
                   max_concurrent_queries: int = 64, **kwargs):
    """A ready-to-run Application serving ``preset``:

        import ray_memory_management_tpu.serve as serve
        handle = serve.run(serve.llm_deployment("gpt2-small"))
        serve.get_handle("LLM").remote({"tokens": [1, 2, 3]})

    On a TPU host pass ``ray_actor_options={"num_tpus": 1}`` so the
    replica takes a chip lease (TPU_VISIBLE_CHIPS isolation) and the
    decode program runs on the chip."""
    return deployment(
        LLMServer, name="LLM", ray_actor_options=ray_actor_options,
        max_concurrent_queries=max_concurrent_queries,
    ).bind(preset=preset, **kwargs)


__all__ = ["ContinuousBatcher", "DynamicBatcher", "LLMServer",
           "llm_deployment"]
