"""LM serving: a deployment wrapping the KV-cached decode path.

The reference serves models through generic deployments plus the
``serve.batch`` request coalescer (python/ray/serve/batching.py:279;
replica loop serve/_private/replica.py:250). Here the same two pieces are
TPU-shaped:

  - :class:`DynamicBatcher` — a thread-based request coalescer: callers
    block, a background thread collects up to ``max_batch_size`` requests
    within ``batch_wait_timeout_s`` and runs them as ONE model call. On a
    TPU the batch dimension is nearly free (MXU width), so coalescing is
    the difference between 1x and Nx decode throughput under load.
  - :class:`LLMServer` — the deployment class: holds params on device,
    pads each batch to a fixed shape bucket (batch -> ``max_batch_size``
    rows, prompt -> multiple of ``pad_multiple``), so XLA compiles ONE
    prefill+decode program per bucket and reuses it forever
    (models/gpt.py generate's compile-once contract).

Requests carry token ids (``{"tokens": [...]}``) or plain text
(``{"text": ...}``, byte-level fallback tokenizer) — the deployment is
model-complete without shipping a tokenizer dependency.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from .deployment import deployment


class _Pending:
    __slots__ = ("item", "event", "result", "error")

    def __init__(self, item):
        self.item = item
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None


class DynamicBatcher:
    """Coalesce concurrent blocking calls into batched ``fn`` invocations.

    ``fn(items: list) -> list`` runs on the batcher thread; callers park
    in :meth:`submit` until their result is ready. The first arrival opens
    a window of ``batch_wait_timeout_s``; the batch launches when the
    window closes or ``max_batch_size`` is reached, whichever is first
    (the reference's @serve.batch semantics, batching.py:279)."""

    def __init__(self, fn, max_batch_size: int = 8,
                 batch_wait_timeout_s: float = 0.01):
        self._fn = fn
        self.max_batch_size = max_batch_size
        self.batch_wait_timeout_s = batch_wait_timeout_s
        self._q: List[_Pending] = []
        self._cond = threading.Condition()
        self._stop = False
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="llm-batcher")
        self._thread.start()

    def submit(self, item, timeout: float = 300.0):
        p = _Pending(item)
        with self._cond:
            if self._stop:
                raise RuntimeError("batcher closed")
            self._q.append(p)
            self._cond.notify()
        if not p.event.wait(timeout):
            raise TimeoutError("batched call timed out")
        if p.error is not None:
            raise p.error
        return p.result

    def _loop(self) -> None:
        while not self._stop:
            with self._cond:
                while not self._q and not self._stop:
                    self._cond.wait(timeout=1.0)
                if self._stop:
                    return
                deadline = time.monotonic() + self.batch_wait_timeout_s
                while (len(self._q) < self.max_batch_size
                       and time.monotonic() < deadline):
                    self._cond.wait(timeout=max(
                        0.0, deadline - time.monotonic()))
                batch = self._q[: self.max_batch_size]
                del self._q[: self.max_batch_size]
            try:
                results = self._fn([p.item for p in batch])
                if len(results) != len(batch):
                    raise ValueError(
                        f"batch fn returned {len(results)} results for "
                        f"{len(batch)} items")
                for p, r in zip(batch, results):
                    p.result = r
                    p.event.set()
            except BaseException as e:  # noqa: BLE001 — deliver to callers
                for p in batch:
                    p.error = e
                    p.event.set()

    def close(self) -> None:
        with self._cond:
            self._stop = True
            drained = list(self._q)
            self._q.clear()
            self._cond.notify_all()
        for p in drained:  # fail parked callers promptly, not by timeout
            p.error = RuntimeError("batcher closed")
            p.event.set()


def _bytes_tokenize(text: str, vocab_size: int) -> List[int]:
    """Byte-level fallback: utf-8 bytes offset past the special range."""
    return [2 + (b % (vocab_size - 2)) for b in text.encode()]


class LLMServer:
    """Deployment class: KV-cached batched generation on one chip.

    ``user_config`` (reconfigure) can retune ``max_new_tokens`` /
    ``temperature`` without a redeploy."""

    def __init__(self, preset: str = "gpt2-small",
                 max_batch_size: int = 8,
                 batch_wait_timeout_s: float = 0.01,
                 max_new_tokens: int = 32,
                 temperature: float = 0.0,
                 pad_multiple: int = 64,
                 seed: int = 0):
        import jax

        from ..models import gpt

        self.cfg = gpt.PRESETS[preset]
        if max_new_tokens + pad_multiple > self.cfg.max_seq:
            raise ValueError(
                f"max_new_tokens={max_new_tokens} leaves no room for a "
                f"{pad_multiple}-token prompt bucket within the model's "
                f"max_seq={self.cfg.max_seq}")
        self.gpt = gpt
        self.params = gpt.init_params(jax.random.PRNGKey(seed), self.cfg)
        self.max_new_tokens = max_new_tokens
        self.temperature = temperature
        self.pad_multiple = pad_multiple
        self.max_batch_size = max_batch_size
        self._key = jax.random.PRNGKey(seed + 1)
        self._stats = {"requests": 0, "batches": 0, "generated_tokens": 0}
        self._batcher = DynamicBatcher(
            self._run_batch, max_batch_size=max_batch_size,
            batch_wait_timeout_s=batch_wait_timeout_s)

    # -- config ---------------------------------------------------------------
    def reconfigure(self, user_config: Optional[dict]) -> None:
        if not user_config:
            return
        new_tokens = int(user_config.get(
            "max_new_tokens", self.max_new_tokens))
        if new_tokens + self.pad_multiple > self.cfg.max_seq:
            raise ValueError(
                f"max_new_tokens={new_tokens} leaves no room for a "
                f"{self.pad_multiple}-token prompt bucket within "
                f"max_seq={self.cfg.max_seq}")
        self.max_new_tokens = new_tokens
        self.temperature = float(user_config.get(
            "temperature", self.temperature))

    # -- request surface ------------------------------------------------------
    def __call__(self, request: Any = None) -> Dict[str, Any]:
        """HTTP entrypoint: {"tokens": [...]} or {"text": "..."}. Returns
        {"tokens": [...]}. The continuation length is the deployment's
        ``max_new_tokens`` (per-request overrides would defeat the
        one-compiled-program-per-bucket batching; retune it via
        ``user_config`` reconfigure instead)."""
        if isinstance(request, str):
            request = {"text": request}
        request = request or {}
        tokens = request.get("tokens")
        if tokens is None:
            tokens = _bytes_tokenize(request.get("text", ""),
                                     self.cfg.vocab_size)
        if not tokens:
            tokens = [1]
        out = self.generate(tokens)
        return {"tokens": out, "prompt_len": len(tokens)}

    def generate(self, tokens: Sequence[int]) -> List[int]:
        """Generate ``max_new_tokens`` continuation ids for one prompt
        (batched under the hood with whatever arrives concurrently)."""
        return self._batcher.submit(list(tokens))

    def stats(self) -> dict:
        return dict(self._stats)

    # -- batched model call ---------------------------------------------------
    def _run_batch(self, prompts: List[List[int]]) -> List[List[int]]:
        """One prefill+decode for a batch of prompts. Shapes are bucketed:
        batch padded to max_batch_size rows, prompt length to the next
        pad_multiple — one compiled program per (bucket, steps), reused
        across calls.

        Rows shorter than the bucket are right-padded by repeating their
        own final token. Equal-length batches (the common serving shape)
        are exact; a shorter row in a mixed batch conditions on those
        repeats — the standard padded-batch approximation (exact handling
        would need per-row position masks through prefill)."""
        import jax.numpy as jnp
        import numpy as np

        import jax

        n = len(prompts)
        lens = [len(p) for p in prompts]
        s0 = max(lens)
        bucket = ((s0 + self.pad_multiple - 1)
                  // self.pad_multiple) * self.pad_multiple
        bucket = min(bucket, self.cfg.max_seq - self.max_new_tokens)
        B = self.max_batch_size
        arr = np.ones((B, bucket), np.int32)  # dummy rows: token 1
        for i, p in enumerate(prompts):
            p = p[-bucket:]  # truncate over-long prompts from the left
            arr[i, : len(p)] = p
            if len(p) < bucket:
                # right-pad with the row's final token: with causal
                # attention the FINAL position's logits (which seed the
                # decode) see the true prompt plus harmless repeats
                arr[i, len(p):] = p[-1]
        self._key, sub = jax.random.split(self._key)
        out = self.gpt.generate(
            self.params, self.cfg, jnp.asarray(arr),
            steps=self.max_new_tokens, temperature=self.temperature,
            key=sub)
        out_np = np.asarray(out)
        self._stats["requests"] += n
        self._stats["batches"] += 1
        self._stats["generated_tokens"] += n * self.max_new_tokens
        return [out_np[i, bucket: bucket + self.max_new_tokens].tolist()
                for i in range(n)]


def llm_deployment(preset: str = "gpt2-small",
                   ray_actor_options: Optional[dict] = None,
                   max_concurrent_queries: int = 64, **kwargs):
    """A ready-to-run Application serving ``preset``:

        import ray_memory_management_tpu.serve as serve
        handle = serve.run(serve.llm_deployment("gpt2-small"))
        serve.get_handle("LLM").remote({"tokens": [1, 2, 3]})

    On a TPU host pass ``ray_actor_options={"num_tpus": 1}`` so the
    replica takes a chip lease (TPU_VISIBLE_CHIPS isolation) and the
    decode program runs on the chip."""
    return deployment(
        LLMServer, name="LLM", ray_actor_options=ray_actor_options,
        max_concurrent_queries=max_concurrent_queries,
    ).bind(preset=preset, **kwargs)


__all__ = ["DynamicBatcher", "LLMServer", "llm_deployment"]
