"""LM serving: a deployment wrapping the KV-cached decode path.

The reference serves models through generic deployments plus the
``serve.batch`` request coalescer (python/ray/serve/batching.py:279;
replica loop serve/_private/replica.py:250). Here the same two pieces are
TPU-shaped:

  - :class:`DynamicBatcher` — a thread-based request coalescer: callers
    block, a background thread collects up to ``max_batch_size`` requests
    within ``batch_wait_timeout_s`` and runs them as ONE model call. On a
    TPU the batch dimension is nearly free (MXU width), so coalescing is
    the difference between 1x and Nx decode throughput under load.
  - :class:`LLMServer` — the deployment class: holds params on device,
    pads each batch to a fixed shape bucket (batch -> ``max_batch_size``
    rows, prompt -> multiple of ``pad_multiple``), so XLA compiles ONE
    prefill+decode program per bucket and reuses it forever
    (models/gpt.py generate's compile-once contract).

Requests carry token ids (``{"tokens": [...]}``) or plain text
(``{"text": ...}``, byte-level fallback tokenizer) — the deployment is
model-complete without shipping a tokenizer dependency.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from ..utils import faults
from .deployment import deployment


class _Pending:
    __slots__ = ("item", "event", "result", "error")

    def __init__(self, item):
        self.item = item
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None


class DynamicBatcher:
    """Coalesce concurrent blocking calls into batched ``fn`` invocations.

    ``fn(items: list) -> list`` runs on the batcher thread; callers park
    in :meth:`submit` until their result is ready. The first arrival opens
    a window of ``batch_wait_timeout_s``; the batch launches when the
    window closes or ``max_batch_size`` is reached, whichever is first
    (the reference's @serve.batch semantics, batching.py:279)."""

    def __init__(self, fn, max_batch_size: int = 8,
                 batch_wait_timeout_s: float = 0.01):
        self._fn = fn
        self.max_batch_size = max_batch_size
        self.batch_wait_timeout_s = batch_wait_timeout_s
        self._q: List[_Pending] = []
        self._cond = threading.Condition()
        self._stop = False
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="llm-batcher")
        self._thread.start()

    def submit(self, item, timeout: float = 300.0):
        p = _Pending(item)
        with self._cond:
            if self._stop:
                raise RuntimeError("batcher closed")
            self._q.append(p)
            self._cond.notify()
        if not p.event.wait(timeout):
            raise TimeoutError("batched call timed out")
        if p.error is not None:
            raise p.error
        return p.result

    def _loop(self) -> None:
        while not self._stop:
            with self._cond:
                while not self._q and not self._stop:
                    self._cond.wait(timeout=1.0)
                if self._stop:
                    return
                deadline = time.monotonic() + self.batch_wait_timeout_s
                while (len(self._q) < self.max_batch_size
                       and time.monotonic() < deadline):
                    self._cond.wait(timeout=max(
                        0.0, deadline - time.monotonic()))
                batch = self._q[: self.max_batch_size]
                del self._q[: self.max_batch_size]
            try:
                results = self._fn([p.item for p in batch])
                if len(results) != len(batch):
                    raise ValueError(
                        f"batch fn returned {len(results)} results for "
                        f"{len(batch)} items")
                for p, r in zip(batch, results):
                    p.result = r
                    p.event.set()
            except BaseException as e:  # noqa: BLE001 — deliver to callers
                for p in batch:
                    p.error = e
                    p.event.set()

    def close(self) -> None:
        with self._cond:
            self._stop = True
            drained = list(self._q)
            self._q.clear()
            self._cond.notify_all()
        for p in drained:  # fail parked callers promptly, not by timeout
            p.error = RuntimeError("batcher closed")
            p.event.set()


def _bytes_tokenize(text: str, vocab_size: int) -> List[int]:
    """Byte-level fallback: utf-8 bytes offset past the special range."""
    return [2 + (b % (vocab_size - 2)) for b in text.encode()]


class ContinuousBatcher:
    """Decode-step-granular request scheduler (continuous batching).

    The DynamicBatcher above is a whole-batch barrier: every request in a
    batch decodes the full ``max_new_tokens`` before ANY new request joins,
    so under streaming arrivals the chip idles on retired rows and new
    arrivals queue behind the stragglers. This engine schedules at decode-
    step granularity over a fixed slot table (the vLLM/Orca iteration-level
    scheduling idea, TPU-shaped):

      - a KV cache of ``max_slots`` rows lives across requests; a new
        request is PREFILLED into a free row the moment one exists
        (per-bucket compiled prefill writes its prompt's KV at positions
        [0, len));
      - every engine iteration runs ONE single-token decode step over all
        occupied rows (one compiled program, static [max_slots, 1] shape,
        per-row offsets via models/gpt.forward_with_cache_rows);
      - a row that reaches its request's token budget retires immediately
        and its slot admits the next queued request at the very next step.

    Per-row offsets also make mixed-length batches EXACT: each row attends
    only to its own true history with its own rope phases — the padded-
    batch approximation (a short row conditioning on its repeated final
    token) is gone.

    KV memory is PAGED by default (``kv_cache="paged"``): instead of a
    monolithic ``max_slots x max_seq`` slab pinned forever, each admitted
    request reserves page-aligned KV capacity for its own lifetime
    (prompt + budget) from a :class:`~.kv_cache.KVPagePool` of pinned
    device objects. Between iterations the pool's device store owns every
    live slot's KV rows; each iteration consumes them (``take`` — a
    donation read), packs them into one working slab whose sequence
    capacity is the page-aligned max over LIVE slots (not ``max_seq``),
    runs the donated compiled step, and pins the surviving rows back.
    ``_retire`` frees the slot's pages, so a replica's HBM tracks live
    tokens; pool exhaustion defers admission (backpressure) instead of
    OOMing. ``kv_cache="slab"`` keeps the old monolithic layout for A/B
    benchmarking.
    """

    def __init__(self, params, cfg, max_slots: int = 8,
                 max_new_tokens: int = 32, temperature: float = 0.0,
                 pad_multiple: int = 64, seed: int = 0,
                 steps_per_iter: int = 8,
                 kv_cache: str = "paged",
                 kv_page_tokens: Optional[int] = None,
                 kv_pool_bytes: Optional[int] = None):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from ..models import gpt

        self._jax, self._jnp, self._np, self._gpt = jax, jnp, np, gpt
        self.cfg = cfg
        self.params = params
        self.max_slots = max_slots
        self.max_new_tokens = max_new_tokens
        self.temperature = temperature
        self.pad_multiple = pad_multiple
        # scheduling quantum: each engine iteration decodes K tokens for
        # every occupied row inside ONE compiled lax.scan — per-step
        # Python dispatch would otherwise eat the step-granularity win
        # (the barrier mode scans its whole budget in one program; K
        # amortizes dispatch K-fold while arrivals still join within K
        # steps and finished rows retire within K steps)
        self.steps_per_iter = max(1, min(steps_per_iter, max_new_tokens))
        self._key = jax.random.PRNGKey(seed)
        if kv_cache not in ("paged", "slab"):
            raise ValueError(f"unknown kv_cache mode: {kv_cache!r}")
        self.kv_cache_mode = kv_cache
        if kv_cache == "paged":
            from ..config import global_config
            from .kv_cache import KVPagePool

            gcfg = global_config()
            self.kv_pool: Optional[KVPagePool] = KVPagePool(
                cfg, max_slots=max_slots,
                page_tokens=kv_page_tokens or gcfg.kv_page_tokens,
                pool_bytes=kv_pool_bytes if kv_pool_bytes is not None
                else gcfg.serve_kv_pool_bytes)
            self._cache = None
        else:
            self.kv_pool = None
            self._cache = gpt.init_kv_cache(cfg, max_slots, cfg.max_seq)
        self._prefill_cache: Dict[Any, Any] = {}  # bucket[, cap] -> fn

        def _sample(logits, key):
            if self.temperature > 0:
                return jax.random.categorical(key, logits / self.temperature)
            return jnp.argmax(logits, axis=-1)

        K = self.steps_per_iter

        def step_fn(params, cache, last, offsets, key):
            def body(carry, t):
                cache, last, key = carry
                key, sub = jax.random.split(key)
                logits, cache = gpt.forward_with_cache_rows(
                    params, last[:, None], cache, offsets + t, cfg)
                nxt = _sample(logits[:, 0], sub)
                return (cache, nxt, key), nxt

            (cache, _, _), toks = jax.lax.scan(
                body, (cache, last, key), jnp.arange(K))
            return cache, toks  # [K, B]

        # donate the cache so each iteration updates it in place on device
        # instead of allocating a fresh multi-hundred-MB copy
        self._step = jax.jit(step_fn, donate_argnums=(1,))
        self._sample = _sample

        # slot state (host side)
        self._slot_pending: List[Optional[_Pending]] = [None] * max_slots
        self._slot_offset = np.zeros(max_slots, np.int32)
        self._slot_last = np.ones(max_slots, np.int32)
        self._slot_out: List[List[int]] = [[] for _ in range(max_slots)]
        self._slot_budget = np.zeros(max_slots, np.int32)
        self._slot_cap = np.zeros(max_slots, np.int32)  # paged: reserved
        self.kv_backpressure = 0  # admissions deferred on pool exhaustion

        self._q: List[_Pending] = []
        self._cond = threading.Condition()
        self._stop = False
        self.steps = 0  # decode steps executed (the "batches" analog)
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="llm-engine")
        self._thread.start()

    # -- client side ----------------------------------------------------------
    def submit(self, tokens: List[int], timeout: float = 300.0,
               max_new_tokens: Optional[int] = None):
        """Blocking generate. ``max_new_tokens`` may be set PER REQUEST
        (capped by the engine default): with step-granular scheduling a
        short request retires early and frees its slot — under the old
        whole-batch barrier every request paid the longest budget."""
        budget = self.max_new_tokens if max_new_tokens is None else \
            max(1, min(int(max_new_tokens), self.max_new_tokens))
        p = _Pending((list(tokens), budget))
        with self._cond:
            if self._stop:
                raise RuntimeError("engine closed")
            self._q.append(p)
            self._cond.notify()
        if not p.event.wait(timeout):
            raise TimeoutError("generation timed out")
        if p.error is not None:
            raise p.error
        return p.result

    def close(self) -> None:
        """Stop the engine, failing queued AND slot-resident requests
        promptly with "engine closed" (never leaving a caller to ride out
        its full submit timeout). Slot state belongs to the engine thread,
        so its _stop exit path fails the resident rows; this thread only
        drains the queue."""
        with self._cond:
            self._stop = True
            drained = list(self._q)
            self._q.clear()
            self._cond.notify_all()
        for p in drained:
            p.error = RuntimeError("engine closed")
            p.event.set()

    # -- engine side ----------------------------------------------------------
    def _clip_tokens(self, toks: List[int]) -> List[int]:
        limit = self.cfg.max_seq - self.max_new_tokens
        return toks[-limit:]

    def _bucket_for(self, toks: List[int]) -> int:
        limit = self.cfg.max_seq - self.max_new_tokens
        bucket = max(self.pad_multiple,
                     ((len(toks) + self.pad_multiple - 1)
                      // self.pad_multiple) * self.pad_multiple)
        return min(bucket, limit)

    def _need_tokens(self, p: _Pending) -> int:
        """Page-aligned KV capacity one request needs for its whole
        lifetime: the prefill bucket (whose junk tail must fit) or
        prompt + token budget, whichever is larger."""
        toks, budget = p.item
        toks = self._clip_tokens(list(toks))
        need = max(self._bucket_for(toks), len(toks) + budget)
        return min(self.kv_pool.round_tokens(need), self.cfg.max_seq)

    def _prefill_fn(self, bucket: int):
        jax, jnp, gpt, cfg = self._jax, self._jnp, self._gpt, self.cfg
        fn = self._prefill_cache.get(bucket)
        if fn is not None:
            return fn

        def prefill(params, cache, tokens, row, true_len, key):
            lax = jax.lax
            row_cache = {
                "k": lax.dynamic_slice_in_dim(cache["k"], row, 1, axis=1),
                "v": lax.dynamic_slice_in_dim(cache["v"], row, 1, axis=1),
            }
            logits, row_cache = gpt.forward_with_cache_rows(
                params, tokens, row_cache, jnp.zeros((1,), jnp.int32), cfg)
            cache = {
                "k": lax.dynamic_update_slice_in_dim(
                    cache["k"], row_cache["k"], row, axis=1),
                "v": lax.dynamic_update_slice_in_dim(
                    cache["v"], row_cache["v"], row, axis=1),
            }
            first = self._sample(logits[0, true_len - 1][None], key)[0]
            return cache, first

        fn = jax.jit(prefill, donate_argnums=(1,))
        self._prefill_cache[bucket] = fn
        return fn

    def _paged_prefill_fn(self, bucket: int, cap: int):
        """Prefill into a FRESH single-row cache of seq capacity ``cap``
        (the slot's page-aligned reservation) — no slab to splice into;
        the row cache becomes the slot's pooled KV object. Compiled per
        (bucket, cap) pair; both are page/pad-aligned so the variant set
        stays small."""
        jax, jnp, gpt, cfg = self._jax, self._jnp, self._gpt, self.cfg
        key_ = ("paged", bucket, cap)
        fn = self._prefill_cache.get(key_)
        if fn is not None:
            return fn

        def prefill(params, tokens, true_len, key):
            row_cache = gpt.init_kv_cache(cfg, 1, cap)
            logits, row_cache = gpt.forward_with_cache_rows(
                params, tokens, row_cache, jnp.zeros((1,), jnp.int32), cfg)
            first = self._sample(logits[0, true_len - 1][None], key)[0]
            return row_cache, first

        fn = jax.jit(prefill)
        self._prefill_cache[key_] = fn
        return fn

    def _admit(self, p: _Pending, row: int) -> None:
        act = faults.fire("serve.admit")
        if act is not None:
            if act.mode == "stall":
                act.sleep()
            else:  # error/drop: fail ONLY this request, engine keeps going
                act.raise_()
        np, jnp = self._np, self._jnp
        toks, budget = p.item
        toks = self._clip_tokens(toks)
        bucket = self._bucket_for(toks)
        arr = np.ones((1, bucket), np.int32)
        arr[0, : len(toks)] = toks  # right-pad junk is invisible: the
        # per-row mask stops at true_len and decode overwrites those slots
        self._key, sub = self._jax.random.split(self._key)
        if self.kv_pool is not None:
            cap = int(self._slot_cap[row])  # reserved by the admit gate
            row_cache, first = self._paged_prefill_fn(bucket, cap)(
                self.params, jnp.asarray(arr), jnp.int32(len(toks)), sub)
            self.kv_pool.put_row(row, row_cache)
        else:
            self._cache, first = self._prefill_fn(bucket)(
                self.params, self._cache, jnp.asarray(arr),
                jnp.int32(row), jnp.int32(len(toks)), sub)
        self._slot_pending[row] = p
        self._slot_offset[row] = len(toks)
        self._slot_last[row] = int(first)
        self._slot_out[row] = [int(first)]
        self._slot_budget[row] = budget - 1

    def _retire(self, row: int) -> None:
        p = self._slot_pending[row]
        self._slot_pending[row] = None
        self._slot_offset[row] = 0
        self._slot_last[row] = 1
        if self.kv_pool is not None:
            # pages return to the pool and the slot's KV objects drop out
            # of the device tier: rmt_device_bytes_pinned falls by this
            # slot's live footprint, and a queued request can now reserve
            self.kv_pool.free(row)
            self._slot_cap[row] = 0
        if p is not None:
            p.result = self._slot_out[row]
            p.event.set()

    def _assemble(self, active: List[int]):
        """Consume every active slot's pooled KV rows (``take`` — the
        store drops its reference so the step can DONATE the buffers) and
        pack them into one working slab whose seq capacity is the page-
        aligned max over LIVE slots — not ``max_seq``. Batch dim stays
        ``max_slots`` so the compiled step only re-specializes on S."""
        jnp, cfg = self._jnp, self.cfg
        S = max(int(self._slot_cap[r]) for r in active)
        active_set = set(active)
        zeros = None
        parts_k, parts_v = [], []
        for r in range(self.max_slots):
            rc = self.kv_pool.take_row(r) if r in active_set else None
            if rc is None:  # idle slot: a zero row keeps shapes static
                if zeros is None:
                    zeros = jnp.zeros(
                        (cfg.n_layers, 1, cfg.kv_heads, S, cfg.head_dim),
                        jnp.dtype(cfg.dtype))
                parts_k.append(zeros)
                parts_v.append(zeros)
                continue
            cap = int(self._slot_cap[r])
            if cap < S:
                pad = ((0, 0), (0, 0), (0, 0), (0, S - cap), (0, 0))
                rc = {"k": jnp.pad(rc["k"], pad),
                      "v": jnp.pad(rc["v"], pad)}
            parts_k.append(rc["k"])
            parts_v.append(rc["v"])
        return {"k": jnp.concatenate(parts_k, axis=1),
                "v": jnp.concatenate(parts_v, axis=1)}

    def _disassemble(self, cache, rows: List[int]) -> None:
        """Slice each surviving slot's reserved capacity back out of the
        working slab and pin it in the pool; the slab itself is dropped
        (retired slots' rows simply are not put back — that plus
        ``_retire``'s free() is how HBM tracks live tokens)."""
        for r in rows:
            cap = int(self._slot_cap[r])
            self.kv_pool.put_row(r, {
                "k": cache["k"][:, r:r + 1, :, :cap, :],
                "v": cache["v"][:, r:r + 1, :, :cap, :]})

    def _admit_gate(self) -> List:
        """Pop admissible queued requests (head-of-line FIFO) into free
        slots. Paged mode reserves each request's lifetime pages FIRST —
        a failed reserve defers admission (backpressure) until a retiring
        slot frees pages, so decode can never OOM mid-request. Caller
        holds ``_cond``."""
        admits = []
        for row in range(self.max_slots):
            if not self._q:
                break
            if self._slot_pending[row] is not None:
                continue
            if self.kv_pool is None:
                admits.append((self._q.pop(0), row))
                continue
            p = self._q[0]
            need = self._need_tokens(p)
            if self.kv_pool.pages_for(need) > self.kv_pool.capacity_pages:
                # can never fit even in an empty pool: fail fast instead
                # of backpressuring forever
                self._q.pop(0)
                p.error = RuntimeError(
                    f"request needs {need} KV tokens "
                    f"({self.kv_pool.pages_for(need)} pages) but the pool "
                    f"capacity is {self.kv_pool.capacity_pages} pages")
                p.event.set()
                continue
            if not self.kv_pool.reserve(row, need):
                # pool exhausted: keep FIFO order, admit nothing past the
                # head — pages free at the next retire
                self.kv_backpressure += 1
                try:
                    from ..core import metrics_defs as mdefs
                    mdefs.serve_kv_backpressure().inc()
                except Exception:  # noqa: BLE001
                    pass
                break
            self._slot_cap[row] = need
            admits.append((self._q.pop(0), row))
        return admits

    def _loop(self) -> None:
        jnp, np = self._jnp, self._np
        while True:
            with self._cond:
                while (not self._stop and not self._q
                       and all(p is None for p in self._slot_pending)):
                    self._cond.wait(timeout=1.0)
                if self._stop:
                    # fail slot-resident requests too: close() cannot
                    # touch slot state (it races this thread), so the
                    # exit path owns that cleanup
                    victims = [p for p in self._slot_pending
                               if p is not None]
                    self._slot_pending = [None] * self.max_slots
                    if self.kv_pool is not None:
                        self.kv_pool.free_all()
                        self._slot_cap[:] = 0
                    for p in victims:
                        p.error = RuntimeError("engine closed")
                        p.event.set()
                    return
                admits = self._admit_gate()
            try:
                for p, row in admits:
                    try:
                        self._admit(p, row)
                    except faults.FaultInjected as e:
                        # injected admit failure takes down ONE request,
                        # not the engine: release the reservation and
                        # keep admitting
                        if self.kv_pool is not None:
                            self.kv_pool.free(row)
                            self._slot_cap[row] = 0
                        self._slot_pending[row] = None
                        p.error = e
                        p.event.set()
                        continue
                    if self._slot_budget[row] <= 0:
                        self._retire(row)  # max_new_tokens == 1
                active = [r for r in range(self.max_slots)
                          if self._slot_pending[r] is not None]
                if not active:
                    continue
                self._key, sub = self._jax.random.split(self._key)
                cache = self._assemble(active) if self.kv_pool is not None \
                    else self._cache
                cache, toks = self._step(
                    self.params, cache,
                    jnp.asarray(self._slot_last),
                    jnp.asarray(self._slot_offset), sub)
                toks = np.asarray(toks)  # [K, B]
                self.steps += self.steps_per_iter
                for r in active:
                    # a row finishing mid-iteration consumes only what its
                    # budget allows; the surplus decoded junk wrote into
                    # its OWN cache rows beyond its end, which the per-row
                    # mask keeps invisible and retire/prefill discards
                    take = min(self.steps_per_iter,
                               int(self._slot_budget[r]))
                    self._slot_out[r].extend(
                        int(toks[t, r]) for t in range(take))
                    self._slot_last[r] = int(toks[take - 1, r])
                    self._slot_offset[r] += take
                    self._slot_budget[r] -= take
                    if self._slot_budget[r] <= 0:
                        self._retire(r)
                if self.kv_pool is not None:
                    self._disassemble(cache, [
                        r for r in active
                        if self._slot_pending[r] is not None])
                else:
                    self._cache = cache
            except BaseException as e:  # noqa: BLE001 — fail loudly to
                with self._cond:        # every parked caller, keep serving
                    victims = ([p for p in self._slot_pending
                                if p is not None] + self._q)
                    self._slot_pending = [None] * self.max_slots
                    self._q.clear()
                if self.kv_pool is not None:
                    self.kv_pool.free_all()
                    self._slot_cap[:] = 0
                for p in victims:
                    p.error = e
                    p.event.set()

    def kv_stats(self) -> Dict[str, Any]:
        """Pool occupancy snapshot (paged mode) for metrics/benchmarks."""
        if self.kv_pool is None:
            return {"mode": "slab", "kv_backpressure": 0}
        out = dict(self.kv_pool.stats())
        out["mode"] = "paged"
        out["kv_backpressure"] = self.kv_backpressure
        return out


def pack_weights(params, precision: str = "bf16") -> Dict[str, Any]:
    """Quantize a param tree for the movement plane: per-leaf
    :func:`~..core.codec.quantize_array` payloads (bf16 ~2x, int8 ~4x
    smaller than f32), so shipping weights to a cold replica moves a
    fraction of the bytes a full-precision pickle would. Counted under
    ``rmt_collective_quantized_ops_total{op="serve.weights"}``."""
    import jax
    import numpy as np

    from ..core import codec

    leaves, treedef = jax.tree_util.tree_flatten(params)
    payloads = [codec.quantize_array(np.asarray(leaf, dtype=np.float32),
                                     precision) for leaf in leaves]
    codec.count_quantized_op("serve.weights", precision)
    return {"treedef": treedef, "leaves": payloads, "p": precision}


def unpack_weights(payload: Dict[str, Any]):
    """Inverse of :func:`pack_weights` — dequantize each leaf to f32 and
    rebuild the param tree on the replica's device."""
    import jax
    import jax.numpy as jnp

    from ..core import codec

    leaves = [jnp.asarray(codec.dequantize_array(p))
              for p in payload["leaves"]]
    return jax.tree_util.tree_unflatten(payload["treedef"], leaves)


class LLMServer:
    """Deployment class: KV-cached batched generation on one chip.

    ``user_config`` (reconfigure) can retune ``max_new_tokens`` /
    ``temperature`` without a redeploy. ``weights`` (a
    :func:`pack_weights` payload) skips the replica-side param init —
    the cold-start path for scale-up replicas; both paths time their
    init under ``rmt_serve_cold_start_seconds{source=shipped|init}``."""

    def __init__(self, preset: str = "gpt2-small",
                 max_batch_size: int = 8,
                 batch_wait_timeout_s: float = 0.01,
                 max_new_tokens: int = 32,
                 temperature: float = 0.0,
                 pad_multiple: int = 64,
                 seed: int = 0,
                 batching: str = "continuous",
                 steps_per_iter: int = 8,
                 kv_cache: str = "paged",
                 kv_page_tokens: Optional[int] = None,
                 kv_pool_bytes: Optional[int] = None,
                 weights: Optional[Dict[str, Any]] = None):
        t0 = time.monotonic()
        import jax

        from ..models import gpt

        self.cfg = gpt.PRESETS[preset]
        if max_new_tokens + pad_multiple > self.cfg.max_seq:
            raise ValueError(
                f"max_new_tokens={max_new_tokens} leaves no room for a "
                f"{pad_multiple}-token prompt bucket within the model's "
                f"max_seq={self.cfg.max_seq}")
        self.gpt = gpt
        if weights is not None:
            self.params = unpack_weights(weights)
            cold_source = "shipped"
        else:
            self.params = gpt.init_params(
                jax.random.PRNGKey(seed), self.cfg)
            cold_source = "init"
        self.max_new_tokens = max_new_tokens
        self.temperature = temperature
        self.pad_multiple = pad_multiple
        self.max_batch_size = max_batch_size
        self.seed = seed
        self._key = jax.random.PRNGKey(seed + 1)
        self._stats = {"requests": 0, "batches": 0, "generated_tokens": 0}
        self.batching = batching
        self.steps_per_iter = steps_per_iter
        self.kv_cache = kv_cache
        self.kv_page_tokens = kv_page_tokens
        self.kv_pool_bytes = kv_pool_bytes
        if batching == "continuous":
            # decode-step-granular join/leave + exact per-row positions
            self._engine = ContinuousBatcher(
                self.params, self.cfg, max_slots=max_batch_size,
                max_new_tokens=max_new_tokens, temperature=temperature,
                pad_multiple=pad_multiple, seed=seed + 1,
                steps_per_iter=steps_per_iter, kv_cache=kv_cache,
                kv_page_tokens=kv_page_tokens, kv_pool_bytes=kv_pool_bytes)
            self._batcher = None
        elif batching == "barrier":
            # legacy whole-batch mode (kept for A/B benchmarking)
            self._engine = None
            self._batcher = DynamicBatcher(
                self._run_batch, max_batch_size=max_batch_size,
                batch_wait_timeout_s=batch_wait_timeout_s)
        else:
            raise ValueError(f"unknown batching mode: {batching!r}")
        try:
            from ..core import metrics_defs as mdefs
            mdefs.serve_cold_start_seconds().observe(
                time.monotonic() - t0, tags={"source": cold_source})
        except Exception:  # noqa: BLE001 — metrics never fail init
            pass

    # -- config ---------------------------------------------------------------
    def reconfigure(self, user_config: Optional[dict]) -> None:
        if not user_config:
            return
        new_tokens = int(user_config.get(
            "max_new_tokens", self.max_new_tokens))
        if new_tokens + self.pad_multiple > self.cfg.max_seq:
            raise ValueError(
                f"max_new_tokens={new_tokens} leaves no room for a "
                f"{self.pad_multiple}-token prompt bucket within "
                f"max_seq={self.cfg.max_seq}")
        new_temp = float(user_config.get("temperature", self.temperature))
        changed = (new_tokens != self.max_new_tokens
                   or new_temp != self.temperature)
        self.max_new_tokens = new_tokens
        self.temperature = new_temp
        if self._engine is not None and changed:
            # temperature is baked into the engine's compiled sampler at
            # trace time (and the token budget into its slot accounting):
            # swap in a fresh engine rather than mutating a live one
            old = self._engine
            self._engine = ContinuousBatcher(
                self.params, self.cfg, max_slots=self.max_batch_size,
                max_new_tokens=new_tokens, temperature=new_temp,
                pad_multiple=self.pad_multiple, seed=self.seed + 1,
                steps_per_iter=self.steps_per_iter,
                kv_cache=self.kv_cache,
                kv_page_tokens=self.kv_page_tokens,
                kv_pool_bytes=self.kv_pool_bytes)
            old.close()

    # -- request surface ------------------------------------------------------
    def __call__(self, request: Any = None) -> Dict[str, Any]:
        """HTTP entrypoint: {"tokens": [...]} or {"text": "..."}. Returns
        {"tokens": [...]}. An optional per-request "max_new_tokens"
        (capped by the deployment default) is honored in continuous mode —
        step-granular scheduling makes short requests retire early; in
        barrier mode the whole batch decodes the deployment default."""
        if isinstance(request, str):
            request = {"text": request}
        request = request or {}
        tokens = request.get("tokens")
        if tokens is None:
            tokens = _bytes_tokenize(request.get("text", ""),
                                     self.cfg.vocab_size)
        if not tokens:
            tokens = [1]
        out = self.generate(tokens,
                            max_new_tokens=request.get("max_new_tokens"))
        return {"tokens": out, "prompt_len": len(tokens)}

    def generate(self, tokens: Sequence[int],
                 max_new_tokens: Optional[int] = None) -> List[int]:
        """Generate continuation ids for one prompt (batched under the
        hood with whatever arrives concurrently). ``max_new_tokens`` can
        be set per request in continuous mode (capped by the deployment
        default); barrier mode always decodes the full default."""
        if self._engine is not None:
            out = self._engine.submit(list(tokens),
                                      max_new_tokens=max_new_tokens)
            self._stats["requests"] += 1
            self._stats["generated_tokens"] += len(out)
            self._stats["batches"] = self._engine.steps
            return out
        return self._batcher.submit(list(tokens))

    def stats(self) -> dict:
        out = dict(self._stats)
        if self._engine is not None:
            out["kv"] = self._engine.kv_stats()
        return out

    # -- batched model call ---------------------------------------------------
    def _run_batch(self, prompts: List[List[int]]) -> List[List[int]]:
        """One prefill+decode for a batch of prompts. Shapes are bucketed:
        batch padded to max_batch_size rows, prompt length to the next
        pad_multiple — one compiled program per (bucket, steps), reused
        across calls.

        Rows shorter than the bucket are right-padded by repeating their
        own final token. Equal-length batches (the common serving shape)
        are exact; a shorter row in a mixed batch conditions on those
        repeats — the standard padded-batch approximation (exact handling
        would need per-row position masks through prefill)."""
        import jax.numpy as jnp
        import numpy as np

        import jax

        n = len(prompts)
        lens = [len(p) for p in prompts]
        s0 = max(lens)
        bucket = ((s0 + self.pad_multiple - 1)
                  // self.pad_multiple) * self.pad_multiple
        bucket = min(bucket, self.cfg.max_seq - self.max_new_tokens)
        B = self.max_batch_size
        arr = np.ones((B, bucket), np.int32)  # dummy rows: token 1
        for i, p in enumerate(prompts):
            p = p[-bucket:]  # truncate over-long prompts from the left
            arr[i, : len(p)] = p
            if len(p) < bucket:
                # right-pad with the row's final token: with causal
                # attention the FINAL position's logits (which seed the
                # decode) see the true prompt plus harmless repeats
                arr[i, len(p):] = p[-1]
        self._key, sub = jax.random.split(self._key)
        out = self.gpt.generate(
            self.params, self.cfg, jnp.asarray(arr),
            steps=self.max_new_tokens, temperature=self.temperature,
            key=sub)
        out_np = np.asarray(out)
        self._stats["requests"] += n
        self._stats["batches"] += 1
        self._stats["generated_tokens"] += n * self.max_new_tokens
        return [out_np[i, bucket: bucket + self.max_new_tokens].tolist()
                for i in range(n)]


def llm_deployment(preset: str = "gpt2-small",
                   ray_actor_options: Optional[dict] = None,
                   max_concurrent_queries: int = 64,
                   ship_weights: Optional[str] = None, **kwargs):
    """A ready-to-run Application serving ``preset``:

        import ray_memory_management_tpu.serve as serve
        handle = serve.run(serve.llm_deployment("gpt2-small"))
        serve.get_handle("LLM").remote({"tokens": [1, 2, 3]})

    On a TPU host pass ``ray_actor_options={"num_tpus": 1}`` so the
    replica takes a chip lease (TPU_VISIBLE_CHIPS isolation) and the
    decode program runs on the chip.

    ``ship_weights="bf16"|"int8"`` initializes params ONCE on the driver
    and ships them quantized to every replica (:func:`pack_weights` over
    the movement-plane codec) instead of each replica re-initializing —
    the scale-up cold-start path. The payload is also put into the object
    store so the controller can place new replicas near the tier holding
    it (the ``placement_hint`` in the deployment config)."""
    placement_hint = None
    if ship_weights:
        import jax

        from ..models import gpt

        cfg = gpt.PRESETS[preset]
        seed = kwargs.get("seed", 0)
        params = gpt.init_params(jax.random.PRNGKey(seed), cfg)
        kwargs["weights"] = pack_weights(params, precision=ship_weights)
        try:
            from .. import api as core_api

            placement_hint = core_api.put(kwargs["weights"]).hex()
        except Exception:  # noqa: BLE001 — the hint is best-effort; a
            placement_hint = None  # driver without a running runtime
            # still gets weights shipped via the deployment config
    return deployment(
        LLMServer, name="LLM", ray_actor_options=ray_actor_options,
        max_concurrent_queries=max_concurrent_queries,
        placement_hint=placement_hint,
    ).bind(preset=preset, **kwargs)


__all__ = ["ContinuousBatcher", "DynamicBatcher", "LLMServer",
           "llm_deployment", "pack_weights", "unpack_weights"]
