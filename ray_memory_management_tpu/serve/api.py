"""Serve public API: start/run/delete/shutdown + handles.

The reference's serve.api (python/ray/serve/api.py — ``serve.start``,
``serve.run(graph)``, ``serve.delete``, ``serve.shutdown``,
``serve.get_deployment``/``list_deployments``).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from .. import api as core_api
from .controller import CONTROLLER_NAME, get_or_create_controller
from .deployment import Application, Deployment, deployment  # noqa: F401
from .handle import DeploymentHandle

_lock = threading.Lock()
_controller = None
_handles: Dict[str, DeploymentHandle] = {}


def start(detached: bool = True, http_port: Optional[int] = None):
    """Start (or connect to) the Serve instance: ensures the controller
    actor exists; optionally starts the HTTP proxy."""
    global _controller
    with _lock:
        if _controller is None:
            _controller = get_or_create_controller()
    if http_port is not None:
        from .http_proxy import start_proxy

        start_proxy(_controller, http_port)
    return _controller


def _ctrl():
    global _controller
    with _lock:
        if _controller is None:
            _controller = get_or_create_controller()
        return _controller


def _deploy(d: Deployment) -> DeploymentHandle:
    ctrl = _ctrl()
    core_api.get(ctrl.deploy.remote(d.name, d.to_config()), timeout=120)
    return get_deployment_handle(d.name)


def run(target, *, name: Optional[str] = None) -> DeploymentHandle:
    """Deploy an Application (bound deployment graph): dependencies bound
    as init args become handles, depth-first (the reference's
    deployment-graph build, serve/_private/deployment_graph_build.py)."""
    if isinstance(target, Deployment):
        target = target.bind()
    if not isinstance(target, Application):
        raise TypeError("serve.run expects a Deployment or Application")
    return _run_app(target)


def _run_app(app: Application) -> DeploymentHandle:
    resolved_args = tuple(
        _run_app(a) if isinstance(a, Application) else a for a in app.args)
    resolved_kwargs = {
        k: _run_app(v) if isinstance(v, Application) else v
        for k, v in app.kwargs.items()}
    d = app.deployment.options(
        init_args=resolved_args, init_kwargs=resolved_kwargs)
    return _deploy(d)


def get_deployment_handle(name: str) -> DeploymentHandle:
    ctrl = _ctrl()
    with _lock:
        h = _handles.get(name)
        if h is None:
            h = DeploymentHandle(ctrl, name)
            _handles[name] = h
        return h


def get_handle(name: str) -> DeploymentHandle:
    return get_deployment_handle(name)


def list_deployments() -> list:
    return core_api.get(_ctrl().list_deployments.remote(), timeout=30)


def status(name: str) -> Optional[dict]:
    return core_api.get(_ctrl().get_deployment_info.remote(name), timeout=30)


def delete(name: str) -> None:
    with _lock:
        h = _handles.pop(name, None)
    if h is not None and h._router_inst is not None:
        h._router_inst.shutdown()
    core_api.get(_ctrl().delete_deployment.remote(name), timeout=60)


def shutdown() -> None:
    global _controller
    with _lock:
        handles = list(_handles.values())
        _handles.clear()
        ctrl = _controller
        _controller = None
    for h in handles:
        if h._router_inst is not None:
            h._router_inst.shutdown()
    if ctrl is None:
        try:
            ctrl = core_api.get_actor(CONTROLLER_NAME)
        except Exception:
            return
    try:
        core_api.get(ctrl.shutdown.remote(), timeout=60)
        core_api.kill(ctrl)
    except Exception:
        pass
