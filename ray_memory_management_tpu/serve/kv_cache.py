"""Paged KV-cache page pool for the serve engine.

The monolithic engine cache reserved ``max_slots x max_seq`` KV
positions in HBM up front — a replica serving short requests paid the
full worst case forever, and the only failure mode past that budget was
an allocator OOM. This module is the paged replacement (the vLLM paged-
attention memory-management idea, TPU-shaped): a slot's KV rows are
allocated in pages of ``kv_page_tokens`` positions from a per-replica
pool, held as **pinned device objects** in a dedicated
:class:`~..core.device_store.DeviceObjectStore` so the HBM they occupy
is first-class observable (``rmt_device_bytes_pinned`` /
``rmt_serve_kv_pages_in_use`` move with every reserve/free):

  - :meth:`reserve` claims the pages a request's full lifetime needs
    (prompt + token budget, page-aligned) at admission time; a ``False``
    return is the engine's admission-backpressure signal — the request
    stays queued until a retiring slot frees pages. The pool NEVER
    overcommits, so decode can never hit an allocation failure mid-
    request.
  - :meth:`put_row` / :meth:`take_row` move a slot's live KV arrays in
    and out of the device store between engine iterations; ``take_row``
    uses the store's consume path (``take``) so the engine owns the sole
    reference and can donate the buffers into its compiled step
    (``donate_argnums`` aliases them instead of copying).
  - :meth:`free` at retire deletes the slot's KV objects and returns its
    pages — HBM held by a replica's cache scales with LIVE tokens, not
    with ``max_slots x max_seq``.

The pool's budget is enforced by page accounting, not by store
eviction: the backing store runs with eviction disabled (demoting a
live KV page to host shm would break the donation contract and stall
decode); pressure surfaces as queueing, never as data movement.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from ..core.device_store import DeviceObjectStore


def row_token_bytes(cfg) -> int:
    """HBM bytes one KV position of one slot occupies (k + v across all
    layers)."""
    import jax.numpy as jnp

    itemsize = jnp.dtype(cfg.dtype).itemsize
    return 2 * cfg.n_layers * cfg.kv_heads * cfg.head_dim * itemsize


class KVPagePool:
    """Page-granular KV allocator over a device-object store.

    ``pool_bytes <= 0`` sizes the pool to the monolithic slab it
    replaces (``max_slots x max_seq`` positions), so the paged engine
    can never hold more HBM than the old design's constant footprint.
    """

    def __init__(self, cfg, max_slots: int, page_tokens: int,
                 pool_bytes: int = 0,
                 store: Optional[DeviceObjectStore] = None):
        self.cfg = cfg
        self.page_tokens = max(1, int(page_tokens))
        self.token_bytes = row_token_bytes(cfg)
        self.page_bytes = self.page_tokens * self.token_bytes
        if pool_bytes and pool_bytes > 0:
            budget = int(pool_bytes)
        else:
            budget = max_slots * cfg.max_seq * self.token_bytes
        self.capacity_pages = max(1, budget // self.page_bytes)
        # eviction disabled: the pool budget is enforced by page
        # accounting and admission backpressure, never by demotion
        self.store = store if store is not None else \
            DeviceObjectStore(capacity_bytes=-1)
        self._lock = threading.Lock()
        self._row_pages: Dict[int, int] = {}  # guarded-by: _lock

    # -- accounting -----------------------------------------------------------
    def pages_for(self, tokens: int) -> int:
        return max(1, -(-int(tokens) // self.page_tokens))

    def round_tokens(self, tokens: int) -> int:
        """Page-align a token count (a slot's reserved KV capacity)."""
        return self.pages_for(tokens) * self.page_tokens

    def reserve(self, row: int, tokens: int) -> bool:
        """Claim the pages ``row`` needs for ``tokens`` KV positions.
        False = pool exhausted (admission backpressure)."""
        need = self.pages_for(tokens)
        with self._lock:
            in_use = sum(self._row_pages.values()) \
                - self._row_pages.get(row, 0)
            if in_use + need > self.capacity_pages:
                return False
            self._row_pages[row] = need
        self._publish()
        return True

    def free(self, row: int) -> None:
        """Return ``row``'s pages and drop its KV objects (the retire
        path: the gauges fall by exactly this slot's live footprint)."""
        with self._lock:
            self._row_pages.pop(row, None)
        self.store.delete(self._oid(row, "k"))
        self.store.delete(self._oid(row, "v"))
        self._publish()

    def free_all(self) -> None:
        with self._lock:
            rows = list(self._row_pages)
            self._row_pages.clear()
        for row in rows:
            self.store.delete(self._oid(row, "k"))
            self.store.delete(self._oid(row, "v"))
        self._publish()

    # -- KV row movement ------------------------------------------------------
    def put_row(self, row: int, cache: Dict[str, Any]) -> None:
        """Pin a slot's live KV arrays in the device tier (between
        engine iterations the store is the owner)."""
        koid, void = self._oid(row, "k"), self._oid(row, "v")
        self.store.put(koid, cache["k"])
        self.store.put(void, cache["v"])
        self.store.pin(koid)
        self.store.pin(void)

    def take_row(self, row: int) -> Optional[Dict[str, Any]]:
        """Consume a slot's KV arrays out of the store (donation read:
        the engine gets the sole reference and feeds the buffers to its
        ``donate_argnums`` step)."""
        k = self.store.take(self._oid(row, "k"))
        v = self.store.take(self._oid(row, "v"))
        if k is None or v is None:
            return None
        return {"k": k, "v": v}

    # -- introspection --------------------------------------------------------
    @property
    def pages_in_use(self) -> int:
        with self._lock:
            return sum(self._row_pages.values())

    def row_tokens(self, row: int) -> int:
        with self._lock:
            return self._row_pages.get(row, 0) * self.page_tokens

    def bytes_in_use(self) -> int:
        return self.pages_in_use * self.page_bytes

    def stats(self) -> Dict[str, int]:
        with self._lock:
            pages = sum(self._row_pages.values())
        return {
            "page_tokens": self.page_tokens,
            "page_bytes": self.page_bytes,
            "capacity_pages": self.capacity_pages,
            "pages_in_use": pages,
            "bytes_in_use": pages * self.page_bytes,
            "store_bytes": self.store.total_bytes(),
        }

    @staticmethod
    def _oid(row: int, part: str) -> bytes:
        return f"serve.kv.{part}.{row}".encode()

    def _publish(self) -> None:
        try:
            from ..core import metrics_defs as mdefs

            mdefs.serve_kv_pages_in_use().set(float(self.pages_in_use))
        except Exception:  # noqa: BLE001 — gauges never fail the pool
            pass


__all__ = ["KVPagePool", "row_token_bytes"]
