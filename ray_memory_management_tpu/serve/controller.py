"""Serve controller: desired-state reconciler + long-poll host.

The reference's ServeController actor (serve/controller.py:61, deploy
:330-393) with the DeploymentState reconciler
(serve/_private/deployment_state.py:942,1612), long-poll config push
(serve/_private/long_poll.py:63 LongPollHost) and the queue-depth
autoscaling policy (serve/_private/autoscaling_policy.py).

All methods are async: they run on the controller actor's event loop, so
state needs no locks and long-poll ``listen`` calls park on awaits
without holding threads. A background reconcile task converges actual
replicas toward desired state and applies autoscaling decisions.

The reconcile tick also polls every replica's ``metrics()`` — those
replies carry each replica's queue depth, which the controller
piggybacks on its routing-table replies (``get_replicas`` and long-poll
``listen``, including timeout ticks) so routers can make power-of-two-
choices decisions against near-real-time load without extra RPCs.
Scaling decisions are logged, counted
(``rmt_serve_autoscale_decisions_total{direction}``), and pinned into
the cluster autoscaler's demand set (``request_resources``) so scale-up
provisions nodes instead of silently queueing replicas.
"""

from __future__ import annotations

import asyncio
import time
from typing import Any, Dict, List, Optional

from .. import api
from ..utils import events, structlog

CONTROLLER_NAME = "SERVE_CONTROLLER"

log = structlog.get_logger(__name__)


class _DeploymentInfo:
    def __init__(self, name: str, cfg: dict):
        self.name = name
        self.cfg = cfg  # func_or_class, init_args/kwargs, num_replicas,
        #                 max_concurrent_queries, user_config, actor_options,
        #                 autoscaling (dict or None), placement_hint
        self.replicas: Dict[str, Any] = {}  # tag -> ActorHandle
        self.version = 0
        self.target_replicas = cfg.get("num_replicas", 1)
        self.deleting = False
        self.next_replica_idx = 0
        self.queue_depths: Dict[str, int] = {}  # tag -> last reported
        self.resources_pinned = False


class ServeController:
    def __init__(self):
        self.deployments: Dict[str, _DeploymentInfo] = {}
        self._listeners: Dict[str, asyncio.Event] = {}
        self._reconcile_task: Optional[asyncio.Task] = None
        self._autoscale_interval_s = 0.5
        self._shutdown = False

    @staticmethod
    async def _aget(ref, timeout: float):
        """api.get without blocking the controller loop: the blocking wait
        runs on the default thread pool so listen()/deploy()/status() stay
        responsive during slow replica startups."""
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            None, lambda: api.get(ref, timeout=timeout))

    async def ready(self) -> str:
        if self._reconcile_task is None:
            self._reconcile_task = asyncio.get_running_loop().create_task(
                self._reconcile_loop())
        return "ok"

    # ------------------------------------------------------------- deploy api
    async def deploy(self, name: str, cfg: dict) -> None:
        """Register/refresh desired state; reconciliation makes it real
        (controller.py:330 deploy → DeploymentState.deploy)."""
        info = self.deployments.get(name)
        if info is None or info.deleting:
            info = _DeploymentInfo(name, cfg)
            self.deployments[name] = info
        else:
            old = info.cfg
            info.cfg = cfg
            info.target_replicas = cfg.get("num_replicas", 1)
            if cfg.get("user_config") != old.get("user_config"):
                await self._reconfigure_replicas(info)
            if (cfg.get("func_or_class_blob") !=
                    old.get("func_or_class_blob") or
                    cfg.get("init_args") != old.get("init_args") or
                    cfg.get("init_kwargs") != old.get("init_kwargs")):
                # code change: rolling replace — drop all, reconcile restarts
                await self._stop_replicas(info, list(info.replicas))
        await self._reconcile_deployment(info)
        # config-only changes (max_concurrent_queries, autoscaling) must
        # still reach long-polling routers even when no replica changed
        self._bump(name)

    async def delete_deployment(self, name: str) -> None:
        info = self.deployments.get(name)
        if info is None:
            return
        info.deleting = True
        info.target_replicas = 0
        await self._reconcile_deployment(info)
        del self.deployments[name]
        self._bump(name)

    async def get_deployment_info(self, name: str) -> Optional[dict]:
        info = self.deployments.get(name)
        if info is None:
            return None
        return {
            "name": name,
            "num_replicas": len(info.replicas),
            "target_replicas": info.target_replicas,
            "version": info.version,
            "max_concurrent_queries": info.cfg.get(
                "max_concurrent_queries", 100),
            "autoscaling": info.cfg.get("autoscaling"),
        }

    async def list_deployments(self) -> List[str]:
        return [n for n, i in self.deployments.items() if not i.deleting]

    # ---------------------------------------------------------- replica state
    async def get_replicas(self, name: str) -> dict:
        """Current routing table for a deployment (what routers consume)."""
        info = self.deployments.get(name)
        if info is None:
            return {"version": -1, "replicas": {},
                    "max_concurrent_queries": 100}
        return {
            "version": info.version,
            "replicas": dict(info.replicas),
            "max_concurrent_queries": info.cfg.get(
                "max_concurrent_queries", 100),
            "queue_depths": dict(info.queue_depths),
        }

    async def listen(self, name: str, last_version: int,
                     timeout_s: float = 30.0) -> dict:
        """Long-poll: return when the deployment's routing table changes
        past ``last_version`` or on timeout (long_poll.py:63 LongPollHost —
        the reply-when-changed contract)."""
        deadline = time.monotonic() + timeout_s
        while not self._shutdown:
            info = self.deployments.get(name)
            if info is not None and info.version > last_version:
                return await self.get_replicas(name)
            if info is None and last_version >= 0:
                return await self.get_replicas(name)  # deleted
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                # timeout tick still refreshes queue depths: depth moves
                # every request, versioning it would defeat long-polling
                return {"version": last_version, "replicas": None,
                        "timeout": True,
                        "queue_depths": dict(info.queue_depths)
                        if info is not None else {}}
            ev = self._listeners.setdefault(name, asyncio.Event())
            try:
                await asyncio.wait_for(ev.wait(), timeout=remaining)
            except asyncio.TimeoutError:
                pass
        info = self.deployments.get(name)
        return {"version": last_version, "replicas": None, "timeout": True,
                "queue_depths": dict(info.queue_depths)
                if info is not None else {}}

    def _bump(self, name: str) -> None:
        info = self.deployments.get(name)
        if info is not None:
            info.version += 1
        ev = self._listeners.pop(name, None)
        if ev is not None:
            ev.set()

    # ------------------------------------------------------------- reconcile
    async def _reconcile_loop(self) -> None:
        while not self._shutdown:
            try:
                for info in list(self.deployments.values()):
                    await self._autoscale(info)
                    await self._reconcile_deployment(info)
            except Exception:
                log.warning("serve reconcile tick failed", exc_info=True)
            await asyncio.sleep(self._autoscale_interval_s)

    async def _reconcile_deployment(self, info: _DeploymentInfo) -> None:
        current = len(info.replicas)
        target = 0 if info.deleting else info.target_replicas
        if current < target:
            await self._start_replicas(info, target - current)
        elif current > target:
            tags = list(info.replicas)[: current - target]
            await self._stop_replicas(info, tags)

    @staticmethod
    def _placement_strategy(info: _DeploymentInfo):
        """Tier-affine placement: when the deployment carries a
        ``placement_hint`` (hex object id of e.g. its shipped weights),
        prefer the node whose DEVICE tier already holds that object —
        the replica's params materialize over local HBM instead of a
        cross-node fetch. Soft affinity: a gone node falls back to
        default placement."""
        hint = info.cfg.get("placement_hint")
        if not hint:
            return None, "default"
        try:
            from ..core.scheduling_strategies import (
                NodeAffinitySchedulingStrategy,
            )
            from ..state import api as state_api

            rows = state_api.list_objects(
                filters=[("object_id", "=", hint)])
            rows.sort(key=lambda r: r.get("tier") != "hbm")  # hbm first
            for row in rows:
                node_id = row.get("node_id")
                if node_id:
                    return (NodeAffinitySchedulingStrategy(
                        node_id, soft=True), "tier_affine")
        except Exception:  # noqa: BLE001 — placement is best-effort
            pass
        return None, "default"

    async def _start_replicas(self, info: _DeploymentInfo, n: int) -> None:
        from .replica import Replica

        opts = dict(info.cfg.get("actor_options") or {})
        opts.setdefault("num_cpus", 0)
        opts["max_concurrency"] = max(
            info.cfg.get("max_concurrent_queries", 100), 2)
        strategy, placement_mode = self._placement_strategy(info)
        if strategy is not None and "scheduling_strategy" not in opts:
            opts["scheduling_strategy"] = strategy
        try:
            from ..core import metrics_defs as mdefs
            mdefs.serve_replica_placements().inc(
                n, tags={"mode": placement_mode})
        except Exception:  # noqa: BLE001
            pass
        new_tags = []
        for _ in range(n):
            tag = f"{info.name}#{info.next_replica_idx}"
            info.next_replica_idx += 1
            handle = api.remote(Replica).options(**opts).remote(
                info.name, tag, info.cfg["func_or_class_blob"],
                info.cfg.get("init_args") or (),
                info.cfg.get("init_kwargs") or {},
                info.cfg.get("user_config"),
            )
            info.replicas[tag] = handle
            new_tags.append(tag)
        # wait for readiness so the routing table only ever lists live
        # replicas (deployment_state reconciler waits for replica startup)
        ready_refs = [info.replicas[t].ready.remote() for t in new_tags]
        for tag, ref in zip(new_tags, ready_refs):
            try:
                await self._aget(ref, timeout=60)
            except Exception:
                # failed/hung startup: remove AND kill, or the actor would
                # finish init later and sit leaked holding its resources
                handle = info.replicas.pop(tag, None)
                if handle is not None:
                    try:
                        api.kill(handle)
                    except Exception:
                        pass
        self._bump(info.name)

    async def _stop_replicas(self, info: _DeploymentInfo,
                             tags: List[str]) -> None:
        for tag in tags:
            handle = info.replicas.pop(tag, None)
            if handle is None:
                continue
            try:
                handle.drain.remote(2.0)
                api.kill(handle)
            except Exception:
                pass
        self._bump(info.name)

    async def _reconfigure_replicas(self, info: _DeploymentInfo) -> None:
        refs = [h.reconfigure.remote(info.cfg.get("user_config"))
                for h in info.replicas.values()]
        for r in refs:
            try:
                await self._aget(r, timeout=30)
            except Exception:
                pass

    # ------------------------------------------------------------ autoscaler
    async def _poll_metrics(self, info: _DeploymentInfo) -> List[int]:
        """Fetch every replica's queue depth (runs each reconcile tick
        whether or not autoscaling is on — the depths feed routers' p2c
        choices via the long-poll channel). Failed fetches are COUNTED
        and logged, never swallowed into a silently stale table."""
        if info.deleting or not info.replicas:
            info.queue_depths = {}
            return []
        tagged = [(t, h.metrics.remote())
                  for t, h in info.replicas.items()]
        depths: Dict[str, int] = {}
        ongoing: List[int] = []
        for tag, ref in tagged:
            try:
                m = await self._aget(ref, timeout=5)
                depths[tag] = int(m["num_ongoing_requests"])
                ongoing.append(depths[tag])
            except Exception:
                try:
                    from ..core import metrics_defs as mdefs
                    mdefs.serve_autoscale_errors().inc()
                except Exception:  # noqa: BLE001
                    pass
                log.warning(
                    "metrics fetch failed for replica %s of %s",
                    tag, info.name, exc_info=True)
        info.queue_depths = depths
        return ongoing

    def _pin_demand(self, info: _DeploymentInfo, desired: int) -> None:
        """Feed the scaling decision into the cluster autoscaler's demand
        set: bumping ``target_replicas`` alone only queues actor creation
        — ``request_resources`` makes the autoscaler PROVISION nodes for
        replicas that don't fit the current cluster."""
        opts = info.cfg.get("actor_options") or {}
        bundle = {k: float(opts[k])
                  for k in ("num_cpus", "num_gpus", "num_tpus")
                  if opts.get(k)}
        if not bundle:
            bundle = {"num_cpus": 1.0}
        try:
            from ..autoscaler import request_resources

            request_resources([dict(bundle)] * desired)
            info.resources_pinned = True
        except Exception:  # noqa: BLE001 — no autoscaler running is fine
            pass

    async def _autoscale(self, info: _DeploymentInfo) -> None:
        ongoing = await self._poll_metrics(info)
        cfg = info.cfg.get("autoscaling")
        if not cfg or info.deleting or not ongoing:
            return
        avg = sum(ongoing) / len(ongoing)
        target_per = cfg.get("target_num_ongoing_requests_per_replica", 1.0)
        desired = max(
            cfg.get("min_replicas", 1),
            min(cfg.get("max_replicas", 1),
                int(round(len(ongoing) * avg / max(target_per, 1e-9)))
                or cfg.get("min_replicas", 1)),
        )
        if desired != info.target_replicas:
            direction = "up" if desired > info.target_replicas else "down"
            log.info(
                "autoscaling %s %s: %d -> %d replicas "
                "(avg ongoing %.2f, target/replica %.2f)",
                info.name, direction, info.target_replicas, desired,
                avg, target_per)
            events.emit(
                "SERVE_AUTOSCALE",
                f"{info.name}: {info.target_replicas} -> {desired} "
                f"(avg ongoing {avg:.2f})",
                severity=events.INFO, source="serve")
            try:
                from ..core import metrics_defs as mdefs
                mdefs.serve_autoscale_decisions().inc(
                    tags={"direction": direction})
            except Exception:  # noqa: BLE001
                pass
            info.target_replicas = desired
            self._pin_demand(info, desired)

    async def shutdown(self) -> None:
        self._shutdown = True
        pinned = any(i.resources_pinned
                     for i in self.deployments.values())
        for info in list(self.deployments.values()):
            info.deleting = True
            info.target_replicas = 0
            await self._reconcile_deployment(info)
        self.deployments.clear()
        if pinned:
            try:
                from ..autoscaler import request_resources

                request_resources([])
            except Exception:  # noqa: BLE001
                pass


def get_or_create_controller():
    """Get the singleton controller actor, creating it if needed (the
    serve.start path; controller is a detached named actor so every
    driver/worker resolves the same one)."""
    try:
        handle = api.get_actor(CONTROLLER_NAME)
    except Exception:
        try:
            handle = api.remote(ServeController).options(
                name=CONTROLLER_NAME, lifetime="detached", num_cpus=0,
                max_concurrency=64,
            ).remote()
        except Exception:
            # lost a concurrent-create race: connect to the winner
            handle = api.get_actor(CONTROLLER_NAME)
    api.get(handle.ready.remote(), timeout=60)
    return handle
