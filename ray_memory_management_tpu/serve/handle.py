"""Deployment handles + client-side router.

The reference's RayServeHandle (serve/handle.py:77,285) backed by the
Router/ReplicaSet with in-flight caps (serve/_private/router.py:62,261,298)
and a LongPollClient keeping the routing table fresh
(serve/_private/long_poll.py:179).

Router policy: pick the live replica with the fewest locally-tracked
in-flight requests (power-of-all least-loaded); when every replica is at
``max_concurrent_queries``, block on wait() until one drains — the
reference's backpressure behavior.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional

from .. import api


class Router:
    def __init__(self, controller, deployment_name: str):
        self._controller = controller
        self._name = deployment_name
        self._lock = threading.Lock()
        self._version = -1
        self._replicas: Dict[str, Any] = {}
        self._max_q = 100
        self._inflight: Dict[str, List[Any]] = {}
        self._stop = threading.Event()
        self._refresh(block=True)
        self._poller = threading.Thread(
            target=self._poll_loop, daemon=True,
            name=f"serve-poll-{deployment_name}")
        self._poller.start()

    def _refresh(self, block: bool = False) -> None:
        state = api.get(
            self._controller.get_replicas.remote(self._name), timeout=30)
        deadline = time.monotonic() + 30
        while block and not state["replicas"] and \
                time.monotonic() < deadline:
            time.sleep(0.05)
            state = api.get(
                self._controller.get_replicas.remote(self._name), timeout=30)
        with self._lock:
            self._version = state["version"]
            self._replicas = state["replicas"] or {}
            self._max_q = state.get("max_concurrent_queries", 100)
            self._inflight = {
                t: self._inflight.get(t, []) for t in self._replicas
            }

    def _poll_loop(self) -> None:
        """LongPollClient: blocks server-side until the table changes."""
        while not self._stop.is_set():
            try:
                state = api.get(self._controller.listen.remote(
                    self._name, self._version, 10.0), timeout=40)
            except Exception:
                if self._stop.is_set():
                    return
                time.sleep(0.5)
                continue
            if state.get("replicas") is None:
                continue  # timeout tick
            with self._lock:
                self._version = state["version"]
                self._replicas = state["replicas"] or {}
                self._max_q = state.get("max_concurrent_queries", 100)
                self._inflight = {
                    t: self._inflight.get(t, []) for t in self._replicas
                }

    def _prune(self) -> None:
        # drop completed refs from in-flight tracking (router.py:298 —
        # the reference decrements on reply callbacks; we poll readiness)
        for tag, refs in self._inflight.items():
            if not refs:
                continue
            ready, not_ready = api.wait(
                refs, num_returns=len(refs), timeout=0)
            self._inflight[tag] = list(not_ready)

    def assign(self, method: str, args, kwargs):
        deadline = time.monotonic() + 60
        while True:
            with self._lock:
                self._prune()
                candidates = [
                    (len(self._inflight.get(t, [])), t, h)
                    for t, h in self._replicas.items()
                ]
                open_slots = [c for c in candidates if c[0] < self._max_q]
                if open_slots:
                    open_slots.sort(key=lambda c: (c[0], random.random()))
                    _, tag, handle = open_slots[0]
                    ref = handle.handle_request.remote(method, args, kwargs)
                    self._inflight.setdefault(tag, []).append(ref)
                    return ref
                pending = [r for refs in self._inflight.values()
                           for r in refs]
            if not pending:
                # no replicas yet: wait for the routing table to fill
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"no replicas available for {self._name}")
                time.sleep(0.05)
                continue
            # every replica at max_concurrent_queries: wait for one to drain
            api.wait(pending, num_returns=1, timeout=1.0)
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"backpressure timeout routing to {self._name}")

    def shutdown(self) -> None:
        self._stop.set()


class DeploymentHandle:
    """User-facing handle: ``h.remote(*args)`` → ObjectRef; method handles
    via ``h.method_name.remote(...)`` (reference handle.py:285
    RayServeSyncHandle / method handles)."""

    def __init__(self, controller, deployment_name: str,
                 method: str = "__call__", _router: Optional[Router] = None):
        self._controller = controller
        self._name = deployment_name
        self._method = method
        self._router_inst = _router
        self._router_lock = threading.Lock()

    @property
    def _router(self) -> Router:
        # created lazily so handles pickle cleanly into replicas (the
        # router holds live threads; each process builds its own)
        with self._router_lock:
            if self._router_inst is None:
                self._router_inst = Router(self._controller, self._name)
            return self._router_inst

    def remote(self, *args, **kwargs):
        return self._router.assign(self._method, args, kwargs)

    def __getattr__(self, name: str) -> "DeploymentHandle":
        if name.startswith("_") or name in ("remote",):
            raise AttributeError(name)
        return DeploymentHandle(
            self._controller, self._name, method=name,
            _router=self._router_inst)

    def __reduce__(self):
        return (DeploymentHandle, (self._controller, self._name,
                                   self._method))

    def __repr__(self):
        return f"DeploymentHandle({self._name!r}, method={self._method!r})"
