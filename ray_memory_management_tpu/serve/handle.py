"""Deployment handles + client-side router.

The reference's RayServeHandle (serve/handle.py:77,285) backed by the
Router/ReplicaSet with in-flight caps (serve/_private/router.py:62,261,298)
and a LongPollClient keeping the routing table fresh
(serve/_private/long_poll.py:179).

Router policy: power-of-two-choices — sample two replicas with open
slots and take the one with the lower load score, where the score is the
router's OWN in-flight count plus the replica's last-reported queue
depth (snapshots the controller piggybacks on its ``metrics()`` poll
replies and pushes through the long-poll channel, including timeout
ticks, so depth stays fresh without version churn). Scan-all least-
loaded degrades at fleet size (every router herds onto the same
momentarily-idle replica); two random choices keep the max queue within
O(log log n) of optimal while reading O(1) state. When every replica is
at ``max_concurrent_queries``, block on wait() until one drains — the
reference's backpressure behavior — and give up after
``serve_backpressure_timeout_s`` with a typed, counted error
(:class:`BackpressureTimeout`, ``rmt_serve_shed_total``).
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional

from .. import api


class NoReplicasError(RuntimeError):
    """The routing table stayed empty for the whole backpressure window
    (deployment deleted, all replicas dead, or never started)."""


class BackpressureTimeout(RuntimeError):
    """Every replica sat at ``max_concurrent_queries`` for the whole
    backpressure window — the load-shedding signal (HTTP 429 at the
    proxy)."""


def _count_shed(reason: str) -> None:
    try:
        from ..core import metrics_defs as mdefs

        mdefs.serve_shed().inc(tags={"reason": reason})
    except Exception:  # noqa: BLE001 — metrics never fail routing
        pass


class Router:
    def __init__(self, controller, deployment_name: str):
        self._controller = controller
        self._name = deployment_name
        self._lock = threading.Lock()
        self._version = -1
        self._replicas: Dict[str, Any] = {}
        self._max_q = 100
        self._inflight: Dict[str, List[Any]] = {}
        self._depths: Dict[str, int] = {}  # replica-reported queue depth
        self._stop = threading.Event()
        self._refresh(block=True)
        self._poller = threading.Thread(
            target=self._poll_loop, daemon=True,
            name=f"serve-poll-{deployment_name}")
        self._poller.start()

    def _refresh(self, block: bool = False) -> None:
        state = api.get(
            self._controller.get_replicas.remote(self._name), timeout=30)
        deadline = time.monotonic() + 30
        while block and not state["replicas"] and \
                time.monotonic() < deadline:
            time.sleep(0.05)
            state = api.get(
                self._controller.get_replicas.remote(self._name), timeout=30)
        self._apply_state(state)

    def _apply_state(self, state: Dict[str, Any]) -> None:
        """Install a routing-table snapshot; ``replicas is None`` means a
        long-poll timeout tick, which still refreshes queue depths (they
        change every request — bumping the table version for them would
        defeat long-polling)."""
        with self._lock:
            depths = state.get("queue_depths")
            if depths is not None:
                self._depths = dict(depths)
            if state.get("replicas") is None:
                return
            self._version = state["version"]
            self._replicas = state["replicas"] or {}
            self._max_q = state.get("max_concurrent_queries", 100)
            self._inflight = {
                t: self._inflight.get(t, []) for t in self._replicas
            }
        self._publish_depth()

    def _publish_depth(self) -> None:
        try:
            from ..core import metrics_defs as mdefs

            with self._lock:
                depth = sum(len(v) for v in self._inflight.values())
            mdefs.serve_queue_depth().set(
                float(depth), tags={"deployment": self._name})
        except Exception:  # noqa: BLE001
            pass

    def _poll_loop(self) -> None:
        """LongPollClient: blocks server-side until the table changes."""
        while not self._stop.is_set():
            try:
                state = api.get(self._controller.listen.remote(
                    self._name, self._version, 10.0), timeout=40)
            except Exception:
                if self._stop.is_set():
                    return
                time.sleep(0.5)
                continue
            self._apply_state(state)

    def _prune(self) -> None:
        # drop completed refs from in-flight tracking (router.py:298 —
        # the reference decrements on reply callbacks; we poll readiness)
        # in ONE batched zero-timeout wait across all replicas — the old
        # per-replica loop paid one runtime round-trip per replica per
        # assign, which dominated routing cost at fleet size
        all_refs = [r for refs in self._inflight.values() for r in refs]
        if not all_refs:
            return
        ready, _ = api.wait(all_refs, num_returns=len(all_refs), timeout=0)
        done = set(ready)
        if not done:
            return
        for tag, refs in self._inflight.items():
            self._inflight[tag] = [r for r in refs if r not in done]

    def _score(self, tag: str) -> int:
        """Load score: locally-tracked in-flight plus the replica's last
        self-reported queue depth (covers load from OTHER routers)."""
        return len(self._inflight.get(tag, [])) + self._depths.get(tag, 0)

    def assign(self, method: str, args, kwargs):
        from ..config import global_config

        deadline = time.monotonic() + \
            global_config().serve_backpressure_timeout_s
        while True:
            with self._lock:
                self._prune()
                open_slots = [
                    (t, h) for t, h in self._replicas.items()
                    if len(self._inflight.get(t, [])) < self._max_q
                ]
                if open_slots:
                    # power of two choices over the open slots
                    picks = random.sample(open_slots, 2) \
                        if len(open_slots) > 2 else open_slots
                    tag, handle = min(
                        picks,
                        key=lambda th: (self._score(th[0]),
                                        random.random()))
                    ref = handle.handle_request.remote(method, args, kwargs)
                    self._inflight.setdefault(tag, []).append(ref)
                    return ref
                pending = [r for refs in self._inflight.values()
                           for r in refs]
            if not pending:
                # no replicas yet: wait for the routing table to fill
                if time.monotonic() > deadline:
                    _count_shed("no_replicas")
                    raise NoReplicasError(
                        f"no replicas available for {self._name}")
                time.sleep(0.05)
                continue
            # every replica at max_concurrent_queries: wait for one to drain
            api.wait(pending, num_returns=1, timeout=1.0)
            if time.monotonic() > deadline:
                _count_shed("backpressure_timeout")
                raise BackpressureTimeout(
                    f"backpressure timeout routing to {self._name}")

    def queue_depth(self) -> int:
        """Known outstanding requests for this deployment: the larger of
        this router's in-flight view and the replicas' self-reported
        depths (other routers' load)."""
        with self._lock:
            local = sum(len(v) for v in self._inflight.values())
            remote = sum(self._depths.get(t, 0) for t in self._replicas)
        return max(local, remote)

    def overloaded(self) -> bool:
        """Proxy-side shed signal: queue depth at or beyond
        ``serve_shed_queue_factor x replicas x max_concurrent_queries``
        means a new request would only wait out its whole backpressure
        window — reject it up front (HTTP 429) instead."""
        from ..config import global_config

        with self._lock:
            n = len(self._replicas)
        if n == 0:
            return False  # cold table: let assign() wait for replicas
        cap = global_config().serve_shed_queue_factor * n * self._max_q
        return self.queue_depth() >= cap

    def shutdown(self) -> None:
        self._stop.set()


class DeploymentHandle:
    """User-facing handle: ``h.remote(*args)`` → ObjectRef; method handles
    via ``h.method_name.remote(...)`` (reference handle.py:285
    RayServeSyncHandle / method handles)."""

    def __init__(self, controller, deployment_name: str,
                 method: str = "__call__", _router: Optional[Router] = None):
        self._controller = controller
        self._name = deployment_name
        self._method = method
        self._router_inst = _router
        self._router_lock = threading.Lock()

    @property
    def _router(self) -> Router:
        # created lazily so handles pickle cleanly into replicas (the
        # router holds live threads; each process builds its own)
        with self._router_lock:
            if self._router_inst is None:
                self._router_inst = Router(self._controller, self._name)
            return self._router_inst

    def remote(self, *args, **kwargs):
        return self._router.assign(self._method, args, kwargs)

    def __getattr__(self, name: str) -> "DeploymentHandle":
        if name.startswith("_") or name in ("remote",):
            raise AttributeError(name)
        return DeploymentHandle(
            self._controller, self._name, method=name,
            _router=self._router_inst)

    def __reduce__(self):
        return (DeploymentHandle, (self._controller, self._name,
                                   self._method))

    def __repr__(self):
        return f"DeploymentHandle({self._name!r}, method={self._method!r})"
