"""Object serialization: pickle5 with out-of-band buffers in a framed envelope.

Mirrors the reference's SerializationContext (python/ray/_private/serialization.py:89,363,411):
values are pickled with protocol 5; large contiguous buffers (numpy arrays,
bytes) travel out-of-band and are laid out 64-byte aligned after the pickle
stream, so deserializing from a shared-memory mapping yields **zero-copy numpy
views onto the store** (serialization.py:341 in the reference).

jax.Array values are converted to host numpy on serialize and rebuilt with
``jax.numpy.asarray`` on deserialize (device placement is the consumer's
choice; a device-buffer fast path lives in core/object_store.py). jax is
imported lazily so plain workers never pay its import cost.
"""

from __future__ import annotations

import io
import pickle
import sys
import threading
from typing import Any, List, Tuple

import msgpack

_MAGIC = b"RMT1"
# No-buffer fast envelope: magic + raw pickle stream, no msgpack header.
# Small control values (task args, tiny returns) dominate message traffic;
# the full header costs ~5 us per envelope that this path skips.
_MAGIC_SMALL = b"RMT0"
_ALIGN = 64


def _is_jax_array(value) -> bool:
    mod = type(value).__module__
    if not (mod.startswith("jax") or mod.startswith("jaxlib")):
        return False
    jax = sys.modules.get("jax")
    return jax is not None and isinstance(value, jax.Array)


class _JaxAwarePickler(pickle.Pickler):
    """Pickler that ships jax.Arrays as host numpy + a rebuild marker, and
    closures/lambdas/script-local functions by value via cloudpickle (plain
    pickle can only reference importable module-level names; the reference
    routes all of this through cloudpickle too)."""

    def reducer_override(self, obj):
        if _is_jax_array(obj):
            import numpy as np

            return (_rebuild_jax_array, (np.asarray(obj),))
        import types

        if isinstance(obj, types.FunctionType) and _needs_by_value(obj):
            return (_loads_cloudpickle, (dumps_function(obj),))
        return NotImplemented


_installed_paths: Tuple[str, ...] = ()


def _installed_prefixes() -> Tuple[str, ...]:
    """site-packages/stdlib prefixes, computed once (sysconfig.get_paths
    re-expands its config vars on every call — ~0.4 ms that used to tax
    every serialize on the put hot path)."""
    global _installed_paths
    if not _installed_paths:
        import sysconfig

        paths = sysconfig.get_paths()
        _installed_paths = (paths["purelib"], paths["platlib"],
                            paths["stdlib"])
    return _installed_paths


def _needs_by_value(fn) -> bool:
    qualname = getattr(fn, "__qualname__", "")
    if "<locals>" in qualname or "<lambda>" in qualname:
        return True
    mod = getattr(fn, "__module__", None)
    if mod in (None, "__main__"):
        return True
    if mod.startswith("ray_memory_management_tpu"):
        return False
    module = sys.modules.get(mod)
    f = getattr(module, "__file__", None)
    if f is None:
        return False  # builtin/frozen: importable everywhere
    return not f.startswith(_installed_prefixes())


def _loads_cloudpickle(blob: bytes):
    import cloudpickle

    return cloudpickle.loads(blob)


def _rebuild_jax_array(np_value):
    import jax.numpy as jnp

    return jnp.asarray(np_value)


def _rehydrate_demoted(payload):
    """Unpickle hook for :class:`DemotedDeviceArray`: dequantize the
    PR 7 envelope and land the value back as a jax.Array — every reader
    of a demoted device object sees an array, never the envelope."""
    import jax.numpy as jnp

    from .core.codec import dequantize_array

    return jnp.asarray(dequantize_array(payload))


class DemotedDeviceArray:
    """Host-side envelope for a device object demoted with a dtype-aware
    downcast (``device_demote_precision=bf16``): carries the PR 7
    quantize payload and unpickles STRAIGHT to the rehydrated jax.Array
    via ``__reduce__`` — consumers on the normal get path are oblivious
    to the demotion codec."""

    __slots__ = ("payload",)

    def __init__(self, payload):
        self.payload = payload

    def __reduce__(self):
        return (_rehydrate_demoted, (self.payload,))


def serialize_device_demotion(array, precision: str) -> "SerializedObject":
    """Device→host demotion serializer: float32 payloads honor the
    configured downcast (bf16 halves the host/spill bytes through the
    PR 7 quantize envelope, rel err <= 2^-8); everything else demotes
    exact through the normal jax-aware path."""
    import numpy as np

    np_value = np.asarray(array)
    if precision == "bf16" and np_value.dtype == np.float32:
        from .core.codec import quantize_array

        return serialize(DemotedDeviceArray(
            quantize_array(np_value, "bf16")))
    return serialize(array)


def _align(offset: int) -> int:
    return (offset + _ALIGN - 1) & ~(_ALIGN - 1)


_copy_pool = None
_COPY_THREADS = 0
_copy_init_lock = threading.Lock()


def _parallel_copy(dest: memoryview, src: memoryview) -> None:
    """Striped memcpy across a small worker pool. np.copyto releases the
    GIL, so the stripes genuinely run in parallel; single-core hosts fall
    back to one plain copy."""
    global _copy_pool, _COPY_THREADS
    import numpy as np

    if _COPY_THREADS == 0:
        with _copy_init_lock:
            if _COPY_THREADS == 0:
                import os as _os
                from concurrent.futures import ThreadPoolExecutor

                n = min(4, _os.cpu_count() or 1)
                if n > 1:
                    _copy_pool = ThreadPoolExecutor(
                        max_workers=n, thread_name_prefix="rmt-copy")
                _COPY_THREADS = n  # published last: pool visible first
    d = np.frombuffer(dest, np.uint8)
    s = np.frombuffer(src, np.uint8)
    if _copy_pool is None:
        np.copyto(d, s)
        return
    n = len(d)
    step = (n + _COPY_THREADS - 1) // _COPY_THREADS
    futs = [
        _copy_pool.submit(np.copyto, d[i : i + step], s[i : i + step])
        for i in range(0, n, step)
    ]
    for f in futs:
        f.result()


class SerializedObject:
    """A serialized value: header + pickle stream + aligned raw buffers."""

    __slots__ = ("_header", "_pickled", "_buffers", "total_size")

    def __init__(self, header: bytes, pickled: bytes,
                 buffers: List[memoryview], total_size: int):
        self._header = header
        self._pickled = pickled
        self._buffers = buffers
        self.total_size = total_size

    def write_into(self, dest: memoryview) -> None:
        """Write the full envelope into ``dest`` (a store allocation)."""
        pos = 0
        for part in (self._header, self._pickled):
            dest[pos : pos + len(part)] = part
            pos += len(part)
        for buf in self._buffers:
            pos = _align(pos)
            n = buf.nbytes
            flat = buf.cast("B") if buf.format != "B" or buf.ndim != 1 else buf
            if n >= (16 << 20):
                # very large buffers: striped copy across threads —
                # np.copyto releases the GIL, so N threads reach N memory
                # channels; this is what closes the gap to plasma's put
                # bandwidth on multi-core hosts
                _parallel_copy(dest[pos : pos + n], flat)
            elif n >= (1 << 20):
                # numpy's copy loop beats memoryview slice assignment on
                # large buffers (and releases the GIL for the duration)
                import numpy as np

                np.copyto(np.frombuffer(dest[pos : pos + n], np.uint8),
                          np.frombuffer(flat, np.uint8))
            else:
                dest[pos : pos + n] = flat
            pos += n

    def to_bytes(self) -> bytes:
        out = bytearray(self.total_size)
        self.write_into(memoryview(out))
        return bytes(out)


# Memoized pickle streams for plain bulk ndarrays: with protocol-5
# out-of-band buffers the stream is a pure function of (shape, dtype,
# layout, writeability) — buffer references are POSITIONAL — so the
# pickler run can be skipped entirely on the bulk-put hot path (it was
# ~4% of a 16 MB put, all of the non-memcpy overhead that remained).
_ARRAY_STREAM_CACHE: dict = {}
_ARRAY_CACHE_MIN_BYTES = 1 << 20


def _plain_array_key(value):
    import numpy as np

    if (type(value) is np.ndarray
            and value.nbytes >= _ARRAY_CACHE_MIN_BYTES
            and value.dtype != object
            and (value.flags.c_contiguous or value.flags.f_contiguous)):
        return (value.shape, value.dtype.str, value.flags.c_contiguous,
                value.flags.writeable)
    return None


def serialize(value: Any) -> SerializedObject:
    key = _plain_array_key(value)
    if key is not None:
        hit = _ARRAY_STREAM_CACHE.get(key)
        if hit is not None:
            # the same raw view the pickler's buffer_callback would yield
            return _assemble(hit, [pickle.PickleBuffer(value).raw()])
    stream = io.BytesIO()
    raw_buffers: List[pickle.PickleBuffer] = []
    pickler = _JaxAwarePickler(
        stream, protocol=5, buffer_callback=raw_buffers.append
    )
    pickler.dump(value)
    pickled = stream.getvalue()
    if key is not None and len(raw_buffers) == 1:
        if len(_ARRAY_STREAM_CACHE) >= 256:  # bound shape-churn growth
            _ARRAY_STREAM_CACHE.clear()
        _ARRAY_STREAM_CACHE[key] = pickled

    if not raw_buffers:
        return SerializedObject(_MAGIC_SMALL, pickled, [],
                                len(_MAGIC_SMALL) + len(pickled))
    return _assemble(pickled, [pb.raw() for pb in raw_buffers])


def _assemble(pickled: bytes, views: List[memoryview]) -> SerializedObject:
    sizes = [mv.nbytes for mv in views]
    # Header: MAGIC | u64 meta_len | msgpack{pickle_off, pickle_len, buf_sizes, total}
    # Two-pass: meta length depends on total, which depends on meta length; the
    # meta is small so iterate to fixed point (at most twice).
    meta = {"pickle_len": len(pickled), "buf_sizes": sizes, "total": 0}
    for _ in range(3):
        packed = msgpack.packb(meta)
        header_len = len(_MAGIC) + 8 + len(packed)
        pos = header_len + len(pickled)
        for s in sizes:
            pos = _align(pos) + s
        if meta["total"] == pos:
            break
        meta["total"] = pos
    header = _MAGIC + len(packed).to_bytes(8, "little") + packed
    return SerializedObject(header, pickled, views, meta["total"])


class _StoreBufferView:
    """PEP-688 buffer wrapper tying a store refcount to view lifetime.

    numpy/pickle keep the wrapper alive as the ``base`` of every zero-copy
    array deserialized from the store; when the last view dies, ``notify``
    fires and the caller releases its store reference — exactly the plasma
    client's buffer-lifetime semantics (plasma/client.cc Release on buffer
    destruction). Views are read-only, matching plasma's sealed-object rule.
    """

    __slots__ = ("_mv", "_notify")

    def __init__(self, mv: memoryview, notify):
        self._mv = mv
        self._notify = notify

    def __buffer__(self, flags):
        return memoryview(self._mv)

    def __del__(self):
        if self._notify is not None:
            self._notify()


# pickle consumes out-of-band buffers through the C buffer protocol; a
# pure-Python ``__buffer__`` only participates from Python 3.12 (PEP 688)
_HAS_PEP688 = sys.version_info >= (3, 12)


def _wrap_buffer(sl: memoryview, notify):
    """Wrap one aligned store slice so its release is tied to the life of
    whatever pickle reconstructs from it."""
    if _HAS_PEP688:
        return _StoreBufferView(sl, notify)
    # Python < 3.12 ignores _StoreBufferView.__buffer__, so hand pickle a
    # buffer it CAN consume: a zero-copy uint8 ndarray over the read-only
    # slice. Reconstructed arrays keep it alive as their base, and the
    # finalizer fires notify when the last of them dies — same lifetime
    # semantics as the PEP-688 wrapper (memoryview itself cannot carry a
    # weakref, ndarray can).
    try:
        import numpy as np
    except ImportError:
        # no numpy: copy the payload so the store ref can drop now; this
        # buffer's share of the release fires immediately
        data = bytes(sl)
        notify()
        return data
    import weakref

    arr = np.frombuffer(sl, dtype=np.uint8)
    weakref.finalize(arr, notify)
    return arr


def deserialize(data: memoryview | bytes, on_release=None) -> Any:
    """Deserialize an envelope. If ``on_release`` is given, it is called once
    all zero-copy views into ``data`` are garbage (immediately if there are
    none, and also if deserialization fails before any view is handed out) —
    used by store readers to drop their refcount safely. All zero-copy views
    are read-only (plasma's sealed-object rule)."""
    wrappers_made = False
    try:
        mv = memoryview(data)
        magic = bytes(mv[: len(_MAGIC)])
        if magic == _MAGIC_SMALL:
            value = pickle.loads(mv[len(_MAGIC_SMALL):])
            if on_release is not None:
                on_release()
            return value
        if magic != _MAGIC:
            raise ValueError("corrupt object envelope (bad magic)")
        meta_len = int.from_bytes(mv[len(_MAGIC) : len(_MAGIC) + 8], "little")
        meta_start = len(_MAGIC) + 8
        meta = msgpack.unpackb(mv[meta_start : meta_start + meta_len])
        pos = meta_start + meta_len
        pickled = mv[pos : pos + meta["pickle_len"]]
        pos += meta["pickle_len"]
        buffers: List[Any] = []
        notify = None
        if on_release is not None and meta["buf_sizes"]:
            import threading

            remaining = [len(meta["buf_sizes"])]
            notify_lock = threading.Lock()

            def notify():  # noqa: ANN001 — fires from __del__ on any thread
                with notify_lock:
                    remaining[0] -= 1
                    fire = remaining[0] == 0
                if fire:
                    on_release()

        for size in meta["buf_sizes"]:
            pos = _align(pos)
            sl = mv[pos : pos + size].toreadonly()  # zero-copy, read-only
            if notify is not None:
                buffers.append(_wrap_buffer(sl, notify))
            else:
                buffers.append(sl)
            pos += size
        wrappers_made = notify is not None
        value = pickle.loads(pickled, buffers=buffers)
        if on_release is not None and not meta["buf_sizes"]:
            on_release()
        return value
    except BaseException:
        # No wrapper will ever fire notify on a pre-wrapper failure: release
        # the caller's store ref here so the object is not pinned forever.
        if on_release is not None and not wrappers_made:
            on_release()
        raise


def dumps_function(fn) -> bytes:
    """cloudpickle a callable so it unpickles in workers that cannot import
    its defining module (pytest test modules, scripts run by path...). The
    module is temporarily registered for by-value pickling unless it is this
    package or an installed library (those import fine remotely). Mirrors the
    reference's function-export-by-value behavior (its function manager ships
    code through GCS rather than by module path)."""
    import inspect

    import cloudpickle

    mod = inspect.getmodule(fn)
    registered = False
    if (
        mod is not None
        and getattr(mod, "__file__", None)
        and mod.__name__ != "__main__"
        and not mod.__name__.startswith("ray_memory_management_tpu")
    ):
        if not mod.__file__.startswith(_installed_prefixes()):
            try:
                cloudpickle.register_pickle_by_value(mod)
                registered = True
            except Exception:
                pass
    try:
        return cloudpickle.dumps(fn)
    finally:
        if registered:
            cloudpickle.unregister_pickle_by_value(mod)


def dumps(value: Any) -> bytes:
    return serialize(value).to_bytes()


def loads(data: bytes | memoryview) -> Any:
    return deserialize(data)
