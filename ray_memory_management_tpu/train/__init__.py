"""Train library: distributed training over worker-group actors (Ray Train
analog, jax/TPU-native)."""

from . import session  # noqa: F401
from .backend_executor import (  # noqa: F401
    BackendExecutor,
    ElasticResize,
    TrainingFailedError,
    placeable_world_size,
)
from .checkpoint import (  # noqa: F401
    AsyncCheckpointManager,
    Checkpoint,
    verify_checkpoint_dir,
)
from .trainer import (  # noqa: F401
    CheckpointConfig,
    ElasticConfig,
    FailureConfig,
    JaxTrainer,
    Result,
    RunConfig,
    ScalingConfig,
)
