"""Train library: distributed training over worker-group actors (Ray Train
analog, jax/TPU-native)."""

from . import session  # noqa: F401
from .backend_executor import BackendExecutor, TrainingFailedError  # noqa: F401
from .checkpoint import Checkpoint  # noqa: F401
from .trainer import (  # noqa: F401
    FailureConfig,
    JaxTrainer,
    Result,
    RunConfig,
    ScalingConfig,
)
