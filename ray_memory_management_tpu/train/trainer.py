"""Trainers: the user-facing fit() surface.

Mirrors the reference's trainer stack (train/base_trainer.py:328 fit,
train/data_parallel_trainer.py:52,314) re-targeted for jax:

    trainer = JaxTrainer(
        train_loop_per_worker,
        train_loop_config={...},
        scaling_config=ScalingConfig(num_workers=4, chips_per_worker=4),
        run_config=RunConfig(name="run", storage_path=...),
        datasets={"train": ds},
    )
    result = trainer.fit()

Unlike the reference, fit() does NOT detour through the Tune trial runner
(base_trainer.py:354 wraps every trainer as a Tune trainable); the tune/
library composes the other way around (Tuner runs trainers), which keeps the
single-run path dependency-free.

Preemption tolerance (the PR-6 contract): checkpoints reported from the
loop drain through an :class:`~.checkpoint.AsyncCheckpointManager`
(atomic, CRC-manifested, retention-K, optional cloud mirror) on a
background thread; with an :class:`ElasticConfig` a worker/node death
mid-run re-sizes the gang to whatever the surviving cluster can place
(bounded [min_workers, max_workers]), re-partitions chips, re-forms the
collective world, and resumes every rank from the latest DURABLE
checkpoint with per-rank loader state restored; run metadata (latest
checkpoint, step, world size) lives in the GCS kv so
``JaxTrainer(..., resume_from="auto")`` continues an interrupted run even
across head restart (sqlite-backed kv, test_gcs_persistence.py).
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..exceptions import (ActorError, NodeDeadError, TaskError,
                          WorkerCrashedError)
from .backend_executor import (BackendExecutor, ElasticResize,
                               TrainingFailedError, placeable_world_size)
from .checkpoint import AsyncCheckpointManager, Checkpoint


@dataclasses.dataclass
class ScalingConfig:
    """air/config.py ScalingConfig analog, TPU-first: ``chips_per_worker``
    replaces GPUs-per-worker; a worker is a host-process."""

    num_workers: int = 1
    use_tpu: bool = False
    chips_per_worker: int = 0
    resources_per_worker: Optional[Dict[str, float]] = None
    placement_strategy: str = "PACK"
    # cross-worker gradient plane: "objstore" (CPU collective group) or
    # "xla" (jax.distributed world — one global mesh spanning all worker
    # processes; gradient sync rides XLA collectives over ICI/DCN)
    collective_backend: str = "objstore"

    def bundle(self) -> Dict[str, float]:
        res = dict(self.resources_per_worker or {})
        res.setdefault("CPU", 1)
        if self.use_tpu and self.chips_per_worker and "TPU" not in res:
            res["TPU"] = self.chips_per_worker
        return res


@dataclasses.dataclass
class FailureConfig:
    max_failures: int = 0


@dataclasses.dataclass
class ElasticConfig:
    """Bounds for elastic re-sharding: after a worker/node death the gang
    is rebuilt at ``min(max_workers, placeable)`` as long as the cluster
    can still place at least ``min_workers`` bundles; while running below
    ``max_workers`` the executor watches capacity and triggers an upsize
    (ElasticResize — no failure budget consumed) when it grows back.

    ``max_workers=None`` means the ScalingConfig's num_workers. Elastic
    restarts get their own ``max_restarts`` budget when
    FailureConfig.max_failures is 0 (the default would otherwise forbid
    the very restarts elasticity exists for)."""

    min_workers: int = 1
    max_workers: Optional[int] = None
    max_restarts: int = 8
    # how long a failure path polls for min_workers of capacity before
    # giving up (node replacement races this; the watcher handles growth
    # AFTER the rebuild, so this stays short — dip now, recover later)
    settle_s: float = 5.0
    # watcher rate limit: capacity probe at most once per interval
    resize_check_interval_s: float = 2.0


@dataclasses.dataclass
class CheckpointConfig:
    """air CheckpointConfig analog: retention + durability mode.

    ``mode="async"`` (default) returns control to the training loop as
    soon as the shard bytes are snapshotted — the durable write drains on
    a background thread. ``mode="sync"`` blocks the report until durable
    (the bench's comparison baseline). ``storage_uri`` mirrors every
    checkpoint to a CloudStorage tier (s3:// gs:// or any registered
    scheme)."""

    num_to_keep: int = 3
    mode: str = "async"
    storage_uri: Optional[str] = None


@dataclasses.dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: str = "/tmp/rmt_runs"
    failure_config: FailureConfig = dataclasses.field(
        default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = dataclasses.field(
        default_factory=CheckpointConfig)


@dataclasses.dataclass
class Result:
    """air Result analog: final metrics + checkpoint + full history."""

    metrics: Dict[str, Any]
    checkpoint: Optional[Checkpoint]
    metrics_history: List[Dict[str, Any]]
    error: Optional[BaseException] = None
    path: Optional[str] = None


def _runtime_or_none():
    from .. import _worker_context

    try:
        return _worker_context.get_runtime()
    except Exception:  # noqa: BLE001 - no cluster: local-only run
        return None


def run_state_key(run_name: str) -> str:
    return f"train/run/{run_name}"


class JaxTrainer:
    """Data-parallel jax trainer (DataParallelTrainer analog)."""

    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[dict] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
        elastic_config: Optional[ElasticConfig] = None,
        resume_from: Optional[str] = None,
    ):
        self.train_loop = train_loop_per_worker
        self.config = train_loop_config
        self.scaling = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.datasets = datasets or {}
        self.resume_checkpoint = resume_from_checkpoint
        self.elastic = elastic_config
        # "auto" → continue this run from its durable state (local run
        # dir, falling back to the GCS-kv-recorded checkpoint URI); any
        # other string → an explicit checkpoint path/URI to start from
        self.resume_from = resume_from

    # -- dataset sharding -----------------------------------------------------
    def _shards(self, n: int) -> Optional[List[Any]]:
        if not self.datasets:
            return None
        shards: List[Dict[str, Any]] = [dict() for _ in range(n)]
        for name, ds in self.datasets.items():
            if hasattr(ds, "split"):
                parts = ds.split(n)
            else:
                parts = [ds] * n
            for i in range(n):
                shards[i][name] = parts[i]
        return shards

    # -- durable run state ----------------------------------------------------
    def _record_run_state(self, run_name: str,
                          info: Dict[str, Any]) -> None:
        rt = _runtime_or_none()
        if rt is None:
            return
        doc = {"run_name": run_name, "path": info.get("path"),
               "uri": info.get("uri"), "step": info.get("step"),
               "world_size": info.get("world_size")}
        try:
            rt.gcs.kv_put(run_state_key(run_name),
                          json.dumps(doc).encode())
        except Exception:  # noqa: BLE001 - bookkeeping never fails a save
            pass

    def _read_run_state(self, run_name: str) -> Optional[Dict[str, Any]]:
        rt = _runtime_or_none()
        if rt is None:
            return None
        try:
            raw = rt.gcs.kv_get(run_state_key(run_name))
        except Exception:  # noqa: BLE001
            return None
        if not raw:
            return None
        try:
            return json.loads(raw)
        except ValueError:
            return None

    def _resolve_resume(self, manager: AsyncCheckpointManager,
                        run_name: str
                        ) -> Tuple[Optional[Checkpoint], Dict[int, bytes]]:
        """Initial (checkpoint, rank_states) for this run."""
        if self.resume_from is None:
            return self.resume_checkpoint, {}
        if self.resume_from != "auto":
            return Checkpoint.from_uri(self.resume_from), {}
        rec = manager.latest()
        if rec is not None:
            return rec["checkpoint"], dict(rec["rank_states"])
        # no local checkpoints (fresh head / wiped disk): follow the
        # durable run record to the mirrored URI
        meta = self._read_run_state(run_name) or {}
        target = meta.get("uri") or meta.get("path")
        if target:
            try:
                return Checkpoint.from_uri(target), {}
            except (OSError, ValueError):
                pass  # record points at storage that no longer verifies
        return self.resume_checkpoint, {}

    # -- fit ------------------------------------------------------------------
    def fit(self) -> Result:
        from ..core import metrics_defs as mdefs

        run_name = self.run_config.name or f"run_{int(time.time())}"
        run_dir = os.path.join(self.run_config.storage_path, run_name)
        os.makedirs(run_dir, exist_ok=True)
        cc = self.run_config.checkpoint_config
        manager = AsyncCheckpointManager(
            run_dir, retain_k=cc.num_to_keep, mode=cc.mode,
            storage_uri=cc.storage_uri,
            on_durable=lambda info: self._record_run_state(run_name, info),
        )

        history: List[Dict[str, Any]] = []
        latest_ckpt, rank_states = self._resolve_resume(manager, run_name)
        latest_holder: List[Optional[Checkpoint]] = [latest_ckpt]
        pending_shards: Dict[int, bytes] = {}

        def on_report(batch: List[dict]) -> None:
            # absorb every non-zero rank's shard first: the executor
            # drains workers in rank order, so a batch can carry rank 0's
            # step-N trigger ahead of rank 1's step-N shard — the save
            # must see the freshest peer shards the batch contains
            for item in batch:
                if item["rank"] != 0 and item.get("checkpoint"):
                    pending_shards[item["rank"]] = item["checkpoint"]
            for item in batch:
                if item["rank"] != 0:
                    continue
                history.append(item["metrics"])
                if item.get("checkpoint"):
                    # rank 0 (the model shard) completes the set and
                    # triggers the durable save; peer shards persist in
                    # pending_shards across saves so every checkpoint
                    # dir carries the newest known loader state per rank
                    pending_shards[0] = item["checkpoint"]
                    step = item["metrics"].get("step", len(history))
                    manager.save(dict(pending_shards), int(step))
                    latest_holder[0] = Checkpoint.from_bytes(
                        item["checkpoint"])

        bundle = self.scaling.bundle()
        desired = self.scaling.num_workers
        elastic = self.elastic
        emin = max(1, elastic.min_workers) if elastic else desired
        emax = (elastic.max_workers or desired) if elastic else desired
        world = max(emin, min(emax, desired))

        fc = self.run_config.failure_config
        failures_left = fc.max_failures
        if elastic and fc.max_failures == 0:
            failures_left = elastic.max_restarts

        if elastic:
            # pin the demand floor so an autoscaler Monitor replaces dead
            # nodes even while no tasks are queued (sdk request_resources)
            try:
                from ..autoscaler import request_resources

                request_resources([dict(bundle)] * min(emax, desired))
            except Exception:  # noqa: BLE001
                pass

        last_probe = [0.0]

        def make_watcher(current_world: int):
            if not elastic or current_world >= emax:
                return None

            def watcher() -> Optional[int]:
                now = time.monotonic()
                if now - last_probe[0] < elastic.resize_check_interval_s:
                    return None
                last_probe[0] = now
                rt = _runtime_or_none()
                if rt is None:
                    return None
                spare = placeable_world_size(
                    bundle, emax - current_world, runtime=rt)
                if spare > 0:
                    return min(emax, current_world + spare)
                return None

            return watcher

        def resume_point() -> None:
            """Refresh (latest_holder, rank_states) from the newest
            DURABLE checkpoint — the restart contract: at most one
            checkpoint interval of progress is lost."""
            nonlocal rank_states
            manager.drain()
            rec = manager.latest()
            if rec is not None:
                latest_holder[0] = rec["checkpoint"]
                rank_states = dict(rec["rank_states"])

        error: Optional[BaseException] = None
        try:
            while True:
                executor = BackendExecutor(
                    world,
                    bundle,
                    self.scaling.placement_strategy,
                    collective_backend=self.scaling.collective_backend,
                )
                try:
                    executor.start()
                    executor.run(
                        self.train_loop, self.config, latest_holder[0],
                        self._shards(world), on_report,
                        rank_states=rank_states,
                        world_watcher=make_watcher(world),
                    )
                    error = None
                    break
                except ElasticResize as e:
                    # capacity grew back: rebuild bigger; NOT a failure
                    executor.shutdown()
                    try:
                        mdefs.train_elastic_resizes().inc(tags={
                            "direction":
                            "up" if e.target_world > world else "down"})
                    except Exception:  # noqa: BLE001
                        pass
                    world = e.target_world
                    resume_point()
                    continue
                except (TrainingFailedError, ActorError, TaskError,
                        WorkerCrashedError, NodeDeadError) as e:
                    # start() can hit a node that is dying but not yet
                    # marked dead (rebuild racing death detection) — the
                    # raw runtime failure joins the same retry path as a
                    # failure surfaced from run()
                    error = (e if isinstance(e, TrainingFailedError)
                             else TrainingFailedError(str(e)))
                    if failures_left <= 0:
                        break
                    failures_left -= 1
                    # release the dead group's leases BEFORE sizing the
                    # rebuild off available capacity
                    executor.shutdown()
                    if elastic:
                        new_world = self._await_capacity(
                            bundle, emin, min(emax, world), elastic)
                        if new_world < emin:
                            break  # cluster can no longer host the run
                        if new_world != world:
                            try:
                                mdefs.train_elastic_resizes().inc(tags={
                                    "direction": "up"
                                    if new_world > world else "down"})
                            except Exception:  # noqa: BLE001
                                pass
                        world = new_world
                    resume_point()
                    continue
                finally:
                    executor.shutdown()
        finally:
            if elastic:
                try:
                    from ..autoscaler import request_resources

                    request_resources([])
                except Exception:  # noqa: BLE001
                    pass
            manager.close()

        return Result(
            metrics=history[-1] if history else {},
            checkpoint=latest_holder[0],
            metrics_history=history,
            error=error,
            path=run_dir,
        )

    @staticmethod
    def _await_capacity(bundle: Dict[str, float], emin: int, cap: int,
                        elastic: ElasticConfig) -> int:
        """Poll briefly for at least ``emin`` placeable bundles after a
        failure (failure detection + autoscaler replacement race this);
        returns the best world ≤ cap seen before the settle deadline —
        dip now, let the watcher grow the gang back later."""
        deadline = time.monotonic() + elastic.settle_s
        best = 0
        while True:
            rt = _runtime_or_none()
            if rt is not None:
                best = placeable_world_size(bundle, cap, runtime=rt)
                if best >= cap:
                    return best
            if time.monotonic() >= deadline:
                return best
            time.sleep(0.2)
