"""Trainers: the user-facing fit() surface.

Mirrors the reference's trainer stack (train/base_trainer.py:328 fit,
train/data_parallel_trainer.py:52,314) re-targeted for jax:

    trainer = JaxTrainer(
        train_loop_per_worker,
        train_loop_config={...},
        scaling_config=ScalingConfig(num_workers=4, chips_per_worker=4),
        run_config=RunConfig(name="run", storage_path=...),
        datasets={"train": ds},
    )
    result = trainer.fit()

Unlike the reference, fit() does NOT detour through the Tune trial runner
(base_trainer.py:354 wraps every trainer as a Tune trainable); the tune/
library composes the other way around (Tuner runs trainers), which keeps the
single-run path dependency-free. Failure handling matches FailureConfig:
worker-group restart from the latest checkpoint, max_failures times.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, Callable, Dict, List, Optional

from .backend_executor import BackendExecutor, TrainingFailedError
from .checkpoint import Checkpoint


@dataclasses.dataclass
class ScalingConfig:
    """air/config.py ScalingConfig analog, TPU-first: ``chips_per_worker``
    replaces GPUs-per-worker; a worker is a host-process."""

    num_workers: int = 1
    use_tpu: bool = False
    chips_per_worker: int = 0
    resources_per_worker: Optional[Dict[str, float]] = None
    placement_strategy: str = "PACK"
    # cross-worker gradient plane: "objstore" (CPU collective group) or
    # "xla" (jax.distributed world — one global mesh spanning all worker
    # processes; gradient sync rides XLA collectives over ICI/DCN)
    collective_backend: str = "objstore"

    def bundle(self) -> Dict[str, float]:
        res = dict(self.resources_per_worker or {})
        res.setdefault("CPU", 1)
        if self.use_tpu and self.chips_per_worker and "TPU" not in res:
            res["TPU"] = self.chips_per_worker
        return res


@dataclasses.dataclass
class FailureConfig:
    max_failures: int = 0


@dataclasses.dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: str = "/tmp/rmt_runs"
    failure_config: FailureConfig = dataclasses.field(
        default_factory=FailureConfig)


@dataclasses.dataclass
class Result:
    """air Result analog: final metrics + checkpoint + full history."""

    metrics: Dict[str, Any]
    checkpoint: Optional[Checkpoint]
    metrics_history: List[Dict[str, Any]]
    error: Optional[BaseException] = None
    path: Optional[str] = None


class JaxTrainer:
    """Data-parallel jax trainer (DataParallelTrainer analog)."""

    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[dict] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
    ):
        self.train_loop = train_loop_per_worker
        self.config = train_loop_config
        self.scaling = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.datasets = datasets or {}
        self.resume_checkpoint = resume_from_checkpoint

    # -- dataset sharding -----------------------------------------------------
    def _shards(self) -> Optional[List[Any]]:
        if not self.datasets:
            return None
        n = self.scaling.num_workers
        shards: List[Dict[str, Any]] = [dict() for _ in range(n)]
        for name, ds in self.datasets.items():
            if hasattr(ds, "split"):
                parts = ds.split(n)
            else:
                parts = [ds] * n
            for i in range(n):
                shards[i][name] = parts[i]
        return shards

    # -- fit ------------------------------------------------------------------
    def fit(self) -> Result:
        run_name = self.run_config.name or f"run_{int(time.time())}"
        run_dir = os.path.join(self.run_config.storage_path, run_name)
        os.makedirs(run_dir, exist_ok=True)

        history: List[Dict[str, Any]] = []
        latest_ckpt: List[Optional[Checkpoint]] = [self.resume_checkpoint]
        ckpt_index = [0]

        def on_report(batch: List[dict]) -> None:
            for item in batch:
                if item["rank"] == 0:
                    history.append(item["metrics"])
                if item.get("checkpoint") and item["rank"] == 0:
                    ckpt = Checkpoint.from_bytes(item["checkpoint"])
                    path = os.path.join(
                        run_dir, f"checkpoint_{ckpt_index[0]:06d}")
                    ckpt.to_directory(path)
                    ckpt_index[0] += 1
                    latest_ckpt[0] = Checkpoint.from_directory(path)

        failures_left = self.run_config.failure_config.max_failures
        error: Optional[BaseException] = None
        while True:
            executor = BackendExecutor(
                self.scaling.num_workers,
                self.scaling.bundle(),
                self.scaling.placement_strategy,
                collective_backend=self.scaling.collective_backend,
            )
            try:
                executor.start()
                executor.run(
                    self.train_loop, self.config, latest_ckpt[0],
                    self._shards(), on_report,
                )
                error = None
                break
            except TrainingFailedError as e:
                error = e
                if failures_left > 0:
                    failures_left -= 1
                    # elastic restart from the latest checkpoint (the
                    # reference restarts failed workers the same way)
                    continue
                break
            finally:
                executor.shutdown()

        return Result(
            metrics=history[-1] if history else {},
            checkpoint=latest_ckpt[0],
            metrics_history=history,
            error=error,
            path=run_dir,
        )
