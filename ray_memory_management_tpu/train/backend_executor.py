"""BackendExecutor + WorkerGroup: driver-side machinery behind a Trainer.

Mirrors the reference's train/_internal/backend_executor.py:42 and
worker_group.py:91 — create a placement group for the gang (:137), start one
actor per worker (:178,335), run the backend's on_start hook (:127) (here:
objstore collective-group formation — the jax.distributed /
_setup_torch_process_group analog, train/torch/config.py:54), ship the user
loop (:275,356-360), and drain per-worker result queues
(train/_internal/session.py:144 → get_next_results, backend_executor.py:362).

TPU mapping: each TrainWorker is a host-process actor; ``chips_per_worker``
TPU chips are leased to it (TPU_VISIBLE_CHIPS), and inside the loop the user
builds meshes over the worker's local chips with parallel.make_mesh. Data
parallelism ACROSS workers rides the collective group exposed via
``session_collective_group_name``.
"""

from __future__ import annotations

import time
import uuid
from typing import Any, Callable, Dict, List, Optional

from .. import api
from ..exceptions import (ActorError, NodeDeadError, RmtError, TaskError,
                          WorkerCrashedError)
from .checkpoint import Checkpoint


class TrainingFailedError(RmtError):
    pass


class ElasticResize(RmtError):
    """Raised out of BackendExecutor.run when the elastic world watcher
    wants a DIFFERENT world size (capacity grew back after a downsize).
    Not a failure: the trainer rebuilds the group at ``target_world`` and
    resumes from the latest checkpoint without consuming failure budget."""

    def __init__(self, target_world: int):
        super().__init__(f"elastic resize to world={target_world}")
        self.target_world = target_world


def placeable_world_size(bundle: Dict[str, Any], cap: int,
                         runtime=None) -> int:
    """How many copies of ``bundle`` the cluster can place RIGHT NOW
    (greedy first-fit over alive nodes' available resources), capped at
    ``cap``. This is the elastic trainer's sizing signal after a node
    death — rebuild the gang at whatever the surviving nodes can hold —
    and its recovery signal once the autoscaler replaces the node."""
    from .. import _worker_context
    from ..core.resources import Resources

    rt = runtime or _worker_context.get_runtime()
    req = Resources(dict(bundle) or {"CPU": 1})
    with rt._lock:
        nodes = [nm for nm in rt.nodes.values() if nm.alive]
        frees = [Resources.from_fixed(nm.resources.available.fixed())
                 for nm in nodes]
    count = 0
    while count < cap:
        for i, free in enumerate(frees):
            if req.fits_in(free):
                frees[i] = free - req
                count += 1
                break
        else:
            break
    return count


def partition_chips_for_host(n_chips: int, n_workers: int,
                             exclude: Optional[set] = None) -> List[str]:
    """Split a host's chips into ``n_workers`` DISJOINT contiguous slices
    covering every available chip (sizes differ by at most one when the
    count does not divide evenly). One process per host is the preferred
    TPU layout (SURVEY §7); when a gang does co-locate processes, each
    must own its slice outright — TPU runtimes cannot time-share a chip
    between jax.distributed processes. ``exclude`` removes chips already
    leased to sibling workers through the scheduler."""
    chips = [c for c in range(n_chips) if not exclude or c not in exclude]
    if n_workers > len(chips):
        raise TrainingFailedError(
            f"{n_workers} xla-mode workers share a host with only "
            f"{len(chips)} free chips; use at most one worker per chip "
            "(or one worker per host controlling all its chips)")
    base, extra = divmod(len(chips), n_workers)
    out, pos = [], 0
    for i in range(n_workers):
        take = base + (1 if i < extra else 0)
        out.append(",".join(str(c) for c in chips[pos:pos + take]))
        pos += take
    return out


class _TrainWorkerImpl:
    """The per-worker actor (RayTrainWorker analog, worker_group.py:335)."""

    def __init__(self, rank: int, world_size: int, group_name: str):
        import os

        self.rank = rank
        self.world_size = world_size
        self.group_name = group_name
        os.environ["RMT_TRAIN_RANK"] = str(rank)
        os.environ["RMT_TRAIN_WORLD"] = str(world_size)
        os.environ["RMT_TRAIN_GROUP"] = group_name

    def _rmt_init_collective(self, world_size, rank, backend, group_name):
        from ..collective import init_collective_group

        init_collective_group(world_size, rank, backend, group_name)
        return True

    def _rmt_host_info(self) -> dict:
        """Where this worker runs and what chips it already leased — the
        input to the head's per-host chip partitioning."""
        import os

        return {
            "node_id": os.environ.get("RMT_NODE_ID", ""),
            "visible_chips": os.environ.get("TPU_VISIBLE_CHIPS"),
        }

    def _rmt_set_visible_chips(self, chips_csv: str) -> bool:
        """Pin this worker to a disjoint chip subset BEFORE any jax backend
        initializes (the torch _share_cuda_visible_devices analog,
        train/backend_executor.py:195 + torch/config.py:108-156 — except
        TPU processes must own DISJOINT chips, so the head partitions
        rather than shares)."""
        import os

        os.environ["TPU_VISIBLE_CHIPS"] = chips_csv
        if os.environ.get("JAX_PLATFORMS") == "cpu":
            del os.environ["JAX_PLATFORMS"]
        return True

    def _rmt_pick_coordinator(self) -> str:
        """Rank-0 hook: choose the jax.distributed coordinator address on
        THIS worker's host (the reference's rank-0 addr/port selection for
        torch process groups, train/torch/config.py:108-156)."""
        import socket

        s = socket.socket()
        s.bind(("0.0.0.0", 0))
        port = s.getsockname()[1]
        s.close()
        # routable address of this host (agents may live on other machines);
        # a UDP connect learns the outbound interface without sending
        try:
            probe = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            probe.connect(("8.8.8.8", 53))
            host = probe.getsockname()[0]
            probe.close()
        except OSError:
            host = "127.0.0.1"
        return f"{host}:{port}"

    def _rmt_init_jax_world(self, coordinator: str, world: int,
                            rank: int) -> int:
        """Form one global jax world across the worker processes
        (jax.distributed.initialize — the NCCLUniqueID-rendezvous /
        _setup_torch_process_group analog, SURVEY §2.3). Must run before
        this process initializes any jax backend; afterwards jax.devices()
        is the GLOBAL device list and one jit program spans every worker."""
        import sys

        jax_mod = sys.modules.get("jax")
        if jax_mod is not None:
            try:
                if jax_mod._src.xla_bridge._backends:  # noqa: SLF001
                    raise RuntimeError(
                        "jax backends already initialized in this worker; "
                        "xla cross-worker mode requires a fresh process")
            except AttributeError:
                pass
        import jax

        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=world, process_id=rank)
        return jax.device_count()

    def run_loop(self, loop_blob: bytes, config: Optional[dict],
                 checkpoint_blob: Optional[bytes], dataset_shard,
                 rank_state_blob: Optional[bytes] = None) -> bool:
        """Execute the user's train_loop_per_worker to completion. Runs on
        one actor thread while next_results() is served on another
        (max_concurrency=2 — the reference pairs a train thread with the
        session queue the same way)."""
        import pickle

        import cloudpickle

        from . import session as session_mod

        rank_state = (pickle.loads(rank_state_blob)
                      if rank_state_blob else None)
        # init the session before anything that can fail or block, so a
        # concurrent next_results() poll never mistakes "not started yet"
        # for "finished" (it reports None only after s.finished is set)
        s = session_mod.init_session(
            world_rank=self.rank, world_size=self.world_size,
            checkpoint=None, dataset_shard=dataset_shard,
            rank_state=rank_state,
        )
        try:
            loop = cloudpickle.loads(loop_blob)
            s.loaded_checkpoint = (
                Checkpoint.from_bytes(checkpoint_blob)
                if checkpoint_blob else None
            )
            if config is not None:
                loop(config)
            else:
                loop()
            return True
        except BaseException as e:
            s.error = e
            raise
        finally:
            s.finished.set()

    def next_results(self, timeout_s: float = 1.0) -> Optional[List[dict]]:
        """Drain queued session.report() payloads; None once the loop has
        finished and the queue is empty. Checkpoints travel as bytes."""
        import queue as queue_mod

        from . import session as session_mod

        try:
            s = session_mod.get_session()
        except RuntimeError:
            return []  # run_loop hasn't started yet — poll again
        out: List[dict] = []
        deadline = time.monotonic() + timeout_s
        while True:
            try:
                item = s.queue.get(timeout=max(0.0, deadline -
                                               time.monotonic()))
            except queue_mod.Empty:
                break
            ckpt = item.get("checkpoint")
            item["checkpoint"] = ckpt.to_bytes() if ckpt else None
            out.append(item)
            if not s.queue.empty():
                continue
            break
        if not out and s.finished.is_set() and s.queue.empty():
            return None
        return out


class WorkerGroup:
    def __init__(self, num_workers: int, resources_per_worker: Dict[str, Any],
                 placement_strategy: str = "PACK"):
        from ..core.placement_group import placement_group

        self.num_workers = num_workers
        self.group_name = f"train_{uuid.uuid4().hex[:8]}"
        bundle = dict(resources_per_worker) or {"CPU": 1}
        self.pg = placement_group([bundle] * num_workers,
                                  strategy=placement_strategy)
        if not self.pg.wait(60):
            raise TrainingFailedError(
                f"placement group for {num_workers} workers "
                f"({bundle} each) could not be scheduled"
            )
        cls = api.remote(_TrainWorkerImpl)
        self.actors = []
        for rank in range(num_workers):
            self.actors.append(
                cls.options(
                    max_concurrency=2,
                    num_cpus=resources_per_worker.get("CPU", 1),
                    num_tpus=resources_per_worker.get("TPU", 0),
                    placement_group=self.pg,
                    placement_group_bundle_index=rank,
                ).remote(rank, num_workers, self.group_name)
            )

    def setup_collective(self) -> None:
        from ..collective import create_collective_group

        create_collective_group(
            self.actors, self.num_workers, list(range(self.num_workers)),
            backend="objstore", group_name=self.group_name,
        )

    def partition_chips(self) -> None:
        """Give xla-mode workers sharing a host DISJOINT TPU_VISIBLE_CHIPS.

        Workers that leased chips through the scheduler (num_tpus>0)
        already hold disjoint sets; this covers the bare-CPU-request case
        where two xla workers on one TPU host would otherwise both claim
        every local chip when jax.distributed initializes (VERDICT r2
        item 7; reference analog _share_cuda_visible_devices,
        train/backend_executor.py:195)."""
        from ..state.api import list_nodes

        infos = api.get([a._rmt_host_info.remote() for a in self.actors],
                        timeout=120)
        totals = {row["node_id"]: int(
            row["resources_total"].get("TPU", 0) or 0)
            for row in list_nodes()}
        by_node: Dict[str, List[int]] = {}
        for rank, info in enumerate(infos):
            by_node.setdefault(info["node_id"], []).append(rank)
        calls = []
        for node_id, ranks in by_node.items():
            n_chips = totals.get(node_id, 0)
            if n_chips <= 0:
                continue  # CPU-only host: nothing to partition
            # workers whose scheduler lease already pinned chips keep
            # them; the UNLEASED siblings must still be fenced off those
            # chips, or their jax.distributed init claims the whole host
            leased_chips: set = set()
            unleased: List[int] = []
            for r in ranks:
                csv = infos[r]["visible_chips"]
                if csv:
                    leased_chips.update(int(c) for c in csv.split(","))
                else:
                    unleased.append(r)
            if not unleased:
                continue
            slices = partition_chips_for_host(n_chips, len(unleased),
                                              exclude=leased_chips)
            for csv, rank in zip(slices, sorted(unleased)):
                calls.append(
                    self.actors[rank]._rmt_set_visible_chips.remote(csv))
        if calls:
            api.get(calls, timeout=120)

    def setup_xla_world(self) -> int:
        """Cross-worker XLA mode: every worker process joins one
        jax.distributed world so the user loop jits over ONE global mesh —
        gradients sync through XLA collectives (ICI/DCN), never the object
        plane. Returns the global device count."""
        self.partition_chips()
        coordinator = api.get(
            self.actors[0]._rmt_pick_coordinator.remote(), timeout=120)
        counts = api.get(
            [a._rmt_init_jax_world.remote(coordinator, self.num_workers, r)
             for r, a in enumerate(self.actors)],
            timeout=300,
        )
        if len(set(counts)) != 1:
            raise TrainingFailedError(
                f"workers disagree on global device count: {counts}")
        return counts[0]

    def shutdown(self) -> None:
        from ..core.placement_group import remove_placement_group

        for a in self.actors:
            try:
                api.kill(a)
            except Exception:
                pass
        try:
            from ..collective.coordinator import destroy_coordinator

            destroy_coordinator(self.group_name)
        except Exception:
            pass
        remove_placement_group(self.pg)


class BackendExecutor:
    def __init__(self, num_workers: int,
                 resources_per_worker: Optional[Dict[str, Any]] = None,
                 placement_strategy: str = "PACK",
                 use_collective: bool = True,
                 collective_backend: str = "objstore"):
        self.num_workers = num_workers
        self.resources_per_worker = resources_per_worker or {"CPU": 1}
        self.placement_strategy = placement_strategy
        self.use_collective = use_collective and num_workers > 1
        self.collective_backend = collective_backend
        self.group: Optional[WorkerGroup] = None

    def start(self) -> None:
        self.group = WorkerGroup(
            self.num_workers, self.resources_per_worker,
            self.placement_strategy,
        )
        if self.use_collective:
            if self.collective_backend == "xla":
                self.group.setup_xla_world()
            else:
                self.group.setup_collective()

    def run(
        self,
        train_loop: Callable,
        config: Optional[dict],
        checkpoint: Optional[Checkpoint],
        dataset_shards: Optional[List[Any]] = None,
        on_report: Optional[Callable[[List[dict]], None]] = None,
        poll_interval_s: float = 0.2,
        rank_states: Optional[Dict[int, bytes]] = None,
        world_watcher: Optional[Callable[[], Optional[int]]] = None,
    ) -> None:
        """Ship the loop to every worker and drain reports until all loops
        complete. Raises TrainingFailedError on worker failure (a dead
        worker, actor, or NODE — the PR-3 agent-death plumbing surfaces
        all three as errors on the polled refs) and ElasticResize when
        ``world_watcher`` returns a different target world size.

        ``rank_states`` hands each rank its restored loader-state shard
        (session.get_rank_state()); ranks absent from the dict start
        fresh."""
        from ..serialization import dumps_function

        assert self.group is not None, "call start() first"
        loop_blob = dumps_function(train_loop)
        ckpt_blob = checkpoint.to_bytes() if checkpoint else None
        shards = dataset_shards or [None] * self.num_workers
        states = rank_states or {}
        done_refs = [
            a.run_loop.remote(loop_blob, config, ckpt_blob, shards[i],
                              states.get(i))
            for i, a in enumerate(self.group.actors)
        ]
        live = set(range(self.num_workers))
        batches: List[dict] = []

        def flush() -> None:
            # deliver everything collected this round before any error can
            # propagate — a healthy worker's checkpoint must not be lost
            # because a peer died mid-round
            if batches and on_report is not None:
                on_report(list(batches))
            batches.clear()

        try:
            while live:
                if world_watcher is not None:
                    target = world_watcher()
                    if target is not None and target != self.num_workers:
                        flush()
                        raise ElasticResize(target)
                refs = [
                    (i, self.group.actors[i].next_results.remote(0.5))
                    for i in sorted(live)
                ]
                for i, ref in refs:
                    res = api.get(ref, timeout=120)
                    if res is None:
                        live.discard(i)
                    elif res:
                        batches.extend(res)
                    else:
                        # empty batch: either the loop hasn't started or it
                        # died before init_session (e.g. a shard failed to
                        # deserialize). If run_loop already finished, a final
                        # drain is safe and prevents polling forever.
                        ready, _ = api.wait([done_refs[i]], timeout=0)
                        if ready:
                            api.get(done_refs[i])  # surfaces loop errors
                            final = api.get(
                                self.group.actors[i].next_results.remote(0.0),
                                timeout=120,
                            )
                            if final:
                                batches.extend(final)
                            live.discard(i)
                flush()
                if live:
                    time.sleep(poll_interval_s)
            # surface loop errors (worker finished exceptionally)
            api.get(done_refs, timeout=60)
        except (ActorError, TaskError, WorkerCrashedError,
                NodeDeadError) as e:
            flush()
            raise TrainingFailedError(str(e)) from e

    def shutdown(self) -> None:
        if self.group is not None:
            self.group.shutdown()
            self.group = None
