"""Train session: the API surface visible inside a user's train loop.

Mirrors the reference's air.session (python/ray/air/session.py:12,64,221 —
report / get_checkpoint / get_world_rank / get_world_size /
get_dataset_shard) backed by the per-worker _TrainSession queue
(train/_internal/session.py:54,144,261): ``report`` enqueues results that the
driver-side BackendExecutor drains between rounds.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Dict, Optional

from .checkpoint import Checkpoint


class _TrainSession:
    def __init__(self, world_rank: int, world_size: int,
                 checkpoint: Optional[Checkpoint], dataset_shard=None,
                 trial_info: Optional[dict] = None,
                 rank_state: Optional[Dict[str, Any]] = None):
        self.world_rank = world_rank
        self.world_size = world_size
        self.queue: "queue.Queue" = queue.Queue()
        self.loaded_checkpoint = checkpoint
        self.dataset_shard = dataset_shard
        self.trial_info = trial_info or {}
        # per-rank loader state (step, rng, dataset offset) restored from
        # the sharded checkpoint on elastic resume — see get_rank_state()
        self.loaded_rank_state = rank_state
        self.finished = threading.Event()
        self.error: Optional[BaseException] = None


_session: Optional[_TrainSession] = None
_lock = threading.Lock()


def init_session(**kwargs) -> _TrainSession:
    global _session
    with _lock:
        _session = _TrainSession(**kwargs)
        return _session


def get_session() -> _TrainSession:
    if _session is None:
        raise RuntimeError(
            "no train session: this API is only valid inside a train loop"
        )
    return _session


def shutdown_session() -> None:
    global _session
    with _lock:
        _session = None


# -- public api (air/session.py surface) --------------------------------------
def report(metrics: Dict[str, Any],
           checkpoint: Optional[Checkpoint] = None) -> None:
    """Stream metrics (and optionally a checkpoint) to the driver."""
    s = get_session()
    s.queue.put({"metrics": dict(metrics), "checkpoint": checkpoint,
                 "rank": s.world_rank})


def get_checkpoint() -> Optional[Checkpoint]:
    return get_session().loaded_checkpoint


def get_rank_state() -> Optional[Dict[str, Any]]:
    """This rank's data-loader state (step, rng, dataset offset, ...) as
    restored from the latest sharded checkpoint, or None on a fresh start.
    The loop saves it by passing its state dict to ``report(...,
    checkpoint=...)`` on every rank — rank 0's checkpoint is the model,
    every other rank's dict rides the same durable save as a shard.

    After an ELASTIC resize the world size may differ from the one that
    wrote the state: ranks beyond the old world get None, and the loop
    re-derives its shard offsets from (step, world_size)."""
    return get_session().loaded_rank_state


def get_loader_state() -> Optional[Dict[str, Any]]:
    """Alias for :func:`get_rank_state`."""
    return get_rank_state()


def get_world_rank() -> int:
    return get_session().world_rank


def get_world_size() -> int:
    return get_session().world_size


def get_local_rank() -> int:
    return get_session().world_rank  # one worker per host-process


def get_dataset_shard(name: str = "train"):
    shard = get_session().dataset_shard
    if isinstance(shard, dict):
        return shard.get(name)
    return shard


def get_collective_group_name() -> str:
    """Name of the cross-worker collective group the BackendExecutor formed
    (usable with collective.allreduce etc. — the process-group handle of
    train/torch/config.py:54 in the reference)."""
    import os

    return os.environ.get("RMT_TRAIN_GROUP", "default")


def get_trial_name() -> str:
    return get_session().trial_info.get("name", "default")


def get_trial_id() -> str:
    return get_session().trial_info.get("id", "default")
