"""Checkpoint: a value-semantic handle convertible between dict / directory /
bytes / URI forms, plus the driver-side AsyncCheckpointManager.

Mirrors the reference's AIR Checkpoint (python/ray/air/checkpoint.py:42 —
from_dict:215/to_dict:239, from_directory:327/to_directory:432,
from_bytes:536/to_bytes:551, from_uri/to_uri). jax pytrees (params/opt state)
are stored via orbax when saved to a directory, so TPU-sharded trees
round-trip correctly; plain picklable state rides cloudpickle.

Durability model (the preemption-tolerance contract):

- ``to_directory`` is ATOMIC: payload lands in a ``.tmp-*`` sibling, a
  MANIFEST.json with per-file CRC32s is written last, and the sibling is
  renamed into place. A crash mid-save leaves either the previous valid
  directory or a ``.tmp-*`` orphan — never a half-written directory that
  ``from_directory`` would happily load.
- ``to_uri``/``from_uri`` route every non-``file://`` scheme through the
  ``core.external_storage`` registry (CloudStorage for s3://gs://), so
  object-store IO code lives in exactly one place.
- :class:`AsyncCheckpointManager` drains durable writes on a background
  thread (training steps keep running), retains the last K checkpoints,
  verifies manifests on restore (falling back to the previous checkpoint
  on CRC mismatch), and mirrors to a cloud tier when configured.
"""

from __future__ import annotations

import json
import os
import pickle
import queue
import shutil
import tempfile
import threading
import time
import uuid
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

_PYTREE_KEY = "__rmt_pytree__"
_SKELETON_KEY = "__rmt_pytree_skeleton__"
_PICKLE_FILE = "checkpoint.pkl"
_ORBAX_DIR = "pytree"
_MANIFEST = "MANIFEST.json"
_RANK_STATES_FILE = "rank_states.pkl"
_MANIFEST_FORMAT = 1


# -- manifest / atomicity helpers ---------------------------------------------
def _iter_files(root: str):
    for dirpath, _dirs, files in os.walk(root):
        for name in files:
            full = os.path.join(dirpath, name)
            yield os.path.relpath(full, root), full


def write_manifest(path: str, **meta: Any) -> None:
    """Write MANIFEST.json over every file currently in ``path`` (CRC32 +
    size per file). Written LAST during a save: its presence certifies the
    payload, its checksums catch torn/corrupted files on restore."""
    files: Dict[str, Dict[str, int]] = {}
    for rel, full in _iter_files(path):
        if rel == _MANIFEST:
            continue
        crc = 0
        size = 0
        with open(full, "rb") as f:
            while True:
                chunk = f.read(1 << 20)
                if not chunk:
                    break
                crc = zlib.crc32(chunk, crc)
                size += len(chunk)
        files[rel] = {"crc32": crc & 0xFFFFFFFF, "size": size}
    doc = {"format": _MANIFEST_FORMAT, "files": files}
    doc.update(meta)
    tmp = os.path.join(path, _MANIFEST + ".tmp")
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, os.path.join(path, _MANIFEST))


def read_manifest(path: str) -> Optional[Dict[str, Any]]:
    try:
        with open(os.path.join(path, _MANIFEST)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def verify_checkpoint_dir(path: str) -> Tuple[bool, str]:
    """(ok, reason): the directory has a manifest and every listed file is
    present with a matching CRC32. A directory that fails is treated as
    LOSS — the caller falls back to an older checkpoint, never loads
    corrupt state."""
    doc = read_manifest(path)
    if doc is None:
        return False, "missing or unreadable MANIFEST.json"
    for rel, want in doc.get("files", {}).items():
        full = os.path.join(path, rel)
        try:
            crc = 0
            size = 0
            with open(full, "rb") as f:
                while True:
                    chunk = f.read(1 << 20)
                    if not chunk:
                        break
                    crc = zlib.crc32(chunk, crc)
                    size += len(chunk)
        except OSError:
            return False, f"missing file {rel}"
        if size != want.get("size") or (crc & 0xFFFFFFFF) != want.get("crc32"):
            return False, f"checksum mismatch on {rel}"
    return True, "ok"


def _replace_dir(tmp: str, final: str) -> None:
    """Swap a fully-written ``tmp`` directory into ``final``. When final
    does not exist this is one atomic rename; when it does, the old tree
    is moved aside first and removed only after the new one is in place —
    the old checkpoint is never destroyed before the new one is durable."""
    if not os.path.isdir(final):
        try:
            os.rename(tmp, final)
            return
        except OSError:
            pass  # lost a creation race; fall through to the swap path
    old = f"{final}.old-{uuid.uuid4().hex[:8]}"
    os.rename(final, old)
    try:
        os.rename(tmp, final)
    except OSError:
        os.rename(old, final)  # restore the previous valid directory
        raise
    shutil.rmtree(old, ignore_errors=True)


class Checkpoint:
    def __init__(self, data: Optional[Dict[str, Any]] = None,
                 directory: Optional[str] = None):
        self._data = data
        self._directory = directory

    # -- constructors ---------------------------------------------------------
    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Checkpoint":
        return cls(data=dict(data))

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(directory=os.path.abspath(path))

    @classmethod
    def from_bytes(cls, blob: bytes) -> "Checkpoint":
        return cls(data=pickle.loads(blob))

    @classmethod
    def from_uri(cls, uri: str) -> "Checkpoint":
        """Load from file://, a bare path, or any scheme registered with
        ``core.external_storage`` (s3://, gs://, ...). Cloud checkpoints
        download into a temp directory and verify their manifest."""
        if uri.startswith("file://"):
            return cls.from_directory(uri[len("file://"):])
        if "://" not in uri:
            return cls.from_directory(uri)
        local = download_checkpoint_uri(uri)
        ok, why = verify_checkpoint_dir(local)
        if not ok:
            raise ValueError(f"checkpoint at {uri!r} failed verification: "
                             f"{why}")
        return cls.from_directory(local)

    # -- conversions ----------------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        if self._data is not None:
            return dict(self._data)
        assert self._directory is not None
        out: Dict[str, Any] = {}
        pkl = os.path.join(self._directory, _PICKLE_FILE)
        if os.path.exists(pkl):
            with open(pkl, "rb") as f:
                out.update(pickle.load(f))
        orbax_path = os.path.join(self._directory, _ORBAX_DIR)
        if os.path.exists(orbax_path):
            import jax
            import numpy as np
            import orbax.checkpoint as ocp

            # restore as host numpy; consumers re-shard with parallel.
            # shard_pytree for their own mesh. The saved skeleton supplies
            # the tree structure orbax needs for restore_args.
            skeleton = out.pop(_SKELETON_KEY, None)
            with ocp.PyTreeCheckpointer() as ckptr:
                if skeleton is not None:
                    restore_args = jax.tree.map(
                        lambda _: ocp.RestoreArgs(restore_type=np.ndarray),
                        skeleton,
                    )
                    out[_PYTREE_KEY] = ckptr.restore(
                        orbax_path, restore_args=restore_args)
                else:
                    out[_PYTREE_KEY] = ckptr.restore(orbax_path)
        return out

    def _materialize(self, path: str) -> None:
        """Write this checkpoint's payload into ``path`` (an existing
        private directory) — no manifest, no swap; the atomic wrapper is
        :meth:`to_directory`. The orbax subtree is itself written to a
        ``.tmp`` sibling and swapped so even a payload-level overwrite
        never destroys an old tree before the new save succeeds."""
        if self._directory is not None:
            if os.path.abspath(path) != self._directory:
                shutil.copytree(self._directory, path, dirs_exist_ok=True)
            return
        data = dict(self._data or {})
        pytree = data.pop(_PYTREE_KEY, None)
        if pytree is not None:
            import jax

            data[_SKELETON_KEY] = jax.tree.map(lambda _: 0, pytree)
        with open(os.path.join(path, _PICKLE_FILE), "wb") as f:
            import cloudpickle

            cloudpickle.dump(data, f)
        if pytree is not None:
            import orbax.checkpoint as ocp

            target = os.path.join(path, _ORBAX_DIR)
            tmp = f"{target}.tmp-{uuid.uuid4().hex[:8]}"
            with ocp.PyTreeCheckpointer() as ckptr:
                ckptr.save(tmp, pytree)
            _replace_dir(tmp, target)

    def to_directory(self, path: Optional[str] = None) -> str:
        """Materialize to a directory ATOMICALLY: payload + MANIFEST.json
        land in a ``.tmp-*`` sibling which is renamed into place, so a
        crash mid-save can never leave a half-written directory at
        ``path`` (the previous contents, if any, survive)."""
        if path is None:
            path = tempfile.mkdtemp(prefix="rmt_ckpt_")
            self._materialize(path)
            write_manifest(path)
            return path
        final = os.path.abspath(path)
        if self._directory is not None and final == self._directory:
            return final
        tmp = f"{final}.tmp-{uuid.uuid4().hex[:8]}"
        os.makedirs(tmp)
        try:
            self._materialize(tmp)
            write_manifest(tmp)
            _replace_dir(tmp, final)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        return final

    def to_bytes(self) -> bytes:
        import cloudpickle

        return cloudpickle.dumps(self.to_dict())

    def to_uri(self, uri: str) -> str:
        """Persist to file://, a bare path, or any scheme registered with
        ``core.external_storage`` (s3://, gs://, ...) — cloud schemes ride
        the CloudStorage blob surface, one key per checkpoint file."""
        if uri.startswith("file://"):
            self.to_directory(uri[len("file://"):])
            return uri
        if "://" not in uri:
            self.to_directory(uri)
            return f"file://{uri}"
        local = self._directory
        if local is None or not os.path.exists(
                os.path.join(local, _MANIFEST)):
            local = self.to_directory()
        upload_checkpoint_dir(local, uri)
        return uri

    # -- pytree sugar ---------------------------------------------------------
    @classmethod
    def from_pytree(cls, pytree, extra: Optional[Dict[str, Any]] = None
                    ) -> "Checkpoint":
        """Checkpoint carrying a jax pytree (params/opt state); saved with
        orbax on to_directory()."""
        data = dict(extra or {})
        data[_PYTREE_KEY] = pytree
        return cls(data=data)

    def get_pytree(self):
        return self.to_dict().get(_PYTREE_KEY)

    def __repr__(self):
        kind = "dict" if self._data is not None else f"dir:{self._directory}"
        return f"Checkpoint({kind})"


# -- uri transport (CloudStorage-backed) --------------------------------------
def _storage_for(uri: str):
    from ..core.external_storage import storage_for_uri

    return storage_for_uri(uri)


def upload_checkpoint_dir(local: str, uri: str) -> None:
    """Mirror a checkpoint directory to ``uri`` through the external-
    storage registry. The manifest uploads LAST — a reader that sees it
    can trust every other key is already there."""
    storage = _storage_for(uri)
    base = uri.rstrip("/")
    manifest_rel = None
    for rel, full in _iter_files(local):
        if rel == _MANIFEST:
            manifest_rel = (rel, full)
            continue
        with open(full, "rb") as f:
            storage.put_blob(f"{base}/{rel}", f.read())
    if manifest_rel is not None:
        rel, full = manifest_rel
        with open(full, "rb") as f:
            storage.put_blob(f"{base}/{rel}", f.read())


def download_checkpoint_uri(uri: str, dest: Optional[str] = None) -> str:
    """Fetch every blob under ``uri`` into a local directory."""
    storage = _storage_for(uri)
    base = uri.rstrip("/")
    urls = storage.list_blobs(base)
    if not urls:
        raise FileNotFoundError(f"no checkpoint found at {uri!r}")
    dest = dest or tempfile.mkdtemp(prefix="rmt_ckpt_dl_")
    for url in urls:
        rel = url[len(base):].lstrip("/")
        full = os.path.join(dest, rel)
        os.makedirs(os.path.dirname(full) or ".", exist_ok=True)
        with open(full, "wb") as f:
            f.write(storage.get_blob(url))
    return dest


def delete_checkpoint_uri(uri: str) -> None:
    storage = _storage_for(uri)
    storage.delete_prefix(uri.rstrip("/"))


# -- the async checkpoint manager ---------------------------------------------
class AsyncCheckpointManager:
    """Driver-side durable checkpoint writer for a training run.

    ``save()`` is the step-blocking slice: it snapshots the (already
    host-resident) per-rank shard bytes and enqueues them; a background
    writer thread does the durable work — atomic directory write with
    CRC32 manifest, optional mirror to a CloudStorage uri, retention GC,
    and the ``on_durable`` callback (the trainer records run state in the
    GCS kv there). Training steps keep running while the save drains.

    ``mode``:
      - "async": background writer (default);
      - "sync":  ``save()`` blocks until the checkpoint is durable — the
        baseline the bench compares against.

    Restore (:meth:`latest`) verifies manifests newest-first and falls
    back to the previous checkpoint on mismatch: a torn or corrupted
    newest checkpoint costs one extra interval of progress, never a
    poisoned resume.
    """

    def __init__(self, run_dir: str, *, retain_k: int = 3,
                 mode: str = "async", storage_uri: Optional[str] = None,
                 on_durable: Optional[Callable[[Dict[str, Any]], None]]
                 = None):
        if mode not in ("async", "sync"):
            raise ValueError(f"unknown checkpoint mode {mode!r}")
        self.run_dir = os.path.abspath(run_dir)
        os.makedirs(self.run_dir, exist_ok=True)
        self.retain_k = max(1, int(retain_k))
        self.mode = mode
        self.storage_uri = storage_uri.rstrip("/") if storage_uri else None
        self.on_durable = on_durable
        self.last_error: Optional[BaseException] = None
        self._q: "queue.Queue" = queue.Queue()
        self._mu = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._index = self._next_index()

    # -- directory scan -------------------------------------------------------
    def _dirs(self) -> List[str]:
        """checkpoint_* directories, oldest first."""
        try:
            names = sorted(
                n for n in os.listdir(self.run_dir)
                if n.startswith("checkpoint_") and ".tmp" not in n
                and ".old" not in n
                and os.path.isdir(os.path.join(self.run_dir, n)))
        except OSError:
            return []
        return [os.path.join(self.run_dir, n) for n in names]

    def _next_index(self) -> int:
        idx = 0
        for d in self._dirs():
            try:
                idx = max(idx, int(os.path.basename(d).split("_")[1]) + 1)
            except (IndexError, ValueError):
                continue
        return idx

    # -- save path ------------------------------------------------------------
    def save(self, shards: Dict[int, bytes], step: int) -> float:
        """Submit one checkpoint (per-rank shard bytes, rank 0 = model
        state) for durable write; returns the step-blocking seconds."""
        from ..core import metrics_defs as mdefs

        t0 = time.perf_counter()
        item = (dict(shards), int(step))
        if self.mode == "sync":
            try:
                self._write(*item)
            except BaseException as e:  # noqa: BLE001 - surfaced via state
                self._record_failure(e)
        else:
            self._ensure_thread()
            self._q.put(item)
        dt = time.perf_counter() - t0
        try:
            mdefs.train_checkpoint_save_seconds().observe(
                dt, tags={"phase": "blocking"})
        except Exception:  # noqa: BLE001
            pass
        return dt

    def _ensure_thread(self) -> None:
        with self._mu:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._writer_loop, daemon=True,
                    name="rmt-ckpt-writer")
                self._thread.start()

    def _writer_loop(self) -> None:
        while True:
            item = self._q.get()
            try:
                if item is None:
                    return
                # coalesce: if the trainer outran the writer, only the
                # NEWEST pending checkpoint matters (latest-wins); older
                # pending saves would be GC'd by retention immediately
                while True:
                    try:
                        nxt = self._q.get_nowait()
                    except queue.Empty:
                        break
                    if nxt is None:
                        self._q.put(None)
                        break
                    self._q.task_done()
                    item = nxt
                try:
                    self._write(*item)
                except BaseException as e:  # noqa: BLE001
                    self._record_failure(e)
            finally:
                self._q.task_done()

    def drain(self, timeout_s: float = 120.0) -> bool:
        """Block until every enqueued save is durable (or timeout)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self._q.unfinished_tasks == 0:  # noqa: SLF001 - stdlib attr
                return True
            time.sleep(0.02)
        return False

    def close(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            self.drain()
            self._q.put(None)
            self._thread.join(timeout=10.0)

    def _record_failure(self, e: BaseException) -> None:
        from ..core import metrics_defs as mdefs

        self.last_error = e
        try:
            mdefs.train_checkpoint_saves().inc(tags={"result": "error"})
        except Exception:  # noqa: BLE001
            pass
        try:
            from ..utils import events

            events.emit("CHECKPOINT_SAVE_FAILED",
                        f"checkpoint save failed: {e!r}",
                        severity=events.ERROR, source="train")
        except Exception:  # noqa: BLE001
            pass

    def _write(self, shards: Dict[int, bytes], step: int) -> None:
        from ..core import metrics_defs as mdefs
        from ..utils import faults

        t0 = time.perf_counter()
        act = faults.fire("checkpoint.save")
        if act is not None:
            if act.mode == "stall":
                act.sleep()
            elif act.mode in ("error", "drop"):
                act.raise_()
        with self._mu:
            idx = self._index
            self._index += 1
        name = f"checkpoint_{idx:06d}"
        final = os.path.join(self.run_dir, name)
        tmp = f"{final}.tmp-{uuid.uuid4().hex[:8]}"
        os.makedirs(tmp)
        try:
            rank0 = shards.get(0)
            if rank0 is not None:
                Checkpoint.from_bytes(rank0)._materialize(tmp)
            others = {r: b for r, b in shards.items() if r != 0}
            if others:
                with open(os.path.join(tmp, _RANK_STATES_FILE), "wb") as f:
                    pickle.dump(others, f)
            write_manifest(tmp, step=step, world_size=len(shards))
            if act is not None and act.mode == "corrupt":
                # flip one byte in the payload AFTER the manifest was
                # computed — only restore-time CRC verification can catch
                # this (the disk-corruption physics of spill.write)
                self._corrupt_one_file(tmp)
            _replace_dir(tmp, final)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        uri = None
        if self.storage_uri is not None:
            uri = f"{self.storage_uri}/{name}"
            upload_checkpoint_dir(final, uri)
        self._gc()
        dt = time.perf_counter() - t0
        try:
            mdefs.train_checkpoint_saves().inc(tags={"result": "ok"})
            mdefs.train_checkpoint_save_seconds().observe(
                dt, tags={"phase": "drain"})
        except Exception:  # noqa: BLE001
            pass
        if self.on_durable is not None:
            try:
                self.on_durable({"step": step, "index": idx,
                                 "path": final, "uri": uri,
                                 "world_size": len(shards)})
            except Exception:  # noqa: BLE001 - bookkeeping never fails a save
                pass

    @staticmethod
    def _corrupt_one_file(path: str) -> None:
        for rel, full in sorted(_iter_files(path)):
            if rel == _MANIFEST:
                continue
            with open(full, "r+b") as f:
                b = f.read(1)
                if not b:
                    continue
                f.seek(0)
                f.write(bytes([b[0] ^ 0xFF]))
            return

    def _gc(self) -> None:
        """Retain the newest ``retain_k`` checkpoints; older ones (and
        their cloud mirrors) are removed."""
        dirs = self._dirs()
        for d in dirs[:-self.retain_k]:
            shutil.rmtree(d, ignore_errors=True)
            if self.storage_uri is not None:
                try:
                    delete_checkpoint_uri(
                        f"{self.storage_uri}/{os.path.basename(d)}")
                except Exception:  # noqa: BLE001 - best-effort GC
                    pass

    # -- restore path ---------------------------------------------------------
    def latest(self) -> Optional[Dict[str, Any]]:
        """Newest VERIFIED checkpoint as ``{step, checkpoint, rank_states,
        path}`` — scans newest-first and falls back past any directory
        whose manifest is missing or whose CRCs mismatch."""
        from ..core import metrics_defs as mdefs
        from ..utils import faults

        dirs = self._dirs()
        fell_back = False
        for d in reversed(dirs):
            act = faults.fire("checkpoint.restore")
            corrupted_by_fault = False
            if act is not None:
                if act.mode == "stall":
                    act.sleep()
                elif act.mode in ("error", "drop"):
                    fell_back = True
                    continue  # injected read failure: this dir unusable
                elif act.mode == "corrupt":
                    corrupted_by_fault = True
            ok, why = verify_checkpoint_dir(d)
            if corrupted_by_fault:
                ok, why = False, "injected corruption"
            if not ok:
                fell_back = True
                try:
                    from ..utils import events

                    events.emit(
                        "CHECKPOINT_CORRUPT",
                        f"checkpoint {os.path.basename(d)} failed "
                        f"verification ({why}); falling back",
                        severity=events.WARNING, source="train")
                except Exception:  # noqa: BLE001
                    pass
                continue
            doc = read_manifest(d) or {}
            rank_states: Dict[int, bytes] = {}
            rs_path = os.path.join(d, _RANK_STATES_FILE)
            if os.path.exists(rs_path):
                with open(rs_path, "rb") as f:
                    rank_states = pickle.load(f)
            try:
                mdefs.train_checkpoint_restores().inc(
                    tags={"source": "fallback" if fell_back else "latest"})
            except Exception:  # noqa: BLE001
                pass
            return {"step": doc.get("step"),
                    "checkpoint": Checkpoint.from_directory(d),
                    "rank_states": rank_states, "path": d}
        return None
