"""Checkpoint: a value-semantic handle convertible between dict / directory /
bytes / URI forms.

Mirrors the reference's AIR Checkpoint (python/ray/air/checkpoint.py:42 —
from_dict:215/to_dict:239, from_directory:327/to_directory:432,
from_bytes:536/to_bytes:551, from_uri/to_uri). jax pytrees (params/opt state)
are stored via orbax when saved to a directory, so TPU-sharded trees
round-trip correctly; plain picklable state rides cloudpickle.
"""

from __future__ import annotations

import os
import pickle
import shutil
import tempfile
from typing import Any, Dict, Optional

_PYTREE_KEY = "__rmt_pytree__"
_SKELETON_KEY = "__rmt_pytree_skeleton__"
_PICKLE_FILE = "checkpoint.pkl"
_ORBAX_DIR = "pytree"


class Checkpoint:
    def __init__(self, data: Optional[Dict[str, Any]] = None,
                 directory: Optional[str] = None):
        self._data = data
        self._directory = directory

    # -- constructors ---------------------------------------------------------
    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Checkpoint":
        return cls(data=dict(data))

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(directory=os.path.abspath(path))

    @classmethod
    def from_bytes(cls, blob: bytes) -> "Checkpoint":
        return cls(data=pickle.loads(blob))

    @classmethod
    def from_uri(cls, uri: str) -> "Checkpoint":
        if uri.startswith("file://"):
            return cls.from_directory(uri[len("file://"):])
        if "://" not in uri:
            return cls.from_directory(uri)
        raise ValueError(f"unsupported checkpoint uri {uri!r}")

    # -- conversions ----------------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        if self._data is not None:
            return dict(self._data)
        assert self._directory is not None
        out: Dict[str, Any] = {}
        pkl = os.path.join(self._directory, _PICKLE_FILE)
        if os.path.exists(pkl):
            with open(pkl, "rb") as f:
                out.update(pickle.load(f))
        orbax_path = os.path.join(self._directory, _ORBAX_DIR)
        if os.path.exists(orbax_path):
            import jax
            import numpy as np
            import orbax.checkpoint as ocp

            # restore as host numpy; consumers re-shard with parallel.
            # shard_pytree for their own mesh. The saved skeleton supplies
            # the tree structure orbax needs for restore_args.
            skeleton = out.pop(_SKELETON_KEY, None)
            with ocp.PyTreeCheckpointer() as ckptr:
                if skeleton is not None:
                    restore_args = jax.tree.map(
                        lambda _: ocp.RestoreArgs(restore_type=np.ndarray),
                        skeleton,
                    )
                    out[_PYTREE_KEY] = ckptr.restore(
                        orbax_path, restore_args=restore_args)
                else:
                    out[_PYTREE_KEY] = ckptr.restore(orbax_path)
        return out

    def to_directory(self, path: Optional[str] = None) -> str:
        if path is None:
            path = tempfile.mkdtemp(prefix="rmt_ckpt_")
        os.makedirs(path, exist_ok=True)
        if self._directory is not None:
            if os.path.abspath(path) != self._directory:
                shutil.copytree(self._directory, path, dirs_exist_ok=True)
            return path
        data = dict(self._data or {})
        pytree = data.pop(_PYTREE_KEY, None)
        if pytree is not None:
            import jax

            data[_SKELETON_KEY] = jax.tree.map(lambda _: 0, pytree)
        with open(os.path.join(path, _PICKLE_FILE), "wb") as f:
            import cloudpickle

            cloudpickle.dump(data, f)
        if pytree is not None:
            import orbax.checkpoint as ocp

            target = os.path.join(path, _ORBAX_DIR)
            if os.path.exists(target):
                shutil.rmtree(target)
            with ocp.PyTreeCheckpointer() as ckptr:
                ckptr.save(target, pytree)
        return path

    def to_bytes(self) -> bytes:
        import cloudpickle

        return cloudpickle.dumps(self.to_dict())

    def to_uri(self, uri: str) -> str:
        if uri.startswith("file://"):
            self.to_directory(uri[len("file://"):])
            return uri
        if "://" not in uri:
            self.to_directory(uri)
            return f"file://{uri}"
        raise ValueError(f"unsupported checkpoint uri {uri!r}")

    # -- pytree sugar ---------------------------------------------------------
    @classmethod
    def from_pytree(cls, pytree, extra: Optional[Dict[str, Any]] = None
                    ) -> "Checkpoint":
        """Checkpoint carrying a jax pytree (params/opt state); saved with
        orbax on to_directory()."""
        data = dict(extra or {})
        data[_PYTREE_KEY] = pytree
        return cls(data=data)

    def get_pytree(self):
        return self.to_dict().get(_PYTREE_KEY)

    def __repr__(self):
        kind = "dict" if self._data is not None else f"dir:{self._directory}"
        return f"Checkpoint({kind})"
