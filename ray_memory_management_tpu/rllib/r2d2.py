"""R2D2: recurrent replay distributed DQN.

The reference's R2D2 (rllib/algorithms/r2d2/r2d2.py — DQN over LSTM
models with sequence replay; r2d2_tf_policy.py:113 the burn-in: the
first ``burn_in`` steps of each stored sequence warm the recurrent state
WITHOUT gradient before the TD loss applies to the remainder; stored
initial states per sequence per Kapturowski et al. 2019). Composition
here: the LSTM trunk is recurrent.py's (one cell between an embedding
and a Q head), sequences are fixed-length fragments with their initial
(h, c) recorded at collection, and the whole update — burn-in unroll,
online/target unrolls, double-Q TD over the post-burn-in tail, Adam —
is ONE jit'd program vmapped over the sequence batch.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

from .. import api
from . import sample_batch as sb
from .algorithm import Algorithm, AlgorithmConfig
from .env import make_env
from .models import mlp_apply, mlp_init, params_from_numpy, params_to_numpy
from .recurrent import _cell, lstm_zero_state
from .rollout_worker import WorkerSet

H0 = "lstm_h0"
C0 = "lstm_c0"
NEXT_OBS_LAST = "next_obs_last"  # successor of each sequence's last step


def lstm_q_init(rng, obs_dim: int, num_actions: int,
                embed_dim: int = 64, lstm_dim: int = 64) -> Dict[str, Any]:
    import jax
    import jax.numpy as jnp

    k_e, k_l, k_q = jax.random.split(rng, 3)
    scale = 1.0 / np.sqrt(embed_dim + lstm_dim)
    return {
        "embed": mlp_init(k_e, [obs_dim, embed_dim]),
        "lstm": {
            "w": jax.random.normal(
                k_l, (embed_dim + lstm_dim, 4 * lstm_dim)) * scale,
            "b": jnp.zeros((4 * lstm_dim,))
            .at[lstm_dim:2 * lstm_dim].set(1.0),
        },
        "q": mlp_init(k_q, [lstm_dim, num_actions]),
    }


def lstm_q_step(params, obs, h, c):
    import jax

    x = jax.nn.tanh(mlp_apply(params["embed"], obs))
    h, c = _cell(params["lstm"], x, h, c)
    return mlp_apply(params["q"], h), h, c


def lstm_q_seq(params, obs_seq, dones, h0, c0):
    """Q-values along one sequence [T, D], resetting state after done
    steps (matching collection). Returns (q [T, A], (hT, cT))."""
    import jax

    def step(carry, inp):
        h, c = carry
        obs, done = inp
        q, h, c = lstm_q_step(params, obs, h, c)
        mask = 1.0 - done
        return (h * mask, c * mask), q

    carry, q = jax.lax.scan(step, (h0, c0), (obs_seq, dones))
    return q, carry


class SequenceReplayBuffer:
    """Ring buffer of fixed-length sequences (obs/actions/rewards/dones
    plus the recorded initial LSTM state and each sequence's final
    successor observation) — the reference's replay of length-m
    sequences with stored states (r2d2.py's zero_init_states=False
    path)."""

    def __init__(self, capacity_seqs: int, seed: int = 0):
        self.capacity = capacity_seqs
        self._data: List[Dict[str, np.ndarray]] = []
        self._next = 0
        self._rng = np.random.default_rng(seed)

    def add(self, seq: Dict[str, np.ndarray]) -> None:
        if len(self._data) < self.capacity:
            self._data.append(seq)
        else:
            self._data[self._next] = seq
        self._next = (self._next + 1) % self.capacity

    def sample(self, n: int) -> Dict[str, np.ndarray]:
        idx = self._rng.integers(0, len(self._data), size=n)
        return {
            k: np.stack([self._data[i][k] for i in idx])
            for k in self._data[0]
        }

    def __len__(self) -> int:
        return len(self._data)


class R2D2RolloutWorker:
    """Epsilon-greedy collector over the recurrent Q-network: carries
    (h, c) across steps, resets at episode ends, and emits fixed-length
    sequences with their initial state and final successor."""

    def __init__(self, env_spec, env_config: Optional[dict],
                 hidden, seed: int, gamma: float = 0.99,
                 lam: float = 0.95, connectors=None,
                 embed_dim: int = 64, lstm_dim: int = 64):
        import jax

        from .. import _worker_context

        if connectors:
            raise ValueError(
                "connectors are not supported with recurrent policies yet")
        del hidden, gamma, lam  # WorkerSet calling convention; unused here
        if _worker_context.in_worker():
            jax.config.update("jax_default_device", jax.devices("cpu")[0])
        self.env = make_env(env_spec, env_config)
        self.obs_dim = self.env.observation_dim
        self.lstm_dim = lstm_dim
        self.rng = np.random.default_rng(seed)
        self.params = lstm_q_init(
            jax.random.key(0), self.obs_dim, self.env.num_actions,
            embed_dim, lstm_dim)
        self._obs = self.env.reset(seed=seed)
        self._h, self._c = lstm_zero_state(lstm_dim)
        self._episode_reward = 0.0
        self._episode_len = 0
        self.episode_rewards: List[float] = []
        self.episode_lengths: List[int] = []
        self._q_jit = None

    def ready(self) -> str:
        return "ok"

    def set_weights(self, weights) -> None:
        self.params = params_from_numpy(weights)

    def _q_step(self, obs, h, c):
        import jax
        import jax.numpy as jnp

        if self._q_jit is None:
            self._q_jit = jax.jit(lstm_q_step)
        return self._q_jit(self.params, jnp.asarray(obs),
                           jnp.asarray(h), jnp.asarray(c))

    def sample(self, seq_len: int, epsilon: float) -> Dict[str, np.ndarray]:
        obs_buf = np.zeros((seq_len, self.obs_dim), np.float32)
        act_buf = np.zeros(seq_len, np.int32)
        rew_buf = np.zeros(seq_len, np.float32)
        done_buf = np.zeros(seq_len, np.float32)  # episode boundary
        h0, c0 = np.asarray(self._h), np.asarray(self._c)

        for t in range(seq_len):
            q, h, c = self._q_step(self._obs, self._h, self._c)
            if self.rng.random() < epsilon:
                a = int(self.rng.integers(self.env.num_actions))
            else:
                a = int(np.asarray(q).argmax())
            obs_buf[t] = self._obs
            act_buf[t] = a
            next_obs, reward, terminated, truncated, _ = self.env.step(a)
            rew_buf[t] = reward
            done_buf[t] = float(terminated or truncated)
            self._episode_reward += reward
            self._episode_len += 1
            self._h, self._c = np.asarray(h), np.asarray(c)
            if terminated or truncated:
                self.episode_rewards.append(self._episode_reward)
                self.episode_lengths.append(self._episode_len)
                self._episode_reward = 0.0
                self._episode_len = 0
                next_obs = self.env.reset(
                    seed=int(self.rng.integers(1 << 31)))
                self._h, self._c = lstm_zero_state(self.lstm_dim)
            self._obs = next_obs
        return {
            sb.OBS: obs_buf, sb.ACTIONS: act_buf, sb.REWARDS: rew_buf,
            sb.DONES: done_buf,
            H0: h0, C0: c0,
            NEXT_OBS_LAST: np.asarray(self._obs, np.float32),
        }

    def episode_stats(self, window: int = 100) -> Dict[str, Any]:
        return sb.episode_stats_summary(
            self.episode_rewards, self.episode_lengths, window)


def make_r2d2_update(optimizer, gamma: float, burn_in: int):
    import jax
    import jax.numpy as jnp
    import optax

    def loss_fn(params, target_params, batch):
        def per_seq(obs, actions, rewards, dones, h0, c0,
                    next_last):
            # burn-in: warm the state with NO gradient (the stored h0
            # is stale relative to current params; r2d2_tf_policy.py:113).
            # The ONLINE tail warms through the online net; the TARGET
            # tail warms through the TARGET net — otherwise every Adam
            # step would shift the target's recurrent state and the TD
            # target would move between target syncs.
            if burn_in > 0:
                _, (bh, bc) = lstm_q_seq(
                    jax.lax.stop_gradient(params), obs[:burn_in],
                    dones[:burn_in], h0, c0)
                bh = jax.lax.stop_gradient(bh)
                bc = jax.lax.stop_gradient(bc)
                _, (tbh, tbc) = lstm_q_seq(
                    target_params, obs[:burn_in], dones[:burn_in],
                    h0, c0)
                obs_t = obs[burn_in:]
                dones_t = dones[burn_in:]
            else:
                bh, bc = h0, c0
                tbh, tbc = h0, c0
                obs_t = obs
                dones_t = dones
            q_online, (hT, cT) = lstm_q_seq(params, obs_t, dones_t,
                                            bh, bc)
            q_target, (tT, tC) = lstm_q_seq(target_params, obs_t,
                                            dones_t, tbh, tbc)
            # successor Q-values: shift by one inside the tail, with the
            # recorded final successor evaluated from the final states
            q_next_last_online, _, _ = lstm_q_step(
                params, next_last, hT, cT)
            q_next_last_target, _, _ = lstm_q_step(
                target_params, next_last, tT, tC)
            next_online = jnp.concatenate(
                [q_online[1:], q_next_last_online[None]], axis=0)
            next_target = jnp.concatenate(
                [q_target[1:], q_next_last_target[None]], axis=0)
            acts = actions[burn_in:]
            rews = rewards[burn_in:]
            # bootstrap mask: EVERY episode boundary — the shifted
            # successor after a boundary is the NEXT episode's first
            # state under a reset LSTM, which must never leak into this
            # episode's target. For true terminals that is exact; for
            # time-limit truncations it under-bootstraps (the classic
            # DQN bias), which beats bootstrapping across episodes.
            # (Per-kind handling would need a per-step next_obs column —
            # the reset overwrites the truncated step's true successor —
            # so the sequence schema records boundaries only.)
            boundary = dones_t
            q_taken = jnp.take_along_axis(
                q_online, acts[:, None], axis=-1)[:, 0]
            next_a = jnp.argmax(next_online, axis=-1)
            next_q = jnp.take_along_axis(
                next_target, next_a[:, None], axis=-1)[:, 0]
            target = rews + gamma * (1.0 - boundary) * \
                jax.lax.stop_gradient(next_q)
            return optax.huber_loss(q_taken, target), q_taken

        losses, q_taken = jax.vmap(per_seq)(*batch)
        return losses.mean(), q_taken.mean()

    @jax.jit
    def update(params, target_params, opt_state, batch):
        (loss, mean_q), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, target_params, batch)
        upd, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, upd)
        return params, opt_state, {"td_loss": loss, "mean_q": mean_q}

    return update


class R2D2(Algorithm):
    def setup(self, config: Dict[str, Any]) -> None:
        import jax
        import optax

        self.cfg = config
        if config.get("connectors"):
            raise ValueError(
                "connectors are not supported with recurrent policies yet")
        seed = config.get("seed", 0)
        probe_env = make_env(config["env_spec"], config.get("env_config"))
        embed_dim = config.get("embed_dim", 64)
        self.lstm_dim = config.get("lstm_dim", 64)
        self.params = lstm_q_init(
            jax.random.key(seed), probe_env.observation_dim,
            probe_env.num_actions, embed_dim, self.lstm_dim)
        self.target_params = jax.tree_util.tree_map(
            lambda x: x, self.params)
        self.optimizer = optax.adam(config.get("lr", 1e-3))
        self.opt_state = self.optimizer.init(self.params)
        self.seq_len = config.get("seq_len", 20)
        self.burn_in = config.get("burn_in", 4)
        if self.burn_in >= self.seq_len:
            raise ValueError("burn_in must be < seq_len")
        self._update = make_r2d2_update(
            self.optimizer, config.get("gamma", 0.99), self.burn_in)
        self.replay = SequenceReplayBuffer(
            config.get("replay_capacity_seqs", 2000), seed=seed)
        self.learning_starts_seqs = config.get("learning_starts_seqs", 20)
        self.seqs_per_step = config.get("seqs_per_step", 8)
        self.train_batch_seqs = config.get("train_batch_seqs", 16)
        self.updates_per_step = config.get("updates_per_step", 8)
        self.target_update_freq = config.get("target_update_freq", 100)
        # same exploration config surface as DQN (dqn.py:167)
        self.eps_initial = config.get("epsilon_initial", 1.0)
        self.eps_final = config.get("epsilon_final", 0.05)
        self.eps_timesteps = config.get("epsilon_timesteps", 20_000)
        self._updates_done = 0
        self._timesteps_total = 0

        n_workers = config.get("num_rollout_workers", 0)
        self.workers = None
        self.local_worker = None
        worker_kwargs = dict(embed_dim=embed_dim, lstm_dim=self.lstm_dim)
        if n_workers > 0:
            self.workers = WorkerSet(
                config["env_spec"], config.get("env_config"), None,
                n_workers, seed, worker_cls=R2D2RolloutWorker,
                worker_kwargs=worker_kwargs)
        else:
            self.local_worker = R2D2RolloutWorker(
                config["env_spec"], config.get("env_config"), None, seed,
                **worker_kwargs)

    def _epsilon(self) -> float:
        frac = min(1.0, self._timesteps_total / max(1, self.eps_timesteps))
        return self.eps_initial + frac * (self.eps_final
                                          - self.eps_initial)

    def training_step(self) -> Dict[str, Any]:
        import jax
        import jax.numpy as jnp

        t0 = time.time()
        eps = self._epsilon()
        seqs: List[Dict[str, np.ndarray]] = []
        if self.workers is not None:
            ws = self.workers.remote_workers
            self.workers.set_weights(self.get_weights())
            while len(seqs) < self.seqs_per_step:
                seqs.extend(api.get([
                    w.sample.remote(self.seq_len, eps) for w in ws]))
        else:
            self.local_worker.set_weights(self.get_weights())
            while len(seqs) < self.seqs_per_step:
                seqs.append(self.local_worker.sample(self.seq_len, eps))
        for s in seqs:
            self.replay.add(s)
            self._timesteps_total += self.seq_len
        sample_time = time.time() - t0

        stats: Dict[str, Any] = {}
        t1 = time.time()
        if len(self.replay) >= self.learning_starts_seqs:
            for _ in range(self.updates_per_step):
                mb = self.replay.sample(self.train_batch_seqs)
                batch = (
                    jnp.asarray(mb[sb.OBS]), jnp.asarray(mb[sb.ACTIONS]),
                    jnp.asarray(mb[sb.REWARDS]), jnp.asarray(mb[sb.DONES]),
                    jnp.asarray(mb[H0]), jnp.asarray(mb[C0]),
                    jnp.asarray(mb[NEXT_OBS_LAST]))
                self.params, self.opt_state, stats = self._update(
                    self.params, self.target_params, self.opt_state,
                    batch)
                self._updates_done += 1
                if self._updates_done % self.target_update_freq == 0:
                    self.target_params = jax.tree_util.tree_map(
                        lambda x: x, self.params)
        learn_time = time.time() - t1

        out = {k: float(v) for k, v in stats.items()}
        out.update({
            "num_env_steps_sampled": len(seqs) * self.seq_len,
            "replay_seqs": len(self.replay),
            "num_updates": self._updates_done,
            "epsilon": eps,
            "sample_time_s": sample_time,
            "learn_time_s": learn_time,
        })
        return out

    def compute_single_action(self, obs: np.ndarray,
                              state: Optional[tuple] = None):
        import jax.numpy as jnp

        if state is None:
            state = lstm_zero_state(self.lstm_dim)
        h, c = state
        q, h, c = lstm_q_step(self.params, jnp.asarray(obs),
                              jnp.asarray(h), jnp.asarray(c))
        return int(np.asarray(q).argmax()), (np.asarray(h), np.asarray(c))

    def _sync_weights(self) -> None:
        pass  # weights ship inside training_step

    def _save_extra_state(self):
        return {
            "target_params": params_to_numpy(self.target_params),
            "opt_state": params_to_numpy(self.opt_state),
            "updates_done": self._updates_done,
            "timesteps": self._timesteps_total,
        }

    def _load_extra_state(self, state) -> None:
        if not state:
            return
        if "target_params" in state:
            self.target_params = params_from_numpy(state["target_params"])
        if "opt_state" in state:
            self.opt_state = params_from_numpy(state["opt_state"])
        self._updates_done = state.get("updates_done", 0)
        self._timesteps_total = state.get("timesteps", 0)


class R2D2Config(AlgorithmConfig):
    def __init__(self):
        super().__init__(R2D2)
        self.num_rollout_workers = 0
        self.extra.update({
            "seq_len": 20, "burn_in": 4, "replay_capacity_seqs": 2000,
            "learning_starts_seqs": 20, "seqs_per_step": 8,
            "train_batch_seqs": 16, "updates_per_step": 8,
            "target_update_freq": 100, "embed_dim": 64, "lstm_dim": 64,
            "epsilon_initial": 1.0, "epsilon_final": 0.05,
            "epsilon_timesteps": 20_000,
        })

    def training(self, *, seq_len=None, burn_in=None,
                 replay_capacity_seqs=None, learning_starts_seqs=None,
                 seqs_per_step=None, train_batch_seqs=None,
                 updates_per_step=None, target_update_freq=None,
                 embed_dim=None, lstm_dim=None, epsilon_initial=None,
                 epsilon_final=None, epsilon_timesteps=None,
                 **kwargs) -> "R2D2Config":
        super().training(**kwargs)
        for k, v in (
                ("seq_len", seq_len), ("burn_in", burn_in),
                ("replay_capacity_seqs", replay_capacity_seqs),
                ("learning_starts_seqs", learning_starts_seqs),
                ("seqs_per_step", seqs_per_step),
                ("train_batch_seqs", train_batch_seqs),
                ("updates_per_step", updates_per_step),
                ("target_update_freq", target_update_freq),
                ("embed_dim", embed_dim), ("lstm_dim", lstm_dim),
                ("epsilon_initial", epsilon_initial),
                ("epsilon_final", epsilon_final),
                ("epsilon_timesteps", epsilon_timesteps)):
            if v is not None:
                self.extra[k] = v
        return self
