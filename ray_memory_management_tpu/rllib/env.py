"""Environment API + built-in envs.

The reference wraps gym environments (rllib/env/); this image has no gym,
so the classic-control envs used by the reference's smoke tests are
implemented in-repo with the same reset/step contract
(obs, reward, terminated, truncated, info). Envs are numpy-only — rollouts
run on CPU actors; the learner owns the accelerator (the reference's
CPU-sampler/GPU-learner split, e.g. impala.py's learner thread).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np


class Env:
    """Minimal env contract (gymnasium-style step tuple)."""

    observation_dim: int
    num_actions: int
    max_episode_steps: int = 500

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        raise NotImplementedError

    def step(self, action: int
             ) -> Tuple[np.ndarray, float, bool, bool, Dict[str, Any]]:
        raise NotImplementedError


class CartPole(Env):
    """Classic cart-pole balancing (the dynamics of gym CartPole-v1:
    4-dim observation, 2 actions, reward 1 per step, fails past
    ±12° / ±2.4m, truncates at max_episode_steps)."""

    observation_dim = 4
    num_actions = 2

    GRAVITY = 9.8
    MASS_CART = 1.0
    MASS_POLE = 0.1
    LENGTH = 0.5  # half pole length
    FORCE_MAG = 10.0
    TAU = 0.02
    THETA_LIMIT = 12 * 2 * np.pi / 360
    X_LIMIT = 2.4

    def __init__(self, max_episode_steps: int = 500):
        self.max_episode_steps = max_episode_steps
        self._rng = np.random.default_rng(0)
        self._state = np.zeros(4, dtype=np.float64)
        self._steps = 0

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._state = self._rng.uniform(-0.05, 0.05, size=4)
        self._steps = 0
        return self._state.astype(np.float32)

    def step(self, action: int):
        x, x_dot, theta, theta_dot = self._state
        force = self.FORCE_MAG if action == 1 else -self.FORCE_MAG
        total_mass = self.MASS_CART + self.MASS_POLE
        polemass_length = self.MASS_POLE * self.LENGTH
        cos_t, sin_t = np.cos(theta), np.sin(theta)
        temp = (force + polemass_length * theta_dot**2 * sin_t) / total_mass
        theta_acc = (self.GRAVITY * sin_t - cos_t * temp) / (
            self.LENGTH * (4.0 / 3.0 - self.MASS_POLE * cos_t**2 / total_mass)
        )
        x_acc = temp - polemass_length * theta_acc * cos_t / total_mass
        x += self.TAU * x_dot
        x_dot += self.TAU * x_acc
        theta += self.TAU * theta_dot
        theta_dot += self.TAU * theta_acc
        self._state = np.array([x, x_dot, theta, theta_dot])
        self._steps += 1
        terminated = bool(
            abs(x) > self.X_LIMIT or abs(theta) > self.THETA_LIMIT)
        truncated = self._steps >= self.max_episode_steps
        return (self._state.astype(np.float32), 1.0, terminated, truncated,
                {})


class Pendulum(Env):
    """Classic inverted-pendulum swing-up (the dynamics of gym
    Pendulum-v1: obs [cos th, sin th, thdot], one torque action in
    [-2, 2], reward -(th^2 + 0.1 thdot^2 + 0.001 u^2), 200-step episodes,
    never terminates). The standard continuous-control smoke env — the
    reference's SAC regression runs on it
    (rllib/tuned_examples/sac/pendulum-sac.yaml)."""

    observation_dim = 3
    num_actions = 0          # continuous: no discrete action set
    action_dim = 1
    action_bound = 2.0
    max_episode_steps = 200

    G = 10.0
    MASS = 1.0
    LENGTH = 1.0
    DT = 0.05
    MAX_SPEED = 8.0

    def __init__(self, max_episode_steps: int = 200):
        self.max_episode_steps = max_episode_steps
        self._rng = np.random.default_rng(0)
        self._th = 0.0
        self._thdot = 0.0
        self._steps = 0

    def _obs(self) -> np.ndarray:
        return np.array([np.cos(self._th), np.sin(self._th), self._thdot],
                        np.float32)

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._th = self._rng.uniform(-np.pi, np.pi)
        self._thdot = self._rng.uniform(-1.0, 1.0)
        self._steps = 0
        return self._obs()

    def step(self, action):
        u = float(np.clip(np.asarray(action).reshape(-1)[0],
                          -self.action_bound, self.action_bound))
        th, thdot = self._th, self._thdot
        norm_th = ((th + np.pi) % (2 * np.pi)) - np.pi
        reward = -(norm_th ** 2 + 0.1 * thdot ** 2 + 0.001 * u ** 2)
        thdot = thdot + (
            3 * self.G / (2 * self.LENGTH) * np.sin(th)
            + 3.0 / (self.MASS * self.LENGTH ** 2) * u) * self.DT
        thdot = float(np.clip(thdot, -self.MAX_SPEED, self.MAX_SPEED))
        th = th + thdot * self.DT
        self._th, self._thdot = th, thdot
        self._steps += 1
        truncated = self._steps >= self.max_episode_steps
        return self._obs(), float(reward), False, truncated, {}


ENV_REGISTRY: Dict[str, Callable[..., Env]] = {
    "CartPole": CartPole,
    "Pendulum": Pendulum,
}


def register_env(name: str, creator: Callable[..., Env]) -> None:
    """User env registration (the reference's tune.register_env analog)."""
    ENV_REGISTRY[name] = creator


def make_env(spec, env_config: Optional[dict] = None) -> Env:
    env_config = env_config or {}
    if isinstance(spec, str):
        if spec not in ENV_REGISTRY:
            raise ValueError(
                f"unknown env {spec!r}; register it with register_env")
        return ENV_REGISTRY[spec](**env_config)
    if callable(spec):
        return spec(**env_config)
    raise TypeError(f"env spec must be a name or callable, got {type(spec)}")
