"""AlphaZero: MCTS-guided self-play policy/value learning.

The reference's rllib/algorithms/alpha_zero/ (mcts.py PUCT search +
alpha_zero_policy.py self-play training on a perfect-information env)
restructured around batched evaluation: the reference expands ONE leaf
per network call; here self-play runs N games in lockstep and every
MCTS simulation wave evaluates ALL games' leaves in ONE forward pass
(shape [n_games, obs]) — the XLA-friendly schedule, since a [64, obs]
matmul costs the same accelerator step a [1, obs] one does. The tree
itself stays numpy (irregular, data-dependent — exactly what jit can't
help), mirroring how production AlphaZero splits search (host) from
evaluation (accelerator).

Training is one jit'd step: cross-entropy of the policy head against
MCTS visit distributions + MSE of the value head against final game
outcomes (the AlphaZero loss), over minibatches from a replay window.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .algorithm import Algorithm, AlgorithmConfig
from .models import mlp_apply, mlp_init


class TicTacToe:
    """Perfect-information benchmark game (two players, 3x3).

    Board: 9 cells in {0 empty, +1, -1}; the CURRENT player always sees
    the board from their own perspective (their stones are +1), so one
    network plays both sides — the AlphaZero convention."""

    n_actions = 9
    obs_dim = 9

    _LINES = np.array([
        [0, 1, 2], [3, 4, 5], [6, 7, 8],
        [0, 3, 6], [1, 4, 7], [2, 5, 8],
        [0, 4, 8], [2, 4, 6],
    ])

    def __init__(self):
        self.board = np.zeros(9, np.int8)
        self.player = 1

    def clone(self) -> "TicTacToe":
        g = TicTacToe.__new__(TicTacToe)
        g.board = self.board.copy()
        g.player = self.player
        return g

    def obs(self) -> np.ndarray:
        return (self.board * self.player).astype(np.float32)

    def legal(self) -> np.ndarray:
        return self.board == 0

    def step(self, a: int) -> None:
        assert self.board[a] == 0
        self.board[a] = self.player
        self.player = -self.player

    def outcome(self) -> Optional[int]:
        """None while running; else +1/-1 (winner's stone) or 0 draw."""
        sums = self.board[self._LINES].sum(axis=1)
        if (sums == 3).any():
            return 1
        if (sums == -3).any():
            return -1
        if (self.board != 0).all():
            return 0
        return None


class _Node:
    __slots__ = ("prior", "visits", "value_sum", "children", "legal")

    def __init__(self, prior: np.ndarray, legal: np.ndarray):
        self.prior = prior
        self.visits = np.zeros(len(prior), np.int32)
        self.value_sum = np.zeros(len(prior), np.float64)
        self.children: Dict[int, "_Node"] = {}
        self.legal = legal


def _puct_pick(node: _Node, c_puct: float) -> int:
    """argmax over legal actions of Q + c * P * sqrt(N) / (1 + n)."""
    n_total = node.visits.sum()
    q = np.where(node.visits > 0,
                 node.value_sum / np.maximum(node.visits, 1), 0.0)
    u = c_puct * node.prior * np.sqrt(n_total + 1) / (1.0 + node.visits)
    score = np.where(node.legal, q + u, -np.inf)
    return int(score.argmax())


class BatchedMCTS:
    """PUCT search over N games in lockstep: each simulation wave walks
    every game's tree to a leaf (host-side numpy), then evaluates ALL
    leaves in one batched network call (mcts.py's per-leaf evaluation,
    re-scheduled for the accelerator)."""

    def __init__(self, evaluate, n_sims: int, c_puct: float = 1.5,
                 dirichlet_alpha: float = 0.6,
                 dirichlet_frac: float = 0.25,
                 rng: Optional[np.random.Generator] = None):
        self.evaluate = evaluate  # [B, obs] -> (priors [B, A], values [B])
        self.n_sims = n_sims
        self.c_puct = c_puct
        self.alpha = dirichlet_alpha
        self.frac = dirichlet_frac
        self.rng = rng or np.random.default_rng(0)

    def _root(self, game, add_noise: bool) -> _Node:
        priors, _ = self.evaluate(game.obs()[None, :])
        p = np.asarray(priors[0], np.float64)
        legal = game.legal()
        p = np.where(legal, p, 0.0)
        p /= max(p.sum(), 1e-9)
        if add_noise:
            noise = self.rng.dirichlet([self.alpha] * int(legal.sum()))
            full = np.zeros_like(p)
            full[np.flatnonzero(legal)] = noise
            p = (1 - self.frac) * p + self.frac * full
        return _Node(p, legal)

    def search_batch(self, games: List, add_noise: bool = True
                     ) -> List[np.ndarray]:
        """Visit-count distributions for each game's root."""
        roots = [self._root(g, add_noise) for g in games]
        for _ in range(self.n_sims):
            leaves = []      # (game idx, path, leaf game or None terminal)
            for gi, (g, root) in enumerate(zip(games, roots)):
                sim = g.clone()
                node = root
                path: List[Tuple[_Node, int]] = []
                value = None
                while True:
                    a = _puct_pick(node, self.c_puct)
                    path.append((node, a))
                    sim.step(a)
                    out = sim.outcome()
                    if out is not None:
                        # terminal: exact value, no evaluation needed.
                        # `out` is in stone units; convert to the value
                        # FROM THE PERSPECTIVE of the player to move at
                        # the leaf, then back up the path
                        value = 0.0 if out == 0 else \
                            (1.0 if out == sim.player else -1.0)
                        break
                    child = node.children.get(a)
                    if child is None:
                        break  # unexpanded leaf: queue for batched eval
                    node = child
                leaves.append((gi, path, None if value is not None
                               else sim, value))
            # ONE network call for every unexpanded leaf this wave
            pend = [(i, item) for i, item in enumerate(leaves)
                    if item[2] is not None]
            if pend:
                obs = np.stack([item[2].obs() for _, item in pend])
                priors, values = self.evaluate(obs)
                priors = np.asarray(priors, np.float64)
                values = np.asarray(values, np.float64)
                for k, (i, (gi, path, sim, _)) in enumerate(pend):
                    legal = sim.legal()
                    p = np.where(legal, priors[k], 0.0)
                    p /= max(p.sum(), 1e-9)
                    parent, a = path[-1]
                    parent.children[a] = _Node(p, legal)
                    leaves[i] = (gi, path, sim, float(values[k]))
            # back up: value is from the leaf player's perspective;
            # alternate sign walking up (two-player zero-sum)
            for gi, path, sim, value in leaves:
                v = value
                for node, a in reversed(path):
                    v = -v  # parent player is the opponent of the child
                    node.visits[a] += 1
                    node.value_sum[a] += v
        return [r.visits.astype(np.float64) / max(r.visits.sum(), 1)
                for r in roots]


def make_az_update(opt, l2: float):
    import jax
    import jax.numpy as jnp
    import optax

    def loss(params, obs, target_pi, target_v):
        logits = mlp_apply(params["torso_pi"], obs)
        v = jnp.tanh(mlp_apply(params["torso_v"], obs))[..., 0]
        logp = jax.nn.log_softmax(logits)
        pi_loss = -jnp.mean(jnp.sum(target_pi * logp, axis=-1))
        v_loss = jnp.mean((v - target_v) ** 2)
        reg = sum(jnp.sum(w * w) for w in jax.tree_util.tree_leaves(params))
        return pi_loss + v_loss + l2 * reg, (pi_loss, v_loss)

    @jax.jit
    def update(params, opt_state, obs, target_pi, target_v):
        (total, (pl, vl)), grads = jax.value_and_grad(
            loss, has_aux=True)(params, obs, target_pi, target_v)
        upd, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, upd)
        return params, opt_state, {"policy_loss": pl, "value_loss": vl,
                                   "total_loss": total}

    return update


class AlphaZero(Algorithm):
    def setup(self, config: Dict[str, Any]) -> None:
        import jax
        import optax

        self.cfg = config
        seed = config.get("seed", 0)
        game_cls = config.get("game", TicTacToe)
        self.game_cls = game_cls
        hidden = config.get("hidden", (64,))
        k1, k2 = jax.random.split(jax.random.key(seed))
        self.params = {
            "torso_pi": mlp_init(
                k1, [game_cls.obs_dim, *hidden, game_cls.n_actions]),
            "torso_v": mlp_init(k2, [game_cls.obs_dim, *hidden, 1]),
        }
        self.opt = optax.adam(config.get("lr", 3e-3))
        self.opt_state = self.opt.init(self.params)
        self._update = make_az_update(self.opt,
                                      config.get("l2_coeff", 1e-4))
        self._rng = np.random.default_rng(seed)
        self.n_sims = config.get("num_simulations", 32)
        self.games_per_iter = config.get("games_per_iter", 32)
        self.batch_size = config.get("train_batch_size", 128)
        self.sgd_iters = config.get("num_sgd_iter", 8)
        self.temp_moves = config.get("temperature_moves", 4)
        self.window: List[tuple] = []   # (obs, pi, z)
        self.window_size = config.get("replay_window", 4096)
        self._timesteps_total = 0
        self._updates_done = 0
        self.workers = None
        self.local_worker = None
        self.episode_rewards: list = []

    # ------------------------------------------------------------- network
    def _evaluate(self, obs: np.ndarray):
        import jax
        import jax.numpy as jnp

        o = jnp.asarray(obs, jnp.float32)
        logits = mlp_apply(self.params["torso_pi"], o)
        v = jnp.tanh(mlp_apply(self.params["torso_v"], o))[..., 0]
        return (np.asarray(jax.nn.softmax(logits)), np.asarray(v))

    # ------------------------------------------------------------ self-play
    def _self_play(self) -> None:
        mcts = BatchedMCTS(self._evaluate, self.n_sims,
                           c_puct=self.cfg.get("c_puct", 1.5),
                           rng=self._rng)
        games = [self.game_cls() for _ in range(self.games_per_iter)]
        halves: List[List[tuple]] = [[] for _ in games]  # (obs, pi, player)
        results = [None] * len(games)
        move_no = 0
        live = list(range(len(games)))
        while live:
            dists = mcts.search_batch([games[i] for i in live])
            for k, i in enumerate(list(live)):
                g = games[i]
                pi = dists[k]
                halves[i].append((g.obs().copy(), pi.copy(), g.player))
                if move_no < self.temp_moves:
                    a = int(self._rng.choice(len(pi), p=pi))
                else:
                    a = int(pi.argmax())
                g.step(a)
                out = g.outcome()
                if out is not None:
                    results[i] = out
                    live.remove(i)
            move_no += 1
        for i, g in enumerate(games):
            z = results[i]
            for obs, pi, player in halves[i]:
                # outcome from the acting player's perspective
                zp = 0.0 if z == 0 else (1.0 if z == player else -1.0)
                self.window.append((obs, pi, zp))
            self._timesteps_total += len(halves[i])
            self.episode_rewards.append(float(z))
        if len(self.window) > self.window_size:
            self.window = self.window[-self.window_size:]

    # ------------------------------------------------------------- training
    def training_step(self) -> Dict[str, Any]:
        import jax.numpy as jnp

        t0 = time.time()
        self._self_play()
        stats = {}
        n = len(self.window)
        for _ in range(self.sgd_iters):
            idx = self._rng.integers(0, n, size=min(self.batch_size, n))
            obs = jnp.asarray(np.stack([self.window[i][0] for i in idx]))
            tpi = jnp.asarray(np.stack([self.window[i][1] for i in idx]),
                              jnp.float32)
            tv = jnp.asarray(np.asarray(
                [self.window[i][2] for i in idx], np.float32))
            self.params, self.opt_state, stats = self._update(
                self.params, self.opt_state, obs, tpi, tv)
            self._updates_done += 1
        return {
            "episodes_this_iter": self.games_per_iter,
            "replay_window": n,
            "num_updates": self._updates_done,
            **{k: float(v) for k, v in stats.items()},
            "time_this_iter_s": time.time() - t0,
        }

    def _episode_metrics(self) -> Dict[str, Any]:
        recent = self.episode_rewards[-200:]
        return {
            "episode_reward_mean": float(np.mean(recent)) if recent
            else None,
            "episode_len_mean": None,
            "episodes_total": len(self.episode_rewards),
        }

    # ------------------------------------------------------------ inference
    def compute_single_action(self, game, greedy_sims: int = 0) -> int:
        """Best move for ``game`` (a live game object): raw policy argmax,
        or a noise-free MCTS when ``greedy_sims`` > 0."""
        if greedy_sims:
            mcts = BatchedMCTS(self._evaluate, greedy_sims,
                               rng=self._rng)
            pi = mcts.search_batch([game], add_noise=False)[0]
            legal_pi = np.where(game.legal(), pi, -np.inf)
            return int(legal_pi.argmax())
        priors, _ = self._evaluate(game.obs()[None, :])
        p = np.where(game.legal(), priors[0], -np.inf)
        return int(p.argmax())

    def get_weights(self):
        import jax

        return jax.tree_util.tree_map(np.asarray, self.params)

    def set_weights(self, weights) -> None:
        import jax.numpy as jnp
        import jax

        self.params = jax.tree_util.tree_map(jnp.asarray, weights)

    def _sync_weights(self) -> None:
        pass  # self-play runs in-process

    def _save_extra_state(self):
        import jax

        return {"params": jax.tree_util.tree_map(np.asarray, self.params),
                "updates": self._updates_done}

    def _load_extra_state(self, state) -> None:
        if not state:
            return
        self.set_weights(state["params"])
        self.opt_state = self.opt.init(self.params)
        self._updates_done = state.get("updates", 0)


class AlphaZeroConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(AlphaZero)
        self.extra.update({
            "num_simulations": 32, "games_per_iter": 32,
            "num_sgd_iter": 8, "temperature_moves": 4,
            "replay_window": 4096, "c_puct": 1.5, "l2_coeff": 1e-4,
        })

    def training(self, *, num_simulations=None, games_per_iter=None,
                 num_sgd_iter=None, replay_window=None,
                 **kwargs) -> "AlphaZeroConfig":
        super().training(**kwargs)
        for k, v in (("num_simulations", num_simulations),
                     ("games_per_iter", games_per_iter),
                     ("num_sgd_iter", num_sgd_iter),
                     ("replay_window", replay_window)):
            if v is not None:
                self.extra[k] = v
        return self
