"""PPO: clipped-surrogate policy optimization with a jax learner.

The reference's PPO (rllib/algorithms/ppo/ppo.py:289,401): synchronous
sampling from rollout workers, GAE postprocessing (done worker-side here),
then ``num_sgd_iter`` epochs of minibatch SGD. The update is one jit'd
function — on TPU the whole minibatch step (forward, backward, Adam) is a
single XLA program on the MXU; rollouts stay on CPU actors.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Any, Dict

import numpy as np

from .. import api
from . import sample_batch as sb
from .algorithm import Algorithm, AlgorithmConfig
from .models import ac_apply


def make_ppo_update(optimizer, clip_param: float, vf_coeff: float,
                    entropy_coeff: float, donate: bool = False):
    """Build the jit'd minibatch update.

    ``donate`` hands params/opt_state buffers back to XLA so the TPU
    learner updates in place (no HBM copy per SGD step — the pattern the
    train-step bench uses); callers must treat the passed-in pytrees as
    consumed. Off by default: CPU jax ignores donation with a warning.
    """
    import jax
    import jax.numpy as jnp
    import optax

    def loss_fn(params, obs, actions, old_logp, advantages, targets):
        logits, values = ac_apply(params, obs)
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(
            logp_all, actions[:, None], axis=-1)[:, 0]
        ratio = jnp.exp(logp - old_logp)
        adv = (advantages - advantages.mean()) / (advantages.std() + 1e-8)
        surrogate = jnp.minimum(
            ratio * adv,
            jnp.clip(ratio, 1 - clip_param, 1 + clip_param) * adv)
        pg_loss = -surrogate.mean()
        vf_loss = jnp.square(values - targets).mean()
        entropy = -jnp.sum(
            jnp.exp(logp_all) * logp_all, axis=-1).mean()
        total = pg_loss + vf_coeff * vf_loss - entropy_coeff * entropy
        return total, {
            "policy_loss": pg_loss, "vf_loss": vf_loss, "entropy": entropy,
            "kl": (old_logp - logp).mean(),
        }

    @partial(jax.jit, donate_argnums=(0, 1) if donate else ())
    def update(params, opt_state, obs, actions, old_logp, advantages,
               targets):
        (loss, stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, obs, actions, old_logp, advantages, targets)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        stats["total_loss"] = loss
        return params, opt_state, stats

    return update


class PPO(Algorithm):
    def setup(self, config: Dict[str, Any]) -> None:
        import optax

        super().setup(config)
        self.clip_param = config.get("clip_param", 0.2)
        self.vf_coeff = config.get("vf_loss_coeff", 0.5)
        self.entropy_coeff = config.get("entropy_coeff", 0.01)
        self.num_sgd_iter = config.get("num_sgd_iter", 6)
        self.sgd_minibatch_size = config.get("sgd_minibatch_size", 128)
        self.optimizer = optax.adam(config.get("lr", 5e-4))
        self.opt_state = self.optimizer.init(self.params)
        self._update = make_ppo_update(
            self.optimizer, self.clip_param, self.vf_coeff,
            self.entropy_coeff,
            donate=config.get("donate_learner_state", False))

    def training_step(self) -> Dict[str, Any]:
        import jax.numpy as jnp

        t0 = time.time()
        fragment = self.cfg.get("rollout_fragment_length", 200)
        target = self.cfg.get("train_batch_size", 4000)

        # 1. broadcast current weights, sample synchronously
        batches = []
        if self.workers is not None:
            self._sync_weights()
            while sum(sb.batch_size(b) for b in batches) < target:
                refs = self.workers.sample(fragment)
                batches.extend(api.get(refs))
        else:
            self.local_worker.set_weights(self.get_weights())
            while sum(sb.batch_size(b) for b in batches) < target:
                batches.append(self.local_worker.sample(fragment))
        batch = sb.concat_batches(batches)
        n = sb.batch_size(batch)
        self._timesteps_total += n
        sample_time = time.time() - t0

        # 2. minibatch SGD epochs on the learner device
        t1 = time.time()
        obs = jnp.asarray(batch[sb.OBS])
        actions = jnp.asarray(batch[sb.ACTIONS])
        old_logp = jnp.asarray(batch[sb.LOGP])
        advantages = jnp.asarray(batch[sb.ADVANTAGES])
        targets = jnp.asarray(batch[sb.TARGETS])
        stats = {}
        mb = min(self.sgd_minibatch_size, n)
        for _epoch in range(self.num_sgd_iter):
            for idx in sb.minibatch_indices(n, mb, self.np_rng):
                i = jnp.asarray(idx)
                self.params, self.opt_state, stats = self._update(
                    self.params, self.opt_state, obs[i], actions[i],
                    old_logp[i], advantages[i], targets[i])
        learn_time = time.time() - t1

        out = {k: float(v) for k, v in stats.items()}
        out.update({
            "num_env_steps_sampled": n,
            "sample_time_s": sample_time,
            "learn_time_s": learn_time,
            "steps_per_s": n / max(sample_time + learn_time, 1e-9),
        })
        return out

    def _save_extra_state(self):
        from .models import params_to_numpy

        return {"opt_state": params_to_numpy(self.opt_state)}

    def _load_extra_state(self, state) -> None:
        if state and "opt_state" in state:
            from .models import params_from_numpy

            self.opt_state = params_from_numpy(state["opt_state"])


class PPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(PPO)
        self.extra.update({
            "clip_param": 0.2, "vf_loss_coeff": 0.5, "entropy_coeff": 0.01,
            "num_sgd_iter": 6, "sgd_minibatch_size": 128,
        })

    def training(self, *, clip_param=None, vf_loss_coeff=None,
                 entropy_coeff=None, num_sgd_iter=None,
                 sgd_minibatch_size=None, **kwargs) -> "PPOConfig":
        super().training(**kwargs)
        for k, v in (("clip_param", clip_param),
                     ("vf_loss_coeff", vf_loss_coeff),
                     ("entropy_coeff", entropy_coeff),
                     ("num_sgd_iter", num_sgd_iter),
                     ("sgd_minibatch_size", sgd_minibatch_size)):
            if v is not None:
                self.extra[k] = v
        return self
