"""Offline RL: dataset IO, behavior cloning, and off-policy evaluation.

The reference's offline stack (rllib/offline/: json_writer.py:31 /
json_reader.py:198 dataset IO, estimators/importance_sampling.py off-policy
evaluation; BC is the reference's simplest offline algorithm, built on the
same input pipeline). TPU-first shape: datasets are columnar ``.npz``
shards — the exact arrays jax consumes, written zero-copy from sample
batches — rather than row-wise JSON; the BC update (policy forward,
cross-entropy, Adam) is one jit'd XLA program fed contiguous minibatches.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, Iterator, List, Optional

import numpy as np

from . import sample_batch as sb
from .algorithm import Algorithm, AlgorithmConfig
from .collector import NEXT_OBS
from .env import make_env
from .models import mlp_apply, mlp_init

# behavior-policy action log-prob column (needed for off-policy evaluation)
BEHAVIOR_LOGP = sb.LOGP
# true environment termination, distinct from the episode-boundary DONES
# (which also marks time-limit truncations): TD learners must bootstrap
# through a truncation but not through a termination (collector.py applies
# the same rule to live rollouts)
TERMINATED = "terminated"


class DatasetWriter:
    """Append sample batches to a directory of columnar ``.npz`` shards
    (the OutputWriter/JsonWriter contract, json_writer.py:31,72 — with
    arrays instead of rows)."""

    def __init__(self, path: str, shard_size: int = 10_000):
        self.path = path
        self.shard_size = shard_size
        os.makedirs(path, exist_ok=True)
        self._buf: List[Dict[str, np.ndarray]] = []
        self._buffered = 0
        self._shard = 0
        # per-writer token: two writers appending to one directory (same
        # pid or not) must never collide — shard-{pid}-{n} alone made a
        # second same-process writer silently overwrite the first's
        # shards, turning "append a second recording" into "replace"
        self._uid = os.urandom(4).hex()

    def write(self, batch: Dict[str, np.ndarray]) -> None:
        self._buf.append({k: np.asarray(v) for k, v in batch.items()})
        self._buffered += sb.batch_size(batch)
        if self._buffered >= self.shard_size:
            self.flush()

    def flush(self) -> None:
        if not self._buf:
            return
        merged = sb.concat_batches(self._buf)
        fname = os.path.join(
            self.path,
            f"shard-{os.getpid()}-{self._uid}-{self._shard:05d}.npz")
        np.savez_compressed(fname + ".tmp.npz", **merged)
        os.replace(fname + ".tmp.npz", fname)  # readers never see partials
        self._shard += 1
        self._buf = []
        self._buffered = 0

    def close(self) -> None:
        self.flush()


class DatasetReader:
    """Load a shard directory; serve shuffled minibatches (the
    InputReader/JsonReader contract, json_reader.py:198,264).

    A directory may hold several independent RECORDINGS (one per
    DatasetWriter — appended runs, parallel collectors). Shards are
    grouped by their writer prefix so each recording's shards concatenate
    in write order, and ``recording_starts`` marks where each recording
    begins in the concatenated arrays: time order exists only WITHIN a
    recording, and everything trajectory-shaped (episode splits, returns,
    TD successors) must stop at those boundaries rather than bleed one
    recording's truncated tail into the next recording's first episode.
    Mixed schemas (a legacy recording without next_obs beside a new one)
    keep the INTERSECTION of columns, so the reader never crashes or
    keeps a column only some rows actually have."""

    def __init__(self, path: str, seed: int = 0):
        import re

        files = sorted(
            os.path.join(path, f) for f in os.listdir(path)
            if f.endswith(".npz") and not f.endswith(".tmp.npz"))
        if not files:
            raise FileNotFoundError(f"no dataset shards under {path}")
        groups: Dict[str, list] = {}
        for f in files:
            m = re.match(r"(.+)-(\d+)\.npz$", os.path.basename(f))
            prefix, num = ((m.group(1), int(m.group(2))) if m
                           else (os.path.basename(f), 0))
            groups.setdefault(prefix, []).append((num, f))
        loaded = [[dict(np.load(f)) for _, f in sorted(groups[p])]
                  for p in sorted(groups)]
        keys = None
        for arrs in loaded:
            for a in arrs:
                keys = set(a) if keys is None else keys & set(a)
        shards, starts, offset = [], [], 0
        for arrs in loaded:
            starts.append(offset)
            for a in arrs:
                shards.append({k: a[k] for k in keys})
                offset += sb.batch_size(a)
        self.data = sb.concat_batches(shards)
        self.num_samples = sb.batch_size(self.data)
        self.recording_starts = np.asarray(starts, np.int64)
        self._rng = np.random.default_rng(seed)

    def sample(self, n: int) -> Dict[str, np.ndarray]:
        idx = self._rng.integers(0, self.num_samples, size=n)
        return {k: v[idx] for k, v in self.data.items()}

    def iter_episodes(self, include_partial: bool = False
                      ) -> Iterator[Dict[str, np.ndarray]]:
        """Split at terminal flags WITHIN each recording — what the
        trajectory-level OPE estimators consume. A fragment that reaches
        a recording boundary with no terminal flag is a TRUNCATED
        recording, not an episode: it is excluded by default (treating
        it as complete biases per-episode return estimates low; the
        reference's estimators likewise consume only completed
        episodes)."""
        dones = self.data[sb.DONES]
        bounds = list(self.recording_starts[1:]) + [len(dones)]
        start = 0
        for rec_end in bounds:
            for t in range(start, rec_end):
                if dones[t]:
                    yield {k: v[start:t + 1]
                           for k, v in self.data.items()}
                    start = t + 1
            if start < rec_end:
                if include_partial:
                    yield {k: v[start:rec_end]
                           for k, v in self.data.items()}
                start = rec_end


def collect_dataset(env_spec, path: str, num_steps: int = 10_000,
                    policy=None, env_config: Optional[dict] = None,
                    seed: int = 0, shard_size: int = 10_000) -> str:
    """Roll a policy (default: uniform random) through the env and write
    (obs, action, reward, next_obs, done, behavior logp) shards — the
    offline counterpart of the reference's ``output`` rollout recording.
    next_obs makes the recording sufficient for TD-based offline
    learners (CQL); return-based ones (BC/MARWIL) ignore it."""
    env = make_env(env_spec, env_config)
    rng = np.random.default_rng(seed)
    writer = DatasetWriter(path, shard_size=shard_size)
    obs = env.reset(seed=seed)
    n_act = env.num_actions

    def fresh() -> Dict[str, List]:
        return {sb.OBS: [], sb.ACTIONS: [], sb.REWARDS: [],
                NEXT_OBS: [], sb.DONES: [], TERMINATED: [],
                BEHAVIOR_LOGP: []}

    def emit(cols: Dict[str, List]) -> None:
        writer.write({
            sb.OBS: np.asarray(cols[sb.OBS], np.float32),
            sb.ACTIONS: np.asarray(cols[sb.ACTIONS], np.int32),
            sb.REWARDS: np.asarray(cols[sb.REWARDS], np.float32),
            NEXT_OBS: np.asarray(cols[NEXT_OBS], np.float32),
            sb.DONES: np.asarray(cols[sb.DONES], np.float32),
            TERMINATED: np.asarray(cols[TERMINATED], np.float32),
            BEHAVIOR_LOGP: np.asarray(cols[BEHAVIOR_LOGP], np.float32),
        })

    cols = fresh()
    for _ in range(num_steps):
        if policy is None:
            a = int(rng.integers(n_act))
            logp = -float(np.log(n_act))
        else:
            a, logp = policy(obs)
        nxt, reward, terminated, truncated, _ = env.step(a)
        cols[sb.OBS].append(obs)
        cols[sb.ACTIONS].append(a)
        cols[sb.REWARDS].append(reward)
        cols[NEXT_OBS].append(nxt)
        # DONES marks the episode boundary (terminal OR time-limit);
        # TERMINATED carries the true-terminal flag TD learners mask on
        cols[sb.DONES].append(float(terminated or truncated))
        cols[TERMINATED].append(float(terminated))
        cols[BEHAVIOR_LOGP].append(logp)
        obs = nxt
        if terminated or truncated:
            obs = env.reset(seed=int(rng.integers(1 << 31)))
        if len(cols[sb.ACTIONS]) >= shard_size:
            # hand rows to the writer as we go: memory stays O(shard),
            # not O(num_steps), and shard_size actually shards
            emit(cols)
            cols = fresh()
    if cols[sb.ACTIONS]:
        emit(cols)
    writer.close()
    return path


class OfflineAlgorithm(Algorithm):
    """Base for dataset-trained algorithms (BC/MARWIL/CQL): no rollout
    workers, no weight broadcast; episode metrics come from periodic
    greedy eval rollouts against a local env (the reference's
    ``evaluation_interval`` rollouts for its offline family)."""

    def _evaluate(self) -> Dict[str, Any]:
        rewards = []
        for ep in range(self.eval_episodes):
            obs = self.eval_env.reset(seed=1000 + ep)
            total, done = 0.0, False
            while not done:
                a = self.compute_single_action(obs)
                obs, r, term, trunc, _ = self.eval_env.step(a)
                total += r
                done = term or trunc
            rewards.append(total)
        return {"episode_reward_mean": float(np.mean(rewards)),
                "episodes_total": len(rewards)}

    def _episode_metrics(self) -> Dict[str, Any]:
        return {}  # offline: metrics come from the eval rollouts above

    def _sync_weights(self) -> None:
        pass  # offline: no rollout workers exist to receive weights


class BC(OfflineAlgorithm):
    """Behavior cloning: supervised cross-entropy on a recorded dataset —
    the reference's BC algorithm (rllib/algorithms/bc), the simplest
    member of its offline family. No environment interaction during
    training; periodic greedy eval rollouts supply episode metrics."""

    def setup(self, config: Dict[str, Any]) -> None:
        import jax
        import optax

        self.cfg = config
        seed = config.get("seed", 0)
        self.reader = DatasetReader(config["input_path"], seed=seed)
        probe_env = make_env(config["env_spec"], config.get("env_config"))
        self.eval_env = probe_env
        hidden = config.get("hidden", (64, 64))
        self.params = {"pi": mlp_init(
            jax.random.key(seed),
            [probe_env.observation_dim, *hidden, probe_env.num_actions])}
        self.optimizer = optax.adam(config.get("lr", 1e-3))
        self.opt_state = self.optimizer.init(self.params)
        self.train_batch_size = config.get("train_batch_size", 256)
        self.updates_per_step = config.get("updates_per_step", 64)
        self.eval_episodes = config.get("eval_episodes", 2)
        self._updates_done = 0
        self._timesteps_total = 0  # offline: no env steps are sampled
        self.workers = None
        self.local_worker = None

        import jax.numpy as jnp

        def loss_fn(params, obs, actions):
            logits = mlp_apply(params["pi"], obs)
            logp = jax.nn.log_softmax(logits)
            nll = -jnp.take_along_axis(
                logp, actions[:, None], axis=-1)[:, 0]
            acc = (jnp.argmax(logits, -1) == actions).mean()
            return nll.mean(), acc

        @jax.jit
        def update(params, opt_state, obs, actions):
            (loss, acc), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, obs, actions)
            upd, opt_state = self.optimizer.update(grads, opt_state, params)
            import optax as _optax

            params = _optax.apply_updates(params, upd)
            return params, opt_state, loss, acc

        self._update = update

    def training_step(self) -> Dict[str, Any]:
        import jax.numpy as jnp

        t0 = time.time()
        loss = acc = 0.0
        for _ in range(self.updates_per_step):
            mb = self.reader.sample(self.train_batch_size)
            self.params, self.opt_state, loss, acc = self._update(
                self.params, self.opt_state,
                jnp.asarray(mb[sb.OBS]),
                jnp.asarray(mb[sb.ACTIONS].astype(np.int32)))
            self._updates_done += 1
        out = {
            "bc_loss": float(loss),
            "action_match": float(acc),
            "num_updates": self._updates_done,
            "dataset_size": self.reader.num_samples,
            "learn_time_s": time.time() - t0,
        }
        out.update(self._evaluate())
        return out

    def compute_single_action(self, obs: np.ndarray) -> int:
        import jax.numpy as jnp

        logits = mlp_apply(self.params["pi"], jnp.asarray(obs[None, :]))
        return int(np.asarray(logits)[0].argmax())

    def _save_extra_state(self):
        from .models import params_to_numpy

        return {"opt_state": params_to_numpy(self.opt_state),
                "updates_done": self._updates_done}

    def _load_extra_state(self, state) -> None:
        from .models import params_from_numpy

        if not state:
            return
        if "opt_state" in state:
            self.opt_state = params_from_numpy(state["opt_state"])
        self._updates_done = state.get("updates_done", 0)


class BCConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(BC)
        self.extra.update({"updates_per_step": 64, "eval_episodes": 2})

    def offline_data(self, *, input_path: str) -> "BCConfig":
        self.extra["input_path"] = input_path
        return self

    def training(self, *, updates_per_step=None, eval_episodes=None,
                 **kwargs) -> "BCConfig":
        super().training(**kwargs)
        if updates_per_step is not None:
            self.extra["updates_per_step"] = updates_per_step
        if eval_episodes is not None:
            self.extra["eval_episodes"] = eval_episodes
        return self


def importance_sampling_estimate(reader: DatasetReader, target_logp,
                                 gamma: float = 0.99) -> Dict[str, float]:
    """Off-policy evaluation of a target policy from behavior data:
    ordinary (IS) and weighted (WIS) per-episode importance sampling
    (rllib/offline/estimators/importance_sampling.py). ``target_logp``
    maps (obs [T, D], actions [T]) -> log-probs [T] under the policy
    being evaluated; the dataset supplies the behavior log-probs."""
    ep_returns = []
    ep_weights = []
    for ep in reader.iter_episodes():
        T = sb.batch_size(ep)
        discounts = gamma ** np.arange(T)
        ret = float(np.sum(ep[sb.REWARDS] * discounts))
        logp_t = np.asarray(target_logp(ep[sb.OBS], ep[sb.ACTIONS]),
                            np.float64)
        log_ratio = np.clip(logp_t - ep[BEHAVIOR_LOGP], -30.0, 30.0)
        ep_weights.append(float(np.exp(np.sum(log_ratio))))
        ep_returns.append(ret)
    w = np.asarray(ep_weights)
    r = np.asarray(ep_returns)
    return {
        "behavior_mean_return": float(r.mean()),
        "is_estimate": float(np.mean(w * r)),
        "wis_estimate": float(np.sum(w * r) / max(np.sum(w), 1e-12)),
        "episodes": len(r),
        "effective_sample_size": float(
            np.sum(w) ** 2 / max(np.sum(w ** 2), 1e-12)),
    }
