"""QMIX: cooperative multi-agent Q-learning with monotonic value
factorization.

The reference's QMIX (rllib/algorithms/qmix/qmix.py — replay-trained
joint Q; rllib/algorithms/qmix/qmix_policy.py:141 QMixLoss: per-agent
double-Q values fed through a state-conditioned monotonic mixing
network, Rashid et al. 2018). TPU-first shape: the whole update — every
agent's Q forward in ONE batched matmul (agents stack into the batch
axis), hypernetwork mixer, double-Q target mix, Huber TD loss, Adam —
is a single jit'd XLA program; epsilon-greedy rollouts run on CPU.

The mixer enforces dQ_tot/dQ_i >= 0 by taking ``abs`` of hypernetwork-
generated mixing weights (qmix_policy.py's QMixer.forward), so the
argmax over each agent's own Q is the argmax of Q_tot — decentralized
execution stays greedy-consistent with the centralized critic.

``TwoStepCoop`` is the paper's two-step coordination game (QMIX §7.1):
greedy independent learners settle for the safe 7-reward branch; value
factorization with a state-conditioned mixer finds the coordinated
8-reward branch. The suite's learning-regression test requires passing
the 7.0 plateau.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

from .. import api
from . import sample_batch as sb
from .algorithm import Algorithm, AlgorithmConfig
from .env import register_env
from .models import mlp_apply, mlp_init, params_from_numpy, params_to_numpy
from .multi_agent import MultiAgentEnv
from .replay import ReplayBuffer

STATE = "state"
NEXT_STATE = "next_state"
NEXT_OBS = "next_obs"


class TwoStepCoop(MultiAgentEnv):
    """The QMIX paper's two-step cooperative game. Step 1: agent_0's
    action picks the branch (0 -> safe state 2A, 1 -> risky state 2B).
    Step 2: 2A pays 7 whatever the joint action; 2B pays the matrix
    [[0, 1], [1, 8]] — both agents must pick action 1 for the 8.
    Observations: one-hot state (3) + one-hot agent id (N)."""

    N_STATES = 3  # 0 = first step, 1 = 2A, 2 = 2B

    def __init__(self, n_agents: int = 2, **_):
        self.n_agents = n_agents
        self.agent_ids = [f"agent_{i}" for i in range(n_agents)]
        self.observation_dim = self.N_STATES + n_agents
        self.num_actions = 2
        self._state = 0

    def state(self) -> np.ndarray:
        s = np.zeros(self.N_STATES, np.float32)
        s[self._state] = 1.0
        return s

    def _obs(self) -> Dict[str, np.ndarray]:
        out = {}
        for i, aid in enumerate(self.agent_ids):
            o = np.zeros(self.observation_dim, np.float32)
            o[self._state] = 1.0
            o[self.N_STATES + i] = 1.0
            out[aid] = o
        return out

    def reset(self, seed: Optional[int] = None) -> Dict[str, np.ndarray]:
        self._state = 0
        return self._obs()

    def step(self, actions: Dict[str, Any]):
        acts = [int(actions[aid]) for aid in self.agent_ids]
        if self._state == 0:
            self._state = 1 if acts[0] == 0 else 2
            reward, done = 0.0, False
        elif self._state == 1:
            reward, done = 7.0, True
        else:
            reward = float([[0.0, 1.0], [1.0, 8.0]][acts[0]][acts[1]])
            done = True
        obs = self._obs()
        rewards = {aid: reward for aid in self.agent_ids}
        dones = {aid: done for aid in self.agent_ids}
        dones["__all__"] = done
        truncs = {aid: False for aid in self.agent_ids}
        truncs["__all__"] = False
        return obs, rewards, dones, truncs, {}


register_env("TwoStepCoop", lambda **kw: TwoStepCoop(**kw))


# ---------------------------------------------------------------- networks
def qmix_init(rng, obs_dim: int, num_actions: int, n_agents: int,
              state_dim: int, hidden=(64,), mixing_dim: int = 32):
    """Shared per-agent Q net + hypernetwork mixer params."""
    import jax

    ks = jax.random.split(rng, 5)
    return {
        "agent": mlp_init(ks[0], [obs_dim, *hidden, num_actions]),
        # hypernetworks: linear maps from the global state to the mixing
        # weights (abs applied at use — monotonicity), plus a 2-layer
        # state bias for the output (qmix_policy.py QMixer.V)
        "hyper_w1": mlp_init(ks[1], [state_dim, n_agents * mixing_dim]),
        "hyper_b1": mlp_init(ks[2], [state_dim, mixing_dim]),
        "hyper_w2": mlp_init(ks[3], [state_dim, mixing_dim]),
        "hyper_b2": mlp_init(ks[4], [state_dim, mixing_dim, 1]),
    }


def agent_q(params, obs):
    """Per-agent Q-values; obs may be (..., obs_dim) — agents fold into
    the batch axis so the MXU sees one big matmul."""
    return mlp_apply(params["agent"], obs)


def mix(params, state, qs, n_agents: int, mixing_dim: int):
    """Monotonic mixer: Q_tot(state, q_1..q_N). qs: (B, N) -> (B,)."""
    import jax
    import jax.numpy as jnp

    B = qs.shape[0]
    w1 = jnp.abs(mlp_apply(params["hyper_w1"], state)).reshape(
        B, n_agents, mixing_dim)
    b1 = mlp_apply(params["hyper_b1"], state)
    h = jax.nn.elu(jnp.einsum("bn,bnm->bm", qs, w1) + b1)
    w2 = jnp.abs(mlp_apply(params["hyper_w2"], state))
    b2 = mlp_apply(params["hyper_b2"], state)[:, 0]
    return jnp.einsum("bm,bm->b", h, w2) + b2


def make_qmix_update(optimizer, gamma: float, n_agents: int,
                     mixing_dim: int):
    import jax
    import jax.numpy as jnp
    import optax

    def loss_fn(params, target_params, state, obs, actions, rewards,
                next_state, next_obs, dones):
        B = actions.shape[0]
        # (B, N, D) -> (B*N, D): every agent's forward in one matmul
        flat = obs.reshape(B * n_agents, -1)
        q = agent_q(params, flat).reshape(B, n_agents, -1)
        q_taken = jnp.take_along_axis(
            q, actions[..., None], axis=-1)[..., 0]          # (B, N)
        q_tot = mix(params, state, q_taken, n_agents, mixing_dim)

        # double-Q per agent: online net argmaxes, target net scores
        nflat = next_obs.reshape(B * n_agents, -1)
        nq_online = agent_q(params, nflat).reshape(B, n_agents, -1)
        next_a = jnp.argmax(nq_online, axis=-1)
        nq_target = agent_q(target_params, nflat).reshape(B, n_agents, -1)
        next_q = jnp.take_along_axis(
            nq_target, next_a[..., None], axis=-1)[..., 0]   # (B, N)
        next_tot = mix(target_params, next_state, next_q, n_agents,
                       mixing_dim)
        td_target = rewards + gamma * (1.0 - dones) * \
            jax.lax.stop_gradient(next_tot)
        loss = jnp.mean(optax.huber_loss(q_tot, td_target))
        return loss, {
            "mean_q_tot": q_tot.mean(),
            "mean_td_error": jnp.abs(q_tot - td_target).mean(),
        }

    @jax.jit
    def update(params, target_params, opt_state, state, obs, actions,
               rewards, next_state, next_obs, dones):
        (loss, stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, target_params, state, obs, actions, rewards,
            next_state, next_obs, dones)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        stats["loss"] = loss
        return params, opt_state, stats

    return update


# ---------------------------------------------------------------- rollouts
class QMixRolloutWorker:
    """Epsilon-greedy joint-transition collector over a cooperative
    MultiAgentEnv. Emits columnar joint transitions: state (S,),
    obs/next_obs (N, D), actions (N,), team reward, done — the joint
    replay schema QMIX trains on (qmix.py's EpisodeReplayBuffer,
    collapsed to transitions for the feed-forward mixer)."""

    def __init__(self, env_spec, env_config: Optional[dict], hidden,
                 seed: int):
        import jax

        from .env import make_env

        self.env = make_env(env_spec, env_config)
        if not isinstance(self.env, MultiAgentEnv):
            raise ValueError("QMIX requires a MultiAgentEnv")
        self.n_agents = len(self.env.agent_ids)
        self.rng = np.random.default_rng(seed)
        self.params = qmix_init(
            jax.random.key(0), self.env.observation_dim,
            self.env.num_actions, self.n_agents,
            len(self.env.state()), hidden)
        self._epsilon = 1.0
        self._obs = self.env.reset(seed=seed)
        self.episode_rewards: List[float] = []
        self._ep_reward = 0.0
        self.episode_lengths: List[int] = []
        self._ep_len = 0

    def ready(self) -> str:
        return "ok"

    def set_weights(self, weights) -> None:
        self.params = params_from_numpy(weights)

    def get_weights(self):
        return params_to_numpy(self.params)

    def _stack_obs(self) -> np.ndarray:
        return np.stack([self._obs[a] for a in self.env.agent_ids])

    def _select_actions(self) -> np.ndarray:
        import jax.numpy as jnp

        q = np.asarray(agent_q(self.params, jnp.asarray(self._stack_obs())))
        acts = q.argmax(axis=-1)
        explore = self.rng.random(self.n_agents) < self._epsilon
        rand = self.rng.integers(self.env.num_actions, size=self.n_agents)
        return np.where(explore, rand, acts).astype(np.int32)

    def sample(self, num_steps: int, epsilon: float) -> Dict[str, np.ndarray]:
        self._epsilon = epsilon
        N, D = self.n_agents, self.env.observation_dim
        S = len(self.env.state())
        cols = {
            STATE: np.zeros((num_steps, S), np.float32),
            sb.OBS: np.zeros((num_steps, N, D), np.float32),
            sb.ACTIONS: np.zeros((num_steps, N), np.int32),
            sb.REWARDS: np.zeros(num_steps, np.float32),
            NEXT_STATE: np.zeros((num_steps, S), np.float32),
            NEXT_OBS: np.zeros((num_steps, N, D), np.float32),
            sb.DONES: np.zeros(num_steps, np.float32),
        }
        for t in range(num_steps):
            cols[STATE][t] = self.env.state()
            cols[sb.OBS][t] = self._stack_obs()
            acts = self._select_actions()
            cols[sb.ACTIONS][t] = acts
            obs, rewards, dones, truncs, _ = self.env.step(
                {a: int(acts[i])
                 for i, a in enumerate(self.env.agent_ids)})
            self._obs = obs
            # team reward: cooperative tasks share one scalar (the
            # reference sums per-agent rewards into the mixer target)
            r = float(sum(rewards.values())) / self.n_agents
            done = bool(dones.get("__all__")) or bool(
                truncs.get("__all__"))
            cols[sb.REWARDS][t] = r
            cols[NEXT_STATE][t] = self.env.state()
            cols[NEXT_OBS][t] = self._stack_obs()
            cols[sb.DONES][t] = float(done)
            self._ep_reward += r
            self._ep_len += 1
            if done:
                self.episode_rewards.append(self._ep_reward)
                self.episode_lengths.append(self._ep_len)
                self._ep_reward, self._ep_len = 0.0, 0
                self._obs = self.env.reset(
                    seed=int(self.rng.integers(1 << 31)))
        return cols

    def episode_stats(self, window: int = 100) -> Dict[str, Any]:
        return sb.episode_stats_summary(
            self.episode_rewards, self.episode_lengths, window)

    def stop(self) -> str:
        return "stopped"


class _QMixWorkerSet:
    def __init__(self, env_spec, env_config, hidden, num_workers: int,
                 seed: int):
        cls = api.remote(QMixRolloutWorker)
        self.remote_workers = [
            cls.options(num_cpus=1).remote(
                env_spec, env_config, hidden, seed + 1000 * (i + 1))
            for i in range(num_workers)
        ]
        api.get([w.ready.remote() for w in self.remote_workers])

    def sample(self, num_steps: int, epsilon: float = 0.0) -> List:
        return [w.sample.remote(num_steps, epsilon)
                for w in self.remote_workers]

    def set_weights(self, weights) -> List:
        return [w.set_weights.remote(weights)
                for w in self.remote_workers]

    def stats(self) -> List[Dict[str, Any]]:
        return api.get(
            [w.episode_stats.remote() for w in self.remote_workers])

    def stop(self) -> None:
        for w in self.remote_workers:
            try:
                api.get(w.stop.remote(), timeout=5)
            except Exception:  # noqa: BLE001
                pass
            api.kill(w)


class QMix(Algorithm):
    def setup(self, config: Dict[str, Any]) -> None:
        import jax
        import optax

        from .env import make_env

        self.cfg = config
        if config.get("connectors"):
            raise ValueError("connectors are not supported by QMIX's "
                             "joint-transition collectors")
        seed = config.get("seed", 0)
        self.np_rng = np.random.default_rng(seed)
        probe = make_env(config["env_spec"], config.get("env_config"))
        if not isinstance(probe, MultiAgentEnv):
            raise ValueError("QMIX requires a MultiAgentEnv")
        self.n_agents = len(probe.agent_ids)
        self.obs_dim = probe.observation_dim
        self.num_actions = probe.num_actions
        self.state_dim = len(probe.state())
        hidden = config.get("hidden", (64,))
        self.mixing_dim = config.get("mixing_embed_dim", 32)
        self.params = qmix_init(
            jax.random.key(seed), self.obs_dim, self.num_actions,
            self.n_agents, self.state_dim, hidden, self.mixing_dim)
        self.target_params = jax.tree_util.tree_map(
            lambda x: x, self.params)
        self.gamma = config.get("gamma", 0.99)
        self.optimizer = optax.adam(config.get("lr", 5e-4))
        self.opt_state = self.optimizer.init(self.params)
        self._update = make_qmix_update(
            self.optimizer, self.gamma, self.n_agents, self.mixing_dim)
        self.replay = ReplayBuffer(
            config.get("replay_buffer_capacity", 20_000), seed=seed)
        self.learning_starts = config.get("learning_starts", 256)
        self.train_batch_size = config.get("train_batch_size", 64)
        self.target_update_freq = config.get(
            "target_network_update_freq", 100)
        self.updates_per_step = config.get("updates_per_step", 16)
        self.eps_initial = config.get("epsilon_initial", 1.0)
        self.eps_final = config.get("epsilon_final", 0.05)
        self.eps_timesteps = config.get("epsilon_timesteps", 3_000)
        self._updates_done = 0
        self._timesteps_total = 0
        self._iteration = 0

        n_workers = config.get("num_rollout_workers", 0)
        self.workers = None
        self.local_worker = None
        if n_workers > 0:
            self.workers = _QMixWorkerSet(
                config["env_spec"], config.get("env_config"), hidden,
                n_workers, seed)
        else:
            self.local_worker = QMixRolloutWorker(
                config["env_spec"], config.get("env_config"), hidden,
                seed)

    def _epsilon(self) -> float:
        frac = min(1.0, self._timesteps_total / max(1, self.eps_timesteps))
        return self.eps_initial + frac * (self.eps_final - self.eps_initial)

    def training_step(self) -> Dict[str, Any]:
        import jax
        import jax.numpy as jnp

        t0 = time.time()
        fragment = self.cfg.get("rollout_fragment_length", 64)
        eps = self._epsilon()
        self._sync_weights()
        if self.workers is not None:
            batches = api.get(self.workers.sample(fragment, eps))
        else:
            batches = [self.local_worker.sample(fragment, eps)]
        n = 0
        for b in batches:
            self.replay.add_batch(b)
            n += len(b[sb.ACTIONS])
        self._timesteps_total += n
        sample_time = time.time() - t0

        stats: Dict[str, Any] = {}
        t1 = time.time()
        if len(self.replay) >= self.learning_starts:
            for _ in range(self.updates_per_step):
                mb = self.replay.sample(self.train_batch_size)
                self.params, self.opt_state, stats = self._update(
                    self.params, self.target_params, self.opt_state,
                    jnp.asarray(mb[STATE]), jnp.asarray(mb[sb.OBS]),
                    jnp.asarray(mb[sb.ACTIONS]),
                    jnp.asarray(mb[sb.REWARDS]),
                    jnp.asarray(mb[NEXT_STATE]),
                    jnp.asarray(mb[NEXT_OBS]),
                    jnp.asarray(mb[sb.DONES]))
                self._updates_done += 1
                if self._updates_done % self.target_update_freq == 0:
                    self.target_params = jax.tree_util.tree_map(
                        lambda x: x, self.params)
        learn_time = time.time() - t1

        out = {k: float(v) for k, v in stats.items()}
        out.update({
            "num_env_steps_sampled": n,
            "replay_size": len(self.replay),
            "epsilon": eps,
            "num_updates": self._updates_done,
            "sample_time_s": sample_time,
            "learn_time_s": learn_time,
        })
        return out

    def compute_actions(self, obs_by_agent: Dict[str, np.ndarray]
                        ) -> Dict[str, int]:
        """Greedy decentralized execution: each agent argmaxes its own
        Q — monotonic mixing guarantees this also argmaxes Q_tot."""
        import jax.numpy as jnp

        ids = sorted(obs_by_agent)
        q = np.asarray(agent_q(
            self.params,
            jnp.asarray(np.stack([obs_by_agent[a] for a in ids]))))
        return {a: int(q[i].argmax()) for i, a in enumerate(ids)}

    def _save_extra_state(self):
        return {
            "opt_state": params_to_numpy(self.opt_state),
            "target_params": params_to_numpy(self.target_params),
            "updates_done": self._updates_done,
        }

    def _load_extra_state(self, state) -> None:
        if not state:
            return
        if "opt_state" in state:
            self.opt_state = params_from_numpy(state["opt_state"])
        if "target_params" in state:
            self.target_params = params_from_numpy(state["target_params"])
        self._updates_done = state.get("updates_done", 0)


class QMixConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(QMix)
        self.extra.update({
            "replay_buffer_capacity": 20_000, "learning_starts": 256,
            "target_network_update_freq": 100, "updates_per_step": 16,
            "epsilon_initial": 1.0, "epsilon_final": 0.05,
            "epsilon_timesteps": 3_000, "mixing_embed_dim": 32,
            "hidden": (64,),
        })

    def training(self, *, replay_buffer_capacity=None, learning_starts=None,
                 target_network_update_freq=None, updates_per_step=None,
                 epsilon_initial=None, epsilon_final=None,
                 epsilon_timesteps=None, mixing_embed_dim=None,
                 **kwargs) -> "QMixConfig":
        super().training(**kwargs)
        for k, v in (
                ("replay_buffer_capacity", replay_buffer_capacity),
                ("learning_starts", learning_starts),
                ("target_network_update_freq", target_network_update_freq),
                ("updates_per_step", updates_per_step),
                ("epsilon_initial", epsilon_initial),
                ("epsilon_final", epsilon_final),
                ("epsilon_timesteps", epsilon_timesteps),
                ("mixing_embed_dim", mixing_embed_dim)):
            if v is not None:
                self.extra[k] = v
        return self
