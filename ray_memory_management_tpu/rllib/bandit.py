"""Contextual bandits: LinUCB and linear Thompson sampling.

The reference's bandit family (rllib/algorithms/bandit/bandit.py —
BanditLinUCB / BanditLinTS configs; bandit_torch_model.py the disjoint
per-arm linear models with UCB exploration per Li et al. 2010 and
posterior sampling per Agrawal & Goyal 2013). TPU-first shape: all K
per-arm models live as one stacked tensor ([K, d, d] precision matrices,
[K, d] response vectors), arm selection is one jit'd vmap'd solve +
argmax, and the rank-1 posterior update is a second jit — there is no
per-arm Python loop anywhere.

Bandits interact step-by-step (no episodes): the env exposes a context
per step, the policy picks an arm, the env returns that arm's reward.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

import numpy as np

from .algorithm import Algorithm, AlgorithmConfig
from .env import register_env


class LinearDiscreteBandit:
    """K-armed contextual bandit with linear payoffs: reward =
    theta_arm . context + noise (the reference's
    LinearDiscreteEnv, rllib/examples/env/bandit_envs_discrete.py)."""

    def __init__(self, num_arms: int = 5, context_dim: int = 8,
                 noise: float = 0.1, seed: int = 0):
        rng = np.random.default_rng(seed)
        self.num_arms = num_arms
        self.context_dim = context_dim
        self.noise = noise
        self.theta = rng.normal(size=(num_arms, context_dim))
        self.theta /= np.linalg.norm(self.theta, axis=1, keepdims=True)
        self._rng = rng
        self._ctx: Optional[np.ndarray] = None

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        return self._next_context()

    def _next_context(self) -> np.ndarray:
        self._ctx = self._rng.normal(
            size=self.context_dim).astype(np.float32)
        self._ctx /= max(np.linalg.norm(self._ctx), 1e-8)
        return self._ctx

    def step(self, arm: int):
        means = self.theta @ self._ctx
        reward = float(means[arm] + self.noise * self._rng.normal())
        regret = float(means.max() - means[arm])
        ctx = self._next_context()
        return ctx, reward, regret

    @property
    def observation_dim(self) -> int:
        return self.context_dim

    @property
    def num_actions(self) -> int:
        # the shared env registry makes this env discoverable by every
        # algorithm; fail LOUDLY at the probe (Algorithm.setup reads
        # num_actions) instead of letting a rollout worker mis-unpack
        # the bandit step's (ctx, reward, regret) return
        raise TypeError(
            "LinearBandit is a contextual-bandit env (step-level "
            "context/arm/reward, no episodes); train it with "
            "BanditLinUCB / BanditLinTS, not an RL algorithm")


register_env("LinearBandit", LinearDiscreteBandit)


def make_bandit_programs(num_arms: int, dim: int, alpha: float,
                         lam: float, mode: str):
    """Two jit'd programs over the stacked per-arm state:
    select(state, ctx, key) -> arm; update(state, ctx, arm, r) -> state.
    ``mode``: "ucb" (deterministic bonus) or "ts" (posterior draw)."""
    import jax
    import jax.numpy as jnp

    if mode not in ("ucb", "ts"):
        raise ValueError(
            f"unknown bandit exploration mode {mode!r}; use 'ucb' "
            "(LinUCB bonus) or 'ts' (Thompson posterior draw)")

    def init_state():
        A = jnp.tile(lam * jnp.eye(dim)[None], (num_arms, 1, 1))
        b = jnp.zeros((num_arms, dim))
        return {"A": A, "b": b}

    @jax.jit
    def select(state, ctx, key):
        # one batched solve across all arms: A_k theta_k = b_k and
        # A_k u_k = ctx (for the variance term) in a single vmap
        def per_arm(A, b):
            theta = jnp.linalg.solve(A, b)
            u = jnp.linalg.solve(A, ctx)
            mean = theta @ ctx
            var = jnp.maximum(ctx @ u, 1e-12)
            return mean, var

        means, variances = jax.vmap(per_arm)(state["A"], state["b"])
        if mode == "ts":
            # Thompson: one Gaussian draw per arm from the posterior
            # payoff distribution N(mean, alpha^2 * var)
            scores = means + alpha * jnp.sqrt(variances) * \
                jax.random.normal(key, means.shape)
        else:
            scores = means + alpha * jnp.sqrt(variances)
        return jnp.argmax(scores)

    @jax.jit
    def update(state, ctx, arm, reward):
        # rank-1 update of the chosen arm only (scatter via .at)
        A = state["A"].at[arm].add(jnp.outer(ctx, ctx))
        b = state["b"].at[arm].add(reward * ctx)
        return {"A": A, "b": b}

    return init_state, select, update


class BanditLinUCB(Algorithm):
    """Disjoint LinUCB (mode="ucb"); BanditLinTS flips the config's
    exploration mode to posterior sampling."""

    _mode = "ucb"

    def setup(self, config: Dict[str, Any]) -> None:
        import jax

        from .env import make_env

        self.cfg = config
        if config.get("connectors"):
            raise ValueError(
                "connectors are not supported by the bandit algorithms; "
                "transform contexts in the env instead")
        seed = config.get("seed", 0)
        self.env = make_env(config["env_spec"], config.get("env_config"))
        if not hasattr(self.env, "num_arms"):
            raise TypeError(
                f"{config['env_spec']!r} is not a contextual-bandit env "
                "(needs num_arms / step(arm) -> (ctx, reward, regret))")
        self.num_arms = self.env.num_arms
        dim = self.env.observation_dim
        init_state, self._select, self._update = make_bandit_programs(
            self.num_arms, dim, config.get("alpha", 1.0),
            config.get("lambda_reg", 1.0),
            config.get("exploration", self._mode))
        self.state = init_state()
        self._key = jax.random.PRNGKey(seed)
        self._ctx = self.env.reset(seed=seed)
        self.steps_per_iter = config.get("steps_per_iter", 100)
        self._timesteps_total = 0
        self.cumulative_reward = 0.0
        self.cumulative_regret = 0.0
        self.workers = None
        self.local_worker = None

    def training_step(self) -> Dict[str, Any]:
        import jax
        import jax.numpy as jnp

        t0 = time.time()
        window_reward = window_regret = 0.0
        for _ in range(self.steps_per_iter):
            self._key, sub = jax.random.split(self._key)
            ctx = jnp.asarray(self._ctx)
            arm = int(self._select(self.state, ctx, sub))
            next_ctx, reward, regret = self.env.step(arm)
            self.state = self._update(self.state, ctx, arm,
                                      jnp.float32(reward))
            self._ctx = next_ctx
            window_reward += reward
            window_regret += regret
            self._timesteps_total += 1
        self.cumulative_reward += window_reward
        self.cumulative_regret += window_regret
        return {
            "num_env_steps_sampled": self.steps_per_iter,
            "episode_reward_mean": window_reward / self.steps_per_iter,
            "regret_mean": window_regret / self.steps_per_iter,
            "cumulative_reward": self.cumulative_reward,
            "cumulative_regret": self.cumulative_regret,
            "time_this_iter_s": time.time() - t0,
        }

    def compute_single_action(self, obs: np.ndarray) -> int:
        import jax
        import jax.numpy as jnp

        self._key, sub = jax.random.split(self._key)
        return int(self._select(self.state, jnp.asarray(obs), sub))

    def _episode_metrics(self) -> Dict[str, Any]:
        return {}  # bandits: per-iter means reported by training_step

    def _sync_weights(self) -> None:
        pass  # no rollout workers: bandits interact synchronously

    def get_weights(self):
        return {k: np.asarray(v) for k, v in self.state.items()}

    def set_weights(self, weights) -> None:
        import jax.numpy as jnp

        self.state = {k: jnp.asarray(v) for k, v in weights.items()}

    def _save_extra_state(self):
        # A/b already persist as the checkpoint's weights (the .params
        # property); duplicating them here would double checkpoint size
        return {"cumulative_reward": self.cumulative_reward,
                "cumulative_regret": self.cumulative_regret,
                "timesteps": self._timesteps_total}

    def _load_extra_state(self, state) -> None:
        if not state:
            return
        self.cumulative_reward = state.get("cumulative_reward", 0.0)
        self.cumulative_regret = state.get("cumulative_regret", 0.0)
        self._timesteps_total = state.get("timesteps", 0)

    # Trainable save path reads .params on algorithms; bandits keep the
    # stacked linear state instead
    @property
    def params(self):
        return self.state

    @params.setter
    def params(self, value):
        self.state = value


class BanditLinTS(BanditLinUCB):
    _mode = "ts"


class BanditLinUCBConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(BanditLinUCB)
        self.env_spec = "LinearBandit"
        self.extra.update({"alpha": 1.0, "lambda_reg": 1.0,
                           "steps_per_iter": 100})

    def training(self, *, alpha=None, lambda_reg=None, steps_per_iter=None,
                 **kwargs) -> "BanditLinUCBConfig":
        super().training(**kwargs)
        for k, v in (("alpha", alpha), ("lambda_reg", lambda_reg),
                     ("steps_per_iter", steps_per_iter)):
            if v is not None:
                self.extra[k] = v
        return self


class BanditLinTSConfig(BanditLinUCBConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = BanditLinTS
