"""Replay buffers for off-policy algorithms.

The reference's replay buffer suite (rllib/utils/replay_buffers/):
uniform ring-buffer replay plus proportional prioritized replay
(Schaul et al.), stored as columnar numpy so sampled batches feed jax
without per-row boxing.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np


class ReplayBuffer:
    """Uniform FIFO replay over columnar transition storage."""

    def __init__(self, capacity: int, seed: int = 0):
        self.capacity = capacity
        self._storage: Dict[str, np.ndarray] = {}
        self._idx = 0
        self._size = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self._size

    def add_batch(self, batch: Dict[str, np.ndarray]) -> None:
        n = len(next(iter(batch.values())))
        if not self._storage:
            for k, v in batch.items():
                v = np.asarray(v)
                self._storage[k] = np.zeros(
                    (self.capacity, *v.shape[1:]), dtype=v.dtype)
        for start in range(0, n, self.capacity):
            chunk = {k: np.asarray(v)[start:start + self.capacity]
                     for k, v in batch.items()}
            m = len(next(iter(chunk.values())))
            end = self._idx + m
            for k, v in chunk.items():
                if end <= self.capacity:
                    self._storage[k][self._idx:end] = v
                else:
                    split = self.capacity - self._idx
                    self._storage[k][self._idx:] = v[:split]
                    self._storage[k][:end - self.capacity] = v[split:]
            self._idx = end % self.capacity
            self._size = min(self._size + m, self.capacity)

    def sample(self, num_items: int) -> Dict[str, np.ndarray]:
        idx = self._rng.integers(0, self._size, size=num_items)
        return {k: v[idx] for k, v in self._storage.items()}


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritization: P(i) ∝ priority_i^alpha, with
    importance-sampling weights beta-annealed by the caller."""

    def __init__(self, capacity: int, alpha: float = 0.6, seed: int = 0):
        super().__init__(capacity, seed)
        self.alpha = alpha
        self._priorities = np.zeros(capacity, dtype=np.float64)
        self._max_priority = 1.0

    def add_batch(self, batch: Dict[str, np.ndarray]) -> None:
        n = len(next(iter(batch.values())))
        start_idx = self._idx
        super().add_batch(batch)
        for i in range(n):
            self._priorities[(start_idx + i) % self.capacity] = \
                self._max_priority

    def sample(self, num_items: int, beta: float = 0.4):
        prios = self._priorities[: self._size] ** self.alpha
        probs = prios / prios.sum()
        idx = self._rng.choice(self._size, size=num_items, p=probs)
        weights = (self._size * probs[idx]) ** (-beta)
        weights /= weights.max()
        out = {k: v[idx] for k, v in self._storage.items()}
        out["_weights"] = weights.astype(np.float32)
        out["_indices"] = idx
        return out

    def update_priorities(self, indices: np.ndarray,
                          priorities: np.ndarray) -> None:
        for i, p in zip(indices, priorities):
            self._priorities[i] = max(float(p), 1e-8)
            self._max_priority = max(self._max_priority, float(p))
