"""MADDPG: multi-agent DDPG with centralized critics (Lowe et al. 2017).

The reference's rllib/algorithms/maddpg/maddpg.py: each agent i trains a
deterministic actor mu_i(o_i) plus a CENTRALIZED critic
Q_i(o_1..o_N, a_1..a_N) that sees every agent's observation and action —
the critic is only needed at training time, so execution stays fully
decentralized. Off-policy over a joint replay buffer; in the actor step
agent i's own action is replaced by mu_i(o_i) while the other agents'
actions come from the batch (the MADDPG gradient).

TPU-first redesign: the reference keeps N independent policy graphs and
loops over them; here the N (homogeneous-shaped) agents' parameters are
STACKED along a leading axis and every per-agent computation — target
actions, critic TD steps, actor gradients, polyak syncs — is vmapped, so
the whole N-agent update is ONE jit'd XLA program whose batch dimension
covers agents x minibatch (the MXU sees [N*B, ...] matmuls instead of N
small graphs).
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

import numpy as np

from .algorithm import Algorithm, AlgorithmConfig
from .env import register_env
from .models import mlp_apply, mlp_init
from .multi_agent import MultiAgentEnv
from .replay import ReplayBuffer


class Rendezvous(MultiAgentEnv):
    """Continuous cooperative rendezvous: N point agents on the [-1,1]^2
    plane apply velocity actions and share the reward
    ``-mean pairwise distance`` (+ a success bonus when gathered) — the
    cooperative-navigation shape of the MADDPG paper's particle envs
    (reference rllib: the MPE simple_spread family), reduced to its
    learning-signal core."""

    def __init__(self, n_agents: int = 2, max_episode_steps: int = 50,
                 gather_radius: float = 0.1):
        self.agent_ids = [f"agent_{i}" for i in range(n_agents)]
        self.n_agents = n_agents
        self.observation_dim = 2 * n_agents  # own pos first, then others
        self.action_dim = 2
        self.action_bound = 1.0
        self.max_episode_steps = max_episode_steps
        self.gather_radius = gather_radius
        self._pos = np.zeros((n_agents, 2), np.float32)
        self._t = 0
        self._rng = np.random.default_rng(0)

    def _obs(self) -> Dict[str, np.ndarray]:
        out = {}
        for i, aid in enumerate(self.agent_ids):
            others = np.delete(self._pos, i, axis=0).ravel()
            out[aid] = np.concatenate([self._pos[i], others]).astype(
                np.float32)
        return out

    def _mean_pairwise(self) -> float:
        d = self._pos[:, None, :] - self._pos[None, :, :]
        dist = np.sqrt((d * d).sum(-1) + 1e-12)
        n = self.n_agents
        return float(dist.sum() / (n * (n - 1))) if n > 1 else 0.0

    def reset(self, seed: Optional[int] = None) -> Dict[str, np.ndarray]:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._pos = self._rng.uniform(-1, 1, (self.n_agents, 2)).astype(
            np.float32)
        self._t = 0
        return self._obs()

    def step(self, actions: Dict[str, Any]):
        self._t += 1
        for i, aid in enumerate(self.agent_ids):
            a = np.clip(np.asarray(actions[aid], np.float32), -1.0, 1.0)
            self._pos[i] = np.clip(self._pos[i] + 0.1 * a, -1.0, 1.0)
        spread = self._mean_pairwise()
        gathered = spread < self.gather_radius
        r = -spread + (5.0 if gathered else 0.0)
        rewards = {aid: r for aid in self.agent_ids}
        term = bool(gathered)
        trunc = self._t >= self.max_episode_steps
        terms = {aid: term for aid in self.agent_ids}
        truncs = {aid: trunc for aid in self.agent_ids}
        terms["__all__"] = term
        truncs["__all__"] = trunc
        return self._obs(), rewards, terms, truncs, {}


register_env("Rendezvous", Rendezvous)


def maddpg_init(rng, n_agents: int, obs_dim: int, act_dim: int,
                hidden=(64, 64)):
    """Per-agent actor + centralized critic, STACKED along agent axis 0
    (every leaf is [N, ...]); built by vmapping the initializer over
    per-agent keys."""
    import jax

    joint = n_agents * (obs_dim + act_dim)

    def one(key):
        k_pi, k_q = jax.random.split(key)
        return {"pi": mlp_init(k_pi, [obs_dim, *hidden, act_dim]),
                "q": mlp_init(k_q, [joint, *hidden, 1])}

    return jax.vmap(one)(jax.random.split(rng, n_agents))


def make_maddpg_update(pi_opt, q_opt, gamma: float, tau: float,
                       bound: float):
    import jax
    import jax.numpy as jnp
    import optax

    def actions_of(pi_stacked, obs_nb):  # obs_nb: [N, B, d_o]
        return jax.vmap(lambda p, o: bound * jnp.tanh(mlp_apply(p, o)))(
            pi_stacked, obs_nb)  # -> [N, B, d_a]

    def q_of(q_stacked, joint_b):  # joint_b: [B, joint] shared input
        return jax.vmap(
            lambda p: mlp_apply(p, joint_b)[..., 0])(q_stacked)  # [N, B]

    def critic_loss(params, target_params, batch):
        obs, act, rew, nxt, done = batch  # [B,N,do],[B,N,da],[B,N],...,[B]
        B = obs.shape[0]
        nxt_nb = jnp.swapaxes(nxt, 0, 1)                  # [N, B, d_o]
        tgt_act = actions_of(target_params["pi"], nxt_nb)
        tgt_joint = jnp.concatenate(
            [nxt.reshape(B, -1),
             jnp.swapaxes(tgt_act, 0, 1).reshape(B, -1)], -1)
        tq = q_of(target_params["q"], tgt_joint)          # [N, B]
        target = jnp.swapaxes(rew, 0, 1) + gamma * (1.0 - done)[None, :] \
            * jax.lax.stop_gradient(tq)
        joint = jnp.concatenate(
            [obs.reshape(B, -1), act.reshape(B, -1)], -1)
        q = q_of(params["q"], joint)                      # [N, B]
        return jnp.mean((q - target) ** 2), q.mean()

    def actor_loss(pi_stacked, params, batch):
        obs, act, _, _, _ = batch
        B, N, d_a = act.shape
        obs_nb = jnp.swapaxes(obs, 0, 1)                  # [N, B, d_o]
        my_act = actions_of(pi_stacked, obs_nb)           # [N, B, d_a]
        # agent i's joint action: batch actions with COLUMN i replaced by
        # mu_i(o_i) — one-hot masking keeps it a single vmapped program
        eye = jnp.eye(N)[:, None, :, None]                # [N, 1, N, 1]
        batch_a = act[None]                               # [1, B, N, d_a]
        mine = jnp.swapaxes(my_act, 0, 1)[None]           # [1, B, N, d_a]

        def joint_for(i_onehot):
            return batch_a * (1.0 - i_onehot) + mine * i_onehot

        joints = jax.vmap(joint_for)(eye)                 # [N,1,B,N,d_a]
        joints = joints[:, 0].reshape(N, B, N * d_a)
        full = jnp.concatenate(
            [jnp.broadcast_to(obs.reshape(B, -1)[None],
                              (N, B, obs.shape[1] * obs.shape[2])),
             joints], -1)                                 # [N, B, joint]
        q = jax.vmap(lambda p, x: mlp_apply(p, x)[..., 0])(
            params["q"], full)                            # [N, B]
        return -jnp.mean(q)

    @jax.jit
    def update(params, target_params, opt_states, batch):
        pi_state, q_state = opt_states
        # critic step: grads flow only through the critics (next actions
        # come from target params), so updating the "q" subtree alone is
        # exact — and keeps each optimizer's moments scoped to its net
        (c_loss, mean_q), c_grads = jax.value_and_grad(
            critic_loss, has_aux=True)(params, target_params, batch)
        q_upd, q_state = q_opt.update(c_grads["q"], q_state, params["q"])
        params = {**params,
                  "q": optax.apply_updates(params["q"], q_upd)}

        a_loss, pi_grads = jax.value_and_grad(actor_loss)(
            params["pi"], params, batch)
        pi_upd, pi_state = pi_opt.update(pi_grads, pi_state, params["pi"])
        params = {**params,
                  "pi": optax.apply_updates(params["pi"], pi_upd)}

        target_params = jax.tree_util.tree_map(
            lambda t, p: (1.0 - tau) * t + tau * p, target_params, params)
        stats = {"critic_loss": c_loss, "actor_loss": a_loss,
                 "mean_q": mean_q}
        return params, target_params, (pi_state, q_state), stats

    return update


class MADDPG(Algorithm):
    def setup(self, config: Dict[str, Any]) -> None:
        import jax
        import optax

        from .env import make_env

        self.cfg = config
        seed = config.get("seed", 0)
        self.env = make_env(config["env_spec"], config.get("env_config"))
        if not isinstance(self.env, MultiAgentEnv):
            raise ValueError("MADDPG trains multi-agent envs; use "
                             "TD3/DDPG for single-agent control")
        self.n_agents = len(self.env.agent_ids)
        self.obs_dim = self.env.observation_dim
        self.act_dim = int(getattr(self.env, "action_dim", 1))
        self.bound = float(getattr(self.env, "action_bound", 1.0))
        hidden = config.get("hidden", (64, 64))
        self.params = maddpg_init(jax.random.key(seed), self.n_agents,
                                  self.obs_dim, self.act_dim, hidden)
        self.target_params = jax.tree_util.tree_map(
            lambda x: x, self.params)
        self.pi_opt = optax.adam(config.get("lr", 1e-3))
        self.q_opt = optax.adam(config.get("lr", 1e-3))
        self.opt_states = (self.pi_opt.init(self.params["pi"]),
                           self.q_opt.init(self.params["q"]))
        self._update = make_maddpg_update(
            self.pi_opt, self.q_opt, config.get("gamma", 0.95),
            config.get("tau", 0.01), self.bound)
        self.buffer = ReplayBuffer(config.get("buffer_size", 100_000))
        self.batch_size = config.get("train_batch_size", 256)
        self.sigma = config.get("exploration_sigma", 0.3)
        self.random_steps = config.get("random_steps", 500)
        self.updates_per_step = config.get("updates_per_iter", 20)
        self.rollout_steps = config.get("rollout_fragment_length", 200)
        self._rng = np.random.default_rng(seed)
        self._obs = self.env.reset(seed=seed)
        self._ep_reward = 0.0
        self._ep_len = 0
        self.episode_rewards: list = []
        self._steps_sampled = 0
        self._timesteps_total = 0  # algorithm.step's progress counter
        self._updates_done = 0
        self.workers = None        # local rollouts only (base contract)
        self.local_worker = None

    # ------------------------------------------------------------ rollouts
    def _act(self, obs_dict, explore: bool = True) -> Dict[str, np.ndarray]:
        import jax.numpy as jnp

        obs_nb = jnp.asarray(
            np.stack([obs_dict[a] for a in self.env.agent_ids])[:, None])
        import jax

        acts = np.asarray(jax.vmap(
            lambda p, o: self.bound * jnp.tanh(mlp_apply(p, o)))(
                self.params["pi"], obs_nb))[:, 0]          # [N, d_a]
        if explore:
            if self._steps_sampled < self.random_steps:
                acts = self._rng.uniform(
                    -self.bound, self.bound, acts.shape).astype(np.float32)
            else:
                acts = np.clip(
                    acts + self.sigma * self._rng.standard_normal(
                        acts.shape).astype(np.float32),
                    -self.bound, self.bound)
        return {aid: acts[i] for i, aid in enumerate(self.env.agent_ids)}

    def _rollout(self, num_steps: int) -> None:
        ids = self.env.agent_ids
        cols = {"obs": [], "act": [], "rew": [], "next_obs": [], "done": []}
        for _ in range(num_steps):
            acts = self._act(self._obs)
            nxt, rew, terms, truncs, _ = self.env.step(acts)
            done = bool(terms.get("__all__"))
            trunc = bool(truncs.get("__all__"))
            cols["obs"].append(np.stack([self._obs[a] for a in ids]))
            cols["act"].append(np.stack(
                [np.asarray(acts[a], np.float32) for a in ids]))
            cols["rew"].append(
                np.asarray([rew[a] for a in ids], np.float32))
            cols["next_obs"].append(np.stack([nxt[a] for a in ids]))
            # truncation bootstraps (done=0), true terminals don't —
            # the same rule the single-agent collectors apply
            cols["done"].append(
                np.float32(1.0 if done and not trunc else 0.0))
            self._ep_reward += float(np.mean([rew[a] for a in ids]))
            self._ep_len += 1
            self._steps_sampled += 1
            self._timesteps_total += 1
            if done or trunc:
                self.episode_rewards.append(self._ep_reward)
                self._obs = self.env.reset(
                    seed=int(self._rng.integers(1 << 31)))
                self._ep_reward, self._ep_len = 0.0, 0
            else:
                self._obs = nxt
        self.buffer.add_batch({k: np.stack(v) for k, v in cols.items()})

    # ------------------------------------------------------------ training
    def training_step(self) -> Dict[str, Any]:
        import jax.numpy as jnp

        t0 = time.time()
        self._rollout(self.rollout_steps)
        stats = {}
        if len(self.buffer) >= self.batch_size:
            for _ in range(self.updates_per_step):
                cols = self.buffer.sample(self.batch_size)
                batch = (
                    jnp.asarray(cols["obs"]), jnp.asarray(cols["act"]),
                    jnp.asarray(cols["rew"]),
                    jnp.asarray(cols["next_obs"]),
                    jnp.asarray(cols["done"]),
                )
                (self.params, self.target_params, self.opt_states,
                 stats) = self._update(self.params, self.target_params,
                                       self.opt_states, batch)
                self._updates_done += 1
        recent = self.episode_rewards[-20:]
        return {
            "episode_reward_mean": float(np.mean(recent)) if recent
            else float("nan"),
            "episodes_total": len(self.episode_rewards),
            "timesteps_total": self._steps_sampled,
            "num_updates": self._updates_done,
            **{k: float(v) for k, v in stats.items()},
            "time_this_iter_s": time.time() - t0,
        }

    def _episode_metrics(self) -> Dict[str, Any]:
        recent = self.episode_rewards[-100:]
        return {
            "episode_reward_mean": float(np.mean(recent)) if recent
            else None,
            "episode_len_mean": None,
            "episodes_total": len(self.episode_rewards),
        }

    def compute_actions(self, obs_dict) -> Dict[str, np.ndarray]:
        """Decentralized execution: actors only, no critic."""
        return self._act(obs_dict, explore=False)

    def get_weights(self):
        import jax

        return jax.tree_util.tree_map(np.asarray, self.params)

    def set_weights(self, weights) -> None:
        import jax
        import jax.numpy as jnp

        self.params = jax.tree_util.tree_map(jnp.asarray, weights)

    def _sync_weights(self) -> None:
        pass  # local rollouts

    def _save_extra_state(self):
        import jax

        return {"params": jax.tree_util.tree_map(np.asarray, self.params),
                "target": jax.tree_util.tree_map(np.asarray,
                                                 self.target_params),
                "steps": self._steps_sampled,
                "updates": self._updates_done}

    def _load_extra_state(self, state) -> None:
        import jax.numpy as jnp

        if not state:
            return
        import jax

        self.params = jax.tree_util.tree_map(jnp.asarray, state["params"])
        self.target_params = jax.tree_util.tree_map(
            jnp.asarray, state["target"])
        self.opt_states = (self.pi_opt.init(self.params["pi"]),
                           self.q_opt.init(self.params["q"]))
        self._steps_sampled = state.get("steps", 0)
        self._updates_done = state.get("updates", 0)


class MADDPGConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(MADDPG)
        self.extra.update({
            "tau": 0.01, "exploration_sigma": 0.3, "random_steps": 500,
            "updates_per_iter": 20, "buffer_size": 100_000,
            "rollout_fragment_length": 200,
        })

    def training(self, *, tau=None, exploration_sigma=None,
                 random_steps=None, updates_per_iter=None,
                 buffer_size=None, **kwargs) -> "MADDPGConfig":
        super().training(**kwargs)
        for k, v in (("tau", tau),
                     ("exploration_sigma", exploration_sigma),
                     ("random_steps", random_steps),
                     ("updates_per_iter", updates_per_iter),
                     ("buffer_size", buffer_size)):
            if v is not None:
                self.extra[k] = v
        return self
