"""Policy/value networks as pure jax functions.

The reference's model catalog builds torch/tf nets (rllib/models/catalog.py,
with a small models/jax/ tree); here nets are jax pytrees + pure apply
functions (module-level, so they pickle by reference into rollout actors).
MLPs batch cleanly onto the MXU; bigger models plug in by passing custom
init/apply callables through the config.
"""

from __future__ import annotations

from typing import Any, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def mlp_init(rng: jax.Array, sizes: Sequence[int]) -> List[Dict[str, Any]]:
    """Orthogonal-ish (scaled normal) init for a relu MLP."""
    params = []
    keys = jax.random.split(rng, len(sizes) - 1)
    for k, (fan_in, fan_out) in zip(keys, zip(sizes[:-1], sizes[1:])):
        w = jax.random.normal(k, (fan_in, fan_out)) * jnp.sqrt(2.0 / fan_in)
        params.append({"w": w, "b": jnp.zeros((fan_out,))})
    return params


def mlp_apply(params: List[Dict[str, Any]], x: jnp.ndarray) -> jnp.ndarray:
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1:
            x = jax.nn.relu(x)
    return x


def ac_init(rng: jax.Array, obs_dim: int, num_actions: int,
            hidden: Sequence[int] = (64, 64)) -> Dict[str, Any]:
    """Separate policy and value towers (the reference's default
    fcnet_hiddens=[256,256] shape, scaled down)."""
    k_pi, k_vf = jax.random.split(rng)
    return {
        "pi": mlp_init(k_pi, [obs_dim, *hidden, num_actions]),
        "vf": mlp_init(k_vf, [obs_dim, *hidden, 1]),
    }


def ac_apply(params: Dict[str, Any],
             obs: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (logits [B, A], values [B])."""
    logits = mlp_apply(params["pi"], obs)
    values = mlp_apply(params["vf"], obs)[..., 0]
    return logits, values


@jax.jit
def sample_actions(params: Dict[str, Any], obs: jnp.ndarray,
                   rng: jax.Array):
    """Sample actions + logp + value for a batch of observations (the
    rollout hot path; jit so repeated sampling reuses the compiled fn)."""
    logits, values = ac_apply(params, obs)
    actions = jax.random.categorical(rng, logits)
    logp_all = jax.nn.log_softmax(logits)
    logp = jnp.take_along_axis(
        logp_all, actions[:, None], axis=-1)[:, 0]
    return actions, logp, values


def params_to_numpy(params) -> Any:
    return jax.tree_util.tree_map(np.asarray, params)


def params_from_numpy(params) -> Any:
    return jax.tree_util.tree_map(jnp.asarray, params)
