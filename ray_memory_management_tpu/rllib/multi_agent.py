"""Multi-agent environments with shared-policy training.

The reference's MultiAgentEnv (rllib/env/multi_agent_env.py:23 — dict
obs/rewards/dones keyed by agent id, "__all__" signalling episode end;
rllib/evaluation/episode.py tracks per-agent trajectories; the common
"parameter sharing" configuration maps every agent to one policy). This
module implements that contract for the shared-policy case, which every
on-policy algorithm here (PPO/PG/IMPALA/APPO) trains without learner
changes:

- per env step, ALL live agents' observations stack into ONE policy
  forward (a single `sample_actions` batch — the MXU-friendly shape);
- each agent accumulates its own trajectory segment; when the agent
  terminates (or the fragment ends mid-episode) the segment closes with
  the truncation rule the single-agent worker uses — fold
  gamma * V(s_next) into the last reward and cut the trace (done=1) —
  so concatenated segments remain a valid flat fragment: GAE's reverse
  scan resets at each segment boundary and the fragment-level bootstrap
  is exactly 0.0 (V-trace consumers see the same contract).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from . import sample_batch as sb
from .env import CartPole, register_env
from .models import ac_init, params_from_numpy, params_to_numpy, \
    sample_actions

ALL_DONE = "__all__"


class MultiAgentEnv:
    """Contract: agents share observation_dim / num_actions (the shared-
    policy case); ids may drop out as agents terminate mid-episode."""

    agent_ids: List[str] = []
    observation_dim: int = 0
    num_actions: int = 0

    def reset(self, seed: Optional[int] = None) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    def step(self, actions: Dict[str, Any]):
        """-> (obs, rewards, terminateds, truncateds, info), each a dict
        keyed by agent id; terminateds/truncateds also carry "__all__"."""
        raise NotImplementedError


class MultiCartPole(MultiAgentEnv):
    """N independent CartPole instances under one episode clock — the
    reference's multi-agent cartpole example (examples/env/
    multi_agent.py:17). An agent whose pole falls drops out; the episode
    ends when every agent is done or the time limit hits."""

    def __init__(self, n_agents: int = 2, max_episode_steps: int = 200):
        self.agent_ids = [f"agent_{i}" for i in range(n_agents)]
        self._envs = {aid: CartPole(max_episode_steps=max_episode_steps)
                      for aid in self.agent_ids}
        self.observation_dim = 4
        self.num_actions = 2
        self.max_episode_steps = max_episode_steps
        self._live: List[str] = []
        self._t = 0

    def reset(self, seed: Optional[int] = None) -> Dict[str, np.ndarray]:
        self._live = list(self.agent_ids)
        self._t = 0
        return {aid: env.reset(
            seed=None if seed is None else seed + i)
            for i, (aid, env) in enumerate(self._envs.items())}

    def step(self, actions: Dict[str, Any]):
        obs, rewards, terms, truncs = {}, {}, {}, {}
        self._t += 1
        for aid in list(self._live):
            o, r, term, trunc, _ = self._envs[aid].step(actions[aid])
            obs[aid], rewards[aid] = o, r
            terms[aid], truncs[aid] = term, trunc
            if term or trunc:
                self._live.remove(aid)
        terms[ALL_DONE] = not self._live
        truncs[ALL_DONE] = self._t >= self.max_episode_steps
        return obs, rewards, terms, truncs, {}


register_env("MultiCartPole", MultiCartPole)


class _Segment:
    """One agent's in-progress trajectory within one episode."""

    __slots__ = ("obs", "act", "rew", "logp", "val")

    def __init__(self):
        self.obs: List[np.ndarray] = []
        self.act: List[int] = []
        self.rew: List[float] = []
        self.logp: List[float] = []
        self.val: List[float] = []


class MultiAgentRolloutWorker:
    """Drop-in for RolloutWorker over a MultiAgentEnv: same interface,
    same flat-fragment output; ``num_steps`` counts AGENT transitions so
    train_batch_size keeps its meaning."""

    def __init__(self, env_spec, env_config: Optional[dict],
                 hidden, seed: int, gamma: float = 0.99,
                 lam: float = 0.95, connectors=None):
        import jax

        from .. import _worker_context
        from .env import make_env

        if connectors:
            raise ValueError(
                "connectors are not supported with multi-agent envs yet")
        if _worker_context.in_worker():
            jax.config.update("jax_default_device", jax.devices("cpu")[0])
        self.env = make_env(env_spec, env_config)
        if not isinstance(self.env, MultiAgentEnv):
            raise TypeError("MultiAgentRolloutWorker needs a MultiAgentEnv")
        self.gamma = gamma
        self.lam = lam
        self.obs_dim = self.env.observation_dim
        self.rng = np.random.default_rng(seed)
        self._jax_key = jax.random.key(seed)
        self.params = ac_init(
            jax.random.key(0), self.obs_dim, self.env.num_actions, hidden)
        self._obs = self.env.reset(seed=seed)
        self._segments: Dict[str, _Segment] = {
            aid: _Segment() for aid in self._obs}
        self._episode_reward = 0.0
        self._episode_len = 0
        self.episode_rewards: List[float] = []
        self.episode_lengths: List[int] = []

    def ready(self) -> str:
        return "ok"

    def set_weights(self, weights) -> None:
        self.params = params_from_numpy(weights)

    def get_weights(self):
        return params_to_numpy(self.params)

    def _values_of(self, obs_batch: List[np.ndarray]) -> np.ndarray:
        """One stacked value forward for a batch of bootstrap
        observations — closing agents at an episode/fragment boundary
        share a single dispatch, like the action forward."""
        import jax

        self._jax_key, sub = jax.random.split(self._jax_key)
        _, _, v = sample_actions(self.params, np.stack(obs_batch), sub)
        return np.asarray(v)

    def _close_segment(self, seg: _Segment, bootstrap: float,
                       out: list) -> None:
        """Finalize one agent-trajectory: non-terminal ends fold the
        bootstrap into the last reward (the single-agent worker's
        truncation rule), so every emitted segment ends done=1."""
        if not seg.act:
            return
        seg.rew[-1] += self.gamma * bootstrap
        n = len(seg.act)
        done = np.zeros(n, np.float32)
        done[-1] = 1.0
        out.append({
            sb.OBS: np.asarray(seg.obs, np.float32),
            sb.ACTIONS: np.asarray(seg.act, np.int32),
            sb.REWARDS: np.asarray(seg.rew, np.float32),
            sb.DONES: done,
            sb.LOGP: np.asarray(seg.logp, np.float32),
            sb.VALUES: np.asarray(seg.val, np.float32),
        })

    def sample(self, num_steps: int) -> Dict[str, np.ndarray]:
        import jax

        closed: list = []
        collected = 0
        while collected < num_steps:
            live = [aid for aid in self._obs if aid in self._segments]
            stacked = np.stack([self._obs[aid] for aid in live])
            self._jax_key, sub = jax.random.split(self._jax_key)
            acts, logps, vals = sample_actions(self.params, stacked, sub)
            actions = {aid: int(acts[i]) for i, aid in enumerate(live)}
            for i, aid in enumerate(live):
                seg = self._segments[aid]
                seg.obs.append(self._obs[aid])
                seg.act.append(int(acts[i]))
                seg.logp.append(float(logps[i]))
                seg.val.append(float(vals[i]))
            next_obs, rewards, terms, truncs, _ = self.env.step(actions)
            for aid in live:
                self._segments[aid].rew.append(float(rewards[aid]))
                self._episode_reward += float(rewards[aid])
            collected += len(live)
            self._episode_len += 1

            episode_over = terms.get(ALL_DONE) or truncs.get(ALL_DONE)
            closing = [aid for aid in live
                       if terms.get(aid) or truncs.get(aid)
                       or episode_over]
            # one stacked forward covers every non-terminal closer
            need_v = [aid for aid in closing
                      if not terms.get(aid)
                      and next_obs.get(aid) is not None]
            values = {}
            if need_v:
                vs = self._values_of([next_obs[aid] for aid in need_v])
                values = dict(zip(need_v, (float(x) for x in vs)))
            for aid in closing:
                self._close_segment(self._segments.pop(aid),
                                    values.get(aid, 0.0), closed)
            self._obs = {aid: o for aid, o in next_obs.items()
                         if aid in self._segments}
            if episode_over or not self._segments:
                self.episode_rewards.append(self._episode_reward)
                self.episode_lengths.append(self._episode_len)
                self._episode_reward = 0.0
                self._episode_len = 0
                self._obs = self.env.reset(
                    seed=int(self.rng.integers(1 << 31)))
                self._segments = {aid: _Segment() for aid in self._obs}

        # fragment boundary: close live segments with their bootstraps
        open_aids = [aid for aid in self._segments
                     if self._segments[aid].act
                     and self._obs.get(aid) is not None]
        values = {}
        if open_aids:
            vs = self._values_of([self._obs[aid] for aid in open_aids])
            values = dict(zip(open_aids, (float(x) for x in vs)))
        for aid in list(self._segments):
            seg = self._segments[aid]
            if seg.act:
                self._close_segment(seg, values.get(aid, 0.0), closed)
                self._segments[aid] = _Segment()

        batch = sb.concat_batches(closed)
        adv, targets = sb.compute_gae(
            batch[sb.REWARDS], batch[sb.VALUES], batch[sb.DONES],
            last_value=0.0, gamma=self.gamma, lam=self.lam)
        batch[sb.ADVANTAGES] = adv
        batch[sb.TARGETS] = targets
        # every segment ends done=1, so the flat-fragment bootstrap is 0
        batch[sb.BOOTSTRAP] = np.array([0.0], np.float32)
        return batch

    def get_connector_state(self):
        return None

    def set_connector_state(self, state) -> None:
        pass

    def episode_stats(self, window: int = 100) -> Dict[str, Any]:
        return sb.episode_stats_summary(
            self.episode_rewards, self.episode_lengths, window)
