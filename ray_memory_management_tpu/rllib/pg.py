"""PG: vanilla policy gradient (REINFORCE), the simplest on-policy member.

The reference's PG (rllib/algorithms/pg/pg_tf_policy.py:31 — loss is just
-mean(logp(a|s) * advantage), one pass over each batch, no ratio, no
clipping). Everything else — rollout workers, GAE postprocessing, the
sync sample/learn loop — is PPO's machinery unchanged, so PG here is PPO
with the surrogate swapped for the plain score-function estimator and a
single SGD pass per batch (re-stepping a policy-gradient loss on stale
logps is exactly what PPO's clip exists to make safe; PG doesn't have it).
"""

from __future__ import annotations

from typing import Any, Dict

from .algorithm import AlgorithmConfig
from .models import ac_apply
from .ppo import PPO


def make_pg_update(optimizer, vf_coeff: float, entropy_coeff: float):
    import jax
    import jax.numpy as jnp
    import optax

    def loss_fn(params, obs, actions, advantages, targets):
        logits, values = ac_apply(params, obs)
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(
            logp_all, actions[:, None], axis=-1)[:, 0]
        adv = (advantages - advantages.mean()) / (advantages.std() + 1e-8)
        pg_loss = -(logp * adv).mean()
        vf_loss = jnp.square(values - targets).mean()
        entropy = -jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1).mean()
        total = pg_loss + vf_coeff * vf_loss - entropy_coeff * entropy
        return total, {"policy_loss": pg_loss, "vf_loss": vf_loss,
                       "entropy": entropy}

    @jax.jit
    def update(params, opt_state, obs, actions, old_logp, advantages,
               targets):
        # old_logp accepted (PPO's calling convention) but unused: PG has
        # no importance ratio
        del old_logp
        (loss, stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, obs, actions, advantages, targets)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        stats["total_loss"] = loss
        return params, opt_state, stats

    return update


class PG(PPO):
    def setup(self, config: Dict[str, Any]) -> None:
        config = dict(config)
        # one pass per batch: PG has no trust region making re-steps safe
        config.setdefault("num_sgd_iter", 1)
        super().setup(config)
        self._update = make_pg_update(
            self.optimizer, self.vf_coeff, self.entropy_coeff)


class PGConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(PG)
        self.extra.update({"vf_loss_coeff": 0.5, "entropy_coeff": 0.01,
                           "num_sgd_iter": 1})

    def training(self, *, vf_loss_coeff=None, entropy_coeff=None,
                 num_sgd_iter=None, sgd_minibatch_size=None,
                 **kwargs) -> "PGConfig":
        super().training(**kwargs)
        for k, v in (("vf_loss_coeff", vf_loss_coeff),
                     ("entropy_coeff", entropy_coeff),
                     ("num_sgd_iter", num_sgd_iter),
                     ("sgd_minibatch_size", sgd_minibatch_size)):
            if v is not None:
                self.extra[k] = v
        return self


class A2CConfig(PGConfig):
    """A2C is PG with the learned value baseline emphasized and larger
    synchronous batches (the reference keeps A2C as its own algorithm,
    rllib/algorithms/a2c/a2c.py — sync parallel rollouts + advantage
    actor-critic loss; that is exactly this estimator with GAE
    advantages, so the preset only retunes coefficients)."""

    def __init__(self):
        super().__init__()
        self.extra.update({"vf_loss_coeff": 1.0, "entropy_coeff": 0.01})
