"""DQN: off-policy Q-learning with a replay buffer on the learner.

The reference's DQN (rllib/algorithms/dqn/dqn.py:394 training_step:
store-to-replay, sample, TD update, periodic target-network sync;
rllib/algorithms/dqn/dqn_tf_policy.py:237 the double-Q TD loss). TPU-first
shape: the whole minibatch update — online forward, DOUBLE-Q target
(argmax from the online net, value from the target net), Huber TD loss,
Adam — is one jit'd XLA program; epsilon-greedy rollouts run on CPU
actors; the replay buffer is host-side numpy (replay.py), feeding the
chip one contiguous minibatch per step.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

from .. import api
from . import sample_batch as sb
from .algorithm import Algorithm, AlgorithmConfig
from .collector import NEXT_OBS, OffPolicyCollector
from .env import make_env
from .models import mlp_apply, mlp_init, params_from_numpy, params_to_numpy
from .replay import ReplayBuffer
from .rollout_worker import WorkerSet


def q_init(rng, obs_dim: int, num_actions: int, hidden=(64, 64)):
    return {"q": mlp_init(rng, [obs_dim, *hidden, num_actions])}


def q_apply(params, obs):
    return mlp_apply(params["q"], obs)


def make_dqn_update(optimizer, gamma: float):
    import jax
    import jax.numpy as jnp
    import optax

    def loss_fn(params, target_params, obs, actions, rewards, next_obs,
                dones):
        q = q_apply(params, obs)
        q_taken = jnp.take_along_axis(q, actions[:, None], axis=-1)[:, 0]
        # double-Q: the ONLINE net picks the next action, the TARGET net
        # scores it (dqn_tf_policy.py:237 double_q branch)
        next_q_online = q_apply(params, next_obs)
        next_a = jnp.argmax(next_q_online, axis=-1)
        next_q_target = q_apply(target_params, next_obs)
        next_val = jnp.take_along_axis(
            next_q_target, next_a[:, None], axis=-1)[:, 0]
        td_target = rewards + gamma * (1.0 - dones) * \
            jax.lax.stop_gradient(next_val)
        td_error = q_taken - td_target
        loss = jnp.mean(optax.huber_loss(q_taken, td_target))
        return loss, {
            "mean_q": q_taken.mean(),
            "mean_td_error": jnp.abs(td_error).mean(),
        }

    @jax.jit
    def update(params, target_params, opt_state, obs, actions, rewards,
               next_obs, dones):
        (loss, stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, target_params, obs, actions, rewards, next_obs, dones)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        stats["loss"] = loss
        return params, opt_state, stats

    return update


class DQNRolloutWorker(OffPolicyCollector):
    """Epsilon-greedy transition collector (the exploration half of the
    reference's EpsilonGreedy rllib/utils/exploration/epsilon_greedy.py:26,
    with the worker loop of rollout_worker.py:124). Emits raw
    (obs, action, reward, next_obs, done) transitions — DQN's replay
    consumes transitions, not GAE fragments."""

    def __init__(self, env_spec, env_config: Optional[dict], hidden,
                 seed: int):
        import jax

        self._setup_env(env_spec, env_config, seed)
        self.params = q_init(
            jax.random.key(0), self.env.observation_dim,
            self.env.num_actions, hidden)
        self._epsilon = 1.0

    def set_weights(self, weights) -> None:
        self.params = params_from_numpy(weights)

    def sample(self, num_steps: int, epsilon: float) -> Dict[str, np.ndarray]:
        self._epsilon = epsilon
        return self._collect(num_steps)

    def _action_buffer(self, num_steps: int) -> np.ndarray:
        return np.zeros(num_steps, np.int32)

    def _select_action(self) -> int:
        import jax.numpy as jnp

        if self.rng.random() < self._epsilon:
            return int(self.rng.integers(self.env.num_actions))
        q = q_apply(self.params, jnp.asarray(self._obs[None, :]))
        return int(np.asarray(q)[0].argmax())


class _DQNWorkerSet(WorkerSet):
    """WorkerSet over epsilon-greedy DQN collectors — inherits the
    broadcast/stats/stop plumbing so the base Algorithm's
    _sync_weights/_episode_metrics/cleanup apply unchanged."""

    def __init__(self, env_spec, env_config, hidden, num_workers: int,
                 seed: int):
        cls = api.remote(DQNRolloutWorker)
        self.remote_workers = [
            cls.options(num_cpus=1).remote(
                env_spec, env_config, hidden, seed + 1000 * (i + 1))
            for i in range(num_workers)
        ]
        api.get([w.ready.remote() for w in self.remote_workers])

    def sample(self, num_steps: int, epsilon: float = 0.0) -> List:
        return [w.sample.remote(num_steps, epsilon)
                for w in self.remote_workers]


class DQN(Algorithm):
    def setup(self, config: Dict[str, Any]) -> None:
        import jax
        import optax

        # Algorithm.setup builds actor-critic params + PG-shaped rollout
        # workers; DQN needs a Q-net and epsilon-greedy transition
        # collectors, so it wires its own (same env/seed plumbing).
        self.cfg = config
        if config.get("connectors"):
            raise ValueError(
                "connectors are not supported by this algorithm's "
                "custom rollout collectors yet; use PPO/IMPALA or "
                "drop the connectors config")
        seed = config.get("seed", 0)
        self.np_rng = np.random.default_rng(seed)
        probe_env = make_env(config["env_spec"], config.get("env_config"))
        self.obs_dim = probe_env.observation_dim
        self.num_actions = probe_env.num_actions
        hidden = config.get("hidden", (64, 64))
        self.params = q_init(jax.random.key(seed), self.obs_dim,
                             self.num_actions, hidden)
        self.target_params = jax.tree_util.tree_map(
            lambda x: x, self.params)
        self.gamma = config.get("gamma", 0.99)
        self.optimizer = optax.adam(config.get("lr", 1e-3))
        self.opt_state = self.optimizer.init(self.params)
        self._update = make_dqn_update(self.optimizer, self.gamma)
        self.replay = ReplayBuffer(
            config.get("replay_buffer_capacity", 50_000), seed=seed)
        self.learning_starts = config.get("learning_starts", 1_000)
        self.train_batch_size = config.get("train_batch_size", 64)
        self.target_update_freq = config.get(
            "target_network_update_freq", 500)
        self.updates_per_step = config.get("updates_per_step", 32)
        self.eps_initial = config.get("epsilon_initial", 1.0)
        self.eps_final = config.get("epsilon_final", 0.02)
        self.eps_timesteps = config.get("epsilon_timesteps", 10_000)
        self._updates_done = 0
        self._timesteps_total = 0

        n_workers = config.get("num_rollout_workers", 0)
        self.workers = None
        self.local_worker = None
        if n_workers > 0:
            self.workers = _DQNWorkerSet(
                config["env_spec"], config.get("env_config"), hidden,
                n_workers, seed)
        else:
            self.local_worker = DQNRolloutWorker(
                config["env_spec"], config.get("env_config"), hidden, seed)

    # -- exploration schedule --------------------------------------------------
    def _epsilon(self) -> float:
        frac = min(1.0, self._timesteps_total / max(1, self.eps_timesteps))
        return self.eps_initial + frac * (self.eps_final - self.eps_initial)

    def training_step(self) -> Dict[str, Any]:
        import jax
        import jax.numpy as jnp

        t0 = time.time()
        fragment = self.cfg.get("rollout_fragment_length", 64)
        eps = self._epsilon()
        self._sync_weights()
        if self.workers is not None:
            batches = api.get(self.workers.sample(fragment, eps))
        else:
            batches = [self.local_worker.sample(fragment, eps)]
        n = 0
        for b in batches:
            self.replay.add_batch(b)
            n += len(b[sb.ACTIONS])
        self._timesteps_total += n
        sample_time = time.time() - t0

        stats: Dict[str, Any] = {}
        t1 = time.time()
        if len(self.replay) >= self.learning_starts:
            for _ in range(self.updates_per_step):
                mb = self.replay.sample(self.train_batch_size)
                self.params, self.opt_state, stats = self._update(
                    self.params, self.target_params, self.opt_state,
                    jnp.asarray(mb[sb.OBS]), jnp.asarray(mb[sb.ACTIONS]),
                    jnp.asarray(mb[sb.REWARDS]),
                    jnp.asarray(mb[NEXT_OBS]),
                    jnp.asarray(mb[sb.DONES]))
                self._updates_done += 1
                if self._updates_done % self.target_update_freq == 0:
                    self.target_params = jax.tree_util.tree_map(
                        lambda x: x, self.params)
        learn_time = time.time() - t1

        out = {k: float(v) for k, v in stats.items()}
        out.update({
            "num_env_steps_sampled": n,
            "replay_size": len(self.replay),
            "epsilon": eps,
            "num_updates": self._updates_done,
            "sample_time_s": sample_time,
            "learn_time_s": learn_time,
        })
        return out

    def compute_single_action(self, obs: np.ndarray) -> int:
        import jax.numpy as jnp

        q = q_apply(self.params, jnp.asarray(obs[None, :]))
        return int(np.asarray(q)[0].argmax())

    def _save_extra_state(self):
        return {
            "opt_state": params_to_numpy(self.opt_state),
            "target_params": params_to_numpy(self.target_params),
            "updates_done": self._updates_done,
        }

    def _load_extra_state(self, state) -> None:
        if not state:
            return
        if "opt_state" in state:
            self.opt_state = params_from_numpy(state["opt_state"])
        if "target_params" in state:
            self.target_params = params_from_numpy(state["target_params"])
        self._updates_done = state.get("updates_done", 0)

class DQNConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(DQN)
        self.extra.update({
            "replay_buffer_capacity": 50_000, "learning_starts": 1_000,
            "target_network_update_freq": 500, "updates_per_step": 32,
            "epsilon_initial": 1.0, "epsilon_final": 0.02,
            "epsilon_timesteps": 10_000,
        })

    def training(self, *, replay_buffer_capacity=None, learning_starts=None,
                 target_network_update_freq=None, updates_per_step=None,
                 epsilon_initial=None, epsilon_final=None,
                 epsilon_timesteps=None, **kwargs) -> "DQNConfig":
        super().training(**kwargs)
        for k, v in (
                ("replay_buffer_capacity", replay_buffer_capacity),
                ("learning_starts", learning_starts),
                ("target_network_update_freq", target_network_update_freq),
                ("updates_per_step", updates_per_step),
                ("epsilon_initial", epsilon_initial),
                ("epsilon_final", epsilon_final),
                ("epsilon_timesteps", epsilon_timesteps)):
            if v is not None:
                self.extra[k] = v
        return self
