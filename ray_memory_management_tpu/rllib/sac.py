"""SAC: soft actor-critic for continuous control.

The reference's SAC (rllib/algorithms/sac/sac.py — config + training_step
wiring; rllib/algorithms/sac/sac_tf_policy.py:268 the twin-Q + squashed-
Gaussian losses; target entropy auto-tuning per Haarnoja et al. 2018).
TPU-first shape, like dqn.py: the ENTIRE update — actor forward, twin-Q
targets with the entropy bonus, three losses (critic, actor, temperature),
Adam on each, and the polyak target-network update — is one jit'd XLA
program; stochastic rollouts run on CPU actors; the replay buffer is
host-side numpy feeding one contiguous minibatch per update.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

from .. import api
from . import sample_batch as sb
from .algorithm import Algorithm, AlgorithmConfig
from .collector import NEXT_OBS, OffPolicyCollector
from .env import make_env
from .models import mlp_apply, mlp_init, params_from_numpy, params_to_numpy
from .replay import ReplayBuffer
from .rollout_worker import WorkerSet

_LOG_STD_MIN, _LOG_STD_MAX = -20.0, 2.0


def sac_init(rng, obs_dim: int, act_dim: int, hidden=(64, 64)):
    """Policy emits (mean, log_std) per action dim; twin Q critics score
    (obs, action) pairs (sac_tf_policy.py's SquashedGaussian + twin_q)."""
    import jax

    k_pi, k_q1, k_q2 = jax.random.split(rng, 3)
    return {
        "pi": mlp_init(k_pi, [obs_dim, *hidden, 2 * act_dim]),
        "q1": mlp_init(k_q1, [obs_dim + act_dim, *hidden, 1]),
        "q2": mlp_init(k_q2, [obs_dim + act_dim, *hidden, 1]),
    }


def pi_sample(params, obs, key, bound: float):
    """Squashed-Gaussian sample: a = bound * tanh(mu + sigma eps), with
    the tanh change-of-variables log-prob correction."""
    import jax
    import jax.numpy as jnp

    out = mlp_apply(params["pi"], obs)
    mu, log_std = jnp.split(out, 2, axis=-1)
    log_std = jnp.clip(log_std, _LOG_STD_MIN, _LOG_STD_MAX)
    std = jnp.exp(log_std)
    eps = jax.random.normal(key, mu.shape)
    pre = mu + std * eps
    a = jnp.tanh(pre)
    # N(pre; mu, std) log-density, then tanh correction (numerically
    # stable form: log(1 - tanh^2 x) = 2(log 2 - x - softplus(-2x)))
    logp = jnp.sum(
        -0.5 * (eps ** 2 + 2 * log_std + jnp.log(2 * jnp.pi))
        - 2 * (jnp.log(2.0) - pre - jax.nn.softplus(-2 * pre)),
        axis=-1)
    return bound * a, logp


def q_value(params, which: str, obs, act):
    import jax.numpy as jnp

    return mlp_apply(params[which], jnp.concatenate([obs, act], -1))[..., 0]


def make_sac_update(pi_opt, q_opt, a_opt, gamma: float, tau: float,
                    target_entropy: float, bound: float):
    import jax
    import jax.numpy as jnp
    import optax

    def critic_loss(params, target_params, log_alpha, batch, key):
        obs, act, rew, nxt, done = batch
        next_a, next_logp = pi_sample(params, nxt, key, bound)
        tq = jnp.minimum(q_value(target_params, "q1", nxt, next_a),
                         q_value(target_params, "q2", nxt, next_a))
        alpha = jnp.exp(log_alpha)
        target = rew + gamma * (1.0 - done) * jax.lax.stop_gradient(
            tq - alpha * next_logp)
        q1 = q_value(params, "q1", obs, act)
        q2 = q_value(params, "q2", obs, act)
        loss = jnp.mean((q1 - target) ** 2) + jnp.mean((q2 - target) ** 2)
        return loss, q1.mean()

    def actor_loss(pi_params, params, log_alpha, obs, key):
        merged = {**params, "pi": pi_params}
        a, logp = pi_sample(merged, obs, key, bound)
        q = jnp.minimum(q_value(params, "q1", obs, a),
                        q_value(params, "q2", obs, a))
        alpha = jax.lax.stop_gradient(jnp.exp(log_alpha))
        return jnp.mean(alpha * logp - q), logp

    def alpha_loss(log_alpha, logp):
        # temperature auto-tuning toward the entropy target
        return -jnp.mean(
            log_alpha * jax.lax.stop_gradient(logp + target_entropy))

    @jax.jit
    def update(params, target_params, log_alpha, opt_states, batch, key):
        k1, k2 = jax.random.split(key)
        pi_state, q_state, a_state = opt_states
        obs = batch[0]

        # critics (gradients flow to q1/q2 only)
        (c_loss, mean_q), c_grads = jax.value_and_grad(
            critic_loss, has_aux=True)(params, target_params, log_alpha,
                                       batch, k1)
        c_grads = {**c_grads, "pi": jax.tree_util.tree_map(
            jnp.zeros_like, c_grads["pi"])}
        q_upd, q_state = q_opt.update(c_grads, q_state, params)
        params = optax.apply_updates(params, q_upd)

        # actor (gradients to pi only, critics frozen)
        (a_loss_v, logp), pi_grads = jax.value_and_grad(
            actor_loss, has_aux=True)(params["pi"], params, log_alpha,
                                      obs, k2)
        pi_upd, pi_state = pi_opt.update(pi_grads, pi_state, params["pi"])
        params = {**params,
                  "pi": optax.apply_updates(params["pi"], pi_upd)}

        # temperature
        al_v, al_grad = jax.value_and_grad(alpha_loss)(log_alpha, logp)
        al_upd, a_state = a_opt.update(al_grad, a_state, log_alpha)
        log_alpha = optax.apply_updates(log_alpha, al_upd)

        # polyak target update (the reference's tau soft sync)
        target_params = jax.tree_util.tree_map(
            lambda t, p: (1.0 - tau) * t + tau * p, target_params, params)

        stats = {"critic_loss": c_loss, "actor_loss": a_loss_v,
                 "alpha_loss": al_v, "alpha": jnp.exp(log_alpha),
                 "mean_q": mean_q, "entropy": -logp.mean()}
        return (params, target_params, log_alpha,
                (pi_state, q_state, a_state), stats)

    return update


class SACRolloutWorker(OffPolicyCollector):
    """Stochastic-policy transition collector for continuous actions:
    samples from the squashed Gaussian (exploration IS the policy noise);
    the first ``random_steps`` draw uniform actions to seed the replay
    (the reference's initial random exploration)."""

    def __init__(self, env_spec, env_config: Optional[dict], hidden,
                 seed: int):
        import jax

        self._setup_env(env_spec, env_config, seed)
        self.bound = float(getattr(self.env, "action_bound", 1.0))
        self.act_dim = int(getattr(self.env, "action_dim", 1))
        self.key = jax.random.PRNGKey(seed)
        self.params = sac_init(jax.random.key(0), self.env.observation_dim,
                               self.act_dim, hidden)
        self._random_steps = 0

    def set_weights(self, weights) -> None:
        # the learner broadcasts only the pi subtree (all a rollout
        # worker ever evaluates); merge it over the local placeholder
        self.params = {**self.params,
                       "pi": params_from_numpy(weights["pi"])}

    def sample(self, num_steps: int,
               random_steps: int = 0) -> Dict[str, np.ndarray]:
        self._random_steps = random_steps
        return self._collect(num_steps)

    def _action_buffer(self, num_steps: int) -> np.ndarray:
        return np.zeros((num_steps, self.act_dim), np.float32)

    def _select_action(self) -> np.ndarray:
        import jax
        import jax.numpy as jnp

        if self._steps_done < self._random_steps:
            return self.rng.uniform(-self.bound, self.bound, self.act_dim)
        self.key, sub = jax.random.split(self.key)
        a, _ = pi_sample(self.params, jnp.asarray(self._obs[None, :]),
                         sub, self.bound)
        return np.asarray(a)[0]


class _SACWorkerSet(WorkerSet):
    def __init__(self, env_spec, env_config, hidden, num_workers: int,
                 seed: int):
        cls = api.remote(SACRolloutWorker)
        self.remote_workers = [
            cls.options(num_cpus=1).remote(
                env_spec, env_config, hidden, seed + 1000 * (i + 1))
            for i in range(num_workers)
        ]
        api.get([w.ready.remote() for w in self.remote_workers])

    def sample(self, num_steps: int, random_steps: int = 0) -> List:
        return [w.sample.remote(num_steps, random_steps)
                for w in self.remote_workers]


class SAC(Algorithm):
    def setup(self, config: Dict[str, Any]) -> None:
        import jax
        import jax.numpy as jnp
        import optax

        self.cfg = config
        if config.get("connectors"):
            raise ValueError(
                "connectors are not supported by this algorithm's "
                "custom rollout collectors yet; use PPO/IMPALA or "
                "drop the connectors config")
        seed = config.get("seed", 0)
        probe_env = make_env(config["env_spec"], config.get("env_config"))
        self.obs_dim = probe_env.observation_dim
        self.act_dim = int(getattr(probe_env, "action_dim", 1))
        self.bound = float(getattr(probe_env, "action_bound", 1.0))
        hidden = config.get("hidden", (64, 64))
        self.params = sac_init(jax.random.key(seed), self.obs_dim,
                               self.act_dim, hidden)
        self.target_params = jax.tree_util.tree_map(
            lambda x: x, self.params)
        self.log_alpha = jnp.asarray(
            float(np.log(config.get("initial_alpha", 1.0))))
        self.gamma = config.get("gamma", 0.99)
        self.tau = config.get("tau", 0.005)
        # the standard heuristic target: -|A|
        self.target_entropy = config.get(
            "target_entropy", -float(self.act_dim))
        lr = config.get("lr", 3e-4)
        self._pi_opt = optax.adam(config.get("actor_lr", lr))
        self._q_opt = optax.adam(config.get("critic_lr", lr))
        self._a_opt = optax.adam(config.get("alpha_lr", lr))
        self.opt_states = (self._pi_opt.init(self.params["pi"]),
                           self._q_opt.init(self.params),
                           self._a_opt.init(self.log_alpha))
        self._update = make_sac_update(
            self._pi_opt, self._q_opt, self._a_opt, self.gamma, self.tau,
            self.target_entropy, self.bound)
        self.replay = ReplayBuffer(
            config.get("replay_buffer_capacity", 100_000), seed=seed)
        self.learning_starts = config.get("learning_starts", 500)
        self.random_steps = config.get("random_steps", 500)
        self.train_batch_size = config.get("train_batch_size", 128)
        self.updates_per_step = config.get("updates_per_step", 32)
        self._key = jax.random.PRNGKey(seed + 7)
        self._updates_done = 0
        self._timesteps_total = 0

        n_workers = config.get("num_rollout_workers", 0)
        self.workers = None
        self.local_worker = None
        if n_workers > 0:
            self.workers = _SACWorkerSet(
                config["env_spec"], config.get("env_config"), hidden,
                n_workers, seed)
        else:
            self.local_worker = SACRolloutWorker(
                config["env_spec"], config.get("env_config"), hidden, seed)

    def training_step(self) -> Dict[str, Any]:
        import jax
        import jax.numpy as jnp

        t0 = time.time()
        fragment = self.cfg.get("rollout_fragment_length", 64)
        self._sync_weights()
        if self.workers is not None:
            batches = api.get(
                self.workers.sample(fragment, self.random_steps))
        else:
            batches = [self.local_worker.sample(
                fragment, self.random_steps)]
        n = 0
        for b in batches:
            self.replay.add_batch(b)
            n += len(b[sb.ACTIONS])
        self._timesteps_total += n
        sample_time = time.time() - t0

        stats: Dict[str, Any] = {}
        t1 = time.time()
        if len(self.replay) >= self.learning_starts:
            for _ in range(self.updates_per_step):
                mb = self.replay.sample(self.train_batch_size)
                self._key, sub = jax.random.split(self._key)
                batch = (jnp.asarray(mb[sb.OBS]),
                         jnp.asarray(mb[sb.ACTIONS]),
                         jnp.asarray(mb[sb.REWARDS]),
                         jnp.asarray(mb[NEXT_OBS]),
                         jnp.asarray(mb[sb.DONES]))
                (self.params, self.target_params, self.log_alpha,
                 self.opt_states, stats) = self._update(
                    self.params, self.target_params, self.log_alpha,
                    self.opt_states, batch, sub)
                self._updates_done += 1
        learn_time = time.time() - t1

        out = {k: float(v) for k, v in stats.items()}
        out.update({
            "num_env_steps_sampled": n,
            "replay_size": len(self.replay),
            "num_updates": self._updates_done,
            "sample_time_s": sample_time,
            "learn_time_s": learn_time,
        })
        return out

    def compute_single_action(self, obs: np.ndarray) -> np.ndarray:
        """Deterministic (mean) action for evaluation."""
        import jax.numpy as jnp

        out = mlp_apply(self.params["pi"], jnp.asarray(obs[None, :]))
        mu = np.asarray(out)[0, : self.act_dim]
        return self.bound * np.tanh(mu)

    def _sync_weights(self) -> None:
        """Rollout workers only run the policy — ship just the pi subtree
        (a third of the full twin-Q tree) per broadcast."""
        weights = {"pi": params_to_numpy(self.params["pi"])}
        if self.workers is not None:
            self.workers.set_weights(weights)
        else:
            self.local_worker.set_weights(weights)

    def _save_extra_state(self):
        return {
            "target_params": params_to_numpy(self.target_params),
            "opt_states": params_to_numpy(self.opt_states),
            "log_alpha": float(self.log_alpha),
            "key": params_to_numpy(self._key),
            "updates_done": self._updates_done,
        }

    def _load_extra_state(self, state) -> None:
        import jax.numpy as jnp

        if not state:
            return
        if "target_params" in state:
            self.target_params = params_from_numpy(state["target_params"])
        if "opt_states" in state:
            # Adam moments restore too — resetting them on restore is an
            # effective learning-rate spike mid-run
            self.opt_states = params_from_numpy(state["opt_states"])
        if "log_alpha" in state:
            self.log_alpha = jnp.asarray(state["log_alpha"])
        if "key" in state:
            self._key = jnp.asarray(state["key"])
        self._updates_done = state.get("updates_done", 0)


class SACConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(SAC)
        self.extra.update({
            "replay_buffer_capacity": 100_000, "learning_starts": 500,
            "random_steps": 500, "updates_per_step": 32, "tau": 0.005,
            "initial_alpha": 1.0,
        })

    def training(self, *, replay_buffer_capacity=None, learning_starts=None,
                 random_steps=None, updates_per_step=None, tau=None,
                 target_entropy=None, actor_lr=None, critic_lr=None,
                 alpha_lr=None, initial_alpha=None, **kwargs) -> "SACConfig":
        super().training(**kwargs)
        for k, v in (
                ("replay_buffer_capacity", replay_buffer_capacity),
                ("learning_starts", learning_starts),
                ("random_steps", random_steps),
                ("updates_per_step", updates_per_step),
                ("tau", tau), ("target_entropy", target_entropy),
                ("actor_lr", actor_lr), ("critic_lr", critic_lr),
                ("alpha_lr", alpha_lr), ("initial_alpha", initial_alpha)):
            if v is not None:
                self.extra[k] = v
        return self
