"""SlateQ: slate recommendation Q-learning with choice-model decomposition.

The reference's rllib/algorithms/slateq/ (Ie et al. 2019, paired with
RecSim's interest-evolution environment): the combinatorial action — a
SLATE of k documents out of N candidates — decomposes under a
single-choice user model into per-item values,

    Q(s, slate) = sum_{i in slate} P(click i | s, slate) * Qbar(s, i),

so only the ITEM-wise Qbar(s, d) must be learned (a |slate|-free
network), the TD backup weights next-slate item values by the choice
model's click probabilities, and slate construction is the standard
top-k-by-score greedy over v(s,d) * Qbar(s,d).

TPU-first shape: every per-item evaluation batches — the update runs
Qbar over [B, N] candidate features in one forward (vmap-free: the MLP
just sees a [B*N, feat] matmul), the choice-model weighting and the
decomposed backup are pure tensor algebra inside ONE jit, and acting
scores all candidates in one call. A compact interest-evolution env
(user interests drift toward clicked topics, engagement is the reward)
stands in for RecSim, with myopic-vs-long-term structure: clickbait
docs get clicks but erode the session, quality docs compound it.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

from .algorithm import Algorithm, AlgorithmConfig
from .env import register_env
from .models import mlp_apply, mlp_init
from .replay import ReplayBuffer


class InterestEvolution:
    """Slate recommendation env (RecSim interest_evolution, reduced).

    - ``n_docs`` documents, each a unit topic vector + a quality scalar;
      low-quality docs are CLICKBAIT: higher click appeal, but clicking
      them drains the session budget with little engagement. High-quality
      docs engage long term (the myopic-vs-SlateQ tension the paper's
      experiments measure).
    - The user holds an interest vector; a click drifts it toward the
      clicked doc's topic.
    - Choice model: conditional logit over the slate + a no-click option
      (exp scores; exposed via :meth:`choice_scores` — SlateQ assumes
      the choice model is known/estimated, as the reference does).
    - obs = [user interests, all doc features flat] (fully observed doc
      corpus; the policy's job is slate COMPOSITION).
    """

    def __init__(self, n_docs: int = 20, n_topics: int = 6,
                 slate_size: int = 3, max_episode_steps: int = 20,
                 seed: int = 0):
        rng = np.random.default_rng(seed)
        self.n_docs = n_docs
        self.n_topics = n_topics
        self.slate_size = slate_size
        self.max_episode_steps = max_episode_steps
        topics = rng.standard_normal((n_docs, n_topics))
        self.doc_topics = (topics / np.linalg.norm(
            topics, axis=1, keepdims=True)).astype(np.float32)
        # quality in [0, 1]; appeal is anti-correlated (clickbait)
        self.doc_quality = rng.uniform(0, 1, n_docs).astype(np.float32)
        self.doc_appeal = (1.2 - self.doc_quality
                           + 0.2 * rng.standard_normal(n_docs)
                           ).astype(np.float32)
        self.doc_feats = np.concatenate(
            [self.doc_topics, self.doc_quality[:, None],
             self.doc_appeal[:, None]], axis=1)  # [N, n_topics+2]
        self.feat_dim = self.doc_feats.shape[1]
        self.observation_dim = n_topics + n_docs * self.feat_dim
        self._rng = rng
        self._interest = np.zeros(n_topics, np.float32)
        self._t = 0

    def _obs(self) -> np.ndarray:
        return np.concatenate(
            [self._interest, self.doc_feats.ravel()]).astype(np.float32)

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        v = self._rng.standard_normal(self.n_topics)
        self._interest = (v / np.linalg.norm(v)).astype(np.float32)
        self._t = 0
        return self._obs()

    def choice_scores(self, docs: np.ndarray) -> np.ndarray:
        """exp conditional-logit scores v(s, d) for given doc indices;
        the no-click option scores exp(0) = 1."""
        affinity = self.doc_topics[docs] @ self._interest
        return np.exp(affinity + self.doc_appeal[docs])

    def step(self, slate: List[int]):
        """slate: doc indices. Returns (obs, reward, term, trunc, info);
        info carries which doc was clicked (or -1)."""
        slate = list(slate)
        self._t += 1
        scores = self.choice_scores(np.asarray(slate))
        total = scores.sum() + 1.0  # + the no-click option
        probs = np.concatenate([scores / total, [1.0 / total]])
        pick = int(self._rng.choice(len(slate) + 1, p=probs))
        reward = 0.0
        clicked = -1
        if pick < len(slate):
            clicked = slate[pick]
            q = float(self.doc_quality[clicked])
            reward = q  # engagement tracks quality, not appeal
            # interests drift toward the clicked topic
            self._interest = (0.9 * self._interest
                              + 0.1 * self.doc_topics[clicked])
            self._interest /= max(np.linalg.norm(self._interest), 1e-6)
        trunc = self._t >= self.max_episode_steps
        return self._obs(), reward, False, trunc, {"clicked": clicked}


register_env("InterestEvolution", InterestEvolution)


def _slate_combos(pruned: int, k: int) -> np.ndarray:
    """All C(pruned, k) index combinations, as a static array — exact
    slate optimization over a pruned candidate set enumerates inside
    jit with fixed shapes (the paper optimizes slates exactly via an
    LP; over <=8 pruned candidates brute force is cheaper than either
    the LP or the top-k greedy's regret)."""
    from itertools import combinations

    return np.asarray(list(combinations(range(pruned), k)), np.int32)


def _best_slate_value(scores, q, combos, prune):
    """max over slates of sum(s_i q_i) / (sum s_i + 1): the decomposed
    slate value under the conditional-logit choice model (+1 = the
    no-click option). scores/q: [..., N]; returns (value, best combo
    rows of the pruned top)."""
    import jax
    import jax.numpy as jnp

    top_s, top_idx = jax.lax.top_k(scores * jnp.maximum(q, 0.0), prune)
    s_p = jnp.take_along_axis(scores, top_idx, axis=-1)   # [..., prune]
    q_p = jnp.take_along_axis(q, top_idx, axis=-1)
    s_c = s_p[..., combos]                                # [..., C, k]
    q_c = q_p[..., combos]
    v = (s_c * q_c).sum(-1) / (s_c.sum(-1) + 1.0)         # [..., C]
    best = v.argmax(-1)
    return jnp.take_along_axis(v, best[..., None], -1)[..., 0], \
        top_idx, best


def make_slateq_update(opt, gamma: float):
    """The decomposed TD step, one jit: Qbar over all [B, N] candidates,
    exact pruned-combinatorial next-slate optimization, and the
    choice-probability-weighted backup (slateq.py's decomposed target;
    slate optimization exact rather than top-k greedy — greedy ranks by
    s*Q and seats clickbait rows whose high appeal STEALS probability
    mass from higher-value items, precisely this env's failure mode)."""
    import jax
    import jax.numpy as jnp
    import optax

    def qbar_all(params, user, feats):
        """[B, n_topics] user x [B, N, feat] docs -> [B, N] item values."""
        B, N, F = feats.shape
        u = jnp.repeat(user[:, None, :], N, axis=1)
        x = jnp.concatenate([u, feats], -1).reshape(B * N, -1)
        return mlp_apply(params, x)[..., 0].reshape(B, N)

    def loss(params, target_params, batch, slate_size, combos, prune):
        (user, feats, clicked_feat, rew, nxt_user, nxt_feats,
         nxt_scores, done) = batch
        # target: value of the BEST next slate (exact over pruned set)
        nq = qbar_all(target_params, nxt_user, nxt_feats)      # [B, N]
        v_next, _, _ = _best_slate_value(nxt_scores, nq, combos, prune)
        target = rew + gamma * (1.0 - done) * \
            jax.lax.stop_gradient(v_next)
        # online: Qbar of the clicked item only (no-click transitions
        # carry zero reward and train nothing item-wise — slateq.py
        # likewise learns from click events)
        x = jnp.concatenate([user, clicked_feat], -1)
        q = mlp_apply(params, x)[..., 0]
        return jnp.mean((q - target) ** 2), q.mean()

    import functools

    @functools.partial(jax.jit, static_argnums=(4, 6))
    def update(params, target_params, opt_state, batch, slate_size,
               combos, prune):
        (l, mean_q), grads = jax.value_and_grad(loss, has_aux=True)(
            params, target_params, batch, slate_size, combos, prune)
        upd, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, upd)
        return params, opt_state, {"td_loss": l, "mean_q": mean_q}

    return update


class SlateQ(Algorithm):
    def setup(self, config: Dict[str, Any]) -> None:
        import jax
        import optax

        from .env import make_env

        self.cfg = config
        seed = config.get("seed", 0)
        self.env = make_env(config["env_spec"], config.get("env_config"))
        if not hasattr(self.env, "choice_scores"):
            raise ValueError("SlateQ needs a slate env exposing the "
                             "user choice model (choice_scores)")
        self.n_docs = self.env.n_docs
        self.slate_size = self.env.slate_size
        self.feat_dim = self.env.feat_dim
        self.n_topics = self.env.n_topics
        hidden = config.get("hidden", (64, 64))
        self.params = mlp_init(
            jax.random.key(seed),
            [self.n_topics + self.feat_dim, *hidden, 1])
        self.target_params = jax.tree_util.tree_map(
            lambda x: x, self.params)
        self.opt = optax.adam(config.get("lr", 1e-3))
        self.opt_state = self.opt.init(self.params)
        self._update = make_slateq_update(self.opt,
                                          config.get("gamma", 0.95))
        self._prune = min(config.get("slate_prune", 8), self.n_docs)
        self._combos = _slate_combos(self._prune, self.slate_size)
        self.buffer = ReplayBuffer(config.get("buffer_size", 50_000))
        self.batch_size = config.get("train_batch_size", 128)
        self.updates_per_iter = config.get("updates_per_iter", 40)
        self.rollout_steps = config.get("rollout_fragment_length", 200)
        self.target_every = config.get("target_update_freq", 200)
        self.eps = config.get("epsilon", 1.0)
        self.eps_final = config.get("epsilon_final", 0.05)
        self.eps_steps = config.get("epsilon_timesteps", 2000)
        self._rng = np.random.default_rng(seed)
        self._obs_user = None
        self.env.reset(seed=seed)
        self._ep_reward = 0.0
        self.episode_rewards: List[float] = []
        self._timesteps_total = 0
        self._updates_done = 0
        self.workers = None
        self.local_worker = None

    # -------------------------------------------------------------- acting
    def _qbar(self, interest: np.ndarray) -> np.ndarray:
        import jax.numpy as jnp

        feats = self.env.doc_feats                      # [N, F]
        u = np.repeat(interest[None, :], self.n_docs, 0)
        x = jnp.asarray(np.concatenate([u, feats], 1))
        return np.asarray(mlp_apply(self.params, x)[..., 0])

    def _slate(self, explore: bool) -> List[int]:
        if explore and self._rng.random() < self._epsilon():
            return list(self._rng.choice(self.n_docs, self.slate_size,
                                         replace=False))
        import jax.numpy as jnp

        interest = self.env._interest
        scores = self.env.choice_scores(np.arange(self.n_docs))
        q = self._qbar(interest)
        _, top_idx, best = _best_slate_value(
            jnp.asarray(scores), jnp.asarray(q), self._combos,
            self._prune)
        rows = self._combos[int(best)]
        return [int(top_idx[r]) for r in rows]

    def _epsilon(self) -> float:
        frac = min(1.0, self._timesteps_total / self.eps_steps)
        return self.eps + frac * (self.eps_final - self.eps)

    # ------------------------------------------------------------- training
    def _collect(self, n: int) -> None:
        env = self.env
        cols = {k: [] for k in ("user", "clicked_feat", "rew", "nxt_user",
                                "nxt_scores", "done")}
        for _ in range(n):
            user = env._interest.copy()
            slate = self._slate(explore=True)
            _, r, term, trunc, info = env.step(slate)
            self._ep_reward += r
            self._timesteps_total += 1
            clicked = info["clicked"]
            if clicked >= 0:  # item-wise learning happens on clicks
                cols["user"].append(user)
                cols["clicked_feat"].append(env.doc_feats[clicked])
                cols["rew"].append(np.float32(r))
                cols["nxt_user"].append(env._interest.copy())
                cols["nxt_scores"].append(env.choice_scores(
                    np.arange(self.n_docs)).astype(np.float32))
                cols["done"].append(np.float32(1.0 if term else 0.0))
            if term or trunc:
                self.episode_rewards.append(self._ep_reward)
                self._ep_reward = 0.0
                env.reset(seed=int(self._rng.integers(1 << 31)))
        if cols["user"]:
            self.buffer.add_batch(
                {k: np.stack(v) for k, v in cols.items()})

    def training_step(self) -> Dict[str, Any]:
        import jax
        import jax.numpy as jnp

        t0 = time.time()
        self._collect(self.rollout_steps)
        stats = {}
        feats_all = jnp.asarray(
            np.repeat(self.env.doc_feats[None], self.batch_size, 0))
        if len(self.buffer) >= self.batch_size:
            for _ in range(self.updates_per_iter):
                cols = self.buffer.sample(self.batch_size)
                batch = (
                    jnp.asarray(cols["user"]), feats_all,
                    jnp.asarray(cols["clicked_feat"]),
                    jnp.asarray(cols["rew"]),
                    jnp.asarray(cols["nxt_user"]), feats_all,
                    jnp.asarray(cols["nxt_scores"]),
                    jnp.asarray(cols["done"]),
                )
                self.params, self.opt_state, stats = self._update(
                    self.params, self.target_params, self.opt_state,
                    batch, self.slate_size, self._combos, self._prune)
                self._updates_done += 1
                if self._updates_done % self.target_every == 0:
                    self.target_params = jax.tree_util.tree_map(
                        lambda x: x, self.params)
        recent = self.episode_rewards[-20:]
        return {
            "episode_reward_mean": float(np.mean(recent)) if recent
            else float("nan"),
            "epsilon": self._epsilon(),
            "num_updates": self._updates_done,
            **{k: float(v) for k, v in stats.items()},
            "time_this_iter_s": time.time() - t0,
        }

    def _episode_metrics(self) -> Dict[str, Any]:
        recent = self.episode_rewards[-50:]
        return {
            "episode_reward_mean": float(np.mean(recent)) if recent
            else None,
            "episode_len_mean": None,
            "episodes_total": len(self.episode_rewards),
        }

    def compute_slate(self) -> List[int]:
        """Greedy slate for the env's CURRENT user state."""
        return self._slate(explore=False)

    def get_weights(self):
        import jax

        return jax.tree_util.tree_map(np.asarray, self.params)

    def set_weights(self, weights) -> None:
        import jax
        import jax.numpy as jnp

        self.params = jax.tree_util.tree_map(jnp.asarray, weights)

    def _sync_weights(self) -> None:
        pass  # local rollouts

    def _save_extra_state(self):
        import jax

        return {"params": jax.tree_util.tree_map(np.asarray, self.params),
                "updates": self._updates_done,
                "steps": self._timesteps_total}

    def _load_extra_state(self, state) -> None:
        if not state:
            return
        import jax

        self.set_weights(state["params"])
        self.target_params = jax.tree_util.tree_map(
            lambda x: x, self.params)
        self.opt_state = self.opt.init(self.params)
        self._updates_done = state.get("updates", 0)
        self._timesteps_total = state.get("steps", 0)


class SlateQConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(SlateQ)
        self.env_spec = "InterestEvolution"
        self.train_batch_size = 128
        self.extra.update({
            "updates_per_iter": 40, "target_update_freq": 200,
            "epsilon": 1.0, "epsilon_final": 0.05,
            "epsilon_timesteps": 2000, "buffer_size": 50_000,
        })

    def training(self, *, updates_per_iter=None, target_update_freq=None,
                 epsilon_timesteps=None, **kwargs) -> "SlateQConfig":
        super().training(**kwargs)
        for k, v in (("updates_per_iter", updates_per_iter),
                     ("target_update_freq", target_update_freq),
                     ("epsilon_timesteps", epsilon_timesteps)):
            if v is not None:
                self.extra[k] = v
        return self
