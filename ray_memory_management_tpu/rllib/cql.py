"""CQL: conservative Q-learning from a recorded dataset.

The reference's CQL (rllib/algorithms/cql/cql.py — offline input wiring
over a Q-learner; cql_tf_policy.py:137 the conservative penalty
min_Q alpha·E[logsumexp_a Q(s,a) − Q(s,a_data)] of Kumar et al. 2020,
there on top of SAC for continuous control). This is the DISCRETE form on
top of the double-Q TD learner (dqn.py): exact logsumexp over the action
set instead of sampled actions. The penalty pushes down Q on actions the
dataset never took, which is what stops offline Q-learning from chasing
its own out-of-distribution overestimates — plain DQN on a fixed buffer
diverges exactly there.

TPU-first shape: the whole update — online/target forwards, double-Q TD
loss, the conservative penalty, Adam — is one jit'd XLA program fed
contiguous minibatches from the host-side dataset; there is no
environment interaction during training.
"""

from __future__ import annotations

import time
from typing import Any, Dict

import numpy as np

from . import sample_batch as sb
from .algorithm import AlgorithmConfig
from .collector import NEXT_OBS
from .dqn import q_apply, q_init
from .env import make_env
from .models import params_from_numpy, params_to_numpy
from .offline import TERMINATED, DatasetReader, OfflineAlgorithm


def derive_next_obs(data: Dict[str, np.ndarray],
                    recording_starts: np.ndarray = None,
                    ) -> Dict[str, np.ndarray]:
    """Back-fill a missing next_obs column from time-ordered recordings:
    next_obs[t] = obs[t+1] within an episode. The last row of EACH
    recording (DatasetReader.recording_starts — appended recordings are
    independent streams) has no successor: it is kept only if terminal
    (done masks the bootstrap); a non-terminal recording tail is dropped,
    since rolling across the boundary would hand it the NEXT recording's
    reset observation as a live TD successor."""
    if NEXT_OBS in data:
        return data
    T = len(data[sb.DONES])
    nxt = np.roll(data[sb.OBS], -1, axis=0)
    keep = np.ones(T, bool)
    if recording_starts is None or len(recording_starts) == 0:
        recording_starts = np.asarray([0])
    last_rows = list(recording_starts[1:] - 1) + [T - 1]
    for t in last_rows:
        if T and not data[sb.DONES][t]:
            keep[t] = False  # truncated tail: no successor, no terminal
    out = {k: v[keep] for k, v in data.items()}
    out[NEXT_OBS] = nxt[keep]
    return out


def make_cql_update(optimizer, gamma: float, cql_alpha: float):
    import jax
    import jax.numpy as jnp
    import optax

    def loss_fn(params, target_params, obs, actions, rewards, nxt, dones):
        q = q_apply(params, obs)
        q_taken = jnp.take_along_axis(q, actions[:, None], axis=-1)[:, 0]
        # double-Q target: online net picks, target net evaluates
        next_online = q_apply(params, nxt)
        next_target = q_apply(target_params, nxt)
        next_a = jnp.argmax(next_online, axis=-1)
        next_q = jnp.take_along_axis(
            next_target, next_a[:, None], axis=-1)[:, 0]
        target = rewards + gamma * (1.0 - dones) * jax.lax.stop_gradient(
            next_q)
        td_loss = jnp.mean(optax.huber_loss(q_taken, target))
        # the conservative term: logsumexp over ALL actions minus the
        # dataset action's Q — exact for a discrete action set
        penalty = jnp.mean(
            jax.scipy.special.logsumexp(q, axis=-1) - q_taken)
        total = td_loss + cql_alpha * penalty
        return total, {"td_loss": td_loss, "cql_penalty": penalty,
                       "mean_q": q_taken.mean()}

    @jax.jit
    def update(params, target_params, opt_state, obs, actions, rewards,
               nxt, dones):
        (loss, stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, target_params, obs, actions, rewards, nxt, dones)
        upd, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, upd)
        stats["total_loss"] = loss
        return params, opt_state, stats

    return update


class CQL(OfflineAlgorithm):
    def setup(self, config: Dict[str, Any]) -> None:
        import jax
        import optax

        self.cfg = config
        seed = config.get("seed", 0)
        self.reader = DatasetReader(config["input_path"], seed=seed)
        self.reader.data = derive_next_obs(self.reader.data,
                                           self.reader.recording_starts)
        self.reader.num_samples = sb.batch_size(self.reader.data)
        probe_env = make_env(config["env_spec"], config.get("env_config"))
        self.eval_env = probe_env
        hidden = config.get("hidden", (64, 64))
        self.params = q_init(jax.random.key(seed),
                             probe_env.observation_dim,
                             probe_env.num_actions, hidden)
        self.target_params = jax.tree_util.tree_map(
            lambda x: x, self.params)
        self.optimizer = optax.adam(config.get("lr", 1e-3))
        self.opt_state = self.optimizer.init(self.params)
        self._update = make_cql_update(
            self.optimizer, config.get("gamma", 0.99),
            config.get("cql_alpha", 1.0))
        self.train_batch_size = config.get("train_batch_size", 256)
        self.updates_per_step = config.get("updates_per_step", 64)
        self.target_update_freq = config.get("target_update_freq", 100)
        self.eval_episodes = config.get("eval_episodes", 2)
        self._rng = np.random.default_rng(seed)
        self._updates_done = 0
        self._timesteps_total = 0
        self.workers = None
        self.local_worker = None

    def training_step(self) -> Dict[str, Any]:
        import jax
        import jax.numpy as jnp

        t0 = time.time()
        stats: Dict[str, Any] = {}
        d = self.reader.data
        n = self.reader.num_samples
        # bootstrap mask: TRUE terminals only — a time-limit truncation
        # still bootstraps from its recorded next_obs (offline.py's
        # TERMINATED column; legacy recordings only have the conflated
        # DONES, which over-masks at truncations — the old bias)
        term_col = TERMINATED if TERMINATED in d else sb.DONES
        for _ in range(self.updates_per_step):
            idx = self._rng.integers(0, n, size=self.train_batch_size)
            self.params, self.opt_state, stats = self._update(
                self.params, self.target_params, self.opt_state,
                jnp.asarray(d[sb.OBS][idx]),
                jnp.asarray(d[sb.ACTIONS][idx].astype(np.int32)),
                jnp.asarray(d[sb.REWARDS][idx]),
                jnp.asarray(d[NEXT_OBS][idx]),
                jnp.asarray(d[term_col][idx]))
            self._updates_done += 1
            if self._updates_done % self.target_update_freq == 0:
                self.target_params = jax.tree_util.tree_map(
                    lambda x: x, self.params)
        out = {k: float(v) for k, v in stats.items()}
        out.update({
            "num_updates": self._updates_done,
            "dataset_size": n,
            "learn_time_s": time.time() - t0,
        })
        out.update(self._evaluate())
        return out

    def compute_single_action(self, obs: np.ndarray) -> int:
        import jax.numpy as jnp

        q = q_apply(self.params, jnp.asarray(obs[None, :]))
        return int(np.asarray(q)[0].argmax())

    def _save_extra_state(self):
        return {"target_params": params_to_numpy(self.target_params),
                "opt_state": params_to_numpy(self.opt_state),
                "updates_done": self._updates_done}

    def _load_extra_state(self, state) -> None:
        if not state:
            return
        if "target_params" in state:
            self.target_params = params_from_numpy(state["target_params"])
        if "opt_state" in state:
            self.opt_state = params_from_numpy(state["opt_state"])
        self._updates_done = state.get("updates_done", 0)


class CQLConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(CQL)
        self.extra.update({"cql_alpha": 1.0, "updates_per_step": 64,
                           "target_update_freq": 100, "eval_episodes": 2})

    def offline_data(self, *, input_path: str) -> "CQLConfig":
        self.extra["input_path"] = input_path
        return self

    def training(self, *, cql_alpha=None, updates_per_step=None,
                 target_update_freq=None, eval_episodes=None,
                 **kwargs) -> "CQLConfig":
        super().training(**kwargs)
        for k, v in (("cql_alpha", cql_alpha),
                     ("updates_per_step", updates_per_step),
                     ("target_update_freq", target_update_freq),
                     ("eval_episodes", eval_episodes)):
            if v is not None:
                self.extra[k] = v
        return self
