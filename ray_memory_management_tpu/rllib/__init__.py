"""RLlib-slim: RL algorithms with CPU rollout actors + a jax learner.

The reference's RLlib (python/ray/rllib/ — Algorithm/AlgorithmConfig,
rollout workers, PPO/IMPALA, replay buffers), rebuilt TPU-first: the
learner update is one jit'd XLA program, rollouts are CPU actors, and
weights broadcast through the object store.
"""

from .algorithm import Algorithm, AlgorithmConfig  # noqa: F401
from .connectors import (  # noqa: F401
    ClipReward,
    Connector,
    ConnectorPipeline,
    FrameStack,
    ObsNormalizer,
    register_connector,
)
from .alphazero import AlphaZero, AlphaZeroConfig, TicTacToe  # noqa: F401
from .appo import APPO, APPOConfig  # noqa: F401
from .ars import ARS, ARSConfig  # noqa: F401
from .bandit import (  # noqa: F401
    BanditLinTS,
    BanditLinTSConfig,
    BanditLinUCB,
    BanditLinUCBConfig,
    LinearDiscreteBandit,
)
from .cql import CQL, CQLConfig  # noqa: F401
from .dqn import DQN, DQNConfig  # noqa: F401
from .marwil import MARWIL, MARWILConfig  # noqa: F401
from .mbrl import MBPETS, MBPETSConfig  # noqa: F401
from .multi_agent import (  # noqa: F401
    MultiAgentEnv,
    MultiAgentRolloutWorker,
    MultiCartPole,
)
from .es import ES, ESConfig  # noqa: F401
from .env import (  # noqa: F401
    CartPole,
    Env,
    Pendulum,
    make_env,
    register_env,
)
from .impala import IMPALA, IMPALAConfig  # noqa: F401
from .maddpg import MADDPG, MADDPGConfig, Rendezvous  # noqa: F401
from .qmix import QMix, QMixConfig, TwoStepCoop  # noqa: F401
from .offline import (  # noqa: F401
    BC,
    BCConfig,
    DatasetReader,
    DatasetWriter,
    collect_dataset,
    importance_sampling_estimate,
)
from .pg import A2CConfig, PG, PGConfig  # noqa: F401
from .ppo import PPO, PPOConfig  # noqa: F401
from .r2d2 import R2D2, R2D2Config  # noqa: F401
from .recurrent import (  # noqa: F401
    RecurrentPPO,
    RecurrentPPOConfig,
    RecurrentRolloutWorker,
)
from .replay import PrioritizedReplayBuffer, ReplayBuffer  # noqa: F401
from .sac import SAC, SACConfig  # noqa: F401
from .slateq import InterestEvolution, SlateQ, SlateQConfig  # noqa: F401
from .td3 import TD3, DDPGConfig, TD3Config  # noqa: F401
from .rollout_worker import RolloutWorker, WorkerSet  # noqa: F401
