"""MARWIL: monotonic advantage re-weighted imitation learning.

The reference's MARWIL (rllib/algorithms/marwil/marwil.py — config and the
offline input wiring; marwil_tf_policy.py:38 the loss: a learned value
baseline, advantages = returns − V(s), and a BC cross-entropy term weighted
by exp(beta · advantage / c) with c a running scale so the exponent stays
O(1); beta = 0 degenerates to plain BC). Per Wang et al. 2018, the
re-weighting lets cloning from MIXED-quality data follow the good
trajectories and ignore the bad ones — the case where plain BC fails.

TPU-first shape like offline.py's BC: per-timestep discounted returns are
precomputed once on the host from the recorded episodes; the whole update
(value forward, advantage, running-scale update, weighted cross-entropy,
Adam) is one jit'd XLA program over contiguous minibatches.
"""

from __future__ import annotations

import time
from typing import Any, Dict

import numpy as np

from . import sample_batch as sb
from .algorithm import AlgorithmConfig
from .env import make_env
from .models import mlp_apply, mlp_init, params_from_numpy, params_to_numpy
from .offline import DatasetReader, OfflineAlgorithm


def episode_returns(
        rewards: np.ndarray, dones: np.ndarray, gamma: float,
        recording_starts: np.ndarray = None,
) -> "tuple[np.ndarray, np.ndarray]":
    """Per-timestep discounted return-to-go within each recorded episode,
    with a validity mask. ``recording_starts`` marks where independent
    recordings begin (DatasetReader.recording_starts): the accumulator
    must reset there, and each recording's trailing run with no terminal
    is a TRUNCATED recording — its tail return is biased low — so those
    rows are flagged invalid (weight 0) rather than trained on. Without
    boundaries the reverse accumulation would run one recording's tail
    straight into the previous recording's episodes."""
    T = len(rewards)
    if recording_starts is None or len(recording_starts) == 0:
        recording_starts = np.asarray([0])
    returns = np.zeros(T, np.float32)
    valid = np.zeros(T, np.float32)
    bounds = list(recording_starts[1:]) + [T]
    for s, e in zip(recording_starts, bounds):
        acc = 0.0
        for t in range(e - 1, s - 1, -1):
            if dones[t]:
                acc = 0.0
            acc = rewards[t] + gamma * acc
            returns[t] = acc
        nz = np.nonzero(dones[s:e])[0]
        if len(nz):
            valid[s: s + nz[-1] + 1] = 1.0
    return returns, valid


def make_marwil_update(optimizer, beta: float, vf_coeff: float,
                       ma_rate: float = 1e-2, weight_clip: float = 20.0):
    import jax
    import jax.numpy as jnp
    import optax

    def loss_fn(params, c_sq, obs, actions, returns, valid):
        logits = mlp_apply(params["pi"], obs)
        values = mlp_apply(params["vf"], obs)[..., 0]
        adv = returns - values
        n = jnp.maximum(valid.sum(), 1.0)
        vf_loss = jnp.sum(jnp.square(adv) * valid) / n
        # running scale of the advantage magnitude (marwil_tf_policy.py's
        # moving-average norm): keeps beta·adv/c O(1) as V(s) improves
        new_c_sq = c_sq + ma_rate * (
            jnp.sum(jnp.square(jax.lax.stop_gradient(adv)) * valid) / n
            - c_sq)
        c = jnp.sqrt(new_c_sq) + 1e-8
        w = jnp.exp(jnp.clip(
            beta * jax.lax.stop_gradient(adv) / c,
            max=jnp.log(weight_clip)))
        logp = jax.nn.log_softmax(logits)
        nll = -jnp.take_along_axis(logp, actions[:, None], axis=-1)[:, 0]
        pi_loss = jnp.sum(w * nll * valid) / n
        acc = jnp.sum((jnp.argmax(logits, -1) == actions) * valid) / n
        total = pi_loss + vf_coeff * vf_loss
        return total, (new_c_sq, {"policy_loss": pi_loss,
                                  "vf_loss": vf_loss,
                                  "action_match": acc,
                                  "mean_weight": (w * valid).sum() / n})

    @jax.jit
    def update(params, opt_state, c_sq, obs, actions, returns, valid):
        (loss, (c_sq, stats)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, c_sq, obs, actions, returns,
                                   valid)
        upd, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, upd)
        stats["total_loss"] = loss
        return params, opt_state, c_sq, stats

    return update


class MARWIL(OfflineAlgorithm):
    def setup(self, config: Dict[str, Any]) -> None:
        import jax
        import jax.numpy as jnp
        import optax

        self.cfg = config
        seed = config.get("seed", 0)
        self.reader = DatasetReader(config["input_path"], seed=seed)
        gamma = config.get("gamma", 0.99)
        self._returns, self._valid = episode_returns(
            self.reader.data[sb.REWARDS], self.reader.data[sb.DONES],
            gamma, self.reader.recording_starts)
        probe_env = make_env(config["env_spec"], config.get("env_config"))
        self.eval_env = probe_env
        hidden = config.get("hidden", (64, 64))
        k_pi, k_vf = jax.random.split(jax.random.key(seed))
        self.params = {
            "pi": mlp_init(k_pi, [probe_env.observation_dim, *hidden,
                                  probe_env.num_actions]),
            "vf": mlp_init(k_vf, [probe_env.observation_dim, *hidden, 1]),
        }
        self.optimizer = optax.adam(config.get("lr", 1e-3))
        self.opt_state = self.optimizer.init(self.params)
        self.c_sq = jnp.float32(1.0)
        self._update = make_marwil_update(
            self.optimizer, config.get("beta", 1.0),
            config.get("vf_coeff", 1.0),
            config.get("moving_average_rate", 1e-2))
        self.train_batch_size = config.get("train_batch_size", 256)
        self.updates_per_step = config.get("updates_per_step", 64)
        self.eval_episodes = config.get("eval_episodes", 2)
        self._rng = np.random.default_rng(seed)
        self._updates_done = 0
        self._timesteps_total = 0
        self.workers = None
        self.local_worker = None

    def training_step(self) -> Dict[str, Any]:
        import jax.numpy as jnp

        t0 = time.time()
        stats: Dict[str, Any] = {}
        n = self.reader.num_samples
        for _ in range(self.updates_per_step):
            idx = self._rng.integers(0, n, size=self.train_batch_size)
            (self.params, self.opt_state, self.c_sq, stats) = self._update(
                self.params, self.opt_state, self.c_sq,
                jnp.asarray(self.reader.data[sb.OBS][idx]),
                jnp.asarray(
                    self.reader.data[sb.ACTIONS][idx].astype(np.int32)),
                jnp.asarray(self._returns[idx]),
                jnp.asarray(self._valid[idx]))
            self._updates_done += 1
        out = {k: float(v) for k, v in stats.items()}
        out.update({
            "num_updates": self._updates_done,
            "dataset_size": n,
            "adv_scale": float(np.sqrt(np.asarray(self.c_sq))),
            "learn_time_s": time.time() - t0,
        })
        out.update(self._evaluate())
        return out

    def compute_single_action(self, obs: np.ndarray) -> int:
        import jax.numpy as jnp

        logits = mlp_apply(self.params["pi"], jnp.asarray(obs[None, :]))
        return int(np.asarray(logits)[0].argmax())

    def _save_extra_state(self):
        return {"opt_state": params_to_numpy(self.opt_state),
                "c_sq": float(self.c_sq),
                "updates_done": self._updates_done}

    def _load_extra_state(self, state) -> None:
        import jax.numpy as jnp

        if not state:
            return
        if "opt_state" in state:
            self.opt_state = params_from_numpy(state["opt_state"])
        if "c_sq" in state:
            self.c_sq = jnp.float32(state["c_sq"])
        self._updates_done = state.get("updates_done", 0)


class MARWILConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(MARWIL)
        self.extra.update({"beta": 1.0, "vf_coeff": 1.0,
                           "updates_per_step": 64, "eval_episodes": 2})

    def offline_data(self, *, input_path: str) -> "MARWILConfig":
        self.extra["input_path"] = input_path
        return self

    def training(self, *, beta=None, vf_coeff=None, updates_per_step=None,
                 eval_episodes=None, moving_average_rate=None,
                 **kwargs) -> "MARWILConfig":
        super().training(**kwargs)
        for k, v in (("beta", beta), ("vf_coeff", vf_coeff),
                     ("updates_per_step", updates_per_step),
                     ("eval_episodes", eval_episodes),
                     ("moving_average_rate", moving_average_rate)):
            if v is not None:
                self.extra[k] = v
        return self
