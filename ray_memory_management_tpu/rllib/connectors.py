"""Connectors: composable observation/reward transforms between env and
policy.

The reference's connector framework (rllib/connectors/ — agent-side
pipelines transform observations before the policy sees them, with
get_state/set_state so the transforms travel with checkpoints and
worker weight broadcasts). TPU-first shape: connectors are small numpy
state machines living in the CPU rollout workers; the policy network
only ever sees transformed observations, so the jit'd learner programs
stay shape-static (a FrameStack widens the observation dimension once,
at build time).

Pipelines are constructed from declarative SPECS — ``[("obs_norm", {}),
("frame_stack", {"k": 4})]`` — because the pipeline must be rebuilt
inside remote rollout actors (specs pickle; live numpy state does not
need to).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

Spec = Tuple[str, Dict[str, Any]]


class Connector:
    """One transform stage. Subclasses override what they need."""

    def obs_dim(self, dim: int) -> int:
        """Output observation width given the input width."""
        return dim

    def on_reset(self, obs: np.ndarray) -> np.ndarray:
        return self.observe(obs)

    def observe(self, obs: np.ndarray, update: bool = True) -> np.ndarray:
        """Transform one observation. ``update=False`` applies the
        transform WITHOUT learning from it (inference/eval: the policy
        must see the same normalization it trained with, but eval
        observations must not perturb the statistics)."""
        return obs

    def reward(self, r: float) -> float:
        return r

    def state(self) -> Dict[str, Any]:
        return {}

    def set_state(self, state: Dict[str, Any]) -> None:
        pass


class ObsNormalizer(Connector):
    """Running mean/std observation normalization (Welford), the
    reference's MeanStdFilter connector. Stats update during sampling
    and ride state()/set_state() through checkpoints."""

    def __init__(self, clip: float = 10.0, eps: float = 1e-8):
        self.clip = clip
        self.eps = eps
        self._count = 0.0
        self._mean: Optional[np.ndarray] = None
        self._m2: Optional[np.ndarray] = None

    def observe(self, obs: np.ndarray, update: bool = True) -> np.ndarray:
        obs = np.asarray(obs, np.float32)
        if self._mean is None:
            self._mean = np.zeros_like(obs)
            self._m2 = np.zeros_like(obs)
        if update:
            self._count += 1.0
            delta = obs - self._mean
            self._mean = self._mean + delta / self._count
            self._m2 = self._m2 + delta * (obs - self._mean)
        var = self._m2 / max(self._count - 1.0, 1.0)
        out = (obs - self._mean) / np.sqrt(var + self.eps)
        return np.clip(out, -self.clip, self.clip).astype(np.float32)

    def state(self) -> Dict[str, Any]:
        return {"count": self._count, "mean": self._mean, "m2": self._m2}

    def set_state(self, state: Dict[str, Any]) -> None:
        self._count = state["count"]
        self._mean = state["mean"]
        self._m2 = state["m2"]


class FrameStack(Connector):
    """Concatenate the last ``k`` observations (the classic partial-
    observability connector; reference frame-stacking trajectory view)."""

    def __init__(self, k: int = 4):
        self.k = int(k)
        self._frames: List[np.ndarray] = []

    def obs_dim(self, dim: int) -> int:
        return dim * self.k

    def on_reset(self, obs: np.ndarray) -> np.ndarray:
        obs = np.asarray(obs, np.float32)
        self._frames = [obs] * self.k
        return np.concatenate(self._frames)

    def observe(self, obs: np.ndarray, update: bool = True) -> np.ndarray:
        # the frame window always advances — it is episode state, not
        # learned statistics, so `update` does not gate it
        obs = np.asarray(obs, np.float32)
        if not self._frames:
            return self.on_reset(obs)
        self._frames = self._frames[1:] + [obs]
        return np.concatenate(self._frames)

    def state(self) -> Dict[str, Any]:
        return {"frames": list(self._frames)}

    def set_state(self, state: Dict[str, Any]) -> None:
        self._frames = list(state["frames"])


class ClipReward(Connector):
    """Clip rewards into [-limit, limit] (the reference's clip_rewards
    agent connector)."""

    def __init__(self, limit: float = 1.0):
        self.limit = float(limit)

    def reward(self, r: float) -> float:
        return float(np.clip(r, -self.limit, self.limit))


_REGISTRY = {
    "obs_norm": ObsNormalizer,
    "frame_stack": FrameStack,
    "clip_reward": ClipReward,
}


def register_connector(name: str, cls) -> None:
    _REGISTRY[name] = cls


class ConnectorPipeline:
    """Ordered connector stages applied env -> policy."""

    def __init__(self, specs: Sequence[Spec]):
        self.specs = list(specs or ())
        self.stages: List[Connector] = []
        for name, kwargs in self.specs:
            if isinstance(name, type) and issubclass(name, Connector):
                # class-valued spec: custom connectors pickle BY VALUE
                # into remote rollout actors (a name registered only in
                # the driver's _REGISTRY would be unknown there)
                self.stages.append(name(**(kwargs or {})))
                continue
            if name not in _REGISTRY:
                raise ValueError(
                    f"unknown connector {name!r}; register it with "
                    "register_connector, or pass the Connector CLASS "
                    "itself in the spec (required for remote workers)")
            self.stages.append(_REGISTRY[name](**(kwargs or {})))

    def obs_dim(self, dim: int) -> int:
        for s in self.stages:
            dim = s.obs_dim(dim)
        return dim

    def on_reset(self, obs: np.ndarray) -> np.ndarray:
        for s in self.stages:
            obs = s.on_reset(obs)
        return obs

    def observe(self, obs: np.ndarray, update: bool = True) -> np.ndarray:
        for s in self.stages:
            obs = s.observe(obs, update)
        return obs

    def reward(self, r: float) -> float:
        for s in self.stages:
            r = s.reward(r)
        return r

    def state(self) -> List[Dict[str, Any]]:
        return [s.state() for s in self.stages]

    def set_state(self, states: Sequence[Dict[str, Any]]) -> None:
        for s, st in zip(self.stages, states):
            s.set_state(st)


def build_pipeline(specs: Optional[Sequence[Spec]]) -> ConnectorPipeline:
    return ConnectorPipeline(specs or ())
