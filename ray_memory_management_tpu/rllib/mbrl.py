"""MBPETS: model-based RL — learned dynamics ensemble + CEM planning.

The reference's model-based family (rllib/algorithms/dreamer,
rllib/algorithms/mbmpo — learn a dynamics model from real transitions,
then get the policy from the MODEL instead of more environment samples).
This implements the family's PETS-shaped core (Chua et al. 2018, the
algorithm MBMPO's model stack builds on): a probabilistic-ensemble
dynamics model trained by supervised regression, with the acting policy
a cross-entropy-method (CEM) planner that rolls action sequences
through the model and executes the first action of the best plan (MPC).

TPU-first shape: planning is the hot loop, and ALL of it — population
rollouts through every ensemble member across every CEM iteration — is
ONE jit'd program: vmap over candidates x ensemble members, lax.scan
over the horizon, lax.fori_loop over CEM refinement rounds. The
reference's model-based stacks thread per-candidate rollouts through
Python; here the accelerator sees [population x ensemble, horizon]
batched MLP steps with no host round trips inside an action choice.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List

import numpy as np

from .algorithm import Algorithm, AlgorithmConfig
from .env import make_env
from .models import mlp_apply, mlp_init
from .replay import ReplayBuffer


def mb_init(rng, n_models: int, obs_dim: int, act_dim: int,
            hidden=(128, 128)):
    """Dynamics ensemble, stacked along axis 0: each member maps
    [obs, act] -> [delta_obs, reward] (delta prediction — the standard
    trick that makes the regression target near-stationary)."""
    import jax

    def one(key):
        return mlp_init(key, [obs_dim + act_dim, *hidden, obs_dim + 1])

    return jax.vmap(one)(jax.random.split(rng, n_models))


def make_model_update(opt):
    import jax
    import jax.numpy as jnp
    import optax

    def loss(params, obs, act, delta, rew):
        x = jnp.concatenate([obs, act], -1)
        # every member trains on every sample (bootstrap disagreement
        # comes from init + SGD noise; PETS's per-member bootstrap
        # resampling adds little at this scale)
        out = jax.vmap(lambda p: mlp_apply(p, x))(params)  # [E, B, d+1]
        tgt = jnp.concatenate([delta, rew[:, None]], -1)[None]
        return jnp.mean((out - tgt) ** 2)

    @jax.jit
    def update(params, opt_state, obs, act, delta, rew):
        val, grads = jax.value_and_grad(loss)(params, obs, act, delta, rew)
        upd, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, upd), opt_state, val

    return update


def make_cem_planner(horizon: int, population: int, elites: int,
                     cem_iters: int, act_dim: int, bound: float,
                     gamma: float, disagreement_coeff: float = 1.0):
    """The whole MPC action choice as one jit: CEM refinement over
    action sequences, each candidate scored by rolling through every
    ensemble member — mean return MINUS a disagreement penalty
    (``disagreement_coeff`` x the across-member return std). Without
    the penalty CEM reliably finds plans that exploit the model's
    out-of-distribution optimism (unvisited states extrapolate toward
    reward 0 in a task whose true rewards are all negative); member
    disagreement is highest exactly there, so penalizing it keeps
    plans inside the data the model actually fits."""
    import jax
    import jax.numpy as jnp

    def rollout_return(model_params, obs0, plan):
        """Return of ``plan`` [H, act] under ONE model from obs0."""
        def step(carry, a):
            obs, disc = carry
            x = jnp.concatenate([obs, a])[None]
            out = mlp_apply(model_params, x)[0]
            nxt = obs + out[:-1]
            r = out[-1]
            return (nxt, disc * gamma), disc * r

        (_, _), rs = jax.lax.scan(step, (obs0, 1.0), plan)
        return rs.sum()

    def score(params, obs0, plans):
        """Disagreement-penalized return of each candidate [P, H, act]."""
        per = jax.vmap(                       # over ensemble members
            lambda p: jax.vmap(               # over candidates
                lambda plan: rollout_return(p, obs0, plan))(plans)
        )(params)                             # [E, P]
        return per.mean(axis=0) - disagreement_coeff * per.std(axis=0)

    @jax.jit
    def plan(params, obs0, key, init_mean):
        def cem_round(i, carry):
            mean, std, key = carry
            key, sub = jax.random.split(key)
            cand = mean[None] + std[None] * jax.random.normal(
                sub, (population, horizon, act_dim))
            cand = jnp.clip(cand, -bound, bound)
            returns = score(params, obs0, cand)
            top = jax.lax.top_k(returns, elites)[1]
            elite = cand[top]                  # [elites, H, act]
            new_mean = elite.mean(axis=0)
            new_std = elite.std(axis=0) + 1e-3
            return (new_mean, new_std, key)

        mean0 = init_mean
        std0 = jnp.full((horizon, act_dim), bound / 2.0)
        mean, _, _ = jax.lax.fori_loop(
            0, cem_iters, cem_round, (mean0, std0, key))
        return mean  # [H, act]: execute mean[0], warm-start with rest

    return plan


class MBPETS(Algorithm):
    def setup(self, config: Dict[str, Any]) -> None:
        import jax
        import jax.numpy as jnp
        import optax

        self.cfg = config
        seed = config.get("seed", 0)
        self.env = make_env(config["env_spec"], config.get("env_config"))
        if hasattr(self.env, "num_actions") and self.env.num_actions:
            raise ValueError("MBPETS plans continuous torques; discrete "
                             "envs train through DQN-family algorithms")
        self.obs_dim = self.env.observation_dim
        self.act_dim = int(getattr(self.env, "action_dim", 1))
        self.bound = float(getattr(self.env, "action_bound", 1.0))
        self.n_models = config.get("ensemble_size", 4)
        self.horizon = config.get("horizon", 12)
        self.params = mb_init(jax.random.key(seed), self.n_models,
                              self.obs_dim, self.act_dim,
                              config.get("hidden", (128, 128)))
        self.opt = optax.adam(config.get("lr", 1e-3))
        self.opt_state = self.opt.init(self.params)
        self._update = make_model_update(self.opt)
        self._plan = make_cem_planner(
            self.horizon, config.get("population", 128),
            config.get("elites", 16), config.get("cem_iters", 4),
            self.act_dim, self.bound, config.get("gamma", 0.99),
            config.get("disagreement_coeff", 1.0))
        self.buffer = ReplayBuffer(config.get("buffer_size", 100_000))
        self.batch_size = config.get("train_batch_size", 256)
        self.model_updates = config.get("model_updates_per_iter", 80)
        self.rollout_steps = config.get("rollout_fragment_length", 200)
        self.random_steps = config.get("random_steps", 200)
        self._rng = np.random.default_rng(seed)
        self._key = jax.random.PRNGKey(seed)
        self._jnp = jnp
        self._obs = self.env.reset(seed=seed)
        self._plan_mean = jnp.zeros((self.horizon, self.act_dim))
        self._ep_reward = 0.0
        self.episode_rewards: List[float] = []
        self._timesteps_total = 0
        self._updates_done = 0
        self.workers = None
        self.local_worker = None

    # -------------------------------------------------------------- acting
    def _act(self, obs, explore: bool) -> np.ndarray:
        import jax

        jnp = self._jnp
        if explore and self._timesteps_total < self.random_steps:
            return self._rng.uniform(
                -self.bound, self.bound, self.act_dim).astype(np.float32)
        self._key, sub = jax.random.split(self._key)
        mean = self._plan(self.params, jnp.asarray(obs, jnp.float32),
                          sub, self._plan_mean)
        # MPC warm start: shift the plan one step, repeat the tail
        self._plan_mean = jnp.concatenate([mean[1:], mean[-1:]])
        a = np.asarray(mean[0])
        if explore:
            a = a + 0.1 * self.bound * self._rng.standard_normal(
                self.act_dim).astype(np.float32)
        return np.clip(a, -self.bound, self.bound)

    def compute_single_action(self, obs) -> np.ndarray:
        return self._act(np.asarray(obs, np.float32), explore=False)

    # ------------------------------------------------------------- training
    def _collect(self, n: int) -> None:
        jnp = self._jnp
        cols = {"obs": [], "act": [], "delta": [], "rew": []}
        for _ in range(n):
            a = self._act(self._obs, explore=True)
            nxt, r, term, trunc, _ = self.env.step(
                a if self.act_dim > 1 else float(a[0]))
            cols["obs"].append(np.asarray(self._obs, np.float32))
            cols["act"].append(np.asarray(a, np.float32).reshape(
                self.act_dim))
            cols["delta"].append(
                np.asarray(nxt, np.float32)
                - np.asarray(self._obs, np.float32))
            cols["rew"].append(np.float32(r))
            self._ep_reward += float(r)
            self._timesteps_total += 1
            if term or trunc:
                self.episode_rewards.append(self._ep_reward)
                self._ep_reward = 0.0
                self._obs = self.env.reset(
                    seed=int(self._rng.integers(1 << 31)))
                self._plan_mean = jnp.zeros_like(self._plan_mean)
            else:
                self._obs = nxt
        self.buffer.add_batch({k: np.stack(v) for k, v in cols.items()})

    def training_step(self) -> Dict[str, Any]:
        jnp = self._jnp
        t0 = time.time()
        self._collect(self.rollout_steps)
        model_loss = float("nan")
        if len(self.buffer) >= self.batch_size:
            for _ in range(self.model_updates):
                cols = self.buffer.sample(self.batch_size)
                self.params, self.opt_state, model_loss = self._update(
                    self.params, self.opt_state,
                    jnp.asarray(cols["obs"]), jnp.asarray(cols["act"]),
                    jnp.asarray(cols["delta"]), jnp.asarray(cols["rew"]))
                self._updates_done += 1
            model_loss = float(model_loss)
        recent = self.episode_rewards[-10:]
        return {
            "episode_reward_mean": float(np.mean(recent)) if recent
            else float("nan"),
            "model_loss": model_loss,
            "episodes_total": len(self.episode_rewards),
            "num_updates": self._updates_done,
            "time_this_iter_s": time.time() - t0,
        }

    def _episode_metrics(self) -> Dict[str, Any]:
        recent = self.episode_rewards[-10:]
        return {
            "episode_reward_mean": float(np.mean(recent)) if recent
            else None,
            "episode_len_mean": None,
            "episodes_total": len(self.episode_rewards),
        }

    def get_weights(self):
        import jax

        return jax.tree_util.tree_map(np.asarray, self.params)

    def set_weights(self, weights) -> None:
        import jax
        import jax.numpy as jnp

        self.params = jax.tree_util.tree_map(jnp.asarray, weights)

    def _sync_weights(self) -> None:
        pass  # planning runs in-process

    def _save_extra_state(self):
        import jax

        return {"params": jax.tree_util.tree_map(np.asarray, self.params),
                "updates": self._updates_done}

    def _load_extra_state(self, state) -> None:
        if not state:
            return
        self.set_weights(state["params"])
        self.opt_state = self.opt.init(self.params)
        self._updates_done = state.get("updates", 0)


class MBPETSConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(MBPETS)
        self.train_batch_size = 256  # model-regression minibatch, not
        # the on-policy 4000-sample fragment the base default serves
        self.extra.update({
            "ensemble_size": 4, "horizon": 12, "population": 128,
            "elites": 16, "cem_iters": 4, "model_updates_per_iter": 80,
            "random_steps": 200, "rollout_fragment_length": 200,
            "buffer_size": 100_000,
        })

    def training(self, *, ensemble_size=None, horizon=None,
                 population=None, cem_iters=None,
                 model_updates_per_iter=None, **kwargs) -> "MBPETSConfig":
        super().training(**kwargs)
        for k, v in (("ensemble_size", ensemble_size),
                     ("horizon", horizon), ("population", population),
                     ("cem_iters", cem_iters),
                     ("model_updates_per_iter", model_updates_per_iter)):
            if v is not None:
                self.extra[k] = v
        return self
