"""Shared transition-collector loop for the off-policy algorithms.

DQN/SAC/TD3 rollout workers all collect raw (obs, action, reward,
next_obs, done) transitions with the same loop (the worker half of the
reference's rollout_worker.py:124 plus the truncation-vs-termination
bootstrap rule of postprocessing.py); only action selection and the
action-buffer spec differ. This base owns the loop so the bootstrap and
reseed-on-reset semantics exist in exactly one place.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from . import sample_batch as sb
from .env import make_env

NEXT_OBS = "next_obs"


class OffPolicyCollector:
    """Base transition collector. Subclasses implement ``_select_action``
    (reading whatever exploration state they stashed on ``self``) and
    ``_action_buffer``; the base runs the env loop, applies the
    truncation-is-not-terminal bootstrap rule, and keeps episode stats."""

    def _setup_env(self, env_spec, env_config: Optional[dict],
                   seed: int) -> None:
        import jax

        from .. import _worker_context

        if _worker_context.in_worker():
            jax.config.update("jax_default_device", jax.devices("cpu")[0])
        self.env = make_env(env_spec, env_config)
        from .multi_agent import MultiAgentEnv

        if isinstance(self.env, MultiAgentEnv):
            raise ValueError(
                "multi-agent envs train through the on-policy algorithms "
                "(PPO/PG/IMPALA/APPO) with the shared-policy collector; "
                "the replay-buffer algorithms need single-agent envs")
        self.rng = np.random.default_rng(seed)
        self._obs = self.env.reset(seed=seed)
        self._episode_reward = 0.0
        self._episode_len = 0
        self.episode_rewards: List[float] = []
        self.episode_lengths: List[int] = []
        self._steps_done = 0

    def ready(self) -> str:
        return "ok"

    def _action_buffer(self, num_steps: int) -> np.ndarray:
        raise NotImplementedError

    def _select_action(self):
        raise NotImplementedError

    def _collect(self, num_steps: int) -> Dict[str, np.ndarray]:
        D = self.env.observation_dim
        obs_buf = np.zeros((num_steps, D), np.float32)
        next_buf = np.zeros((num_steps, D), np.float32)
        act_buf = self._action_buffer(num_steps)
        rew_buf = np.zeros(num_steps, np.float32)
        done_buf = np.zeros(num_steps, np.float32)
        for t in range(num_steps):
            a = self._select_action()
            next_obs, reward, terminated, truncated, _ = self.env.step(a)
            obs_buf[t] = self._obs
            act_buf[t] = a
            rew_buf[t] = reward
            # a time-limit truncation is NOT a terminal: the TD target
            # must still bootstrap from next_obs (postprocessing.py
            # treats truncations the same way)
            done_buf[t] = float(terminated)
            next_buf[t] = next_obs
            self._episode_reward += reward
            self._episode_len += 1
            self._steps_done += 1
            if terminated or truncated:
                self.episode_rewards.append(self._episode_reward)
                self.episode_lengths.append(self._episode_len)
                self._episode_reward = 0.0
                self._episode_len = 0
                next_obs = self.env.reset(
                    seed=int(self.rng.integers(1 << 31)))
            self._obs = next_obs
        return {
            sb.OBS: obs_buf, sb.ACTIONS: act_buf, sb.REWARDS: rew_buf,
            NEXT_OBS: next_buf, sb.DONES: done_buf,
        }

    def episode_stats(self, window: int = 100) -> Dict[str, Any]:
        return sb.episode_stats_summary(
            self.episode_rewards, self.episode_lengths, window)
