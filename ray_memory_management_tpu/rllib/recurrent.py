"""Recurrent (LSTM) policies with truncated-BPTT PPO.

The reference's ``use_lstm`` model option (rllib/models/catalog.py wraps
any model in an LSTM; rllib/policy/rnn_sequencing.py chops batches into
max_seq_len sequences with per-sequence initial states and pads them;
appo/ppo train over those sequences). Here the recurrent path is its own
compact stack:

- one LSTM cell between an embedding MLP and the policy/value heads
  (lstm_ac_* in this module);
- the rollout worker carries (h, c) across env steps, RESETS it at
  episode boundaries, and records the state at each fragment's start —
  so a fragment plus its initial state replays exactly;
- the learner treats each fragment as one sequence: ``lax.scan`` over
  time re-resets the state at recorded done flags (identical to how the
  rollout ran), vmapped over the sequence batch, so fragments ARE the
  reference's max_seq_len sequences without any re-chopping or padding
  (every fragment has the same length by construction);
- PPO's clipped surrogate applies to the flattened [N*T] outputs, and
  minibatches are drawn as SUBSETS OF SEQUENCES (never scattered
  timesteps, which would sever the recurrence being trained).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .. import api
from . import sample_batch as sb
from .algorithm import AlgorithmConfig
from .env import make_env
from .models import mlp_apply, mlp_init, params_from_numpy, params_to_numpy
from .ppo import PPO
from .rollout_worker import WorkerSet

H0 = "lstm_h0"
C0 = "lstm_c0"


# ------------------------------------------------------------------ model
def lstm_ac_init(rng, obs_dim: int, num_actions: int,
                 embed_dim: int = 64, lstm_dim: int = 64) -> Dict[str, Any]:
    import jax
    import jax.numpy as jnp

    k_e, k_l, k_pi, k_vf = jax.random.split(rng, 4)
    scale = 1.0 / np.sqrt(embed_dim + lstm_dim)
    return {
        "embed": mlp_init(k_e, [obs_dim, embed_dim]),
        "lstm": {
            "w": jax.random.normal(
                k_l, (embed_dim + lstm_dim, 4 * lstm_dim)) * scale,
            "b": jnp.zeros((4 * lstm_dim,))
            # forget-gate bias starts at +1 (standard trick: remember by
            # default early in training)
            .at[lstm_dim:2 * lstm_dim].set(1.0),
        },
        "pi": mlp_init(k_pi, [lstm_dim, num_actions]),
        "vf": mlp_init(k_vf, [lstm_dim, 1]),
    }


def lstm_zero_state(lstm_dim: int) -> Tuple[np.ndarray, np.ndarray]:
    z = np.zeros(lstm_dim, np.float32)
    return z.copy(), z.copy()


def _cell(params, x, h, c):
    """Standard LSTM cell; gate order [i, f, g, o]."""
    import jax
    import jax.numpy as jnp

    z = jnp.concatenate([x, h], axis=-1) @ params["w"] + params["b"]
    i, f, g, o = jnp.split(z, 4, axis=-1)
    c = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
    h = jax.nn.sigmoid(o) * jnp.tanh(c)
    return h, c


def lstm_ac_step(params, obs, h, c):
    """One step: obs [D] (or [B, D]) -> (logits, value, h', c')."""
    import jax

    x = jax.nn.tanh(mlp_apply(params["embed"], obs))
    h, c = _cell(params["lstm"], x, h, c)
    logits = mlp_apply(params["pi"], h)
    value = mlp_apply(params["vf"], h)[..., 0]
    return logits, value, h, c


def lstm_ac_seq(params, obs_seq, dones, h0, c0):
    """Unroll over one sequence [T, D]; the state RESETS after any step
    flagged done, replaying exactly what the rollout worker did.
    Returns (logits [T, A], values [T])."""
    import jax
    import jax.numpy as jnp

    def step(carry, inp):
        h, c = carry
        obs, done = inp
        logits, value, h, c = lstm_ac_step(params, obs, h, c)
        mask = 1.0 - done
        return (h * mask, c * mask), (logits, value)

    _, (logits, values) = jax.lax.scan(
        step, (h0, c0), (obs_seq, dones))
    return logits, values


# ---------------------------------------------------------------- rollout
class RecurrentRolloutWorker:
    """RolloutWorker with an LSTM policy: carries (h, c) across steps,
    resets at episode ends, and records each fragment's initial state so
    the learner can replay the recurrence (rnn_sequencing.py's
    state_in columns)."""

    def __init__(self, env_spec, env_config: Optional[dict],
                 hidden, seed: int, gamma: float = 0.99,
                 lam: float = 0.95, connectors=None,
                 embed_dim: int = 64, lstm_dim: int = 64):
        import jax

        from .. import _worker_context

        if connectors:
            raise ValueError(
                "connectors are not supported with recurrent policies yet")
        if _worker_context.in_worker():
            jax.config.update("jax_default_device", jax.devices("cpu")[0])
        del hidden  # recurrent net is embed->lstm->heads, not an MLP stack
        self.env = make_env(env_spec, env_config)
        self.gamma = gamma
        self.lam = lam
        self.obs_dim = self.env.observation_dim
        self.lstm_dim = lstm_dim
        self.rng = np.random.default_rng(seed)
        self._jax_key = jax.random.key(seed)
        self.params = lstm_ac_init(
            jax.random.key(0), self.obs_dim, self.env.num_actions,
            embed_dim, lstm_dim)
        self._obs = self.env.reset(seed=seed)
        self._h, self._c = lstm_zero_state(lstm_dim)
        self._episode_reward = 0.0
        self._episode_len = 0
        self.episode_rewards: List[float] = []
        self.episode_lengths: List[int] = []
        self._step_jit = None

    def ready(self) -> str:
        return "ok"

    def set_weights(self, weights) -> None:
        self.params = params_from_numpy(weights)

    def get_weights(self):
        return params_to_numpy(self.params)

    def _policy_step(self, obs, h, c, key):
        import jax
        import jax.numpy as jnp

        if self._step_jit is None:
            @jax.jit
            def stepper(params, obs, h, c, key):
                logits, value, h, c = lstm_ac_step(params, obs, h, c)
                action = jax.random.categorical(key, logits)
                logp = jax.nn.log_softmax(logits)[action]
                return action, logp, value, h, c

            self._step_jit = stepper
        return self._step_jit(self.params, jnp.asarray(obs),
                              jnp.asarray(h), jnp.asarray(c), key)

    def sample(self, num_steps: int) -> Dict[str, np.ndarray]:
        import jax

        obs_buf = np.zeros((num_steps, self.obs_dim), np.float32)
        act_buf = np.zeros(num_steps, np.int32)
        rew_buf = np.zeros(num_steps, np.float32)
        done_buf = np.zeros(num_steps, np.float32)
        logp_buf = np.zeros(num_steps, np.float32)
        val_buf = np.zeros(num_steps, np.float32)
        h0, c0 = np.asarray(self._h), np.asarray(self._c)

        for t in range(num_steps):
            self._jax_key, sub = jax.random.split(self._jax_key)
            action, logp, value, h, c = self._policy_step(
                self._obs, self._h, self._c, sub)
            a = int(action)
            obs_buf[t] = self._obs
            act_buf[t] = a
            logp_buf[t] = float(logp)
            val_buf[t] = float(value)
            next_obs, reward, terminated, truncated, _ = self.env.step(a)
            rew_buf[t] = reward
            done_buf[t] = float(terminated)
            self._episode_reward += reward
            self._episode_len += 1
            self._h, self._c = h, c
            if truncated and not terminated:
                # the single-agent truncation rule: fold V(s_next) into
                # the reward (evaluated with the CURRENT memory) and cut
                self._jax_key, sub = jax.random.split(self._jax_key)
                _, _, v_next, _, _ = self._policy_step(
                    next_obs, self._h, self._c, sub)
                rew_buf[t] += self.gamma * float(v_next)
                done_buf[t] = 1.0
            if terminated or truncated:
                self.episode_rewards.append(self._episode_reward)
                self.episode_lengths.append(self._episode_len)
                self._episode_reward = 0.0
                self._episode_len = 0
                next_obs = self.env.reset(
                    seed=int(self.rng.integers(1 << 31)))
                self._h, self._c = lstm_zero_state(self.lstm_dim)
            self._obs = next_obs

        self._jax_key, sub = jax.random.split(self._jax_key)
        _, _, last_val, _, _ = self._policy_step(
            self._obs, self._h, self._c, sub)
        bootstrap = float(last_val)
        adv, targets = sb.compute_gae(
            rew_buf, val_buf, done_buf, bootstrap,
            gamma=self.gamma, lam=self.lam)
        return {
            sb.OBS: obs_buf, sb.ACTIONS: act_buf, sb.REWARDS: rew_buf,
            sb.DONES: done_buf, sb.LOGP: logp_buf, sb.VALUES: val_buf,
            sb.ADVANTAGES: adv, sb.TARGETS: targets,
            sb.BOOTSTRAP: np.array([bootstrap], np.float32),
            H0: h0[None, :], C0: c0[None, :],  # [1, lstm_dim] per fragment
        }

    def get_connector_state(self):
        return None

    def set_connector_state(self, state) -> None:
        pass

    def episode_stats(self, window: int = 100) -> Dict[str, Any]:
        return sb.episode_stats_summary(
            self.episode_rewards, self.episode_lengths, window)


# ---------------------------------------------------------------- learner
def make_recurrent_ppo_update(optimizer, clip_param: float, vf_coeff: float,
                              entropy_coeff: float):
    import jax
    import jax.numpy as jnp
    import optax

    def loss_fn(params, obs, actions, old_logp, advantages, targets,
                dones, h0, c0):
        # obs [N, T, D]; unroll each sequence with its recorded initial
        # state, resetting at done flags exactly as collection did
        logits, values = jax.vmap(
            lambda o, d, h, c: lstm_ac_seq(params, o, d, h, c)
        )(obs, dones, h0, c0)
        logp_all = jax.nn.log_softmax(logits)            # [N, T, A]
        logp = jnp.take_along_axis(
            logp_all, actions[..., None], axis=-1)[..., 0]
        ratio = jnp.exp(logp - old_logp)
        adv = (advantages - advantages.mean()) / (advantages.std() + 1e-8)
        surrogate = jnp.minimum(
            ratio * adv,
            jnp.clip(ratio, 1 - clip_param, 1 + clip_param) * adv)
        pg_loss = -surrogate.mean()
        vf_loss = jnp.square(values - targets).mean()
        entropy = -jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1).mean()
        total = pg_loss + vf_coeff * vf_loss - entropy_coeff * entropy
        return total, {"policy_loss": pg_loss, "vf_loss": vf_loss,
                       "entropy": entropy,
                       "kl": (old_logp - logp).mean()}

    @jax.jit
    def update(params, opt_state, obs, actions, old_logp, advantages,
               targets, dones, h0, c0):
        (loss, stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, obs, actions, old_logp, advantages, targets, dones,
            h0, c0)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        stats["total_loss"] = loss
        return params, opt_state, stats

    return update


class RecurrentPPO(PPO):
    """PPO over LSTM policies: fragments are the training sequences.

    Inherits PPO's config surface; overrides the model (lstm_ac), the
    rollout workers (RecurrentRolloutWorker), and the SGD loop (sequence
    minibatches through the scan-based update)."""

    def setup(self, config: Dict[str, Any]) -> None:
        import jax
        import optax

        if config.get("connectors"):
            raise ValueError(
                "connectors are not supported with recurrent policies yet")
        self.cfg = config
        seed = config.get("seed", 0)
        self.np_rng = np.random.default_rng(seed)
        probe_env = make_env(config["env_spec"], config.get("env_config"))
        self.obs_dim = probe_env.observation_dim
        self.num_actions = probe_env.num_actions
        embed_dim = config.get("embed_dim", 64)
        self.lstm_dim = config.get("lstm_dim", 64)
        self.params = lstm_ac_init(
            jax.random.key(seed), self.obs_dim, self.num_actions,
            embed_dim, self.lstm_dim)
        self._connector_specs = None
        gamma = config.get("gamma", 0.99)
        lam = config.get("lambda_", 0.95)
        self.workers = None
        self.local_worker = None
        worker_args = dict(embed_dim=embed_dim, lstm_dim=self.lstm_dim)
        if config.get("num_rollout_workers", 0) > 0:
            self.workers = WorkerSet(
                config["env_spec"], config.get("env_config"), None,
                config["num_rollout_workers"], seed, gamma, lam,
                connectors=None, worker_cls=RecurrentRolloutWorker,
                worker_kwargs=worker_args)
        else:
            self.local_worker = RecurrentRolloutWorker(
                config["env_spec"], config.get("env_config"), None, seed,
                gamma, lam, None, **worker_args)
        self._timesteps_total = 0

        self.clip_param = config.get("clip_param", 0.2)
        self.vf_coeff = config.get("vf_loss_coeff", 0.5)
        self.entropy_coeff = config.get("entropy_coeff", 0.01)
        self.num_sgd_iter = config.get("num_sgd_iter", 6)
        # minibatches are SEQUENCES per epoch, not timesteps
        self.sgd_minibatch_seqs = config.get("sgd_minibatch_seqs", 8)
        self.optimizer = optax.adam(config.get("lr", 5e-4))
        self.opt_state = self.optimizer.init(self.params)
        self._update = make_recurrent_ppo_update(
            self.optimizer, self.clip_param, self.vf_coeff,
            self.entropy_coeff)

    def training_step(self) -> Dict[str, Any]:
        import jax.numpy as jnp

        t0 = time.time()
        fragment = self.cfg.get("rollout_fragment_length", 200)
        target = self.cfg.get("train_batch_size", 4000)

        batches: List[Dict[str, np.ndarray]] = []
        if self.workers is not None:
            self._sync_weights()
            while sum(len(b[sb.ACTIONS]) for b in batches) < target:
                batches.extend(api.get(self.workers.sample(fragment)))
        else:
            self.local_worker.set_weights(self.get_weights())
            while sum(len(b[sb.ACTIONS]) for b in batches) < target:
                batches.append(self.local_worker.sample(fragment))
        n = sum(len(b[sb.ACTIONS]) for b in batches)
        self._timesteps_total += n
        sample_time = time.time() - t0

        # stack fragments into [N, T, ...] sequences
        t1 = time.time()
        seq = {
            k: jnp.asarray(np.stack([b[k] for b in batches]))
            for k in (sb.OBS, sb.ACTIONS, sb.LOGP, sb.ADVANTAGES,
                      sb.TARGETS, sb.DONES)
        }
        h0 = jnp.asarray(np.concatenate([b[H0] for b in batches]))
        c0 = jnp.asarray(np.concatenate([b[C0] for b in batches]))
        N = len(batches)
        stats: Dict[str, Any] = {}
        mb = min(self.sgd_minibatch_seqs, N)
        for _epoch in range(self.num_sgd_iter):
            # sb.minibatch_indices drops the ragged tail, matching PPO
            # (and avoiding a second XLA compile for the odd shape)
            for idx_np in sb.minibatch_indices(N, mb, self.np_rng):
                idx = jnp.asarray(idx_np)
                self.params, self.opt_state, stats = self._update(
                    self.params, self.opt_state,
                    seq[sb.OBS][idx], seq[sb.ACTIONS][idx],
                    seq[sb.LOGP][idx], seq[sb.ADVANTAGES][idx],
                    seq[sb.TARGETS][idx], seq[sb.DONES][idx],
                    h0[idx], c0[idx])
        learn_time = time.time() - t1

        out = {k: float(v) for k, v in stats.items()}
        out.update({
            "num_env_steps_sampled": n,
            "num_sequences": N,
            "sample_time_s": sample_time,
            "learn_time_s": learn_time,
        })
        return out

    def compute_single_action(self, obs: np.ndarray,
                              state: Optional[tuple] = None):
        """Recurrent inference: returns (action, new_state); pass the
        state back on the next call (None = episode start)."""
        import jax
        import jax.numpy as jnp

        if state is None:
            state = lstm_zero_state(self.lstm_dim)
        h, c = state
        logits, _, h, c = lstm_ac_step(
            self.params, jnp.asarray(obs), jnp.asarray(h), jnp.asarray(c))
        action = int(np.asarray(jnp.argmax(logits)))
        return action, (np.asarray(h), np.asarray(c))


class RecurrentPPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(RecurrentPPO)
        self.extra.update({"clip_param": 0.2, "vf_loss_coeff": 0.5,
                           "entropy_coeff": 0.01, "num_sgd_iter": 6,
                           "sgd_minibatch_seqs": 8, "embed_dim": 64,
                           "lstm_dim": 64})

    def training(self, *, clip_param=None, vf_loss_coeff=None,
                 entropy_coeff=None, num_sgd_iter=None,
                 sgd_minibatch_seqs=None, embed_dim=None, lstm_dim=None,
                 **kwargs) -> "RecurrentPPOConfig":
        super().training(**kwargs)
        for k, v in (("clip_param", clip_param),
                     ("vf_loss_coeff", vf_loss_coeff),
                     ("entropy_coeff", entropy_coeff),
                     ("num_sgd_iter", num_sgd_iter),
                     ("sgd_minibatch_seqs", sgd_minibatch_seqs),
                     ("embed_dim", embed_dim), ("lstm_dim", lstm_dim)):
            if v is not None:
                self.extra[k] = v
        return self
