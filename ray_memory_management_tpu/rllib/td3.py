"""TD3 / DDPG: deterministic-policy continuous control.

The reference ships DDPG and TD3 as one family (rllib/algorithms/ddpg/
ddpg_tf_policy.py — deterministic actor + Q critic, Ornstein-Uhlenbeck or
Gaussian exploration; rllib/algorithms/td3/td3.py — the three TD3 deltas
over DDPG: twin critics with a min backup, delayed policy updates, and
target-policy smoothing per Fujimoto et al. 2018). Same family shape here:
``TD3`` implements the general algorithm; ``DDPGConfig`` is the preset that
turns the three deltas off (single critic, every-step policy update, no
smoothing noise).

TPU-first like sac.py: the whole update — critic TD step, the (possibly
skipped) actor step, polyak target syncs — is ONE jit'd XLA program, with
the delayed-policy branch a ``lax.cond`` on a traced flag so the program
never recompiles across the delay schedule.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

from .. import api
from . import sample_batch as sb
from .algorithm import Algorithm, AlgorithmConfig
from .collector import NEXT_OBS, OffPolicyCollector
from .env import make_env
from .models import mlp_apply, mlp_init, params_from_numpy, params_to_numpy
from .replay import ReplayBuffer
from .rollout_worker import WorkerSet


def td3_init(rng, obs_dim: int, act_dim: int, hidden=(64, 64),
             twin_q: bool = True):
    import jax

    k_pi, k_q1, k_q2 = jax.random.split(rng, 3)
    params = {
        "pi": mlp_init(k_pi, [obs_dim, *hidden, act_dim]),
        "q1": mlp_init(k_q1, [obs_dim + act_dim, *hidden, 1]),
    }
    if twin_q:
        params["q2"] = mlp_init(k_q2, [obs_dim + act_dim, *hidden, 1])
    return params


def pi_apply(params, obs, bound: float):
    """Deterministic squashed action: a = bound * tanh(mlp(s))."""
    import jax.numpy as jnp

    return bound * jnp.tanh(mlp_apply(params["pi"], obs))


def _q(params, which: str, obs, act):
    import jax.numpy as jnp

    return mlp_apply(params[which], jnp.concatenate([obs, act], -1))[..., 0]


def make_td3_update(pi_opt, q_opt, gamma: float, tau: float, bound: float,
                    twin_q: bool, smooth_sigma: float, smooth_clip: float):
    import jax
    import jax.numpy as jnp
    import optax

    def critic_loss(params, target_params, batch, key):
        obs, act, rew, nxt, done = batch
        next_a = pi_apply(target_params, nxt, bound)
        if smooth_sigma > 0:
            # target-policy smoothing: clipped Gaussian on the TARGET
            # action, re-clipped to the action range (td3.py's
            # target_noise/target_noise_clip)
            noise = jnp.clip(
                smooth_sigma * jax.random.normal(key, next_a.shape),
                -smooth_clip, smooth_clip)
            next_a = jnp.clip(next_a + noise, -bound, bound)
        tq = _q(target_params, "q1", nxt, next_a)
        if twin_q:
            tq = jnp.minimum(tq, _q(target_params, "q2", nxt, next_a))
        target = rew + gamma * (1.0 - done) * jax.lax.stop_gradient(tq)
        q1 = _q(params, "q1", obs, act)
        loss = jnp.mean((q1 - target) ** 2)
        if twin_q:
            loss = loss + jnp.mean((_q(params, "q2", obs, act) - target) ** 2)
        return loss, q1.mean()

    def actor_loss(pi_params, params, obs):
        merged = {**params, "pi": pi_params}
        return -jnp.mean(_q(params, "q1", obs, pi_apply(merged, obs, bound)))

    @jax.jit
    def update(params, target_params, opt_states, batch, key, do_actor):
        pi_state, q_state = opt_states
        obs = batch[0]

        # critic_loss reads params only through the critics (next actions
        # come from target_params), so c_grads["pi"] is already zeros
        (c_loss, mean_q), c_grads = jax.value_and_grad(
            critic_loss, has_aux=True)(params, target_params, batch, key)
        q_upd, q_state = q_opt.update(c_grads, q_state, params)
        params = optax.apply_updates(params, q_upd)

        # delayed policy update + target sync, one traced branch — skipped
        # steps still run the SAME compiled program (lax.cond, no retrace)
        def with_actor(operand):
            params, target_params, pi_state = operand
            a_loss_v, pi_grads = jax.value_and_grad(actor_loss)(
                params["pi"], params, obs)
            pi_upd, pi_state = pi_opt.update(pi_grads, pi_state,
                                             params["pi"])
            params = {**params,
                      "pi": optax.apply_updates(params["pi"], pi_upd)}
            target_params = jax.tree_util.tree_map(
                lambda t, p: (1.0 - tau) * t + tau * p, target_params,
                params)
            return params, target_params, pi_state, a_loss_v

        def without_actor(operand):
            params, target_params, pi_state = operand
            return params, target_params, pi_state, jnp.float32(0.0)

        params, target_params, pi_state, a_loss_v = jax.lax.cond(
            do_actor, with_actor, without_actor,
            (params, target_params, pi_state))

        stats = {"critic_loss": c_loss, "actor_loss": a_loss_v,
                 "mean_q": mean_q}
        return params, target_params, (pi_state, q_state), stats

    return update


class TD3RolloutWorker(OffPolicyCollector):
    """Deterministic-policy collector: exploration is ADDITIVE Gaussian
    action noise (ddpg.py's exploration_config gaussian sigma), with a
    uniform-random warmup seeding the replay buffer."""

    def __init__(self, env_spec, env_config: Optional[dict], hidden,
                 twin_q: bool, sigma: float, seed: int):
        import jax

        self._setup_env(env_spec, env_config, seed)
        self.bound = float(getattr(self.env, "action_bound", 1.0))
        self.act_dim = int(getattr(self.env, "action_dim", 1))
        self.sigma = sigma
        self.params = td3_init(jax.random.key(0), self.env.observation_dim,
                               self.act_dim, hidden, twin_q)
        self._random_steps = 0

    def set_weights(self, weights) -> None:
        self.params = {**self.params,
                       "pi": params_from_numpy(weights["pi"])}

    def sample(self, num_steps: int,
               random_steps: int = 0) -> Dict[str, np.ndarray]:
        self._random_steps = random_steps
        return self._collect(num_steps)

    def _action_buffer(self, num_steps: int) -> np.ndarray:
        return np.zeros((num_steps, self.act_dim), np.float32)

    def _select_action(self) -> np.ndarray:
        import jax.numpy as jnp

        if self._steps_done < self._random_steps:
            return self.rng.uniform(-self.bound, self.bound, self.act_dim)
        a = np.asarray(pi_apply(
            self.params, jnp.asarray(self._obs[None, :]), self.bound))[0]
        return np.clip(
            a + self.sigma * self.bound
            * self.rng.standard_normal(self.act_dim),
            -self.bound, self.bound)


class _TD3WorkerSet(WorkerSet):
    def __init__(self, env_spec, env_config, hidden, twin_q, sigma,
                 num_workers: int, seed: int):
        cls = api.remote(TD3RolloutWorker)
        self.remote_workers = [
            cls.options(num_cpus=1).remote(
                env_spec, env_config, hidden, twin_q, sigma,
                seed + 1000 * (i + 1))
            for i in range(num_workers)
        ]
        api.get([w.ready.remote() for w in self.remote_workers])

    def sample(self, num_steps: int, random_steps: int = 0) -> List:
        return [w.sample.remote(num_steps, random_steps)
                for w in self.remote_workers]


class TD3(Algorithm):
    def setup(self, config: Dict[str, Any]) -> None:
        import jax
        import jax.numpy as jnp  # noqa: F401  (kept hot for update calls)
        import optax

        self.cfg = config
        if config.get("connectors"):
            raise ValueError(
                "connectors are not supported by this algorithm's "
                "custom rollout collectors yet; use PPO/IMPALA or "
                "drop the connectors config")
        seed = config.get("seed", 0)
        probe_env = make_env(config["env_spec"], config.get("env_config"))
        self.obs_dim = probe_env.observation_dim
        self.act_dim = int(getattr(probe_env, "action_dim", 1))
        self.bound = float(getattr(probe_env, "action_bound", 1.0))
        hidden = config.get("hidden", (64, 64))
        self.twin_q = bool(config.get("twin_q", True))
        self.params = td3_init(jax.random.key(seed), self.obs_dim,
                               self.act_dim, hidden, self.twin_q)
        self.target_params = jax.tree_util.tree_map(
            lambda x: x, self.params)
        self.gamma = config.get("gamma", 0.99)
        self.tau = config.get("tau", 0.005)
        self.policy_delay = int(config.get("policy_delay", 2))
        lr = config.get("lr", 1e-3)
        self._pi_opt = optax.adam(config.get("actor_lr", lr))
        self._q_opt = optax.adam(config.get("critic_lr", lr))
        self.opt_states = (self._pi_opt.init(self.params["pi"]),
                           self._q_opt.init(self.params))
        self._update = make_td3_update(
            self._pi_opt, self._q_opt, self.gamma, self.tau, self.bound,
            self.twin_q, config.get("smooth_sigma", 0.2),
            config.get("smooth_clip", 0.5))
        self.replay = ReplayBuffer(
            config.get("replay_buffer_capacity", 100_000), seed=seed)
        self.learning_starts = config.get("learning_starts", 500)
        self.random_steps = config.get("random_steps", 500)
        self.train_batch_size = config.get("train_batch_size", 128)
        self.updates_per_step = config.get("updates_per_step", 32)
        self.explore_sigma = config.get("explore_sigma", 0.1)
        self._key = jax.random.PRNGKey(seed + 7)
        self._updates_done = 0
        self._timesteps_total = 0

        n_workers = config.get("num_rollout_workers", 0)
        self.workers = None
        self.local_worker = None
        if n_workers > 0:
            self.workers = _TD3WorkerSet(
                config["env_spec"], config.get("env_config"), hidden,
                self.twin_q, self.explore_sigma, n_workers, seed)
        else:
            self.local_worker = TD3RolloutWorker(
                config["env_spec"], config.get("env_config"), hidden,
                self.twin_q, self.explore_sigma, seed)

    def training_step(self) -> Dict[str, Any]:
        import jax
        import jax.numpy as jnp

        t0 = time.time()
        fragment = self.cfg.get("rollout_fragment_length", 64)
        self._sync_weights()
        if self.workers is not None:
            batches = api.get(
                self.workers.sample(fragment, self.random_steps))
        else:
            batches = [self.local_worker.sample(
                fragment, self.random_steps)]
        n = 0
        for b in batches:
            self.replay.add_batch(b)
            n += len(b[sb.ACTIONS])
        self._timesteps_total += n
        sample_time = time.time() - t0

        stats: Dict[str, Any] = {}
        t1 = time.time()
        if len(self.replay) >= self.learning_starts:
            for _ in range(self.updates_per_step):
                mb = self.replay.sample(self.train_batch_size)
                self._key, sub = jax.random.split(self._key)
                batch = (jnp.asarray(mb[sb.OBS]),
                         jnp.asarray(mb[sb.ACTIONS]),
                         jnp.asarray(mb[sb.REWARDS]),
                         jnp.asarray(mb[NEXT_OBS]),
                         jnp.asarray(mb[sb.DONES]))
                do_actor = jnp.asarray(
                    self._updates_done % self.policy_delay == 0)
                (self.params, self.target_params, self.opt_states,
                 stats) = self._update(
                    self.params, self.target_params, self.opt_states,
                    batch, sub, do_actor)
                self._updates_done += 1
        learn_time = time.time() - t1

        out = {k: float(v) for k, v in stats.items()}
        out.update({
            "num_env_steps_sampled": n,
            "replay_size": len(self.replay),
            "num_updates": self._updates_done,
            "sample_time_s": sample_time,
            "learn_time_s": learn_time,
        })
        return out

    def compute_single_action(self, obs: np.ndarray) -> np.ndarray:
        import jax.numpy as jnp

        return np.asarray(pi_apply(
            self.params, jnp.asarray(obs[None, :]), self.bound))[0]

    def _sync_weights(self) -> None:
        weights = {"pi": params_to_numpy(self.params["pi"])}
        if self.workers is not None:
            self.workers.set_weights(weights)
        else:
            self.local_worker.set_weights(weights)

    def _save_extra_state(self):
        return {
            "target_params": params_to_numpy(self.target_params),
            "opt_states": params_to_numpy(self.opt_states),
            "key": params_to_numpy(self._key),
            "updates_done": self._updates_done,
        }

    def _load_extra_state(self, state) -> None:
        import jax.numpy as jnp

        if not state:
            return
        if "target_params" in state:
            self.target_params = params_from_numpy(state["target_params"])
        if "opt_states" in state:
            self.opt_states = params_from_numpy(state["opt_states"])
        if "key" in state:
            self._key = jnp.asarray(state["key"])
        self._updates_done = state.get("updates_done", 0)


class TD3Config(AlgorithmConfig):
    def __init__(self):
        super().__init__(TD3)
        self.extra.update({
            "replay_buffer_capacity": 100_000, "learning_starts": 500,
            "random_steps": 500, "updates_per_step": 32, "tau": 0.005,
            "twin_q": True, "policy_delay": 2, "smooth_sigma": 0.2,
            "smooth_clip": 0.5, "explore_sigma": 0.1,
        })

    def training(self, *, replay_buffer_capacity=None, learning_starts=None,
                 random_steps=None, updates_per_step=None, tau=None,
                 policy_delay=None, smooth_sigma=None, smooth_clip=None,
                 explore_sigma=None, twin_q=None, actor_lr=None,
                 critic_lr=None, **kwargs) -> "TD3Config":
        super().training(**kwargs)
        for k, v in (
                ("replay_buffer_capacity", replay_buffer_capacity),
                ("learning_starts", learning_starts),
                ("random_steps", random_steps),
                ("updates_per_step", updates_per_step),
                ("tau", tau), ("policy_delay", policy_delay),
                ("smooth_sigma", smooth_sigma),
                ("smooth_clip", smooth_clip),
                ("explore_sigma", explore_sigma), ("twin_q", twin_q),
                ("actor_lr", actor_lr), ("critic_lr", critic_lr)):
            if v is not None:
                self.extra[k] = v
        return self


class DDPGConfig(TD3Config):
    """DDPG = TD3 minus the three TD3 deltas (the reference keeps DDPG as
    its own algorithm, rllib/algorithms/ddpg/ddpg.py; here it is the
    degenerate preset: single critic, policy updated every step, no
    target smoothing — Lillicrap et al. 2015 with Gaussian exploration)."""

    def __init__(self):
        super().__init__()
        self.extra.update({
            "twin_q": False, "policy_delay": 1, "smooth_sigma": 0.0,
        })
