"""ARS: augmented random search (Mania et al. 2018) — gradient-free
linear/MLP policy search with three augmentations over vanilla random
search (the reference's rllib/algorithms/ars/ars.py): divide the step by
the std of the selected returns, keep only the top-k best perturbation
directions, and normalize observations with a running mean/std filter
shared across workers (ars.py's MeanStdFilter synchronization).

Shares ES's redesign (es.py): NO shared noise table — every perturbation
is its PRNG seed, regenerated worker-side for the rollout and
learner-side inside one jit'd vmap for the update. The extra ARS state
that must stay consistent is the observation filter: workers return
(count, sum, sumsq) increments and the learner folds them into the
master filter broadcast with the next weight sync — the same
delta-merge the reference's filter sync does.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

from .. import api
from .algorithm import Algorithm, AlgorithmConfig
from .env import make_env
from .es import ESRolloutWorker, flatten_params, unflatten_params
from .models import mlp_apply, mlp_init
from .rollout_worker import WorkerSet


class _ObsFilter:
    """Running mean/std observation normalizer (MeanStdFilter analog).
    Tracks (count, sum, sumsq); normalization uses the fixed snapshot at
    the start of each rollout round so every worker normalizes
    identically, while increments accumulate for the next merge."""

    def __init__(self, dim: int):
        self.count = 0.0
        self.sum = np.zeros(dim, np.float64)
        self.sumsq = np.zeros(dim, np.float64)

    def snapshot(self) -> Dict[str, np.ndarray]:
        if self.count < 2:
            dim = len(self.sum)
            return {"mean": np.zeros(dim, np.float32),
                    "std": np.ones(dim, np.float32)}
        mean = self.sum / self.count
        var = np.maximum(self.sumsq / self.count - mean * mean, 1e-8)
        return {"mean": mean.astype(np.float32),
                "std": np.sqrt(var).astype(np.float32)}

    def merge(self, delta: Dict[str, Any]) -> None:
        self.count += float(delta["count"])
        self.sum += np.asarray(delta["sum"], np.float64)
        self.sumsq += np.asarray(delta["sumsq"], np.float64)


class ARSRolloutWorker(ESRolloutWorker):
    """ES worker + observation filtering: normalizes each observation
    with the master filter snapshot and records raw-obs increments to
    ship back (ars.py workers sync filter deltas the same way)."""

    def __init__(self, env_spec, env_config: Optional[dict], hidden,
                 sigma: float, seed: int):
        super().__init__(env_spec, env_config, hidden, sigma, seed)
        dim = self.env.observation_dim
        self._f_mean = np.zeros(dim, np.float32)
        self._f_std = np.ones(dim, np.float32)
        self._inc_count = 0.0
        self._inc_sum = np.zeros(dim, np.float64)
        self._inc_sumsq = np.zeros(dim, np.float64)

    def set_filter(self, mean: np.ndarray, std: np.ndarray) -> None:
        self._f_mean = np.asarray(mean, np.float32)
        self._f_std = np.maximum(np.asarray(std, np.float32), 1e-4)

    def take_filter_delta(self) -> Dict[str, Any]:
        out = {"count": self._inc_count, "sum": self._inc_sum.copy(),
               "sumsq": self._inc_sumsq.copy()}
        self._inc_count = 0.0
        self._inc_sum[:] = 0.0
        self._inc_sumsq[:] = 0.0
        return out

    def _episode(self, flat: np.ndarray) -> float:
        import jax.numpy as jnp

        params = unflatten_params(flat, self.template)
        obs = self.env.reset(seed=int(self.rng.integers(1 << 31)))
        total, steps, done = 0.0, 0, False
        while not done:
            o = np.asarray(obs, np.float64)
            self._inc_count += 1.0
            self._inc_sum += o
            self._inc_sumsq += o * o
            norm = (obs - self._f_mean) / self._f_std
            out = np.asarray(
                mlp_apply(params, jnp.asarray(norm[None, :])))[0]
            if self.discrete:
                a = int(out.argmax())
            else:
                bound = float(getattr(self.env, "action_bound", 1.0))
                a = bound * np.tanh(out)
            obs, r, term, trunc, _ = self.env.step(a)
            total += r
            steps += 1
            done = term or trunc
        self.episode_rewards.append(total)
        self.episode_lengths.append(steps)
        return total


class _ARSWorkerSet(WorkerSet):
    def __init__(self, env_spec, env_config, hidden, sigma,
                 num_workers: int, seed: int):
        cls = api.remote(ARSRolloutWorker)
        self.remote_workers = [
            cls.options(num_cpus=1).remote(
                env_spec, env_config, hidden, sigma,
                seed + 1000 * (i + 1))
            for i in range(num_workers)
        ]
        api.get([w.ready.remote() for w in self.remote_workers])


def make_ars_update(lr: float, sigma: float):
    """The top-k direction step: grad = sum_k (pos_k - neg_k) * eps_k,
    scaled by 1/(k * sigma * std(selected returns)) — perturbations
    reconstructed from seeds inside one jit (ars.py's sgd step over the
    deltas of the kept directions)."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def update(theta, seeds, pos, neg, ret_std):
        def eps_for(seed):
            return jax.random.normal(
                jax.random.PRNGKey(seed), theta.shape, dtype=jnp.float32)

        eps = jax.vmap(eps_for)(seeds)              # [k, dim]
        grad = ((pos - neg) @ eps) / (len(pos) * sigma)
        return theta + (lr / jnp.maximum(ret_std, 1e-6)) * grad

    return update


class ARS(Algorithm):
    def setup(self, config: Dict[str, Any]) -> None:
        import jax

        self.cfg = config
        if config.get("connectors"):
            raise ValueError(
                "connectors are not supported by ARS's episode-return "
                "evaluation workers")
        seed = config.get("seed", 0)
        probe_env = make_env(config["env_spec"], config.get("env_config"))
        # the ARS paper's headline results use LINEAR policies; hidden=()
        # gives exactly that, deeper nets remain available
        hidden = config.get("hidden", ())
        discrete = hasattr(probe_env, "num_actions")
        out_dim = (probe_env.num_actions if discrete
                   else int(getattr(probe_env, "action_dim", 1)))
        self.template = mlp_init(
            jax.random.key(seed),
            [probe_env.observation_dim, *hidden, out_dim])
        self.theta = flatten_params(self.template)
        self.sigma = config.get("sigma", 0.05)
        self.n_directions = config.get("num_directions", 32)
        self.top_k = min(config.get("top_directions", 16),
                         self.n_directions)
        self._update = make_ars_update(config.get("lr", 0.02), self.sigma)
        self.filter = _ObsFilter(probe_env.observation_dim)
        self._rng = np.random.default_rng(seed)
        self._discrete = discrete
        self._probe_env = probe_env
        self._timesteps_total = 0
        self._updates_done = 0

        n_workers = config.get("num_rollout_workers", 0)
        self.workers = None
        self.local_worker = None
        if n_workers > 0:
            self.workers = _ARSWorkerSet(
                config["env_spec"], config.get("env_config"), hidden,
                self.sigma, n_workers, seed)
        else:
            self.local_worker = ARSRolloutWorker(
                config["env_spec"], config.get("env_config"), hidden,
                self.sigma, seed)

    def training_step(self) -> Dict[str, Any]:
        import jax.numpy as jnp

        t0 = time.time()
        seeds = [int(s) for s in
                 self._rng.integers(0, 1 << 31, size=self.n_directions)]
        snap = self.filter.snapshot()
        if self.workers is not None:
            ws = self.workers.remote_workers
            api.get([w.set_filter.remote(snap["mean"], snap["std"])
                     for w in ws])
            self.workers.set_weights(self.theta)
            shards = np.array_split(np.asarray(seeds), len(ws))
            results = api.get([
                w.evaluate.remote([int(s) for s in shard])
                for w, shard in zip(ws, shards) if len(shard)])
            for delta in api.get(
                    [w.take_filter_delta.remote() for w in ws]):
                self.filter.merge(delta)
        else:
            self.local_worker.set_filter(snap["mean"], snap["std"])
            self.local_worker.set_weights(self.theta)
            results = [self.local_worker.evaluate(seeds)]
            self.filter.merge(self.local_worker.take_filter_delta())
        all_seeds = np.concatenate([r["seeds"] for r in results])
        pos = np.concatenate([r["pos"] for r in results])
        neg = np.concatenate([r["neg"] for r in results])
        self._timesteps_total += int(sum(r["steps"] for r in results))

        # keep the top_k directions by max(pos, neg) (ars.py's deltas_idx
        # selection), scale the step by the std of the kept returns
        score = np.maximum(pos, neg)
        keep = np.argsort(score)[-self.top_k:]
        kept_returns = np.concatenate([pos[keep], neg[keep]])
        self.theta = np.asarray(self._update(
            jnp.asarray(self.theta),
            jnp.asarray(all_seeds[keep]),
            jnp.asarray(pos[keep], jnp.float32),
            jnp.asarray(neg[keep], jnp.float32),
            jnp.float32(np.std(kept_returns))))
        self._updates_done += 1

        return {
            "episodes_this_iter": 2 * len(all_seeds),
            "fitness_mean": float(np.mean(np.concatenate([pos, neg]))),
            "fitness_max": float(max(pos.max(), neg.max())),
            "filter_count": float(self.filter.count),
            "num_updates": self._updates_done,
            "theta_norm": float(np.linalg.norm(self.theta)),
            "time_this_iter_s": time.time() - t0,
        }

    def compute_single_action(self, obs: np.ndarray):
        import jax.numpy as jnp

        snap = self.filter.snapshot()
        norm = (np.asarray(obs, np.float32) - snap["mean"]) / \
            np.maximum(snap["std"], 1e-4)
        params = unflatten_params(self.theta, self.template)
        out = np.asarray(mlp_apply(params, jnp.asarray(norm[None, :])))[0]
        if self._discrete:
            return int(out.argmax())
        bound = float(getattr(self._probe_env, "action_bound", 1.0))
        return bound * np.tanh(out)

    def get_weights(self):
        return self.theta

    def set_weights(self, weights) -> None:
        self.theta = np.asarray(weights, np.float32)

    def _sync_weights(self) -> None:
        pass  # theta broadcasts inside training_step

    def _save_extra_state(self):
        return {"theta": self.theta, "updates_done": self._updates_done,
                "filter": {"count": self.filter.count,
                           "sum": self.filter.sum,
                           "sumsq": self.filter.sumsq}}

    def _load_extra_state(self, state) -> None:
        if not state:
            return
        if "theta" in state:
            self.theta = np.asarray(state["theta"], np.float32)
        self._updates_done = state.get("updates_done", 0)
        f = state.get("filter")
        if f:
            self.filter.count = float(f["count"])
            self.filter.sum = np.asarray(f["sum"], np.float64)
            self.filter.sumsq = np.asarray(f["sumsq"], np.float64)


class ARSConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(ARS)
        self.extra.update({"sigma": 0.05, "num_directions": 32,
                           "top_directions": 16, "hidden": ()})

    def training(self, *, sigma=None, num_directions=None,
                 top_directions=None, **kwargs) -> "ARSConfig":
        super().training(**kwargs)
        for k, v in (("sigma", sigma),
                     ("num_directions", num_directions),
                     ("top_directions", top_directions)):
            if v is not None:
                self.extra[k] = v
        return self
