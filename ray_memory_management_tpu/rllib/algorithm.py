"""Algorithm base + config builder.

The reference's Algorithm(Trainable) (rllib/algorithms/algorithm.py:145,
step:631, training_step:1154) and AlgorithmConfig builder
(algorithm_config.py: .environment()/.rollouts()/.training()/.resources()).
Algorithms implement ``training_step``; the Trainable contract
(train/save/restore) comes from the tune library, so any algorithm drops
straight into the Tuner.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Dict, Optional, Sequence, Type

import numpy as np

from .. import api
from ..tune.trainable import Trainable
from .env import make_env
from .models import ac_init, params_from_numpy, params_to_numpy
from .rollout_worker import RolloutWorker, WorkerSet


class AlgorithmConfig:
    def __init__(self, algo_class: Optional[Type["Algorithm"]] = None):
        self.algo_class = algo_class
        self.env_spec: Any = "CartPole"
        self.env_config: Dict[str, Any] = {}
        self.num_rollout_workers = 2
        self.rollout_fragment_length = 200
        self.train_batch_size = 4000
        self.lr = 5e-4
        self.gamma = 0.99
        self.seed = 0
        self.hidden: Sequence[int] = (64, 64)
        self.extra: Dict[str, Any] = {}

    # builder surface (each returns self, like the reference)
    def environment(self, env=None, *, env_config=None) -> "AlgorithmConfig":
        if env is not None:
            self.env_spec = env
        if env_config is not None:
            self.env_config = dict(env_config)
        return self

    def rollouts(self, *, num_rollout_workers=None,
                 rollout_fragment_length=None) -> "AlgorithmConfig":
        if num_rollout_workers is not None:
            self.num_rollout_workers = num_rollout_workers
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, *, lr=None, gamma=None, train_batch_size=None,
                 model=None, **extra) -> "AlgorithmConfig":
        if lr is not None:
            self.lr = lr
        if gamma is not None:
            self.gamma = gamma
        if train_batch_size is not None:
            self.train_batch_size = train_batch_size
        if model is not None and "fcnet_hiddens" in model:
            self.hidden = tuple(model["fcnet_hiddens"])
        self.extra.update(extra)
        return self

    def connectors(self, specs) -> "AlgorithmConfig":
        """Env->policy transform pipeline specs, e.g.
        [("obs_norm", {}), ("frame_stack", {"k": 4})] — see
        rllib/connectors.py (the reference's connector framework,
        rllib/connectors/)."""
        self.extra["connectors"] = list(specs)
        return self

    def debugging(self, *, seed=None) -> "AlgorithmConfig":
        if seed is not None:
            self.seed = seed
        return self

    def to_dict(self) -> Dict[str, Any]:
        return {
            "env_spec": self.env_spec,
            "env_config": self.env_config,
            "num_rollout_workers": self.num_rollout_workers,
            "rollout_fragment_length": self.rollout_fragment_length,
            "train_batch_size": self.train_batch_size,
            "lr": self.lr,
            "gamma": self.gamma,
            "seed": self.seed,
            "hidden": tuple(self.hidden),
            **self.extra,
        }

    def build(self) -> "Algorithm":
        if self.algo_class is None:
            raise ValueError("config has no algorithm class")
        return self.algo_class(config=self.to_dict())


class Algorithm(Trainable):
    """Common setup: local policy params + remote rollout workers.
    Subclasses implement ``training_step`` returning metrics."""

    # class-level defaults: subclasses with custom setup() (DQN/SAC/BC)
    # reject the connectors config and never populate these
    _connector_specs = None
    _infer_pipeline = None

    def setup(self, config: Dict[str, Any]) -> None:
        import jax

        self.cfg = config
        seed = config.get("seed", 0)
        self.np_rng = np.random.default_rng(seed)
        probe_env = make_env(config["env_spec"], config.get("env_config"))
        connectors = config.get("connectors")
        from .connectors import build_pipeline

        self._connector_specs = connectors
        # the model is sized for the CONNECTOR-TRANSFORMED observation
        # (e.g. frame stacking widens it; rllib/connectors/ analog)
        self.obs_dim = build_pipeline(connectors).obs_dim(
            probe_env.observation_dim)
        self.num_actions = probe_env.num_actions
        self.params = ac_init(
            jax.random.key(seed), self.obs_dim, self.num_actions,
            config.get("hidden", (64, 64)))
        self.workers: Optional[WorkerSet] = None
        self.local_worker: Optional[RolloutWorker] = None
        gamma = config.get("gamma", 0.99)
        lam = config.get("lambda_", 0.95)
        # a MultiAgentEnv spec swaps in the shared-policy multi-agent
        # collector; the learner is unchanged (the fragments it emits
        # honor the same flat-fragment contract)
        from .multi_agent import MultiAgentEnv, MultiAgentRolloutWorker

        worker_cls = (MultiAgentRolloutWorker
                      if isinstance(probe_env, MultiAgentEnv) else
                      RolloutWorker)
        if config.get("num_rollout_workers", 0) > 0:
            self.workers = WorkerSet(
                config["env_spec"], config.get("env_config"),
                config.get("hidden", (64, 64)),
                config["num_rollout_workers"], seed, gamma, lam,
                connectors=connectors, worker_cls=worker_cls)
        else:
            self.local_worker = worker_cls(
                config["env_spec"], config.get("env_config"),
                config.get("hidden", (64, 64)), seed, gamma, lam,
                connectors=connectors)
        # inference pipeline: the local worker's (shared object, stats
        # always warm) or a learner-side copy synced from worker 0 (see
        # _sync_connector_state) — compute_single_action must see the
        # SAME transform the policy trained with
        if worker_cls is MultiAgentRolloutWorker:
            self._infer_pipeline = build_pipeline(None)
        elif self.local_worker is not None:
            self._infer_pipeline = self.local_worker.connectors
        else:
            self._infer_pipeline = build_pipeline(connectors)
        self._timesteps_total = 0

    def _sync_connector_state(self) -> None:
        """Pull connector state (e.g. running obs-norm stats) from worker
        0 into the learner's inference pipeline. No-op without connectors
        or with a shared local worker."""
        if not self._connector_specs or self.workers is None:
            return
        try:
            state = api.get(self.workers.remote_workers[0]
                            .get_connector_state.remote())
            self._infer_pipeline.set_state(state)
        except Exception:  # noqa: BLE001 — eval freshness is best-effort
            pass

    # -- subclass hook ---------------------------------------------------------
    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def step(self) -> Dict[str, Any]:
        result = self.training_step()
        result.setdefault("timesteps_total", self._timesteps_total)
        result.update(self._episode_metrics())
        self._sync_connector_state()  # keep eval/checkpoints warm
        return result

    def _episode_metrics(self) -> Dict[str, Any]:
        if self.workers is not None:
            stats = self.workers.stats()
        else:
            stats = [self.local_worker.episode_stats()]
        rewards = [s["episode_reward_mean"] for s in stats
                   if s["episode_reward_mean"] is not None]
        lengths = [s["episode_len_mean"] for s in stats
                   if s["episode_len_mean"] is not None]
        return {
            "episode_reward_mean": float(np.mean(rewards)) if rewards
            else None,
            "episode_len_mean": float(np.mean(lengths)) if lengths else None,
            "episodes_total": sum(s["episodes"] for s in stats),
        }

    # -- weights ---------------------------------------------------------------
    def get_weights(self):
        return params_to_numpy(self.params)

    def set_weights(self, weights) -> None:
        self.params = params_from_numpy(weights)

    def compute_single_action(self, obs: np.ndarray) -> int:
        """Greedy action for inference/eval (Algorithm.compute_single_action
        in the reference). Observations pass through the connector
        pipeline WITHOUT updating its statistics — the policy trained on
        transformed observations and must see the same transform here."""
        from .models import ac_apply

        import jax.numpy as jnp

        if self._connector_specs:
            obs = self._infer_pipeline.observe(
                np.asarray(obs), update=False)
        logits, _ = ac_apply(self.params, jnp.asarray(obs)[None, :])
        return int(np.argmax(np.asarray(logits)[0]))

    # -- checkpointing ---------------------------------------------------------
    def save_checkpoint(self, checkpoint_dir: str) -> None:
        state = {
            "weights": self.get_weights(),
            "timesteps_total": self._timesteps_total,
            "extra": self._save_extra_state(),
        }
        if getattr(self, "_connector_specs", None):
            # connector statistics (e.g. running obs-norm) travel with
            # the weights: restored policies must see the SAME transform
            self._sync_connector_state()
            state["connectors"] = self._infer_pipeline.state()
        with open(os.path.join(checkpoint_dir, "algo_state.pkl"), "wb") as f:
            pickle.dump(state, f)

    def load_checkpoint(self, checkpoint_dir: str) -> None:
        with open(os.path.join(checkpoint_dir, "algo_state.pkl"), "rb") as f:
            state = pickle.load(f)
        self.set_weights(state["weights"])
        self._timesteps_total = state["timesteps_total"]
        self._load_extra_state(state.get("extra"))
        if state.get("connectors") is not None \
                and getattr(self, "_connector_specs", None):
            self._infer_pipeline.set_state(state["connectors"])
            if self.workers is not None:
                self.workers.set_connector_state(state["connectors"])
            # local mode: _infer_pipeline IS the worker's pipeline
        self._sync_weights()

    def _save_extra_state(self) -> Any:
        return None

    def _load_extra_state(self, state: Any) -> None:
        pass

    def _sync_weights(self) -> None:
        weights = self.get_weights()
        if self.workers is not None:
            self.workers.set_weights(weights)
        else:
            self.local_worker.set_weights(weights)

    def cleanup(self) -> None:
        if self.workers is not None:
            self.workers.stop()
