"""APPO: asynchronous PPO on the IMPALA architecture.

The reference's APPO (rllib/algorithms/appo/appo.py — IMPALA's async
sampling loop with PPO's clipped surrogate objective;
appo_tf_policy.py:120 the loss: importance ratio against the BEHAVIOR
policy that sampled the fragment, clipped PPO-style, with V-trace
advantages/targets correcting the off-policyness). Sampling never blocks
on the learner (IMPALA's overlap), but each gradient step is
trust-region-bounded like PPO — the middle ground between the two.

Implementation: everything is inherited from IMPALA (arming loop,
fragment consumption, bootstrap handling); only the compiled update
differs, swapping V-trace's plain policy-gradient term for the clipped
surrogate on the same V-trace advantages.
"""

from __future__ import annotations

from typing import Any, Dict

from .algorithm import AlgorithmConfig
from .impala import IMPALA, vtrace
from .models import ac_apply


def make_appo_update(optimizer, gamma: float, vf_coeff: float,
                     entropy_coeff: float, clip_param: float,
                     rho_clip: float = 1.0, c_clip: float = 1.0):
    import jax
    import jax.numpy as jnp
    import optax

    def loss_fn(params, obs, actions, behavior_logp, rewards, dones,
                bootstrap_value):
        logits, values = ac_apply(params, obs)
        logp_all = jax.nn.log_softmax(logits)
        target_logp = jnp.take_along_axis(
            logp_all, actions[:, None], axis=-1)[:, 0]
        vs, pg_adv = vtrace(target_logp, behavior_logp, rewards, values,
                            dones, bootstrap_value, gamma=gamma,
                            rho_clip=rho_clip, c_clip=c_clip)
        # PPO clipped surrogate with the ratio against the SAMPLING
        # policy (appo_tf_policy.py's is_ratio * clip scheme)
        ratio = jnp.exp(target_logp - behavior_logp)
        surr = jnp.minimum(
            ratio * pg_adv,
            jnp.clip(ratio, 1.0 - clip_param, 1.0 + clip_param) * pg_adv)
        pg_loss = -surr.mean()
        vf_loss = jnp.square(values - vs).mean()
        entropy = -jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1).mean()
        total = pg_loss + vf_coeff * vf_loss - entropy_coeff * entropy
        return total, {"policy_loss": pg_loss, "vf_loss": vf_loss,
                       "entropy": entropy,
                       "mean_is_ratio": ratio.mean()}

    @jax.jit
    def update(params, opt_state, obs, actions, behavior_logp, rewards,
               dones, bootstrap_value):
        (loss, stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, obs, actions, behavior_logp, rewards, dones,
            bootstrap_value)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        stats["total_loss"] = loss
        return params, opt_state, stats

    return update


class APPO(IMPALA):
    def setup(self, config: Dict[str, Any]) -> None:
        super().setup(config)
        # swap the plain V-trace policy gradient for the clipped
        # surrogate; everything else (arming, fragment loop) is IMPALA's
        self._update = make_appo_update(
            self.optimizer, config.get("gamma", 0.99),
            config.get("vf_loss_coeff", 0.5),
            config.get("entropy_coeff", 0.01),
            config.get("clip_param", 0.3))


class APPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(APPO)
        self.num_rollout_workers = 2
        self.extra.update({"vf_loss_coeff": 0.5, "entropy_coeff": 0.01,
                           "clip_param": 0.3})

    def training(self, *, clip_param=None, vf_loss_coeff=None,
                 entropy_coeff=None, **kwargs) -> "APPOConfig":
        super().training(**kwargs)
        for k, v in (("clip_param", clip_param),
                     ("vf_loss_coeff", vf_loss_coeff),
                     ("entropy_coeff", entropy_coeff)):
            if v is not None:
                self.extra[k] = v
        return self
