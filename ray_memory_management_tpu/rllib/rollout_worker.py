"""Rollout workers: CPU actors stepping envs with the current policy.

The reference's RolloutWorker (rllib/evaluation/rollout_worker.py:124) +
WorkerSet (worker_set.py:50): the algorithm broadcasts weights, workers
sample fixed-length fragments and return batches through the object
store. Workers force jax onto CPU — chips belong to the learner (the
reference's sampler/learner split).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from .. import api
from . import sample_batch as sb
from .env import make_env
from .models import ac_init, params_from_numpy, params_to_numpy, sample_actions


class RolloutWorker:
    def __init__(self, env_spec, env_config: Optional[dict],
                 hidden, seed: int, gamma: float = 0.99,
                 lam: float = 0.95, connectors=None):
        import jax

        from .. import _worker_context
        from .connectors import build_pipeline

        # Rollouts never touch the TPU — but only pin the process-global
        # default device when this IS a dedicated worker process; in
        # local mode (num_rollout_workers=0) the learner shares the
        # process and must keep its accelerator.
        if _worker_context.in_worker():
            jax.config.update("jax_default_device", jax.devices("cpu")[0])
        self.env = make_env(env_spec, env_config)
        self.gamma = gamma
        self.lam = lam
        # env -> policy transform pipeline (rllib/connectors/ analog);
        # the model is sized for the TRANSFORMED observation
        self.connectors = build_pipeline(connectors)
        self.obs_dim = self.connectors.obs_dim(self.env.observation_dim)
        self.rng = np.random.default_rng(seed)
        self._jax_key = jax.random.key(seed)
        self.params = ac_init(
            jax.random.key(0), self.obs_dim,
            self.env.num_actions, hidden)
        self._obs = self.connectors.on_reset(self.env.reset(seed=seed))
        self._episode_reward = 0.0
        self._episode_len = 0
        self.episode_rewards: List[float] = []
        self.episode_lengths: List[int] = []

    def ready(self) -> str:
        return "ok"

    def set_weights(self, weights) -> None:
        self.params = params_from_numpy(weights)

    def get_weights(self):
        return params_to_numpy(self.params)

    def sample(self, num_steps: int) -> Dict[str, np.ndarray]:
        """Collect one fragment of ``num_steps`` transitions (the
        rollout_fragment_length contract; sampler.py SyncSampler)."""
        import jax

        obs_buf = np.zeros((num_steps, self.obs_dim), dtype=np.float32)
        act_buf = np.zeros(num_steps, dtype=np.int32)
        rew_buf = np.zeros(num_steps, dtype=np.float32)
        done_buf = np.zeros(num_steps, dtype=np.float32)
        logp_buf = np.zeros(num_steps, dtype=np.float32)
        val_buf = np.zeros(num_steps, dtype=np.float32)

        for t in range(num_steps):
            self._jax_key, sub = jax.random.split(self._jax_key)
            action, logp, value = sample_actions(
                self.params, self._obs[None, :], sub)
            a = int(action[0])
            obs_buf[t] = self._obs
            act_buf[t] = a
            logp_buf[t] = float(logp[0])
            val_buf[t] = float(value[0])
            next_obs, reward, terminated, truncated, _ = self.env.step(a)
            next_obs = self.connectors.observe(next_obs)
            rew_buf[t] = self.connectors.reward(reward)
            done_buf[t] = float(terminated)
            self._episode_reward += reward  # metrics report RAW reward
            self._episode_len += 1
            if truncated and not terminated:
                # time-limit truncation is not a true terminal: fold the
                # bootstrap V(s_next) into the reward BEFORE the reset
                # replaces next_obs, then cut the trace (done=1) so GAE /
                # V-trace never discount across the episode boundary
                self._jax_key, sub = jax.random.split(self._jax_key)
                _, _, v_next = sample_actions(
                    self.params, next_obs[None, :], sub)
                rew_buf[t] += self.gamma * float(v_next[0])
                done_buf[t] = 1.0
            if terminated or truncated:
                self.episode_rewards.append(self._episode_reward)
                self.episode_lengths.append(self._episode_len)
                self._episode_reward = 0.0
                self._episode_len = 0
                next_obs = self.connectors.on_reset(self.env.reset(
                    seed=int(self.rng.integers(1 << 31))))
            self._obs = next_obs

        # bootstrap value for a fragment ending mid-episode
        self._jax_key, sub = jax.random.split(self._jax_key)
        _, _, last_val = sample_actions(self.params, self._obs[None, :], sub)
        bootstrap = float(last_val[0])
        adv, targets = sb.compute_gae(
            rew_buf, val_buf, done_buf, bootstrap,
            gamma=self.gamma, lam=self.lam)
        return {
            sb.OBS: obs_buf, sb.ACTIONS: act_buf, sb.REWARDS: rew_buf,
            sb.DONES: done_buf, sb.LOGP: logp_buf, sb.VALUES: val_buf,
            sb.ADVANTAGES: adv, sb.TARGETS: targets,
            sb.BOOTSTRAP: np.array([bootstrap], dtype=np.float32),
        }

    def get_connector_state(self):
        return self.connectors.state()

    def set_connector_state(self, state) -> None:
        self.connectors.set_state(state)

    def episode_stats(self, window: int = 100) -> Dict[str, Any]:
        return sb.episode_stats_summary(
            self.episode_rewards, self.episode_lengths, window)


class WorkerSet:
    """Remote rollout workers + broadcast/gather helpers
    (worker_set.py:50)."""

    def __init__(self, env_spec, env_config, hidden, num_workers: int,
                 seed: int, gamma: float = 0.99, lam: float = 0.95,
                 connectors=None, worker_cls=None, worker_kwargs=None):
        # worker_cls swaps the collector while keeping the broadcast/
        # stats plumbing (multi_agent.MultiAgentRolloutWorker and
        # recurrent.RecurrentRolloutWorker plug in here); worker_kwargs
        # carries collector-specific extras (e.g. lstm dims)
        cls = api.remote(worker_cls or RolloutWorker)
        self.remote_workers = [
            cls.options(num_cpus=1).remote(
                env_spec, env_config, hidden, seed + 1000 * (i + 1),
                gamma, lam, connectors, **(worker_kwargs or {}))
            for i in range(num_workers)
        ]
        api.get([w.ready.remote() for w in self.remote_workers])

    def set_weights(self, weights) -> None:
        # one put, many readers: the broadcast rides the object store
        ref = api.put(weights)
        api.get([w.set_weights.remote(ref) for w in self.remote_workers])

    def sample(self, num_steps: int) -> List:
        return [w.sample.remote(num_steps) for w in self.remote_workers]

    def set_connector_state(self, state) -> None:
        api.get([w.set_connector_state.remote(state)
                 for w in self.remote_workers])

    def stats(self) -> List[Dict[str, Any]]:
        return api.get(
            [w.episode_stats.remote() for w in self.remote_workers])

    def stop(self) -> None:
        for w in self.remote_workers:
            api.kill(w)
