"""IMPALA: asynchronous sampling with V-trace off-policy correction.

The reference's IMPALA (rllib/algorithms/impala/impala.py:350-388 wires
async sample requests into learner threads; V-trace from the paper).
Workers sample continuously; the learner consumes fragments as they
arrive (api.wait on in-flight refs), corrects the off-policyness with
V-trace, applies one SGD step per fragment, and immediately re-arms the
worker with fresh weights — sampling and learning overlap instead of the
PPO sync barrier.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List

import numpy as np

from .. import api
from . import sample_batch as sb
from .algorithm import Algorithm, AlgorithmConfig
from .models import ac_apply


def vtrace(target_logp, behavior_logp, rewards, values, dones,
           bootstrap_value, *, gamma: float, rho_clip: float = 1.0,
           c_clip: float = 1.0):
    """V-trace targets (Espeholt et al. 2018) via a reverse scan.
    Returns (vs, pg_adv), both stop-gradiented — shared by IMPALA's
    plain policy gradient and APPO's clipped surrogate (appo.py)."""
    import jax
    import jax.numpy as jnp

    rho = jnp.exp(target_logp - behavior_logp)
    clipped_rho = jnp.minimum(rho, rho_clip)
    cs = jnp.minimum(rho, c_clip)
    discounts = gamma * (1.0 - dones)
    next_values = jnp.concatenate(
        [values[1:], jnp.array([bootstrap_value])])
    deltas = clipped_rho * (rewards + discounts * next_values - values)

    def scan_fn(acc, xs):
        delta, discount, c = xs
        acc = delta + discount * c * acc
        return acc, acc

    _, vs_minus_v = jax.lax.scan(
        scan_fn, jnp.float32(0.0), (deltas, discounts, cs),
        reverse=True)
    vs = values + vs_minus_v
    next_vs = jnp.concatenate([vs[1:], jnp.array([bootstrap_value])])
    pg_adv = clipped_rho * (rewards + discounts * next_vs - values)
    return jax.lax.stop_gradient(vs), jax.lax.stop_gradient(pg_adv)


def make_impala_update(optimizer, gamma: float, vf_coeff: float,
                       entropy_coeff: float, rho_clip: float = 1.0,
                       c_clip: float = 1.0):
    import jax
    import jax.numpy as jnp
    import optax

    def loss_fn(params, obs, actions, behavior_logp, rewards, dones,
                bootstrap_value):
        logits, values = ac_apply(params, obs)
        logp_all = jax.nn.log_softmax(logits)
        target_logp = jnp.take_along_axis(
            logp_all, actions[:, None], axis=-1)[:, 0]
        vs, pg_adv = vtrace(target_logp, behavior_logp, rewards, values,
                            dones, bootstrap_value, gamma=gamma,
                            rho_clip=rho_clip, c_clip=c_clip)
        pg_loss = -(target_logp * pg_adv).mean()
        vf_loss = jnp.square(values - vs).mean()
        entropy = -jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1).mean()
        total = pg_loss + vf_coeff * vf_loss - entropy_coeff * entropy
        return total, {"policy_loss": pg_loss, "vf_loss": vf_loss,
                       "entropy": entropy}

    @jax.jit
    def update(params, opt_state, obs, actions, behavior_logp, rewards,
               dones, bootstrap_value):
        (loss, stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, obs, actions, behavior_logp, rewards, dones,
            bootstrap_value)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        stats["total_loss"] = loss
        return params, opt_state, stats

    return update


class IMPALA(Algorithm):
    def setup(self, config: Dict[str, Any]) -> None:
        import optax

        if config.get("num_rollout_workers", 0) < 1:
            config = dict(config)
            config["num_rollout_workers"] = 1  # async needs remote samplers
        super().setup(config)
        self.optimizer = optax.adam(config.get("lr", 5e-4))
        self.opt_state = self.optimizer.init(self.params)
        self._update = make_impala_update(
            self.optimizer, config.get("gamma", 0.99),
            config.get("vf_loss_coeff", 0.5),
            config.get("entropy_coeff", 0.01))
        self._inflight: Dict[Any, Any] = {}  # sample ref -> worker

    def _arm(self, worker) -> None:
        """Send fresh weights then request the next fragment."""
        worker.set_weights.remote(api.put(self.get_weights()))
        ref = worker.sample.remote(
            self.cfg.get("rollout_fragment_length", 200))
        self._inflight[ref] = worker

    def training_step(self) -> Dict[str, Any]:
        import jax.numpy as jnp

        t0 = time.time()
        target = self.cfg.get("train_batch_size", 4000)
        processed = 0
        stats: Dict[str, Any] = {}
        if not self._inflight:
            for w in self.workers.remote_workers:
                self._arm(w)
        while processed < target:
            ready, _ = api.wait(
                list(self._inflight), num_returns=1, timeout=60)
            if not ready:
                raise TimeoutError("no sample fragments arriving")
            ref = ready[0]
            worker = self._inflight.pop(ref)
            batch = api.get(ref)
            self._arm(worker)  # overlap: next fragment samples while we learn
            n = sb.batch_size(batch)
            processed += n
            self._timesteps_total += n
            # V(s_T) computed by the worker after the fragment's last step
            bootstrap = float(batch[sb.BOOTSTRAP][0])
            self.params, self.opt_state, stats = self._update(
                self.params, self.opt_state,
                jnp.asarray(batch[sb.OBS]),
                jnp.asarray(batch[sb.ACTIONS]),
                jnp.asarray(batch[sb.LOGP]),
                jnp.asarray(batch[sb.REWARDS]),
                jnp.asarray(batch[sb.DONES]),
                jnp.float32(bootstrap),
            )
        out = {k: float(v) for k, v in stats.items()}
        wall = time.time() - t0
        out.update({
            "num_env_steps_sampled": processed,
            "steps_per_s": processed / max(wall, 1e-9),
        })
        return out

    def cleanup(self) -> None:
        self._inflight.clear()
        super().cleanup()


class IMPALAConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(IMPALA)
        self.num_rollout_workers = 2
        self.extra.update({"vf_loss_coeff": 0.5, "entropy_coeff": 0.01})
