"""Sample batches + advantage estimation.

The reference's SampleBatch (rllib/policy/sample_batch.py) and GAE
postprocessing (rllib/evaluation/postprocessing.py compute_advantages).
Batches are plain dicts of contiguous numpy arrays — the shape the object
store moves zero-copy and jax consumes directly.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

OBS = "obs"
ACTIONS = "actions"
REWARDS = "rewards"
DONES = "dones"
LOGP = "logp"
VALUES = "values"
ADVANTAGES = "advantages"
TARGETS = "value_targets"
BOOTSTRAP = "bootstrap_value"  # V(s_T) after the fragment's last step


def concat_batches(batches: List[Dict[str, np.ndarray]]
                   ) -> Dict[str, np.ndarray]:
    if not batches:
        return {}
    return {k: np.concatenate([b[k] for b in batches])
            for k in batches[0]}


def batch_size(batch: Dict[str, np.ndarray]) -> int:
    return len(next(iter(batch.values()))) if batch else 0


def compute_gae(rewards: np.ndarray, values: np.ndarray, dones: np.ndarray,
                last_value: float, gamma: float = 0.99,
                lam: float = 0.95) -> tuple:
    """Generalized Advantage Estimation over one rollout fragment
    (postprocessing.py compute_advantages). ``dones`` marks terminal
    steps; bootstrap from ``last_value`` when the fragment ends
    mid-episode."""
    n = len(rewards)
    adv = np.zeros(n, dtype=np.float32)
    last_gae = 0.0
    next_value = last_value
    for t in range(n - 1, -1, -1):
        nonterminal = 1.0 - float(dones[t])
        delta = rewards[t] + gamma * next_value * nonterminal - values[t]
        last_gae = delta + gamma * lam * nonterminal * last_gae
        adv[t] = last_gae
        next_value = values[t]
    targets = adv + values
    return adv, targets


def minibatch_indices(n: int, minibatch_size: int,
                      rng: np.random.Generator):
    """Shuffled minibatch index iterator for SGD epochs."""
    perm = rng.permutation(n)
    for start in range(0, n - minibatch_size + 1, minibatch_size):
        yield perm[start:start + minibatch_size]


def episode_stats_summary(episode_rewards, episode_lengths,
                          window: int = 100):
    """Windowed episode metrics every collector reports (the reference's
    metrics.py summarize_episodes) — one implementation shared by the
    on-policy, off-policy, ES, and multi-agent collectors."""
    rewards = episode_rewards[-window:]
    lengths = episode_lengths[-window:]
    return {
        "episodes": len(episode_rewards),
        "episode_reward_mean": float(np.mean(rewards)) if rewards
        else None,
        "episode_len_mean": float(np.mean(lengths)) if lengths else None,
    }
