"""ES: OpenAI evolution strategies — gradient-free, embarrassingly parallel.

The reference's ES (rllib/algorithms/es/es.py — Salimans et al. 2017:
perturb a flat parameter vector with antithetic Gaussian noise, evaluate
each perturbation as a full episode on a worker, update with the
centered-rank-weighted sum of the noise; rllib/algorithms/es/optimizers.py
the SGD/Adam step on that pseudo-gradient; utils.py:14 the shared noise
table workers index into).

Redesigned for this runtime's strengths: there is NO noise table. The
reference ships a 250 MB shared noise block to every worker and exchanges
indices into it; here each perturbation is identified by its PRNG SEED —
workers regenerate eps = normal(key(seed)) locally, evaluate theta ± sigma
* eps, and return (seed, fitness+, fitness-) tuples. The broadcast is just
the base parameter vector, the collection is a few floats per rollout, and
the learner reconstructs every eps inside ONE jit'd vmap to apply the
rank-weighted update on the accelerator — communication drops from
O(noise table) to O(params + 3 floats per perturbation).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

from .. import api
from . import sample_batch as sb  # noqa: F401  (kept for API parity)
from .algorithm import Algorithm, AlgorithmConfig
from .env import make_env
from .models import mlp_apply, mlp_init
from .rollout_worker import WorkerSet


def flatten_params(params) -> np.ndarray:
    import jax

    leaves = jax.tree_util.tree_leaves(params)
    return np.concatenate([np.asarray(p).ravel() for p in leaves])


def unflatten_params(flat: np.ndarray, template):
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(template)
    out, pos = [], 0
    for leaf in leaves:
        n = int(np.prod(leaf.shape)) if leaf.shape else 1
        out.append(np.asarray(flat[pos:pos + n], np.float32).reshape(
            leaf.shape))
        pos += n
    return jax.tree_util.tree_unflatten(treedef, out)


def centered_ranks(fitness: np.ndarray) -> np.ndarray:
    """Map fitnesses to centered ranks in [-0.5, 0.5] (es.py's
    compute_centered_ranks) — scale-free, outlier-robust weighting."""
    ranks = np.empty(len(fitness), dtype=np.float32)
    ranks[fitness.argsort()] = np.arange(len(fitness), dtype=np.float32)
    return ranks / (len(fitness) - 1) - 0.5


def _perturbation(seed: int, dim: int) -> np.ndarray:
    """The noise for one perturbation, derived from its seed — identical
    on worker (rollout) and learner (update) by PRNG determinism."""
    import jax

    return np.asarray(jax.random.normal(
        jax.random.PRNGKey(seed), (dim,), dtype=np.float32))


class ESRolloutWorker:
    """Evaluates antithetic perturbation pairs: for each seed, one
    episode with theta + sigma*eps and one with theta - sigma*eps
    (es.py's do_rollouts with antithetic sampling)."""

    def __init__(self, env_spec, env_config: Optional[dict], hidden,
                 sigma: float, seed: int):
        import jax

        from .. import _worker_context

        if _worker_context.in_worker():
            jax.config.update("jax_default_device", jax.devices("cpu")[0])
        self.env = make_env(env_spec, env_config)
        from .multi_agent import MultiAgentEnv

        if isinstance(self.env, MultiAgentEnv):
            raise ValueError(
                "multi-agent envs train through the on-policy algorithms "
                "(PPO/PG/IMPALA/APPO); ES evaluates single-agent episodes")
        self.sigma = sigma
        self.discrete = hasattr(self.env, "num_actions")
        out_dim = (self.env.num_actions if self.discrete
                   else int(getattr(self.env, "action_dim", 1)))
        self.template = mlp_init(
            jax.random.key(0),
            [self.env.observation_dim, *hidden, out_dim])
        self.theta = flatten_params(self.template)
        self.rng = np.random.default_rng(seed)
        self.episode_rewards: List[float] = []
        self.episode_lengths: List[int] = []

    def ready(self) -> str:
        return "ok"

    def set_weights(self, theta: np.ndarray) -> None:
        self.theta = np.asarray(theta, np.float32)

    def _episode(self, flat: np.ndarray) -> float:
        import jax.numpy as jnp

        params = unflatten_params(flat, self.template)
        obs = self.env.reset(seed=int(self.rng.integers(1 << 31)))
        total, steps, done = 0.0, 0, False
        while not done:
            out = np.asarray(mlp_apply(params, jnp.asarray(obs[None, :])))[0]
            if self.discrete:
                a = int(out.argmax())
            else:
                bound = float(getattr(self.env, "action_bound", 1.0))
                a = bound * np.tanh(out)
            obs, r, term, trunc, _ = self.env.step(a)
            total += r
            steps += 1
            done = term or trunc
        self.episode_rewards.append(total)
        self.episode_lengths.append(steps)
        return total

    def evaluate(self, seeds: List[int]) -> Dict[str, np.ndarray]:
        """One antithetic pair of episodes per seed."""
        steps_before = sum(self.episode_lengths)
        pos = np.zeros(len(seeds), np.float32)
        neg = np.zeros(len(seeds), np.float32)
        for i, s in enumerate(seeds):
            eps = _perturbation(s, len(self.theta))
            pos[i] = self._episode(self.theta + self.sigma * eps)
            neg[i] = self._episode(self.theta - self.sigma * eps)
        return {"seeds": np.asarray(seeds, np.int64),
                "pos": pos, "neg": neg,
                "steps": sum(self.episode_lengths) - steps_before}

    def episode_stats(self, window: int = 100) -> Dict[str, Any]:
        return sb.episode_stats_summary(
            self.episode_rewards, self.episode_lengths, window)


class _ESWorkerSet(WorkerSet):
    def __init__(self, env_spec, env_config, hidden, sigma,
                 num_workers: int, seed: int):
        cls = api.remote(ESRolloutWorker)
        self.remote_workers = [
            cls.options(num_cpus=1).remote(
                env_spec, env_config, hidden, sigma,
                seed + 1000 * (i + 1))
            for i in range(num_workers)
        ]
        api.get([w.ready.remote() for w in self.remote_workers])


def make_es_update(lr: float, sigma: float, l2: float):
    """The rank-weighted pseudo-gradient step, reconstructing every
    perturbation from its seed inside one jit (vmapped PRNG)."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def update(theta, seeds, weights):
        def eps_for(seed):
            return jax.random.normal(
                jax.random.PRNGKey(seed), theta.shape, dtype=jnp.float32)

        eps = jax.vmap(eps_for)(seeds)          # [n, dim]
        grad = (weights @ eps) / (len(weights) * sigma)
        return theta + lr * (grad - l2 * theta)

    return update


class ES(Algorithm):
    def setup(self, config: Dict[str, Any]) -> None:
        import jax

        self.cfg = config
        if config.get("connectors"):
            raise ValueError(
                "connectors are not supported by ES's episode-return "
                "evaluation workers")
        seed = config.get("seed", 0)
        probe_env = make_env(config["env_spec"], config.get("env_config"))
        hidden = config.get("hidden", (32,))
        discrete = hasattr(probe_env, "num_actions")
        out_dim = (probe_env.num_actions if discrete
                   else int(getattr(probe_env, "action_dim", 1)))
        self.template = mlp_init(
            jax.random.key(seed),
            [probe_env.observation_dim, *hidden, out_dim])
        self.theta = flatten_params(self.template)
        self.sigma = config.get("sigma", 0.05)
        self.episodes_per_step = config.get("episodes_per_batch", 64)
        self._update = make_es_update(
            config.get("lr", 0.02), self.sigma,
            config.get("l2_coeff", 0.005))
        self._rng = np.random.default_rng(seed)
        self._discrete = discrete
        self._probe_env = probe_env
        self._timesteps_total = 0
        self._updates_done = 0

        n_workers = config.get("num_rollout_workers", 0)
        self.workers = None
        self.local_worker = None
        if n_workers > 0:
            self.workers = _ESWorkerSet(
                config["env_spec"], config.get("env_config"), hidden,
                self.sigma, n_workers, seed)
        else:
            self.local_worker = ESRolloutWorker(
                config["env_spec"], config.get("env_config"), hidden,
                self.sigma, seed)

    def training_step(self) -> Dict[str, Any]:
        import jax.numpy as jnp

        t0 = time.time()
        n_pairs = max(1, self.episodes_per_step // 2)
        seeds = [int(s) for s in
                 self._rng.integers(0, 1 << 31, size=n_pairs)]
        if self.workers is not None:
            ws = self.workers.remote_workers
            # one put, many readers, completion-synced (WorkerSet helper)
            self.workers.set_weights(self.theta)
            shards = np.array_split(np.asarray(seeds), len(ws))
            results = api.get([
                w.evaluate.remote([int(s) for s in shard])
                for w, shard in zip(ws, shards) if len(shard)])
        else:
            self.local_worker.set_weights(self.theta)
            results = [self.local_worker.evaluate(seeds)]
        all_seeds = np.concatenate([r["seeds"] for r in results])
        pos = np.concatenate([r["pos"] for r in results])
        neg = np.concatenate([r["neg"] for r in results])
        self._timesteps_total += int(sum(r["steps"] for r in results))

        # antithetic rank weighting: rank ALL 2n returns together, then
        # weight each eps by (rank+ - rank-) (es.py's batched_weighted_sum
        # over compute_centered_ranks of the full return set)
        ranks = centered_ranks(np.concatenate([pos, neg]))
        weights = ranks[: len(pos)] - ranks[len(pos):]
        self.theta = np.asarray(self._update(
            jnp.asarray(self.theta), jnp.asarray(all_seeds),
            jnp.asarray(weights, jnp.float32)))
        self._updates_done += 1

        out = {
            "episodes_this_iter": 2 * len(all_seeds),
            "fitness_mean": float(np.mean(np.concatenate([pos, neg]))),
            "fitness_max": float(max(pos.max(), neg.max())),
            "num_updates": self._updates_done,
            "theta_norm": float(np.linalg.norm(self.theta)),
            "time_this_iter_s": time.time() - t0,
        }
        return out

    def compute_single_action(self, obs: np.ndarray):
        import jax.numpy as jnp

        params = unflatten_params(self.theta, self.template)
        out = np.asarray(mlp_apply(params, jnp.asarray(obs[None, :])))[0]
        if self._discrete:
            return int(out.argmax())
        bound = float(getattr(self._probe_env, "action_bound", 1.0))
        return bound * np.tanh(out)

    def get_weights(self):
        return self.theta

    def set_weights(self, weights) -> None:
        self.theta = np.asarray(weights, np.float32)

    def _sync_weights(self) -> None:
        pass  # theta broadcasts inside training_step

    def _save_extra_state(self):
        return {"theta": self.theta, "updates_done": self._updates_done}

    def _load_extra_state(self, state) -> None:
        if not state:
            return
        if "theta" in state:
            self.theta = np.asarray(state["theta"], np.float32)
        self._updates_done = state.get("updates_done", 0)


class ESConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(ES)
        self.extra.update({"sigma": 0.05, "episodes_per_batch": 64,
                           "l2_coeff": 0.005})

    def training(self, *, sigma=None, episodes_per_batch=None,
                 l2_coeff=None, **kwargs) -> "ESConfig":
        super().training(**kwargs)
        for k, v in (("sigma", sigma),
                     ("episodes_per_batch", episodes_per_batch),
                     ("l2_coeff", l2_coeff)):
            if v is not None:
                self.extra[k] = v
        return self
