"""Job submission: run driver scripts as managed subprocesses.

The reference's job manager + SDK (dashboard/modules/job/job_manager.py,
python/ray/job_submission/): submit an entrypoint command, track status,
stream logs, stop. No REST head here — the client manages jobs directly,
with state durable in a filesystem job dir so a second client (or CLI)
can list/inspect the same jobs.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import tempfile
import time
import uuid
from typing import Any, Dict, List, Optional

PENDING = "PENDING"
RUNNING = "RUNNING"
SUCCEEDED = "SUCCEEDED"
FAILED = "FAILED"
STOPPED = "STOPPED"

_DEFAULT_DIR = os.path.join(tempfile.gettempdir(), "rmt_jobs")


class JobSubmissionClient:
    def __init__(self, job_dir: Optional[str] = None):
        self.job_dir = job_dir or os.environ.get(
            "RMT_JOB_DIR", _DEFAULT_DIR)
        os.makedirs(self.job_dir, exist_ok=True)
        self._procs: Dict[str, subprocess.Popen] = {}

    # -- paths ----------------------------------------------------------------
    def _meta_path(self, job_id: str) -> str:
        return os.path.join(self.job_dir, job_id, "meta.json")

    def _log_path(self, job_id: str) -> str:
        return os.path.join(self.job_dir, job_id, "driver.log")

    def _write_meta(self, job_id: str, meta: Dict[str, Any]) -> None:
        path = self._meta_path(job_id)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, path)

    def _read_meta(self, job_id: str) -> Dict[str, Any]:
        with open(self._meta_path(job_id)) as f:
            return json.load(f)

    # -- API ------------------------------------------------------------------
    def submit_job(self, *, entrypoint: str,
                   runtime_env: Optional[Dict[str, Any]] = None,
                   submission_id: Optional[str] = None,
                   metadata: Optional[Dict[str, str]] = None) -> str:
        """Launch the entrypoint as a detached subprocess; returns the
        job id (JobSubmissionClient.submit_job in the reference)."""
        if not entrypoint or not entrypoint.strip():
            raise ValueError("entrypoint must be a non-empty command")
        job_id = submission_id or f"raysubmit_{uuid.uuid4().hex[:12]}"
        job_root = os.path.join(self.job_dir, job_id)
        if os.path.exists(job_root):
            raise ValueError(f"job {job_id!r} already exists")
        os.makedirs(job_root)
        env = dict(os.environ)
        renv = runtime_env or {}
        env.update({str(k): str(v)
                    for k, v in (renv.get("env_vars") or {}).items()})
        cwd = renv.get("working_dir") or os.getcwd()
        log = open(self._log_path(job_id), "wb")
        proc = subprocess.Popen(
            entrypoint, shell=True, cwd=cwd, env=env,
            stdout=log, stderr=subprocess.STDOUT,
            start_new_session=True,  # survives this client; killable by pgid
        )
        log.close()
        self._procs[job_id] = proc
        self._write_meta(job_id, {
            "job_id": job_id,
            "entrypoint": entrypoint,
            "status": RUNNING,
            "pid": proc.pid,
            "start_time": time.time(),
            "end_time": None,
            "metadata": metadata or {},
        })
        return job_id

    @staticmethod
    def _proc_start_time(pid: int) -> Optional[float]:
        """The epoch start time of ``pid`` from /proc (Linux), None when
        unreadable. Field 22 of /proc/<pid>/stat is jiffies-since-boot;
        boot time comes from /proc/stat btime."""
        try:
            with open(f"/proc/{pid}/stat", "rb") as f:
                stat = f.read().decode("ascii", "replace")
            # the comm field may contain spaces/parens: split after the
            # LAST ')' so field indexing is immune to process names
            fields = stat[stat.rfind(")") + 2:].split()
            start_jiffies = int(fields[19])  # field 22 overall
            with open("/proc/stat", "rb") as f:
                for line in f:
                    if line.startswith(b"btime "):
                        btime = int(line.split()[1])
                        break
                else:
                    return None
            hz = os.sysconf("SC_CLK_TCK")
            return btime + start_jiffies / float(hz)
        except Exception:  # noqa: BLE001 — non-Linux / races
            return None

    def _pid_is_this_job(self, meta: Dict[str, Any]) -> bool:
        """Is the recorded pid still THIS job's driver? A SIGKILLed
        driver frees its pid, and the kernel may hand it to an unrelated
        process — kill(pid, 0) alone would then report the dead job
        RUNNING forever. Compare the live process's start time against
        the job's: a process born after the job was submitted is a pid
        reuse, not the driver."""
        pid = meta["pid"]
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            return False
        except PermissionError:
            pass  # someone holds the pid; fall through to the birth check
        started = self._proc_start_time(pid)
        if started is None:
            return True  # can't verify: keep the conservative answer
        # 2s slack: btime/jiffies rounding vs time.time() at submit
        return started <= meta["start_time"] + 2.0

    def _refresh(self, job_id: str) -> Dict[str, Any]:
        meta = self._read_meta(job_id)
        if meta["status"] != RUNNING:
            # reap a terminal job's subprocess handle: without this the
            # Popen (and its zombie, if unwaited) lives for the client's
            # lifetime, and a recycled pid could alias a foreign process
            proc = self._procs.pop(job_id, None)
            if proc is not None:
                try:
                    proc.wait(timeout=0)
                except Exception:  # noqa: BLE001
                    pass
            return meta
        proc = self._procs.get(job_id)
        if proc is not None:
            code = proc.poll()
            if code is None:
                return meta
            meta["status"] = SUCCEEDED if code == 0 else FAILED
            meta["returncode"] = code
            self._procs.pop(job_id, None)  # reaped by poll()
        else:
            # job started by another client (or a restarted one): no
            # Popen handle, so liveness comes from the pid — guarded
            # against pid reuse by the birth-time check
            if self._pid_is_this_job(meta):
                return meta
            # SIGKILLed / crashed without a clean exit path: the meta
            # said RUNNING but nothing backs it — fail the job
            meta["status"] = FAILED
            meta.setdefault("returncode", None)
        meta["end_time"] = time.time()
        self._write_meta(job_id, meta)
        return meta

    def get_job_status(self, job_id: str) -> str:
        return self._refresh(job_id)["status"]

    def get_job_info(self, job_id: str) -> Dict[str, Any]:
        return self._refresh(job_id)

    def get_job_logs(self, job_id: str) -> str:
        try:
            with open(self._log_path(job_id), "r", errors="replace") as f:
                return f.read()
        except FileNotFoundError:
            return ""

    def list_jobs(self) -> List[Dict[str, Any]]:
        jobs = []
        for job_id in sorted(os.listdir(self.job_dir)):
            if os.path.exists(self._meta_path(job_id)):
                jobs.append(self._refresh(job_id))
        return jobs

    def stop_job(self, job_id: str) -> bool:
        meta = self._refresh(job_id)
        if meta["status"] != RUNNING:
            return False
        try:
            os.killpg(os.getpgid(meta["pid"]), signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            pass
        deadline = time.time() + 5
        while time.time() < deadline:
            if self._refresh(job_id)["status"] != RUNNING:
                break
            time.sleep(0.1)
        meta = self._refresh(job_id)
        if meta["status"] == RUNNING:
            try:
                os.killpg(os.getpgid(meta["pid"]), signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
            meta["status"] = STOPPED
            meta["end_time"] = time.time()
            self._write_meta(job_id, meta)
        elif meta["status"] in (FAILED, SUCCEEDED):
            # terminated by our signal: record the stop intent
            meta["status"] = STOPPED
            self._write_meta(job_id, meta)
        return True

    def delete_job(self, job_id: str) -> None:
        import shutil

        if self.get_job_status(job_id) == RUNNING:
            raise ValueError("stop the job before deleting it")
        shutil.rmtree(os.path.join(self.job_dir, job_id),
                      ignore_errors=True)

    def tail_job_logs(self, job_id: str, timeout_s: float = 30.0):
        """Generator yielding log chunks until the job finishes."""
        path = self._log_path(job_id)
        pos = 0
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            status = self.get_job_status(job_id)
            try:
                with open(path, "r", errors="replace") as f:
                    f.seek(pos)
                    chunk = f.read()
                    pos = f.tell()
            except FileNotFoundError:
                chunk = ""
            if chunk:
                yield chunk
            if status != RUNNING:
                return
            time.sleep(0.2)
