// End-to-end exercise of the C++ frontend against a live cluster.
//
//   ./rmt_demo <host> <port> [authkey]
//
// Connects, round-trips an object through the store, invokes the
// cluster-registered "cpp_transform" function (bytes in -> bytes out),
// waits on the returned ref, fetches the result, and prints one
// machine-checkable line per step (the Python test asserts on these).

#include <cstdio>
#include <string>
#include <vector>

#include "rmt_client.hpp"

int main(int argc, char** argv) {
  if (argc < 3) {
    std::fprintf(stderr, "usage: %s <host> <port> [authkey]\n", argv[0]);
    return 2;
  }
  std::string host = argv[1];
  int port = std::stoi(argv[2]);
  std::string authkey = argc > 3 ? argv[3] : "rmt-client";

  try {
    rmt::Client client(host, port, authkey);
    std::printf("CONNECTED\n");

    auto resources = client.ClusterResources();
    std::printf("RESOURCES cpu=%.0f\n", resources.count("CPU")
                                            ? resources["CPU"]
                                            : -1.0);

    // object plane round trip
    std::string payload = "hello from c++ \x01\x02\xff";
    std::string oid = client.Put(payload);
    std::printf("PUT id_len=%zu\n", oid.size());
    auto values = client.Get({oid});
    std::printf("GET roundtrip=%s\n",
                values.size() == 1 && values[0] == payload ? "ok" : "MISMATCH");

    // duplicate-id fetch of a large payload: the server pickles the
    // repeated value as a memo BINGET, which the unpickler must resolve
    // (regression: the memo once skipped large bytes)
    std::string big(100 * 1024, 'x');
    std::string big_id = client.Put(big);
    auto twice = client.Get({big_id, big_id});
    std::printf("DUPGET %s\n",
                twice.size() == 2 && twice[0] == big && twice[1] == big
                    ? "ok"
                    : "MISMATCH");
    client.Free({big_id});

    // named-function call: cluster-side Python computes on our bytes
    auto names = client.ListFunctions();
    bool found = false;
    for (const auto& n : names) found = found || n == "cpp_transform";
    std::printf("NAMED registered=%s\n", found ? "yes" : "no");
    if (found) {
      auto rets = client.Call("cpp_transform", {"abc", "def"});
      std::printf("CALL returns=%zu\n", rets.size());
      auto split = client.Wait(rets, int(rets.size()), 60.0);
      std::printf("WAIT ready=%zu not_ready=%zu\n", split.first.size(),
                  split.second.size());
      auto results = client.Get(rets, 60.0);
      std::printf("RESULT %s\n", results[0].c_str());
      client.Free(rets);  // release the pinned returns
      std::printf("FREED\n");
    }
    client.Free({oid});
    std::printf("DEMO OK\n");
    return 0;
  } catch (const std::exception& e) {
    std::fprintf(stderr, "DEMO FAILED: %s\n", e.what());
    return 1;
  }
}
