// Demo C++ task executor (worker-side C++ API): registers three task
// functions and serves them to the cluster. Driven by
// tests/test_cpp_worker.py against a live ClusterServer; the reference's
// analog is a C++ worker executing RAY_REMOTE functions
// (cpp/src/ray/runtime/task/task_executor.cc).

#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <stdexcept>
#include <string>
#include <thread>
#include <vector>

#include "rmt_client.hpp"

int main(int argc, char** argv) {
  if (argc < 3) {
    std::fprintf(stderr, "usage: %s host port\n", argv[0]);
    return 2;
  }
  try {
    rmt::Executor ex(argv[1], std::atoi(argv[2]));
    ex.Register("add_i64", [](const std::vector<std::string>& args) {
      long long total = 0;
      for (const auto& a : args) total += std::strtoll(a.c_str(), nullptr, 10);
      return std::vector<std::string>{std::to_string(total)};
    });
    ex.Register("rev", [](const std::vector<std::string>& args) {
      std::string s = args.empty() ? std::string() : args[0];
      return std::vector<std::string>{std::string(s.rbegin(), s.rend())};
    });
    ex.Register("boom",
                [](const std::vector<std::string>&) -> std::vector<std::string> {
                  throw std::runtime_error("kaboom");
                });
    ex.Register("sleep_ms", [](const std::vector<std::string>& args) {
      long ms = args.empty() ? 0 : std::strtol(args[0].c_str(), nullptr, 10);
      std::this_thread::sleep_for(std::chrono::milliseconds(ms));
      return std::vector<std::string>{std::string("slept")};
    });
    ex.Start();
    std::printf("EXECUTOR READY\n");
    std::fflush(stdout);
    ex.ServeForever();
  } catch (const std::exception& e) {
    // connection loss at cluster shutdown is the normal exit
    std::fprintf(stderr, "executor exit: %s\n", e.what());
  }
  return 0;
}
