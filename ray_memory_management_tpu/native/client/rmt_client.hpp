// C++ frontend for the ray_memory_management_tpu cluster.
//
// Speaks the thin-client wire protocol (client/server.py — authenticated
// multiprocessing.connection frames carrying pickled request/reply dicts)
// directly from C++: length-prefixed frames, the mutual HMAC-SHA256
// challenge handshake, and a small pickle subset for the request/reply
// dictionaries. Values cross the boundary as raw bytes via the server's
// put_bytes/get_bytes/call_named verbs; compute stays registered
// cluster-side by name (register_named_function) — the same opaque-buffer
// boundary the reference draws between its language frontends (its
// msgpack XLANG format), re-drawn over this runtime's native protocol.
//
// Counterpart of the reference's C++ frontend (cpp/src/ray/api.cc): the
// subset here is the driver surface (connect / put / get / call / wait),
// not a C++ worker runtime — tasks execute in the cluster's Python
// workers, which is where the TPU compute path lives anyway.
//
// No dependencies beyond POSIX sockets and the C++17 standard library;
// SHA-256/HMAC are implemented in rmt_client.cpp.

#pragma once

#include <cstdint>
#include <functional>
#include <map>
#include <memory>
#include <stdexcept>
#include <string>
#include <vector>

namespace rmt {

// One decoded Python value from a reply dict (the subset the server
// actually sends: None/bool/int/float/str/bytes and lists/tuples/dicts
// of those).
struct PyVal {
  enum class Kind { None, Bool, Int, Float, Str, Bytes, List, Dict };
  Kind kind = Kind::None;
  bool b = false;
  int64_t i = 0;
  double f = 0.0;
  std::string s;                 // Str and small Bytes payloads
  // large Bytes payloads live behind a shared pointer so copying a
  // PyVal (pickle memo entries, duplicate-id fetches) never duplicates
  // a multi-GB buffer
  std::shared_ptr<const std::string> big;
  std::vector<PyVal> list;       // List (and tuples, decoded as lists)
  std::map<std::string, PyVal> dict;

  bool is_none() const { return kind == Kind::None; }
  const std::string& bytes() const {
    if (kind != Kind::Bytes && kind != Kind::Str)
      throw std::runtime_error("PyVal: not bytes");
    return big ? *big : s;
  }
};

class ClientError : public std::runtime_error {
 public:
  explicit ClientError(const std::string& what) : std::runtime_error(what) {}
};

// Synchronous client: one connection, one in-flight request at a time
// (the server replies per-request; pipelining is unnecessary for a
// driver frontend).
class Client {
 public:
  // host:port of a ClusterServer (serve() side prints/returns it).
  Client(const std::string& host, int port,
         const std::string& authkey = "rmt-client");
  ~Client();
  Client(const Client&) = delete;
  Client& operator=(const Client&) = delete;

  // Raw-bytes object plane.
  std::string Put(const std::string& data);                  // -> object id
  std::vector<std::string> Get(const std::vector<std::string>& ids,
                               double timeout_s = -1.0);     // -> values
  // Invoke a cluster-side function registered via
  // register_named_function(name, fn); args arrive as bytes.
  std::vector<std::string> Call(const std::string& name,
                                const std::vector<std::string>& args,
                                int num_cpus = -1);          // -> return ids
  // wait(): ids split into (ready, not_ready) after num_returns are done
  // or timeout_s elapses (negative = wait forever).
  std::pair<std::vector<std::string>, std::vector<std::string>> Wait(
      const std::vector<std::string>& ids, int num_returns,
      double timeout_s = -1.0);
  // Release results/puts this connection pinned (the server otherwise
  // holds them until disconnect); call after fetching what you need.
  void Free(const std::vector<std::string>& ids);
  std::vector<std::string> ListFunctions();
  std::map<std::string, double> ClusterResources();
  void Close();

  // Generic verb escape hatch: send one request dict (a "type" entry
  // names the verb), return the reply. Raises ClientError when the reply
  // carries a server-side error. The Executor below is built on this.
  PyVal Rpc(std::map<std::string, PyVal> msg);

 private:
  PyVal Request(std::map<std::string, PyVal> msg);
  void SendFrame(const std::string& payload);
  std::string RecvFrame(size_t max = (1u << 31) - 1);
  void Handshake(const std::string& authkey);

  int fd_ = -1;
  int64_t req_counter_ = 0;
};

// Worker-side C++ API: implement task functions IN C++ and serve them to
// the cluster. The executor registers its function names over the client
// protocol (client/server.py register_cpp_executor), long-polls for
// dispatched tasks, runs them, and returns result bytes; Python callers
// use api.cpp_function(name).remote(...) and ordinary ObjectRefs.
// Counterpart of the reference's C++ worker executing RAY_REMOTE
// functions (cpp/include/ray/api.h ray::Task(fn).Remote()) — re-drawn
// over this runtime's authenticated wire protocol with the same
// opaque-bytes cross-language boundary as the thin client.
class Executor {
 public:
  // A task function: raw bytes args in, one result (or num_returns
  // results) out. Throwing std::exception fails the task cluster-side
  // with the exception text.
  using Fn = std::function<std::vector<std::string>(
      const std::vector<std::string>&)>;

  Executor(const std::string& host, int port,
           const std::string& authkey = "rmt-client");

  // Register before Start(); name is what Python callers use.
  void Register(const std::string& name, Fn fn);
  // Announce the registered functions to the cluster. Called implicitly
  // by the first ServeOne/ServeForever.
  void Start();
  // One long-poll round: waits up to poll_timeout_s for a task, runs it,
  // replies. Returns true if a task was served.
  bool ServeOne(double poll_timeout_s = 5.0);
  // Serve until the connection drops (ClientError propagates).
  void ServeForever();

 private:
  Client client_;
  std::map<std::string, Fn> fns_;
  std::string ex_id_;
  bool started_ = false;
};

// Helpers for building request values (exposed for tests).
PyVal PvNone();
PyVal PvBool(bool v);
PyVal PvInt(int64_t v);
PyVal PvFloat(double v);
PyVal PvStr(const std::string& v);
PyVal PvBytes(std::string v);
PyVal PvList(std::vector<PyVal> v);

// Pickle subset codec (exposed for tests).
std::string PickleDict(const std::map<std::string, PyVal>& d);
PyVal Unpickle(const std::string& data);

}  // namespace rmt
