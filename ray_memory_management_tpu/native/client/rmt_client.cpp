// Implementation of the C++ thin-client frontend. See rmt_client.hpp.
//
// Wire stack, bottom to top:
//   1. TCP socket (blocking, TCP_NODELAY)
//   2. multiprocessing.connection frames: 4-byte big-endian signed length;
//      a -1 sentinel promotes to an 8-byte big-endian unsigned length
//   3. mutual HMAC challenge auth (CPython's deliver/answer_challenge:
//      b"#CHALLENGE#{sha256}<32 random bytes>" -> b"{sha256}<mac>" ->
//      b"#WELCOME#", then the same with roles swapped)
//   4. pickled request/reply dicts (a protocol-3 subset on the way out —
//      CPython unpickles any protocol; a protocol-5 subset reader on the
//      way in, which is what the server's pickler emits)

#include "rmt_client.hpp"

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstring>
#include <fstream>
#include <random>

namespace rmt {

// ---------------------------------------------------------------- sha256
// Compact SHA-256 (FIPS 180-4), sufficient for the HMAC handshake.
namespace sha256 {

struct Ctx {
  uint32_t h[8];
  uint64_t len = 0;
  uint8_t buf[64];
  size_t buflen = 0;
};

static const uint32_t K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

static inline uint32_t rotr(uint32_t x, int n) {
  return (x >> n) | (x << (32 - n));
}

static void Init(Ctx* c) {
  static const uint32_t h0[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372,
                                 0xa54ff53a, 0x510e527f, 0x9b05688c,
                                 0x1f83d9ab, 0x5be0cd19};
  std::memcpy(c->h, h0, sizeof(h0));
  c->len = 0;
  c->buflen = 0;
}

static void Block(Ctx* c, const uint8_t* p) {
  uint32_t w[64];
  for (int i = 0; i < 16; i++)
    w[i] = (uint32_t(p[4 * i]) << 24) | (uint32_t(p[4 * i + 1]) << 16) |
           (uint32_t(p[4 * i + 2]) << 8) | uint32_t(p[4 * i + 3]);
  for (int i = 16; i < 64; i++) {
    uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
    uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
    w[i] = w[i - 16] + s0 + w[i - 7] + s1;
  }
  uint32_t a = c->h[0], b = c->h[1], cc = c->h[2], d = c->h[3], e = c->h[4],
           f = c->h[5], g = c->h[6], h = c->h[7];
  for (int i = 0; i < 64; i++) {
    uint32_t S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
    uint32_t ch = (e & f) ^ (~e & g);
    uint32_t t1 = h + S1 + ch + K[i] + w[i];
    uint32_t S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
    uint32_t maj = (a & b) ^ (a & cc) ^ (b & cc);
    uint32_t t2 = S0 + maj;
    h = g; g = f; f = e; e = d + t1;
    d = cc; cc = b; b = a; a = t1 + t2;
  }
  c->h[0] += a; c->h[1] += b; c->h[2] += cc; c->h[3] += d;
  c->h[4] += e; c->h[5] += f; c->h[6] += g; c->h[7] += h;
}

static void Update(Ctx* c, const void* data, size_t n) {
  const uint8_t* p = static_cast<const uint8_t*>(data);
  c->len += n;
  while (n) {
    size_t take = std::min(n, sizeof(c->buf) - c->buflen);
    std::memcpy(c->buf + c->buflen, p, take);
    c->buflen += take;
    p += take;
    n -= take;
    if (c->buflen == 64) {
      Block(c, c->buf);
      c->buflen = 0;
    }
  }
}

static void Final(Ctx* c, uint8_t out[32]) {
  uint64_t bitlen = c->len * 8;
  uint8_t pad = 0x80;
  Update(c, &pad, 1);
  uint8_t zero = 0;
  while (c->buflen != 56) Update(c, &zero, 1);
  uint8_t lenb[8];
  for (int i = 0; i < 8; i++) lenb[i] = uint8_t(bitlen >> (56 - 8 * i));
  Update(c, lenb, 8);  // bitlen was captured before padding
  for (int i = 0; i < 8; i++) {
    out[4 * i] = uint8_t(c->h[i] >> 24);
    out[4 * i + 1] = uint8_t(c->h[i] >> 16);
    out[4 * i + 2] = uint8_t(c->h[i] >> 8);
    out[4 * i + 3] = uint8_t(c->h[i]);
  }
}

static std::string Digest(const std::string& data) {
  Ctx c;
  Init(&c);
  Update(&c, data.data(), data.size());
  uint8_t out[32];
  Final(&c, out);
  return std::string(reinterpret_cast<char*>(out), 32);
}

}  // namespace sha256

static std::string HmacSha256(const std::string& key,
                              const std::string& message) {
  std::string k = key;
  if (k.size() > 64) k = sha256::Digest(k);
  k.resize(64, '\0');
  std::string ipad(64, '\x36'), opad(64, '\x5c');
  for (int i = 0; i < 64; i++) {
    ipad[i] ^= k[i];
    opad[i] ^= k[i];
  }
  return sha256::Digest(opad + sha256::Digest(ipad + message));
}

// ---------------------------------------------------------------- PyVal
PyVal PvNone() { return PyVal{}; }
PyVal PvBool(bool v) {
  PyVal p; p.kind = PyVal::Kind::Bool; p.b = v; return p;
}
PyVal PvInt(int64_t v) {
  PyVal p; p.kind = PyVal::Kind::Int; p.i = v; return p;
}
PyVal PvFloat(double v) {
  PyVal p; p.kind = PyVal::Kind::Float; p.f = v; return p;
}
PyVal PvStr(const std::string& v) {
  PyVal p; p.kind = PyVal::Kind::Str; p.s = v; return p;
}
PyVal PvBytes(std::string v) {
  // by value + move: the unpickler hands in a temporary, so a large
  // payload is materialized exactly once (no transient double-buffer)
  PyVal p;
  p.kind = PyVal::Kind::Bytes;
  if (v.size() > 4096) {
    p.big = std::make_shared<const std::string>(std::move(v));
  } else {
    p.s = std::move(v);
  }
  return p;
}
PyVal PvList(std::vector<PyVal> v) {
  PyVal p; p.kind = PyVal::Kind::List; p.list = std::move(v); return p;
}

// ---------------------------------------------------------------- pickler
namespace {

void PutLE32(std::string* out, uint32_t v) {
  out->push_back(char(v & 0xff));
  out->push_back(char((v >> 8) & 0xff));
  out->push_back(char((v >> 16) & 0xff));
  out->push_back(char((v >> 24) & 0xff));
}

void PickleValue(std::string* out, const PyVal& v) {
  switch (v.kind) {
    case PyVal::Kind::None:
      out->push_back('N');
      break;
    case PyVal::Kind::Bool:
      out->push_back(v.b ? '\x88' : '\x89');
      break;
    case PyVal::Kind::Int:
      if (v.i >= INT32_MIN && v.i <= INT32_MAX) {
        out->push_back('J');  // BININT, 4-byte LE signed
        PutLE32(out, uint32_t(int32_t(v.i)));
      } else {
        out->push_back('\x8a');  // LONG1 <nbytes> <LE signed>
        out->push_back(8);
        uint64_t u = uint64_t(v.i);
        for (int i = 0; i < 8; i++) out->push_back(char((u >> (8 * i)) & 0xff));
      }
      break;
    case PyVal::Kind::Float: {
      out->push_back('G');  // BINFLOAT, 8-byte BE double
      uint64_t bits;
      std::memcpy(&bits, &v.f, 8);
      for (int i = 7; i >= 0; i--) out->push_back(char((bits >> (8 * i)) & 0xff));
      break;
    }
    case PyVal::Kind::Str:
      out->push_back('X');  // BINUNICODE <LE32 len> <utf8>
      PutLE32(out, uint32_t(v.s.size()));
      out->append(v.s);
      break;
    case PyVal::Kind::Bytes: {
      const std::string& payload = v.bytes();
      if (payload.size() > UINT32_MAX)
        throw ClientError(
            "bytes payload exceeds the 4 GiB BINBYTES limit");
      out->push_back('B');  // BINBYTES (protocol 3) <LE32 len> <raw>
      PutLE32(out, uint32_t(payload.size()));
      out->append(payload);
      break;
    }
    case PyVal::Kind::List:
      out->push_back(']');  // EMPTY_LIST
      if (!v.list.empty()) {
        out->push_back('(');  // MARK
        for (const auto& item : v.list) PickleValue(out, item);
        out->push_back('e');  // APPENDS
      }
      break;
    case PyVal::Kind::Dict: {
      out->push_back('}');  // EMPTY_DICT
      if (!v.dict.empty()) {
        out->push_back('(');
        for (const auto& kv : v.dict) {
          PickleValue(out, PvStr(kv.first));
          PickleValue(out, kv.second);
        }
        out->push_back('u');  // SETITEMS
      }
      break;
    }
  }
}

}  // namespace

std::string PickleDict(const std::map<std::string, PyVal>& d) {
  std::string out;
  out.push_back('\x80');  // PROTO
  out.push_back(3);
  PyVal v;
  v.kind = PyVal::Kind::Dict;
  v.dict = d;
  PickleValue(&out, v);
  out.push_back('.');  // STOP
  return out;
}

// -------------------------------------------------------------- unpickler
namespace {

class Reader {
 public:
  explicit Reader(const std::string& d) : d_(d) {}
  uint8_t u8() {
    Need(1);
    return uint8_t(d_[pos_++]);
  }
  uint32_t le32() {
    Need(4);
    uint32_t v = 0;
    for (int i = 0; i < 4; i++) v |= uint32_t(uint8_t(d_[pos_ + i])) << (8 * i);
    pos_ += 4;
    return v;
  }
  uint64_t le64() {
    Need(8);
    uint64_t v = 0;
    for (int i = 0; i < 8; i++) v |= uint64_t(uint8_t(d_[pos_ + i])) << (8 * i);
    pos_ += 8;
    return v;
  }
  uint64_t be64() {
    Need(8);
    uint64_t v = 0;
    for (int i = 0; i < 8; i++) v = (v << 8) | uint8_t(d_[pos_ + i]);
    pos_ += 8;
    return v;
  }
  std::string bytes(size_t n) {
    Need(n);
    std::string s = d_.substr(pos_, n);
    pos_ += n;
    return s;
  }

 private:
  void Need(size_t n) {
    // overflow-safe: pos_ + n can wrap for a hostile BINBYTES8 length,
    // which would pass the naive check and desync the parse
    if (pos_ > d_.size() || n > d_.size() - pos_)
      throw ClientError("pickle: truncated stream");
  }
  const std::string& d_;
  size_t pos_ = 0;
};

constexpr int kMark = -1;  // sentinel index on the mark stack

}  // namespace

PyVal Unpickle(const std::string& data) {
  Reader r(data);
  std::vector<PyVal> stack;
  std::vector<size_t> marks;
  // memo entries are COPIES, but large bytes payloads sit behind a
  // shared_ptr inside PyVal, so protocol-5's MEMOIZE-every-bytes habit
  // costs pointer copies, not buffer copies — and duplicate-id fetches
  // (BINGET of a repeated payload) resolve correctly
  std::vector<PyVal> memo;

  auto memoPut = [&](size_t idx, const PyVal& v) {
    if (memo.size() <= idx) memo.resize(idx + 1);
    memo[idx] = v;
  };
  auto memoGet = [&](size_t idx) -> const PyVal& {
    if (idx >= memo.size()) throw ClientError("pickle: BINGET range");
    return memo[idx];
  };

  auto pop = [&]() {
    if (stack.empty()) throw ClientError("pickle: stack underflow");
    PyVal v = std::move(stack.back());
    stack.pop_back();
    return v;
  };
  auto popToMark = [&]() {
    if (marks.empty()) throw ClientError("pickle: no mark");
    size_t m = marks.back();
    marks.pop_back();
    std::vector<PyVal> items(stack.begin() + m, stack.end());
    stack.resize(m);
    return items;
  };

  for (;;) {
    uint8_t op = r.u8();
    switch (op) {
      case 0x80:  // PROTO
        r.u8();
        break;
      case 0x95:  // FRAME (8-byte length; framing only)
        r.le64();
        break;
      case '.':  // STOP
        if (stack.size() != 1) throw ClientError("pickle: bad final stack");
        return stack[0];
      case 'N':
        stack.push_back(PvNone());
        break;
      case 0x88:
        stack.push_back(PvBool(true));
        break;
      case 0x89:
        stack.push_back(PvBool(false));
        break;
      case 'K':  // BININT1
        stack.push_back(PvInt(r.u8()));
        break;
      case 'M': {  // BININT2 (LE; sequence the reads — '|' operand
                   // evaluation order is unspecified in C++17)
        uint32_t lo = r.u8();
        uint32_t hi = r.u8();
        stack.push_back(PvInt(lo | (hi << 8)));
        break;
      }
      case 'J':  // BININT (signed LE32)
        stack.push_back(PvInt(int32_t(r.le32())));
        break;
      case 0x8a: {  // LONG1 (LE two's complement)
        uint8_t n = r.u8();
        if (n > 8) throw ClientError("pickle: LONG1 too wide");
        std::string raw = r.bytes(n);
        uint64_t u = 0;  // unsigned accumulation: signed << is UB-prone
        for (int i = int(n) - 1; i >= 0; i--)
          u = (u << 8) | uint8_t(raw[size_t(i)]);
        if (n && n < 8 && (uint8_t(raw[n - 1]) & 0x80))
          u -= uint64_t(1) << (8 * n);  // sign-extend; n==8 is already
                                        // the full two's complement
        stack.push_back(PvInt(int64_t(u)));
        break;
      }
      case 'G': {  // BINFLOAT (BE double)
        uint64_t bits = r.be64();
        double f;
        std::memcpy(&f, &bits, 8);
        stack.push_back(PvFloat(f));
        break;
      }
      case 0x8c:  // SHORT_BINUNICODE
        stack.push_back(PvStr(r.bytes(r.u8())));
        break;
      case 'X':  // BINUNICODE
        stack.push_back(PvStr(r.bytes(r.le32())));
        break;
      case 'C':  // SHORT_BINBYTES
        stack.push_back(PvBytes(r.bytes(r.u8())));
        break;
      case 'B':  // BINBYTES
        stack.push_back(PvBytes(r.bytes(r.le32())));
        break;
      case 0x8e:  // BINBYTES8
        stack.push_back(PvBytes(r.bytes(size_t(r.le64()))));
        break;
      case '}': {  // EMPTY_DICT
        PyVal v;
        v.kind = PyVal::Kind::Dict;
        stack.push_back(std::move(v));
        break;
      }
      case ']': {  // EMPTY_LIST
        PyVal v;
        v.kind = PyVal::Kind::List;
        stack.push_back(std::move(v));
        break;
      }
      case ')': {  // EMPTY_TUPLE (tuples decode as lists)
        PyVal v;
        v.kind = PyVal::Kind::List;
        stack.push_back(std::move(v));
        break;
      }
      case '(':  // MARK
        marks.push_back(stack.size());
        break;
      case 'a': {  // APPEND
        PyVal item = pop();
        if (stack.empty() || stack.back().kind != PyVal::Kind::List)
          throw ClientError("pickle: APPEND to non-list");
        stack.back().list.push_back(std::move(item));
        break;
      }
      case 'e': {  // APPENDS
        auto items = popToMark();
        if (stack.empty() || stack.back().kind != PyVal::Kind::List)
          throw ClientError("pickle: APPENDS to non-list");
        for (auto& it : items) stack.back().list.push_back(std::move(it));
        break;
      }
      case 's': {  // SETITEM
        PyVal v = pop();
        PyVal k = pop();
        if (stack.empty() || stack.back().kind != PyVal::Kind::Dict)
          throw ClientError("pickle: SETITEM to non-dict");
        if (k.kind != PyVal::Kind::Str)
          throw ClientError("pickle: non-str dict key");
        stack.back().dict[k.s] = std::move(v);
        break;
      }
      case 'u': {  // SETITEMS
        auto items = popToMark();
        if (items.size() % 2)
          throw ClientError("pickle: odd SETITEMS count");
        if (stack.empty() || stack.back().kind != PyVal::Kind::Dict)
          throw ClientError("pickle: SETITEMS to non-dict");
        for (size_t i = 0; i < items.size(); i += 2) {
          if (items[i].kind != PyVal::Kind::Str)
            throw ClientError("pickle: non-str dict key");
          stack.back().dict[items[i].s] = std::move(items[i + 1]);
        }
        break;
      }
      case 0x85: {  // TUPLE1
        PyVal a = pop();
        stack.push_back(PvList({std::move(a)}));
        break;
      }
      case 0x86: {  // TUPLE2
        PyVal b = pop(), a = pop();
        stack.push_back(PvList({std::move(a), std::move(b)}));
        break;
      }
      case 0x87: {  // TUPLE3
        PyVal c = pop(), b = pop(), a = pop();
        stack.push_back(PvList({std::move(a), std::move(b), std::move(c)}));
        break;
      }
      case 't': {  // TUPLE
        auto items = popToMark();
        stack.push_back(PvList(std::move(items)));
        break;
      }
      case 0x94:  // MEMOIZE
        if (stack.empty()) throw ClientError("pickle: MEMOIZE empty");
        memoPut(memo.size(), stack.back());
        break;
      case 'q':  // BINPUT
        if (stack.empty()) throw ClientError("pickle: BINPUT empty");
        memoPut(r.u8(), stack.back());
        break;
      case 'r':  // LONG_BINPUT
        if (stack.empty()) throw ClientError("pickle: LONG_BINPUT empty");
        memoPut(r.le32(), stack.back());
        break;
      case 'h':  // BINGET
        stack.push_back(memoGet(r.u8()));
        break;
      case 'j':  // LONG_BINGET
        stack.push_back(memoGet(r.le32()));
        break;
      default:
        throw ClientError("pickle: unsupported opcode " +
                          std::to_string(int(op)) +
                          " (reply outside the supported subset)");
    }
  }
}

// ---------------------------------------------------------------- client
static void WriteAll(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n) {
    ssize_t w = ::write(fd, p, n);
    if (w <= 0) throw ClientError("socket write failed");
    p += w;
    n -= size_t(w);
  }
}

static void ReadAll(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n) {
    ssize_t r = ::read(fd, p, n);
    if (r <= 0) throw ClientError("socket read failed (connection lost?)");
    p += r;
    n -= size_t(r);
  }
}

void Client::SendFrame(const std::string& payload) {
  if (payload.size() > 0x7fffffff)
    throw ClientError("frame too large");  // requests never approach this
  uint8_t hdr[4];
  uint32_t n = uint32_t(payload.size());
  for (int i = 0; i < 4; i++) hdr[i] = uint8_t(n >> (24 - 8 * i));
  WriteAll(fd_, hdr, 4);
  WriteAll(fd_, payload.data(), payload.size());
}

std::string Client::RecvFrame(size_t max) {
  uint8_t hdr[4];
  ReadAll(fd_, hdr, 4);
  int32_t n32 = int32_t((uint32_t(hdr[0]) << 24) | (uint32_t(hdr[1]) << 16) |
                        (uint32_t(hdr[2]) << 8) | uint32_t(hdr[3]));
  uint64_t n;
  if (n32 == -1) {  // extended 8-byte length
    uint8_t ext[8];
    ReadAll(fd_, ext, 8);
    n = 0;
    for (int i = 0; i < 8; i++) n = (n << 8) | ext[i];
  } else if (n32 < 0) {
    throw ClientError("bad frame length");
  } else {
    n = uint64_t(n32);
  }
  if (n > max) throw ClientError("frame exceeds limit");
  std::string out(size_t(n), '\0');
  ReadAll(fd_, out.data(), size_t(n));
  return out;
}

void Client::Handshake(const std::string& authkey) {
  static const std::string kChallenge = "#CHALLENGE#";
  static const std::string kWelcome = "#WELCOME#";

  // 1. answer the server's challenge
  std::string msg = RecvFrame(256);
  if (msg.rfind(kChallenge, 0) != 0)
    throw ClientError("auth: expected challenge");
  msg = msg.substr(kChallenge.size());
  // modern messages are b"{digest}<payload>"; the MAC covers the WHOLE
  // message including the prefix
  if (msg.rfind("{sha256}", 0) != 0 && msg[0] == '{')
    throw ClientError("auth: server requested an unsupported digest");
  std::string mac = HmacSha256(authkey, msg);
  SendFrame("{sha256}" + mac);
  if (RecvFrame(256) != kWelcome) throw ClientError("auth: digest rejected");

  // 2. deliver our own challenge (mutual auth)
  std::random_device rd;
  std::string payload = "{sha256}";
  for (int i = 0; i < 32; i++) payload.push_back(char(rd() & 0xff));
  SendFrame(kChallenge + payload);
  std::string response = RecvFrame(256);
  if (response.rfind("{sha256}", 0) == 0)
    response = response.substr(std::string("{sha256}").size());
  if (response != HmacSha256(authkey, payload)) {
    SendFrame("#FAILURE#");
    throw ClientError("auth: server failed our challenge");
  }
  SendFrame(kWelcome);
}

Client::Client(const std::string& host, int port, const std::string& authkey) {
  struct addrinfo hints {};
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  struct addrinfo* res = nullptr;
  if (getaddrinfo(host.c_str(), std::to_string(port).c_str(), &hints, &res) ||
      !res)
    throw ClientError("cannot resolve " + host);
  fd_ = ::socket(res->ai_family, res->ai_socktype, res->ai_protocol);
  if (fd_ < 0 || ::connect(fd_, res->ai_addr, res->ai_addrlen) != 0) {
    freeaddrinfo(res);
    if (fd_ >= 0) ::close(fd_);
    fd_ = -1;
    throw ClientError("cannot connect to " + host + ":" +
                      std::to_string(port));
  }
  freeaddrinfo(res);
  int one = 1;
  setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  try {
    Handshake(authkey);
    // version-checked ping (the server raises on wire-protocol mismatch)
    std::map<std::string, PyVal> ping;
    ping["type"] = PvStr("ping");
    ping["proto"] = PvInt(1);  // config.WIRE_PROTOCOL_VERSION
    Request(std::move(ping));
  } catch (...) {
    // the destructor never runs for a partially constructed object:
    // close here or every failed connect leaks an fd
    ::close(fd_);
    fd_ = -1;
    throw;
  }
}

Client::~Client() { Close(); }

void Client::Close() {
  if (fd_ >= 0) {
    ::close(fd_);
    fd_ = -1;
  }
}

static std::string ScrapePrintable(const std::string& blob) {
  // the error field is a serialized Python exception; surface the
  // readable runs (type name, message) without a full unpickler
  std::string out;
  std::string run;
  for (char c : blob) {
    if (c >= 0x20 && c < 0x7f) {
      run.push_back(c);
    } else {
      if (run.size() >= 5) {
        if (!out.empty()) out += " | ";
        out += run;
      }
      run.clear();
    }
  }
  if (run.size() >= 5) {
    if (!out.empty()) out += " | ";
    out += run;
  }
  return out.empty() ? "<opaque server exception>" : out;
}

PyVal Client::Request(std::map<std::string, PyVal> msg) {
  if (fd_ < 0) throw ClientError("client is closed");
  int64_t req_id = ++req_counter_;
  msg["req_id"] = PvInt(req_id);
  SendFrame(PickleDict(msg));
  PyVal reply = Unpickle(RecvFrame());
  if (reply.kind != PyVal::Kind::Dict)
    throw ClientError("reply is not a dict");
  auto it = reply.dict.find("req_id");
  if (it == reply.dict.end() || it->second.i != req_id)
    throw ClientError("reply req_id mismatch");
  auto err = reply.dict.find("error");
  if (err != reply.dict.end() && !err->second.is_none()) {
    // bytes() dereferences the out-of-line 'big' storage that payloads
    // over 4 KiB land in; .s would be empty for those and report every
    // large serialized exception as opaque
    const PyVal& ev = err->second;
    const std::string& blob =
        (ev.kind == PyVal::Kind::Bytes || ev.kind == PyVal::Kind::Str)
            ? ev.bytes()
            : ev.s;
    throw ClientError("server error: " + ScrapePrintable(blob));
  }
  return reply;
}

std::string Client::Put(const std::string& data) {
  std::map<std::string, PyVal> msg;
  msg["type"] = PvStr("put_bytes");
  msg["data"] = PvBytes(data);
  return Request(std::move(msg)).dict.at("object_id").bytes();
}

std::vector<std::string> Client::Get(const std::vector<std::string>& ids,
                                     double timeout_s) {
  std::map<std::string, PyVal> msg;
  msg["type"] = PvStr("get_bytes");
  std::vector<PyVal> oids;
  for (const auto& id : ids) oids.push_back(PvBytes(id));
  msg["oids"] = PvList(std::move(oids));
  msg["timeout"] = timeout_s < 0 ? PvNone() : PvFloat(timeout_s);
  PyVal reply = Request(std::move(msg));
  std::vector<std::string> out;
  for (const auto& v : reply.dict.at("values").list) out.push_back(v.bytes());
  return out;
}

std::vector<std::string> Client::Call(const std::string& name,
                                      const std::vector<std::string>& args,
                                      int num_cpus) {
  std::map<std::string, PyVal> msg;
  msg["type"] = PvStr("call_named");
  msg["name"] = PvStr(name);
  std::vector<PyVal> a;
  for (const auto& arg : args) a.push_back(PvBytes(arg));
  msg["args"] = PvList(std::move(a));
  if (num_cpus >= 0) {
    PyVal opts;
    opts.kind = PyVal::Kind::Dict;
    opts.dict["num_cpus"] = PvInt(num_cpus);
    msg["opts"] = std::move(opts);
  }
  PyVal reply = Request(std::move(msg));
  std::vector<std::string> out;
  for (const auto& v : reply.dict.at("return_ids").list)
    out.push_back(v.bytes());
  return out;
}

std::pair<std::vector<std::string>, std::vector<std::string>> Client::Wait(
    const std::vector<std::string>& ids, int num_returns, double timeout_s) {
  std::map<std::string, PyVal> msg;
  msg["type"] = PvStr("wait");
  std::vector<PyVal> oids;
  for (const auto& id : ids) oids.push_back(PvBytes(id));
  msg["oids"] = PvList(std::move(oids));
  msg["num_returns"] = PvInt(num_returns);
  msg["timeout"] = timeout_s < 0 ? PvNone() : PvFloat(timeout_s);
  PyVal reply = Request(std::move(msg));
  std::pair<std::vector<std::string>, std::vector<std::string>> out;
  for (const auto& v : reply.dict.at("ready").list)
    out.first.push_back(v.bytes());
  for (const auto& v : reply.dict.at("not_ready").list)
    out.second.push_back(v.bytes());
  return out;
}

void Client::Free(const std::vector<std::string>& ids) {
  std::map<std::string, PyVal> msg;
  msg["type"] = PvStr("free_refs");
  std::vector<PyVal> oids;
  for (const auto& id : ids) oids.push_back(PvBytes(id));
  msg["oids"] = PvList(std::move(oids));
  Request(std::move(msg));
}

std::vector<std::string> Client::ListFunctions() {
  std::map<std::string, PyVal> msg;
  msg["type"] = PvStr("list_named");
  PyVal reply = Request(std::move(msg));
  std::vector<std::string> out;
  for (const auto& v : reply.dict.at("names").list) out.push_back(v.s);
  return out;
}

std::map<std::string, double> Client::ClusterResources() {
  std::map<std::string, PyVal> msg;
  msg["type"] = PvStr("cluster_resources");
  PyVal reply = Request(std::move(msg));
  std::map<std::string, double> out;
  for (const auto& kv : reply.dict.at("resources").dict)
    out[kv.first] = kv.second.kind == PyVal::Kind::Int
                        ? double(kv.second.i)
                        : kv.second.f;
  return out;
}

PyVal Client::Rpc(std::map<std::string, PyVal> msg) {
  return Request(std::move(msg));
}

// ------------------------------------------------------------- Executor

Executor::Executor(const std::string& host, int port,
                   const std::string& authkey)
    : client_(host, port, authkey) {}

void Executor::Register(const std::string& name, Fn fn) {
  fns_[name] = std::move(fn);
}

void Executor::Start() {
  if (started_) return;
  std::vector<PyVal> names;
  for (const auto& kv : fns_) names.push_back(PvStr(kv.first));
  std::map<std::string, PyVal> msg;
  msg["type"] = PvStr("register_cpp_executor");
  msg["functions"] = PvList(std::move(names));
  PyVal reply = client_.Rpc(std::move(msg));
  ex_id_ = reply.dict.at("executor_id").bytes();
  started_ = true;
}

bool Executor::ServeOne(double poll_timeout_s) {
  if (!started_) Start();
  std::map<std::string, PyVal> poll;
  poll["type"] = PvStr("next_cpp_task");
  poll["executor_id"] = PvBytes(ex_id_);
  poll["timeout"] = PvFloat(poll_timeout_s);
  PyVal reply = client_.Rpc(std::move(poll));
  const PyVal& task = reply.dict.at("task");
  if (task.is_none()) return false;

  const std::string& name = task.dict.at("name").s;
  std::vector<std::string> args;
  for (const auto& a : task.dict.at("args").list) args.push_back(a.bytes());

  std::map<std::string, PyVal> done;
  done["type"] = PvStr("cpp_task_done");
  done["executor_id"] = PvBytes(ex_id_);
  done["task_id"] = PvStr(task.dict.at("task_id").s);
  auto it = fns_.find(name);
  if (it == fns_.end()) {
    done["err"] = PvStr("executor has no function '" + name + "'");
  } else {
    try {
      std::vector<std::string> results = it->second(args);
      std::vector<PyVal> out;
      out.reserve(results.size());
      for (auto& r : results) out.push_back(PvBytes(std::move(r)));
      done["results"] = PvList(std::move(out));
    } catch (const std::exception& e) {
      done["err"] = PvStr(std::string("C++ exception: ") + e.what());
    }
  }
  client_.Rpc(std::move(done));
  return true;
}

void Executor::ServeForever() {
  for (;;) ServeOne(5.0);  // connection loss -> ClientError unwinds out
}

}  // namespace rmt
