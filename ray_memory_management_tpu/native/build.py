"""Lazy build of the native shared-memory store library.

The reference builds its C++ runtime with bazel (WORKSPACE, BUILD.bazel); here
the native pieces are small enough that a direct g++ invocation, cached next to
the source and keyed on the source mtime, keeps the install story to "import
the package". A Makefile with the same flags lives alongside for manual builds.
"""

from __future__ import annotations

import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_LOCK = threading.Lock()

_LIBS = {
    "shmstore": ["shmstore.cpp"],
}


def lib_path(name: str = "shmstore") -> str:
    """Return the path to the built .so, compiling it if stale or missing."""
    sources = [os.path.join(_HERE, s) for s in _LIBS[name]]
    out = os.path.join(_HERE, f"lib{name}.so")
    with _LOCK:
        if os.path.exists(out) and all(
            os.path.getmtime(out) >= os.path.getmtime(src) for src in sources
        ):
            return out
        tmp = out + f".tmp.{os.getpid()}"
        cmd = [
            "g++", "-O2", "-g", "-fPIC", "-shared", "-std=c++17",
            "-Wall", "-Werror",
            *sources, "-o", tmp, "-lpthread", "-lrt",
        ]
        # _LOCK exists to serialize the compile itself; concurrent
        # callers waiting for the finished .so is the intended behavior
        # rmtcheck: disable=blocking-under-lock
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, out)  # atomic wrt concurrent builders
    return out
