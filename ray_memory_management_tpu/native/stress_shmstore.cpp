// Multi-threaded stress test for the shared-memory object store.
//
// The reference's race-detection story runs its C++ suite under
// TSAN/ASAN bazel configs (.bazelrc:92-106). This binary is the analog
// for the native store: N threads hammer create/write/seal/get/release/
// contains/delete on a shared store, verifying payload integrity and
// lifecycle rules (get-before-seal fails, delete-while-referenced
// fails). Build and run plain (`make check`) or under `make tsan` /
// `make asan`; any data race, lock bug, or heap corruption trips the
// sanitizer or the integrity checks and exits non-zero.

#include <fcntl.h>
#include <sys/mman.h>
#include <unistd.h>

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {
int64_t store_create(const char* name, uint64_t capacity);
int64_t store_open(const char* name);
void store_close(int64_t h);
int store_unlink(const char* name);
uint64_t store_capacity(int64_t h);
int64_t obj_create(int64_t h, const uint8_t* id, uint64_t size);
int obj_seal(int64_t h, const uint8_t* id);
int obj_get(int64_t h, const uint8_t* id, uint64_t* off, uint64_t* size,
            int inc_ref);
int obj_release(int64_t h, const uint8_t* id);
int obj_delete(int64_t h, const uint8_t* id);
int obj_contains(int64_t h, const uint8_t* id);
void store_usage(int64_t h, uint64_t* used, uint64_t* capacity,
                 uint64_t* num_objects);
}

namespace {

constexpr int kThreads = 8;
constexpr int kItersPerThread = 4000;
constexpr int kIdSpace = 64;
std::atomic<int> failures{0};

void fail(const char* what, int rc) {
  fprintf(stderr, "FAIL: %s rc=%d\n", what, rc);
  failures.fetch_add(1);
}

void make_id(uint8_t* id, int slot, int tid) {
  memset(id, 0, 16);
  id[0] = (uint8_t)slot;
  id[1] = (uint8_t)tid;
  id[15] = 0x5a;
}

void worker(int64_t h, uint8_t* base, int tid) {
  uint8_t id[16];
  uint64_t goff, gsize;
  for (int i = 0; i < kItersPerThread; i++) {
    int slot = (int)((i * 2654435761u + (unsigned)tid) % kIdSpace);
    make_id(id, slot, tid);  // ids are (slot, tid): each thread owns its
                             // ids, but allocator/table/lock are shared
    uint64_t size = 64 + (uint64_t)(slot * 97) % 4096;
    int64_t off = obj_create(h, id, size);
    if (off == -2) continue;          // table slot contention: skip
    if (off <= 0) {                   // exists from an earlier round
      obj_delete(h, id);
      continue;
    }
    // lifecycle rule: get before seal must fail
    if (obj_get(h, id, &goff, &gsize, 0) == 0) fail("get-unsealed", 0);
    uint8_t* payload = base + off;
    memset(payload, (uint8_t)slot, size);
    int rc = obj_seal(h, id);
    if (rc != 0) {
      fail("seal", rc);
      continue;
    }
    rc = obj_get(h, id, &goff, &gsize, 1);
    if (rc != 0) {
      fail("get", rc);
      continue;
    }
    if (goff != (uint64_t)off || gsize != size) fail("geom", 0);
    uint8_t* view = base + goff;
    if (view[0] != (uint8_t)slot || view[gsize - 1] != (uint8_t)slot) {
      fail("integrity", 0);
    }
    // lifecycle rule: delete while referenced must fail
    if (obj_delete(h, id) != -2) fail("delete-while-ref", 0);
    if (obj_release(h, id) != 0) fail("release", 0);
    if (i % 3 == 0) {
      if (obj_contains(h, id) != 1) fail("contains", 0);
      if (obj_delete(h, id) != 0) fail("delete", 0);
    }
  }
}

}  // namespace

int main() {
  const char* name = "/rmt_stress_store";
  store_unlink(name);
  int64_t h = store_create(name, 256ull << 20);
  if (h < 0) {
    fprintf(stderr, "store_create failed\n");
    return 2;
  }
  // clients address payloads by offset from their own mapping of the
  // store file (what the Python StoreClient does via mmap)
  int fd = shm_open(name, O_RDWR, 0600);
  uint64_t cap = store_capacity(h);
  uint8_t* base = (uint8_t*)mmap(nullptr, cap, PROT_READ | PROT_WRITE,
                                 MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) {
    fprintf(stderr, "mmap failed\n");
    return 2;
  }
  std::vector<std::thread> threads;
  for (int t = 0; t < kThreads; t++) {
    threads.emplace_back(worker, h, base, t);
  }
  for (auto& th : threads) th.join();
  uint64_t used, capacity, num;
  store_usage(h, &used, &capacity, &num);
  fprintf(stderr, "done: used=%llu cap=%llu objects=%llu failures=%d\n",
          (unsigned long long)used, (unsigned long long)capacity,
          (unsigned long long)num, failures.load());
  munmap(base, cap);
  store_close(h);
  store_unlink(name);
  if (failures.load() != 0) return 1;
  printf("STRESS OK\n");
  return 0;
}
