from .shm_client import (  # noqa: F401
    ShmStore,
    ShmStoreFullError,
    reap_stale_stores,
)
