from .shm_client import ShmStore, ShmStoreFullError  # noqa: F401
