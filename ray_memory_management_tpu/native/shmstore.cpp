// Shared-memory object store: the TPU-framework equivalent of the reference's
// plasma store (reference: src/ray/object_manager/plasma/{store.h,plasma_allocator.h,
// object_lifecycle_manager.h,eviction_policy.h}).
//
// Design differences from plasma, chosen for the one-process-per-TPU-host model:
//  - No store server process or Unix-socket protocol (plasma runs a thread in
//    the raylet and speaks flatbuffers over a socket, store.h / protocol.h).
//    Instead, ALL state lives inside one shm mapping — an object table and a
//    boundary-tag heap — guarded by a process-shared robust pthread mutex, so
//    any process on the host can create/seal/get objects directly at memory
//    speed. Crashed clients cannot wedge the lock (robust mutex + consistent).
//  - dlmalloc-over-mmap (dlmalloc.cc) is replaced by a first-fit boundary-tag
//    allocator with coalescing; payloads are 64-byte aligned for zero-copy
//    numpy/XLA host-buffer views.
//  - Eviction: callers ask for LRU candidates (eviction_policy.h:105 analog)
//    and spill them via IO threads before deleting (local_object_manager.h:99).
//
// Object lifecycle mirrors plasma: CREATED -> SEALED -> (refcounted) -> DELETED
// (object_lifecycle_manager.h:101). Get on an unsealed object fails; delete
// only succeeds at refcount zero.

#include <cerrno>
#include <cstdint>
#include <utility>
#include <cstdio>
#include <cstring>

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x524d545354524531ull;  // "RMTSTRE1"
constexpr uint32_t kNumEntries = 1 << 16;           // open-addressed table
constexpr uint64_t kAlign = 64;
constexpr uint64_t kBlockHeader = 64;  // keeps payloads 64B-aligned

enum ObjState : uint32_t {
  kEmpty = 0,
  kCreated = 1,
  kSealed = 2,
  kTombstone = 3,
};

struct Entry {
  uint8_t id[16];
  uint64_t offset;  // payload offset from mapping base
  uint64_t size;    // payload size
  uint32_t state;
  int32_t refcount;
  uint64_t lru;  // last-touch tick (eviction_policy.h LRU analog)
};

struct Block {
  uint64_t size;       // total block size including header, multiple of 64
  uint64_t prev_size;  // size of the previous block (0 for first)
  uint32_t free;
  uint32_t pad_[11];   // pad header to 64 bytes
};
static_assert(sizeof(Block) == kBlockHeader, "block header must be 64B");

struct Header {
  uint64_t magic;
  uint64_t capacity;     // total file size
  uint64_t heap_offset;  // first block offset
  uint64_t heap_size;
  uint64_t used;         // payload bytes in live (created|sealed) objects
  uint64_t num_objects;
  uint64_t lru_clock;
  pthread_mutex_t mutex;
};

struct Store {
  uint8_t* base = nullptr;
  uint64_t capacity = 0;
  int fd = -1;
  bool valid = false;
};

constexpr int kMaxStores = 64;
Store g_stores[kMaxStores];

Header* header(Store& s) { return reinterpret_cast<Header*>(s.base); }
Entry* table(Store& s) {
  return reinterpret_cast<Entry*>(s.base + sizeof(Header));
}
Block* block_at(Store& s, uint64_t off) {
  return reinterpret_cast<Block*>(s.base + off);
}

uint64_t table_bytes() { return sizeof(Entry) * (uint64_t)kNumEntries; }

uint64_t hash_id(const uint8_t* id) {
  uint64_t h = 1469598103934665603ull;  // FNV-1a over the 16-byte id
  for (int i = 0; i < 16; i++) {
    h ^= id[i];
    h *= 1099511628211ull;
  }
  return h;
}

// Lock with robust-mutex recovery: if the owner died mid-critical-section we
// mark the state consistent and continue (object table stays valid because all
// mutations below are ordered to be crash-tolerant at entry granularity).
int lock(Store& s) {
  int rc = pthread_mutex_lock(&header(s)->mutex);
  if (rc == EOWNERDEAD) {
    pthread_mutex_consistent(&header(s)->mutex);
    return 0;
  }
  return rc;
}
void unlock(Store& s) { pthread_mutex_unlock(&header(s)->mutex); }

// Find entry for id; returns nullptr if absent. Caller holds the lock.
Entry* find(Store& s, const uint8_t* id) {
  Entry* t = table(s);
  uint64_t h = hash_id(id) & (kNumEntries - 1);
  for (uint32_t probe = 0; probe < kNumEntries; probe++) {
    Entry& e = t[(h + probe) & (kNumEntries - 1)];
    if (e.state == kEmpty) return nullptr;
    if (e.state != kTombstone && memcmp(e.id, id, 16) == 0) return &e;
  }
  return nullptr;
}

Entry* find_slot(Store& s, const uint8_t* id) {
  Entry* t = table(s);
  uint64_t h = hash_id(id) & (kNumEntries - 1);
  Entry* first_tomb = nullptr;
  for (uint32_t probe = 0; probe < kNumEntries; probe++) {
    Entry& e = t[(h + probe) & (kNumEntries - 1)];
    if (e.state == kEmpty) return first_tomb ? first_tomb : &e;
    if (e.state == kTombstone) {
      if (!first_tomb) first_tomb = &e;
      continue;
    }
    if (memcmp(e.id, id, 16) == 0) return nullptr;  // already present
  }
  return first_tomb;
}

uint64_t round_up(uint64_t v, uint64_t a) { return (v + a - 1) & ~(a - 1); }

// First-fit allocation over the boundary-tag heap. Returns payload offset or 0.
uint64_t heap_alloc(Store& s, uint64_t payload_size) {
  Header* h = header(s);
  uint64_t need = round_up(payload_size, kAlign) + kBlockHeader;
  uint64_t off = h->heap_offset;
  uint64_t end = h->heap_offset + h->heap_size;
  while (off < end) {
    Block* b = block_at(s, off);
    if (b->free && b->size >= need) {
      uint64_t remainder = b->size - need;
      if (remainder >= kBlockHeader + kAlign) {
        // split: carve the tail into a new free block
        b->size = need;
        Block* tail = block_at(s, off + need);
        tail->size = remainder;
        tail->prev_size = need;
        tail->free = 1;
        uint64_t after = off + need + remainder;
        if (after < end) block_at(s, after)->prev_size = remainder;
      }
      b->free = 0;
      return off + kBlockHeader;
    }
    off += b->size;
  }
  return 0;
}

void heap_free(Store& s, uint64_t payload_off) {
  Header* h = header(s);
  uint64_t off = payload_off - kBlockHeader;
  Block* b = block_at(s, off);
  b->free = 1;
  uint64_t end = h->heap_offset + h->heap_size;
  // coalesce with next
  uint64_t next_off = off + b->size;
  if (next_off < end) {
    Block* n = block_at(s, next_off);
    if (n->free) {
      b->size += n->size;
      uint64_t after = off + b->size;
      if (after < end) block_at(s, after)->prev_size = b->size;
    }
  }
  // coalesce with prev
  if (b->prev_size != 0) {
    Block* p = block_at(s, off - b->prev_size);
    if (p->free) {
      p->size += b->size;
      uint64_t after = off - b->prev_size + p->size;
      if (after < end) block_at(s, after)->prev_size = p->size;
    }
  }
}

int64_t register_store(Store&& st) {
  for (int i = 0; i < kMaxStores; i++) {
    if (!g_stores[i].valid) {
      g_stores[i] = st;
      g_stores[i].valid = true;
      return i;
    }
  }
  return -1;
}

}  // namespace

extern "C" {

// Create (or truncate) a store named `name` with total file size `capacity`.
int64_t store_create(const char* name, uint64_t capacity) {
  int fd = shm_open(name, O_CREAT | O_RDWR | O_TRUNC, 0600);
  if (fd < 0) return -1;
  uint64_t meta = sizeof(Header) + table_bytes();
  if (capacity < meta + (1 << 20)) capacity = meta + (1 << 20);
  capacity = round_up(capacity, 4096);
  if (ftruncate(fd, (off_t)capacity) != 0) {
    close(fd);
    return -1;
  }
  void* base =
      mmap(nullptr, capacity, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    return -1;
  }
  Store st;
  st.base = (uint8_t*)base;
  st.capacity = capacity;
  st.fd = fd;
  Header* h = header(st);
  memset(h, 0, sizeof(Header));
  memset(table(st), 0, table_bytes());
  h->capacity = capacity;
  h->heap_offset = round_up(sizeof(Header) + table_bytes(), kAlign);
  h->heap_size = capacity - h->heap_offset;
  Block* first = block_at(st, h->heap_offset);
  first->size = h->heap_size & ~(kAlign - 1);
  first->prev_size = 0;
  first->free = 1;
  pthread_mutexattr_t attr;
  pthread_mutexattr_init(&attr);
  pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&h->mutex, &attr);
  __sync_synchronize();
  h->magic = kMagic;
  // NOTE on prefaulting: deliberately NOT done here. Populating the
  // arena (MADV_POPULATE_WRITE) kills first-touch fault costs on bulk
  // writes, but makes the FILE fully resident — and 2,000 spawned
  // workers mapping a fully-resident multi-GB shm file measured 3x
  // slower to boot than against a sparse one. The Python client
  // (shm_client.ShmStore) owns that tradeoff with a size/memory gate.
  return register_store(std::move(st));
}

int64_t store_open(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return -1;
  struct stat sb;
  if (fstat(fd, &sb) != 0) {
    close(fd);
    return -1;
  }
  void* base = mmap(nullptr, (uint64_t)sb.st_size, PROT_READ | PROT_WRITE,
                    MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    return -1;
  }
  Store st;
  st.base = (uint8_t*)base;
  st.capacity = (uint64_t)sb.st_size;
  st.fd = fd;
  if (header(st)->magic != kMagic) {
    munmap(base, st.capacity);
    close(fd);
    return -2;
  }
  return register_store(std::move(st));
}

void store_close(int64_t h) {
  if (h < 0 || h >= kMaxStores || !g_stores[h].valid) return;
  munmap(g_stores[h].base, g_stores[h].capacity);
  close(g_stores[h].fd);
  g_stores[h].valid = false;
}

int store_unlink(const char* name) { return shm_unlink(name); }

// Allocate an object; returns payload offset, or 0 if the heap is full,
// -2 if the id already exists, -1 on bad handle.
int64_t obj_create(int64_t h, const uint8_t* id, uint64_t size) {
  if (h < 0 || h >= kMaxStores || !g_stores[h].valid) return -1;
  Store& s = g_stores[h];
  if (lock(s) != 0) return -1;
  Entry* slot = find_slot(s, id);
  if (slot == nullptr) {
    unlock(s);
    return -2;
  }
  uint64_t off = heap_alloc(s, size);
  if (off == 0) {
    unlock(s);
    return 0;
  }
  memcpy(slot->id, id, 16);
  slot->offset = off;
  slot->size = size;
  slot->state = kCreated;
  slot->refcount = 0;
  slot->lru = ++header(s)->lru_clock;
  header(s)->used += size;
  header(s)->num_objects += 1;
  unlock(s);
  return (int64_t)off;
}

int obj_seal(int64_t h, const uint8_t* id) {
  if (h < 0 || h >= kMaxStores || !g_stores[h].valid) return -1;
  Store& s = g_stores[h];
  if (lock(s) != 0) return -1;
  Entry* e = find(s, id);
  int rc = 0;
  if (!e) {
    rc = -1;
  } else if (e->state == kSealed) {
    rc = -3;  // double seal
  } else {
    e->state = kSealed;
    e->lru = ++header(s)->lru_clock;
  }
  unlock(s);
  return rc;
}

// Look up a sealed object; bumps refcount when inc_ref != 0.
int obj_get(int64_t h, const uint8_t* id, uint64_t* off, uint64_t* size,
            int inc_ref) {
  if (h < 0 || h >= kMaxStores || !g_stores[h].valid) return -1;
  Store& s = g_stores[h];
  if (lock(s) != 0) return -1;
  Entry* e = find(s, id);
  int rc;
  if (!e) {
    rc = -1;
  } else if (e->state != kSealed) {
    rc = -2;
  } else {
    *off = e->offset;
    *size = e->size;
    if (inc_ref) e->refcount++;
    e->lru = ++header(s)->lru_clock;
    rc = 0;
  }
  unlock(s);
  return rc;
}

int obj_release(int64_t h, const uint8_t* id) {
  if (h < 0 || h >= kMaxStores || !g_stores[h].valid) return -1;
  Store& s = g_stores[h];
  if (lock(s) != 0) return -1;
  Entry* e = find(s, id);
  int rc = 0;
  if (!e || e->refcount <= 0) {
    rc = -1;
  } else {
    e->refcount--;
  }
  unlock(s);
  return rc;
}

// Delete (or abort an unsealed create). Fails with -2 while mapped by readers.
int obj_delete(int64_t h, const uint8_t* id) {
  if (h < 0 || h >= kMaxStores || !g_stores[h].valid) return -1;
  Store& s = g_stores[h];
  if (lock(s) != 0) return -1;
  Entry* e = find(s, id);
  int rc = 0;
  if (!e) {
    rc = -1;
  } else if (e->refcount > 0) {
    rc = -2;
  } else {
    heap_free(s, e->offset);
    header(s)->used -= e->size;
    header(s)->num_objects -= 1;
    e->state = kTombstone;
  }
  unlock(s);
  return rc;
}

int obj_contains(int64_t h, const uint8_t* id) {
  if (h < 0 || h >= kMaxStores || !g_stores[h].valid) return -1;
  Store& s = g_stores[h];
  if (lock(s) != 0) return -1;
  Entry* e = find(s, id);
  int rc = (e && e->state == kSealed) ? 1 : 0;
  unlock(s);
  return rc;
}

void store_usage(int64_t h, uint64_t* used, uint64_t* capacity,
                 uint64_t* nobjs) {
  if (h < 0 || h >= kMaxStores || !g_stores[h].valid) return;
  Store& s = g_stores[h];
  if (lock(s) != 0) return;
  *used = header(s)->used;
  *capacity = header(s)->heap_size;
  *nobjs = header(s)->num_objects;
  unlock(s);
}

// Collect up to max_out LRU sealed, unreferenced objects totalling >= need
// bytes (the spill-candidate selection, eviction_policy.h:105,160 analog).
// Writes ids consecutively into out_ids (16 bytes each); returns the count.
int evict_candidates(int64_t h, uint64_t need, uint8_t* out_ids, int max_out) {
  if (h < 0 || h >= kMaxStores || !g_stores[h].valid) return -1;
  Store& s = g_stores[h];
  if (lock(s) != 0) return -1;
  Entry* t = table(s);
  int count = 0;
  uint64_t got = 0;
  while (count < max_out && got < need) {
    Entry* best = nullptr;
    for (uint32_t i = 0; i < kNumEntries; i++) {
      Entry& e = t[i];
      if (e.state != kSealed || e.refcount != 0) continue;
      bool taken = false;
      for (int j = 0; j < count; j++) {
        if (memcmp(out_ids + 16 * j, e.id, 16) == 0) {
          taken = true;
          break;
        }
      }
      if (taken) continue;
      if (!best || e.lru < best->lru) best = &e;
    }
    if (!best) break;
    memcpy(out_ids + 16 * count, best->id, 16);
    got += best->size;
    count++;
  }
  unlock(s);
  return count;
}

uint64_t store_capacity(int64_t h) {
  if (h < 0 || h >= kMaxStores || !g_stores[h].valid) return 0;
  return header(g_stores[h])->heap_size;
}

}  // extern "C"
