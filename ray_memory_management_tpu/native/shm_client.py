"""ctypes client for the native shared-memory object store.

The plasma-client analog (reference: src/ray/object_manager/plasma/client.cc:240
— mmap-cached zero-copy buffer access). Each process opens the store file once
and maps it once; ``get`` returns a memoryview directly into the mapping.
"""

from __future__ import annotations

import ctypes
import mmap
import os
from typing import List, Optional, Tuple

from .build import lib_path

_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    lib = ctypes.CDLL(lib_path("shmstore"))
    lib.store_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
    lib.store_create.restype = ctypes.c_int64
    lib.store_open.argtypes = [ctypes.c_char_p]
    lib.store_open.restype = ctypes.c_int64
    lib.store_close.argtypes = [ctypes.c_int64]
    lib.store_unlink.argtypes = [ctypes.c_char_p]
    lib.store_unlink.restype = ctypes.c_int
    lib.obj_create.argtypes = [ctypes.c_int64, ctypes.c_char_p, ctypes.c_uint64]
    lib.obj_create.restype = ctypes.c_int64
    lib.obj_seal.argtypes = [ctypes.c_int64, ctypes.c_char_p]
    lib.obj_seal.restype = ctypes.c_int
    lib.obj_get.argtypes = [
        ctypes.c_int64, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_int,
    ]
    lib.obj_get.restype = ctypes.c_int
    lib.obj_release.argtypes = [ctypes.c_int64, ctypes.c_char_p]
    lib.obj_release.restype = ctypes.c_int
    lib.obj_delete.argtypes = [ctypes.c_int64, ctypes.c_char_p]
    lib.obj_delete.restype = ctypes.c_int
    lib.obj_contains.argtypes = [ctypes.c_int64, ctypes.c_char_p]
    lib.obj_contains.restype = ctypes.c_int
    lib.store_usage.argtypes = [
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.evict_candidates.argtypes = [
        ctypes.c_int64, ctypes.c_uint64, ctypes.c_char_p, ctypes.c_int,
    ]
    lib.evict_candidates.restype = ctypes.c_int
    _lib = lib
    return lib


class ShmStoreFullError(Exception):
    pass


def reap_stale_stores(prefix: str) -> None:
    """Unlink /dev/shm segments named ``<prefix><pid>_...`` whose owning
    pid is gone — a SIGKILLed owner cannot unlink its own stores, and
    without this a crash-looping process fills /dev/shm. Called at head
    init (prefix "rmt_") and agent start (prefix "rmtA_")."""
    try:
        names = os.listdir("/dev/shm")
    except OSError:
        return
    for name in names:
        if not name.startswith(prefix):
            continue
        try:
            pid = int(name[len(prefix):].split("_")[0])
        except (IndexError, ValueError):
            continue
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            try:
                os.unlink(f"/dev/shm/{name}")
            except OSError:
                pass
        except PermissionError:
            pass  # pid alive under another uid


class ShmStore:
    """One named store; open with ``create=True`` exactly once per store."""

    def __init__(self, name: str, capacity: int = 0, create: bool = False):
        lib = _load()
        self.name = name
        if create:
            self.handle = lib.store_create(name.encode(), capacity)
        else:
            self.handle = lib.store_open(name.encode())
        if self.handle < 0:
            raise OSError(f"failed to open shm store {name}: rc={self.handle}")
        # Map the same file for zero-copy python-side access.
        self._file = open(f"/dev/shm{name}", "r+b")
        self._map = mmap.mmap(self._file.fileno(), 0)
        self._mv = memoryview(self._map)
        self._closed = False
        if create and self._prefault_ok(capacity):
            # Pre-fault the arena in the background: first-touch page
            # faults otherwise dominate the first pass of large writes
            # (plasma pre-touches its mmap the same way). 23 is
            # MADV_POPULATE_WRITE (Linux 5.14+), not yet in the mmap
            # module; unsupported kernels just raise and skip.
            import threading

            def _prefault(m=self._map):
                try:
                    m.madvise(23)
                except (OSError, ValueError):
                    pass

            threading.Thread(target=_prefault, daemon=True,
                             name="shm-prefault").start()

    @staticmethod
    def _prefault_ok(capacity: int) -> bool:
        """Populating dirties the WHOLE arena as resident tmpfs — only do
        it when that commit is clearly affordable (< 1/4 of MemAvailable)
        AND the arena is modest (<= 1 GiB): beyond that the kernel-side
        cost of thousands of worker processes mapping a fully-resident
        multi-GB shared file dominates worker spawn (measured: 2,000 live
        workers spawn at ~90/s against a sparse 3 GiB store but ~30/s
        against a populated one), which is a far worse trade than lazy
        first-touch faults on large writes."""
        if os.environ.get("RMT_DISABLE_PREFAULT"):
            return False
        if (capacity > (1 << 30)
                and not os.environ.get("RMT_FORCE_PREFAULT")):
            # RMT_FORCE_PREFAULT=1 opts a large-store, few-worker
            # deployment (bulk ingest) back into first-touch-free writes
            return False
        try:
            with open("/proc/meminfo") as f:
                for line in f:
                    if line.startswith("MemAvailable:"):
                        avail_kb = int(line.split()[1])
                        return capacity < (avail_kb << 10) // 4
        except (OSError, ValueError, IndexError):
            pass
        return False

    # -- object lifecycle -----------------------------------------------------
    def create(self, object_id: bytes, size: int) -> memoryview:
        """Allocate; returns a writable view. Seal before readers can get it."""
        rc = _load().obj_create(self.handle, object_id, size)
        if rc == 0:
            raise ShmStoreFullError(
                f"store {self.name} full allocating {size} bytes"
            )
        if rc == -2:
            raise ValueError(f"object {object_id.hex()} already exists")
        if rc < 0:
            raise OSError(f"obj_create failed rc={rc}")
        return self._mv[rc : rc + size]

    def seal(self, object_id: bytes) -> None:
        rc = _load().obj_seal(self.handle, object_id)
        if rc != 0:
            raise OSError(f"seal({object_id.hex()}) failed rc={rc}")

    def get(self, object_id: bytes, inc_ref: bool = True) -> Optional[memoryview]:
        """Zero-copy read view of a sealed object, or None if absent."""
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        rc = _load().obj_get(
            self.handle, object_id, ctypes.byref(off), ctypes.byref(size),
            1 if inc_ref else 0,
        )
        if rc == -1:
            return None
        if rc == -2:
            return None  # created but unsealed: not visible yet
        return self._mv[off.value : off.value + size.value]

    def release(self, object_id: bytes) -> None:
        _load().obj_release(self.handle, object_id)

    def delete(self, object_id: bytes) -> bool:
        """True if freed; False while readers still hold references."""
        rc = _load().obj_delete(self.handle, object_id)
        return rc == 0

    def contains(self, object_id: bytes) -> bool:
        return _load().obj_contains(self.handle, object_id) == 1

    # -- store-level ----------------------------------------------------------
    def usage(self) -> Tuple[int, int, int]:
        used = ctypes.c_uint64()
        cap = ctypes.c_uint64()
        n = ctypes.c_uint64()
        _load().store_usage(
            self.handle, ctypes.byref(used), ctypes.byref(cap), ctypes.byref(n)
        )
        return used.value, cap.value, n.value

    def evict_candidates(self, need_bytes: int, max_out: int = 256) -> List[bytes]:
        buf = ctypes.create_string_buffer(16 * max_out)
        n = _load().evict_candidates(self.handle, need_bytes, buf, max_out)
        return [buf.raw[16 * i : 16 * (i + 1)] for i in range(max(n, 0))]

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._mv.release()
            self._map.close()
        except BufferError:
            # Zero-copy views handed to callers are still alive; the mapping
            # stays until they are garbage-collected (the reference's client
            # mmap cache has the same lifetime behavior, plasma/client.cc:240).
            pass
        self._file.close()
        _load().store_close(self.handle)

    @staticmethod
    def unlink(name: str) -> None:
        _load().store_unlink(name.encode())

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
