"""Thin-client mode: drive a remote cluster without joining it.

The reference's Ray Client (python/ray/util/client/ — gRPC proxy server
on the head node at util/client/server/, thin client at worker.py:81,
proto src/ray/protobuf/ray_client.proto). Here the wire is an
authenticated multiprocessing.connection TCP channel: the driver hosts a
``ClusterServer`` and remote processes ``connect()`` a backend that
proxies the full task/actor/object API. All values travel serialized —
the client has no shared-memory store, exactly like the reference's
client mode (and with the same bandwidth trade-off its
client__put_gigabytes benchmark measures).
"""

from .client import ClientBackend, connect, disconnect  # noqa: F401
from .server import ClusterServer  # noqa: F401
