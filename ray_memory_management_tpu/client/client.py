"""Client-side backend: the full core API over a TCP channel.

The reference's thin client (util/client/worker.py:81) re-implements the
worker API surface against the proxy; here ``ClientBackend`` implements
the same backend interface the public api module routes through
(submit/get/put/wait/actors), so after ``connect()`` every ``rmt.*``
call transparently proxies to the remote cluster.
"""

from __future__ import annotations

import threading
from multiprocessing.connection import Client as _MpClient
from typing import Any, Dict, List, Optional, Tuple

from .. import _worker_context
from .. import serialization as ser


class ClientBackend:
    def __init__(self, host: str, port: int,
                 authkey: bytes = b"rmt-client"):
        self._conn = _MpClient((host, port), family="AF_INET",
                               authkey=authkey)
        self._send_lock = threading.Lock()
        self._pending: Dict[int, Any] = {}
        self._events: Dict[int, threading.Event] = {}
        self._counter = 0
        self._lock = threading.Lock()
        self._closed = threading.Event()
        self._recv_thread = threading.Thread(
            target=self._recv_loop, daemon=True, name="rmt-client-recv")
        self._recv_thread.start()
        self.inline_limit = 100 * 1024  # parity with driver-side encoding
        from ..config import WIRE_PROTOCOL_VERSION

        # fail fast on a bad address AND on a version-skewed server (the
        # server raises a mismatch error back through this request)
        self._request({"type": "ping", "proto": WIRE_PROTOCOL_VERSION})

    # -- transport ------------------------------------------------------------
    def _recv_loop(self) -> None:
        while not self._closed.is_set():
            try:
                reply = self._conn.recv()
            except (EOFError, OSError):
                self._closed.set()
                with self._lock:
                    events = list(self._events.values())
                for ev in events:
                    ev.set()
                return
            req_id = reply.get("req_id")
            with self._lock:
                ev = self._events.get(req_id)
                if ev is not None:  # drop late replies to timed-out reqs
                    self._pending[req_id] = reply
            if ev:
                ev.set()

    def _request(self, msg: Dict[str, Any],
                 timeout: Optional[float] = None) -> Dict[str, Any]:
        if self._closed.is_set():
            raise ConnectionError("client connection lost")
        with self._lock:
            self._counter += 1
            req_id = self._counter
            ev = threading.Event()
            self._events[req_id] = ev
        msg["req_id"] = req_id
        with self._send_lock:
            self._conn.send(msg)
        if not ev.wait(timeout if timeout is not None else 3600.0):
            with self._lock:
                self._events.pop(req_id, None)
                self._pending.pop(req_id, None)
            raise TimeoutError(f"client request {msg['type']} timed out")
        with self._lock:
            reply = self._pending.pop(req_id, None)
            self._events.pop(req_id, None)
        if reply is None:
            raise ConnectionError("client connection lost mid-request")
        if reply.get("error") is not None:
            raise ser.loads(reply["error"])
        return reply

    # -- backend interface (mirrors WorkerRuntimeProxy) -----------------------
    def submit_task(self, payload: dict) -> List[bytes]:
        return self._request({"type": "submit_task",
                              "payload": payload})["return_ids"]

    def submit_actor_task(self, payload: dict) -> List[bytes]:
        return self._request({"type": "submit_actor_task",
                              "payload": payload})["return_ids"]

    def create_actor(self, payload: dict) -> bytes:
        return self._request({"type": "create_actor",
                              "payload": payload})["actor_id"]

    def get_objects(self, oids: List[bytes],
                    timeout: Optional[float] = None) -> List[Any]:
        reply = self._request(
            {"type": "get_objects", "oids": oids, "timeout": timeout},
            timeout=None if timeout is None else timeout + 30)
        return [ser.loads(v) for v in reply["values"]]

    def put_object(self, value: Any) -> bytes:
        return self._request(
            {"type": "put", "data": ser.dumps(value)})["object_id"]

    def put_serialized_arg(self, data) -> bytes:
        return self._request(
            {"type": "put", "data": data.to_bytes()})["object_id"]

    def put_device_object(self, value: Any) -> bytes:
        # a thin client has no cluster-side device; the server pins the
        # rebuilt array in the driver's device store
        return self._request(
            {"type": "put_device", "data": ser.dumps(value)})["object_id"]

    def wait(self, oids, num_returns, timeout,
             fetch_local=True) -> Tuple[List[bytes], List[bytes]]:
        reply = self._request(
            {"type": "wait", "oids": oids, "num_returns": num_returns,
             "timeout": timeout},
            timeout=None if timeout is None else timeout + 30)
        return reply["ready"], reply["not_ready"]

    def kill_actor(self, actor_id: bytes, no_restart: bool) -> None:
        self._request({"type": "kill_actor", "actor_id": actor_id,
                       "no_restart": no_restart})

    def cancel_task(self, oid: bytes, force: bool) -> None:
        self._request({"type": "cancel_task", "object_id": oid,
                       "force": force})

    def get_named_actor(self, name: str) -> bytes:
        return self._request({"type": "get_named_actor",
                              "name": name})["actor_id"]

    def cluster_resources(self) -> Dict[str, float]:
        return self._request({"type": "cluster_resources"})["resources"]

    # placement groups proxy like the worker proxy does, so gang-scheduling
    # libraries work from thin clients too
    def create_placement_group(self, bundles, strategy, name="") -> bytes:
        return self._request({"type": "create_pg", "bundles": bundles,
                              "strategy": strategy, "name": name})["pg_id"]

    def placement_group_state(self, pg_id: bytes):
        return self._request({"type": "pg_state", "pg_id": pg_id})["state"]

    def wait_placement_group(self, pg_id: bytes, timeout: float) -> bool:
        return self._request({"type": "wait_pg", "pg_id": pg_id,
                              "timeout": timeout},
                             timeout=timeout + 30)["created"]

    def remove_placement_group(self, pg_id: bytes) -> None:
        self._request({"type": "remove_pg", "pg_id": pg_id})

    # -- job plane ------------------------------------------------------------
    def set_quota(self, cpu_slots: int = 0, object_bytes: int = 0,
                  device_bytes: int = 0, priority: int = 1) -> None:
        """Install this connection's job quota (0 = unlimited). Byte
        quotas reject over-limit puts/pins with QuotaExceededError;
        cpu_slots backpressures task admission; priority weights the
        router's fair share and gates leaf-lease preemption."""
        self._request({"type": "set_quota", "quota": {
            "cpu_slots": cpu_slots, "object_bytes": object_bytes,
            "device_bytes": device_bytes, "priority": priority}})

    def job_usage(self) -> dict:
        """This connection's live quota usage (bytes, slots, counters)."""
        return self._request({"type": "job_usage"})["usage"]

    def close(self) -> None:
        self._closed.set()
        try:
            self._conn.close()
        except OSError:
            pass


_client: Optional[ClientBackend] = None


def connect(address: str, authkey: bytes = b"rmt-client") -> ClientBackend:
    """Connect this process to a served cluster, e.g.
    ``connect("127.0.0.1:10001")``. After this, ``rmt.remote/get/put``
    route through the client (the ray://... init analog)."""
    global _client
    host, _, port = address.partition(":")
    backend = ClientBackend(host or "127.0.0.1", int(port), authkey)
    _worker_context.set_proxy(backend)
    _client = backend
    return backend


def disconnect() -> None:
    global _client
    if _client is not None:
        _client.close()
        _client = None
    _worker_context.set_proxy(None)
