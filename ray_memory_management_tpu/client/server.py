"""Cluster-side server for thin clients.

The reference's client server + proxier (util/client/server/{server,
proxier,dataservicer}.py) collapsed to one in-driver service: each client
connection gets a handler thread; requests reuse the same operations the
worker-request path serves, with object values inlined over the wire.

Every connection is a JOB (the GcsJobManager model, gcs_job_manager.h:28):
it registers in the GCS job table on connect, its created resources are
tracked, and on disconnect everything non-detached it created — actors,
placement groups, put objects — is reclaimed and the job row flips to
FINISHED. This is the multi-driver isolation story: two clients sharing a
cluster cannot leak resources into each other's lifetime.
"""

from __future__ import annotations

import threading
from collections import deque
from multiprocessing import AuthenticationError
from multiprocessing.connection import Listener
from typing import Any, Dict, Optional

from .. import _worker_context
from .. import serialization as ser
from ..ids import JobID


class _JobState:
    """Per-connection resource ledger, reclaimed on disconnect."""

    __slots__ = ("job_id", "actors", "pgs", "puts", "refs", "mu", "closed",
                 "proto_verified", "cpp_executors", "conn_alive")

    def __init__(self, job_id: bytes):
        self.job_id = job_id
        # flipped by _serve_conn's exit; a job with conn_alive False that
        # was never reclaimed (dropped disconnect notification — the
        # job.detach fault site) is an ORPHAN the watchdog sweeps
        self.conn_alive = True
        # set by the first successful versioned ping; every other verb is
        # refused until then, so a frontend cannot skip the handshake and
        # speak unversioned (the node-registration and transfer planes
        # already check every handshake — this closes the client plane)
        self.proto_verified = False
        self.actors: set = set()
        self.pgs: set = set()
        self.puts: set = set()
        # live ObjectRef objects for call_named returns: holding them
        # keeps the driver-side refcount pinning the return values until
        # the frontend disconnects (a non-Python frontend has no
        # distributed-refcount participation of its own)
        self.refs: list = []
        self.cpp_executors: set = set()  # executor ids this conn registered
        self.mu = threading.Lock()
        self.closed = False  # set by _reclaim_job; late tracks reclaim
        # inline instead of landing in an already-drained ledger


# Named-function registry for non-Python frontends (the C++ client,
# native/client/): compute stays registered cluster-side in Python, and a
# frontend drives it by name with bytes in / bytes out — the reference's
# cross-language boundary likewise moves opaque buffers between language
# frontends rather than pickled object graphs (its msgpack XLANG format).
_named_functions: Dict[str, dict] = {}


def register_named_function(name: str, fn, **default_opts) -> None:
    """Expose ``fn`` to non-Python frontends as ``name``. The function
    receives the frontend's raw ``bytes`` args and should return bytes
    (rich returns remain fetchable from Python clients). The remote
    wrapper is built once here and cached per options-set: rebuilding it
    per call would mint a fresh function id each time, growing every
    worker's function cache and re-shipping the pickled function per
    call."""
    _named_functions[name] = {"fn": fn, "defaults": default_opts,
                              "remote_cache": {}}


def unregister_named_function(name: str) -> None:
    _named_functions.pop(name, None)


# --------------------------------------------------------- C++ task plane
# Worker-side C++ story: an EXECUTOR process (native/client Executor,
# built on librmtclient) registers the names of functions it implements
# in C++, long-polls for tasks, and returns result bytes. Python (or any
# frontend) calls them via api.cpp_function(name).remote(...) and gets
# ordinary ObjectRefs — results deliver through runtime promises. The
# reference's counterpart is its C++ worker runtime executing
# RAY_REMOTE-registered functions (cpp/include/ray/api.h ray::Task;
# cross-language calls move opaque buffers the same way).


class _CppExecutor:
    """One connected C++ executor: registered function names, a pending
    queue, and the inflight table (for failing tasks on executor death)."""

    __slots__ = ("ex_id", "functions", "queue", "cond", "inflight",
                 "closed")

    def __init__(self, ex_id: bytes, functions):
        self.ex_id = ex_id
        self.functions = set(functions)
        self.queue: deque = deque()
        self.cond = threading.Condition()
        self.inflight: Dict[str, dict] = {}
        self.closed = False


_cpp_lock = threading.Lock()
_cpp_executors: Dict[bytes, _CppExecutor] = {}


def cpp_function_names() -> list:
    with _cpp_lock:
        names: set = set()
        for ex in _cpp_executors.values():
            if not ex.closed:
                names |= ex.functions
    return sorted(names)


def submit_cpp_task(name: str, args, num_returns: int = 1,
                    adopt: bool = False) -> list:
    """Dispatch a task to the least-loaded C++ executor serving ``name``;
    returns promise object ids (resolved when the executor replies).
    ``adopt=True`` pre-registers one local ref per return for an
    in-process caller's ObjectRef to adopt (the submit_task contract)."""
    rt = _worker_context.get_runtime()
    if rt is None:
        raise RuntimeError("no runtime: init() the cluster first")
    with _cpp_lock:
        candidates = [ex for ex in _cpp_executors.values()
                      if not ex.closed and name in ex.functions]
    if not candidates:
        raise RuntimeError(
            f"no C++ executor serves {name!r}: start one (it registers "
            "its functions over the client protocol) and retry")
    ex = min(candidates, key=lambda e: len(e.queue) + len(e.inflight))
    return_ids = [rt.create_promise() for _ in range(num_returns)]
    if adopt:
        for oid in return_ids:
            rt.add_local_ref(oid)
    task = {
        "task_id": return_ids[0].hex(),
        "name": name,
        "args": [bytes(a) for a in args],
        "return_ids": [o.hex() for o in return_ids],
    }
    with ex.cond:
        if not ex.closed:
            ex.queue.append(task)
            ex.cond.notify()
            return return_ids
    # raced its disconnect: unwind the promises we just minted (their
    # futures and adopt refs would otherwise leak) and fail fast
    if adopt:
        for oid in return_ids:
            rt.remove_local_ref(oid)  # zero -> deferred free purges it
    else:
        rt.free_objects(return_ids)
    raise RuntimeError(f"C++ executor for {name!r} disconnected")


def _cpp_next_task(ex: _CppExecutor, timeout: float) -> Optional[dict]:
    with ex.cond:
        if not ex.queue:
            ex.cond.wait(timeout)
        if not ex.queue:
            return None
        task = ex.queue.popleft()
        ex.inflight[task["task_id"]] = task
        return task


def _cpp_finish_task(rt, ex: _CppExecutor, task_id: str,
                     results, error: Optional[str]) -> None:
    with ex.cond:
        task = ex.inflight.pop(task_id, None)
    if task is None:
        return  # unknown/duplicate completion
    return_ids = [bytes.fromhex(h) for h in task["return_ids"]]
    if error is None and len(results or ()) != len(return_ids):
        error = (f"C++ executor returned {len(results or ())} results "
                 f"for {len(return_ids)} return ids")
    if error is not None:
        from ..exceptions import TaskError

        exc = TaskError(task["name"], RuntimeError(error))
        for oid in return_ids:
            rt.resolve_promise(oid, error=exc)
        return
    try:
        for oid, data in zip(return_ids, results):
            rt.resolve_promise(oid, value=bytes(data))
    except Exception as e:  # noqa: BLE001 — e.g. store full storing a
        # large result: the task is already out of inflight, so the
        # executor-death failsafe can never reach these promises — fail
        # them HERE or the caller's get blocks forever
        from ..exceptions import TaskError

        exc = TaskError(task["name"], e)
        for oid in return_ids:
            rt.resolve_promise(oid, error=exc)
        raise


def _cpp_close_executor(rt, ex_id: bytes) -> None:
    """Executor disconnected: fail everything it held, deregister it."""
    with _cpp_lock:
        ex = _cpp_executors.pop(ex_id, None)
    if ex is None:
        return
    from ..exceptions import TaskError

    with ex.cond:
        ex.closed = True
        orphans = list(ex.queue) + list(ex.inflight.values())
        ex.queue.clear()
        ex.inflight.clear()
    for task in orphans:
        exc = TaskError(task["name"],
                        RuntimeError("C++ executor disconnected"))
        for h in task["return_ids"]:
            rt.resolve_promise(bytes.fromhex(h), error=exc)


class ClusterServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 authkey: bytes = b"rmt-client"):
        rt = _worker_context.get_runtime()
        if rt is None:
            raise RuntimeError("start the cluster first (init()), then "
                               "serve it to clients")
        self._rt = rt
        self._authkey = authkey
        self._listener = Listener((host, port), family="AF_INET",
                                  authkey=authkey)
        self.address = self._listener.address  # (host, bound_port)
        self._stop = threading.Event()
        self._conns_lock = threading.Lock()
        self._conns: set = set()
        # live per-connection job states, keyed by job id: the watchdog
        # scans these for orphans (conn gone, reclaim never ran)
        self._job_states: Dict[bytes, _JobState] = {}  # guarded-by: _conns_lock
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="rmt-client-accept")
        self._accept_thread.start()
        self._watchdog_thread = None
        interval = float(getattr(rt.config, "job_watchdog_interval_s", 0))
        if interval > 0:
            self._watchdog_thread = threading.Thread(
                target=self._watchdog_loop, args=(interval,),
                daemon=True, name="rmt-job-watchdog")
            self._watchdog_thread.start()

    @property
    def port(self) -> int:
        return self.address[1]

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn = self._listener.accept()
            except (OSError, EOFError):
                if self._stop.is_set():
                    return
                continue
            except AuthenticationError:
                # a wrong client authkey raises INSIDE accept()'s
                # handshake; letting it unwind would kill this thread
                # and brick the server for every future client. Only
                # this exception — anything else should surface.
                continue
            with self._conns_lock:
                self._conns.add(conn)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True, name="rmt-client-conn").start()

    def _serve_conn(self, conn) -> None:
        send_lock = threading.Lock()
        job = _JobState(JobID.from_random().binary())
        self._rt.register_client_job(job.job_id, {"type": "client"})
        with self._conns_lock:
            self._job_states[job.job_id] = job
        try:
            while not self._stop.is_set():
                try:
                    msg = conn.recv()
                except (EOFError, OSError):
                    return
                threading.Thread(
                    target=self._handle, args=(conn, send_lock, msg, job),
                    daemon=True).start()
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass
            job.conn_alive = False
            # job.detach fault site: the driver's disconnect notification
            # can be lost (head-side thread dies before cleanup, network
            # partition at exit). drop/error = the notification vanishes —
            # reclaim is skipped HERE and the orphaned job must be found
            # and swept by the watchdog instead.
            from ..utils import faults

            act = faults.fire("job.detach")
            if act is not None:
                if act.mode == "stall":
                    act.sleep()
                elif act.mode in ("error", "drop"):
                    return  # orphan: the watchdog sweeps it
            with self._conns_lock:
                self._job_states.pop(job.job_id, None)
            self._reclaim_job(job, trigger="disconnect")

    def _watchdog_loop(self, interval: float) -> None:
        """Find jobs whose connection died but whose disconnect
        notification was dropped (job.detach), and sweep them — driver
        death must never leak a job, whatever happened to the notice."""
        while not self._stop.wait(interval):
            with self._conns_lock:
                orphans = [j for j in self._job_states.values()
                           if not j.conn_alive]
                for j in orphans:
                    self._job_states.pop(j.job_id, None)
            for j in orphans:
                try:
                    self._reclaim_job(j, trigger="watchdog")
                except Exception:  # noqa: BLE001 — the watchdog survives
                    pass

    def _reclaim_job(self, job: _JobState,
                     trigger: str = "disconnect") -> None:
        """Disconnect cleanup: kill the job's non-detached actors, remove
        its placement groups, free its put objects, then run the
        runtime's job-death sweep (ownership GC over everything the job
        id tagged: directory rows, refcounts, device pins, quota ledger)
        — the reference kills a driver's leases and actors on driver
        death the same way (gcs_job_manager.h:28 MarkJobFinished)."""
        rt = self._rt
        with job.mu:
            job.closed = True
            actors, pgs, puts = list(job.actors), list(job.pgs), \
                list(job.puts)
            executors = list(job.cpp_executors)
            job.actors.clear()
            job.pgs.clear()
            job.puts.clear()
            job.cpp_executors.clear()
            job.refs.clear()  # drop call_named returns: refcount frees them
        for ex_id in executors:
            # fail its queued/inflight tasks, then deregister it
            _cpp_close_executor(rt, ex_id)
        for aid in actors:
            self._reclaim_one("actors", aid)
        for pg_id in pgs:
            self._reclaim_one("pgs", pg_id)
        try:
            rt.free_objects(puts)
        except Exception:  # noqa: BLE001
            pass
        try:
            rt.sweep_job(job.job_id, trigger=trigger)
        except Exception:  # noqa: BLE001 — sweep retries ride heartbeats
            pass

    def _reclaim_one(self, kind: str, value) -> None:
        rt = self._rt
        try:
            if kind == "actors":
                info = rt.actors.get(value)
                if info is not None and not info.spec.detached:
                    rt.kill_actor(value, no_restart=True)
            elif kind == "pgs":
                from ..core.placement_group import _manager

                _manager(rt).remove(value)
            elif kind == "puts":
                rt.free_objects([value])
        except Exception:  # noqa: BLE001 — reclaim is best-effort
            pass

    def _handle(self, conn, send_lock, msg: Dict[str, Any],
                job: _JobState) -> None:
        reply: Dict[str, Any] = {"req_id": msg.get("req_id"), "error": None}
        rt = self._rt

        def track(kind: str, value) -> None:
            with job.mu:
                if not job.closed:
                    getattr(job, kind).add(value)
                    return
            # the client vanished mid-request and reclaim already ran:
            # this straggler resource would leak forever — reclaim it now
            self._reclaim_one(kind, value)

        try:
            mtype = msg["type"]
            if mtype != "ping" and not job.proto_verified:
                raise ValueError(
                    f"request {mtype!r} before the wire-protocol "
                    "handshake: clients must ping (with their proto "
                    "version) first")
            if mtype == "submit_task":
                # the server stamps ownership — a client cannot submit
                # under another job's id (quota/sweep isolation boundary)
                msg["payload"]["job_id"] = job.job_id
                reply["return_ids"] = rt.submit_task(
                    msg["payload"], adopt_returns=False)
            elif mtype == "submit_actor_task":
                msg["payload"]["job_id"] = job.job_id
                reply["return_ids"] = rt.submit_actor_task(
                    msg["payload"], adopt_returns=False)
            elif mtype == "create_actor":
                msg["payload"]["job_id"] = job.job_id
                reply["actor_id"] = rt.create_actor(msg["payload"])
                track("actors", reply["actor_id"])
            elif mtype == "get_objects":
                values = rt.get_objects(msg["oids"], msg.get("timeout"))
                reply["values"] = [ser.dumps(v) for v in values]
            elif mtype == "put":
                reply["object_id"] = rt.put_object(
                    ser.loads(msg["data"]), job_id=job.job_id)
                track("puts", reply["object_id"])
            elif mtype == "put_device":
                reply["object_id"] = rt.put_device_object(
                    ser.loads(msg["data"]), job_id=job.job_id)
                track("puts", reply["object_id"])
            elif mtype == "set_quota":
                # self-service quota (trusted clients); job_submission
                # installs submit-time quotas through the same runtime call
                rt.set_job_quota(job.job_id, msg.get("quota") or {})
            elif mtype == "job_usage":
                reply["usage"] = rt.job_usage(job.job_id)
                reply["job_id"] = job.job_id
            elif mtype == "wait":
                ready, not_ready = rt.wait(
                    msg["oids"], msg["num_returns"], msg["timeout"])
                reply["ready"], reply["not_ready"] = ready, not_ready
            elif mtype == "kill_actor":
                rt.kill_actor(msg["actor_id"], msg["no_restart"])
            elif mtype == "cancel_task":
                rt.cancel(msg["object_id"], msg["force"])
            elif mtype == "get_named_actor":
                rec = rt.gcs.get_named_actor(msg["name"])
                if rec is None:
                    raise ValueError(f"no actor named {msg['name']!r}")
                reply["actor_id"] = rec.actor_id.binary()
            elif mtype == "cluster_resources":
                reply["resources"] = rt.scheduler.cluster_resources()
            elif mtype == "create_pg":
                from ..core.placement_group import _manager

                pg = _manager(rt).create(
                    msg["bundles"], msg["strategy"], msg.get("name", ""))
                reply["pg_id"] = pg.id
                track("pgs", pg.id)
            elif mtype == "pg_state":
                from ..core.placement_group import _manager

                reply["state"] = _manager(rt).state(msg["pg_id"])
            elif mtype == "wait_pg":
                from ..core.placement_group import _manager

                reply["created"] = _manager(rt).wait_created(
                    msg["pg_id"], msg["timeout"])
            elif mtype == "remove_pg":
                from ..core.placement_group import _manager

                _manager(rt).remove(msg["pg_id"])
            elif mtype == "list_named":
                reply["names"] = sorted(_named_functions)
            elif mtype == "call_named":
                from .. import api

                name = msg["name"]
                if name not in _named_functions:
                    raise KeyError(
                        f"no function registered as {name!r}; the cluster "
                        "side must call register_named_function first")
                entry = _named_functions[name]
                opts = {**entry["defaults"], **(msg.get("opts") or {})}
                # repr-keyed: option values may be dicts (resources={...})
                # which are unhashable; a repr collision is impossible for
                # these plain-literal option sets and a repr MISS is just
                # a cache rebuild
                key = repr(sorted(opts.items()))
                rf = entry["remote_cache"].get(key)
                if rf is None:
                    rf = api.remote(entry["fn"])
                    if opts:
                        rf = rf.options(**opts)
                    entry["remote_cache"][key] = rf
                refs = rf.remote(*[bytes(a) for a in msg.get("args", [])])
                refs = list(refs) if isinstance(refs, (list, tuple)) \
                    else [refs]
                with job.mu:
                    if not job.closed:
                        job.refs.extend(refs)
                reply["return_ids"] = [r.binary() for r in refs]
            elif mtype == "free_refs":
                # steady-state release for long-lived frontends: drop the
                # pinned call_named returns / put_bytes objects for these
                # ids so the store does not grow monotonically
                ids = {bytes(o) for o in msg["oids"]}
                with job.mu:
                    job.refs = [r for r in job.refs
                                if r.binary() not in ids]
                    puts = [o for o in ids if o in job.puts]
                    for o in puts:
                        job.puts.discard(o)
                if puts:
                    rt.free_objects(puts)
            elif mtype == "put_bytes":
                # raw-buffer puts for non-Python frontends: the value IS
                # the bytes (no pickle envelope crosses the wire)
                oid = rt.put_object(bytes(msg["data"]), job_id=job.job_id)
                track("puts", oid)
                reply["object_id"] = oid
            elif mtype == "get_bytes":
                values = rt.get_objects(msg["oids"], msg.get("timeout"))
                out = []
                for v in values:
                    if isinstance(v, (bytes, bytearray, memoryview)):
                        out.append(bytes(v))
                    else:
                        raise TypeError(
                            "get_bytes fetched a non-bytes value of type "
                            f"{type(v).__name__}; rich values need a "
                            "Python client")
                reply["values"] = out
            elif mtype == "register_cpp_executor":
                import os as _os

                ex_id = _os.urandom(16)
                ex = _CppExecutor(ex_id, [str(n) for n in msg["functions"]])
                with _cpp_lock:
                    _cpp_executors[ex_id] = ex
                with job.mu:
                    if not job.closed:
                        job.cpp_executors.add(ex_id)
                        ex_id_ok = True
                    else:
                        ex_id_ok = False
                if not ex_id_ok:  # conn died mid-register: deregister
                    _cpp_close_executor(rt, ex_id)
                    raise OSError("connection closed during registration")
                reply["executor_id"] = ex_id
            elif mtype == "next_cpp_task":
                with _cpp_lock:
                    ex = _cpp_executors.get(bytes(msg["executor_id"]))
                if ex is None:
                    raise KeyError("unknown executor id")
                timeout = min(float(msg.get("timeout", 10.0)), 60.0)
                reply["task"] = _cpp_next_task(ex, timeout)
            elif mtype == "cpp_task_done":
                with _cpp_lock:
                    ex = _cpp_executors.get(bytes(msg["executor_id"]))
                if ex is None:
                    raise KeyError("unknown executor id")
                _cpp_finish_task(rt, ex, str(msg["task_id"]),
                                 msg.get("results"), msg.get("err"))
            elif mtype == "call_cpp":
                oids = submit_cpp_task(
                    str(msg["name"]), msg.get("args", []),
                    int(msg.get("num_returns", 1)))
                for oid in oids:
                    # promise returns pin like puts: freed when this
                    # frontend disconnects (or frees them explicitly)
                    track("puts", oid)
                reply["return_ids"] = oids
            elif mtype == "list_cpp":
                reply["names"] = cpp_function_names()
            elif mtype == "ping":
                from ..config import WIRE_PROTOCOL_VERSION

                # strict: a MISSING proto is a pre-versioning peer, the
                # exact population the check exists to refuse
                proto = msg.get("proto")
                if proto != WIRE_PROTOCOL_VERSION:
                    raise ValueError(
                        "wire protocol mismatch: server speaks "
                        f"v{WIRE_PROTOCOL_VERSION}, client spoke "
                        f"v{proto} — upgrade the older side")
                job.proto_verified = True
                reply["pong"] = True
            else:
                raise ValueError(f"unknown client request {mtype!r}")
        except Exception as e:  # noqa: BLE001 — surfaces client-side
            try:
                reply = {"req_id": msg.get("req_id"), "error": ser.dumps(e)}
            except Exception:
                reply = {"req_id": msg.get("req_id"),
                         "error": ser.dumps(RuntimeError(str(e)))}
        try:
            with send_lock:
                conn.send(reply)
        except (OSError, BrokenPipeError):
            pass

    def close(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        # close live client connections so their pending requests fail
        # fast instead of hanging out the full request timeout
        with self._conns_lock:
            conns = list(self._conns)
            self._conns.clear()
        for conn in conns:
            try:
                conn.close()
            except OSError:
                pass
