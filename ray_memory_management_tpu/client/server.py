"""Cluster-side server for thin clients.

The reference's client server + proxier (util/client/server/{server,
proxier,dataservicer}.py) collapsed to one in-driver service: each client
connection gets a handler thread; requests reuse the same operations the
worker-request path serves, with object values inlined over the wire.

Every connection is a JOB (the GcsJobManager model, gcs_job_manager.h:28):
it registers in the GCS job table on connect, its created resources are
tracked, and on disconnect everything non-detached it created — actors,
placement groups, put objects — is reclaimed and the job row flips to
FINISHED. This is the multi-driver isolation story: two clients sharing a
cluster cannot leak resources into each other's lifetime.
"""

from __future__ import annotations

import threading
from multiprocessing import AuthenticationError
from multiprocessing.connection import Listener
from typing import Any, Dict, Optional

from .. import _worker_context
from .. import serialization as ser
from ..ids import JobID


class _JobState:
    """Per-connection resource ledger, reclaimed on disconnect."""

    __slots__ = ("job_id", "actors", "pgs", "puts", "refs", "mu", "closed",
                 "proto_verified")

    def __init__(self, job_id: bytes):
        self.job_id = job_id
        # set by the first successful versioned ping; every other verb is
        # refused until then, so a frontend cannot skip the handshake and
        # speak unversioned (the node-registration and transfer planes
        # already check every handshake — this closes the client plane)
        self.proto_verified = False
        self.actors: set = set()
        self.pgs: set = set()
        self.puts: set = set()
        # live ObjectRef objects for call_named returns: holding them
        # keeps the driver-side refcount pinning the return values until
        # the frontend disconnects (a non-Python frontend has no
        # distributed-refcount participation of its own)
        self.refs: list = []
        self.mu = threading.Lock()
        self.closed = False  # set by _reclaim_job; late tracks reclaim
        # inline instead of landing in an already-drained ledger


# Named-function registry for non-Python frontends (the C++ client,
# native/client/): compute stays registered cluster-side in Python, and a
# frontend drives it by name with bytes in / bytes out — the reference's
# cross-language boundary likewise moves opaque buffers between language
# frontends rather than pickled object graphs (its msgpack XLANG format).
_named_functions: Dict[str, dict] = {}


def register_named_function(name: str, fn, **default_opts) -> None:
    """Expose ``fn`` to non-Python frontends as ``name``. The function
    receives the frontend's raw ``bytes`` args and should return bytes
    (rich returns remain fetchable from Python clients). The remote
    wrapper is built once here and cached per options-set: rebuilding it
    per call would mint a fresh function id each time, growing every
    worker's function cache and re-shipping the pickled function per
    call."""
    _named_functions[name] = {"fn": fn, "defaults": default_opts,
                              "remote_cache": {}}


def unregister_named_function(name: str) -> None:
    _named_functions.pop(name, None)


class ClusterServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 authkey: bytes = b"rmt-client"):
        rt = _worker_context.get_runtime()
        if rt is None:
            raise RuntimeError("start the cluster first (init()), then "
                               "serve it to clients")
        self._rt = rt
        self._authkey = authkey
        self._listener = Listener((host, port), family="AF_INET",
                                  authkey=authkey)
        self.address = self._listener.address  # (host, bound_port)
        self._stop = threading.Event()
        self._conns_lock = threading.Lock()
        self._conns: set = set()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="rmt-client-accept")
        self._accept_thread.start()

    @property
    def port(self) -> int:
        return self.address[1]

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn = self._listener.accept()
            except (OSError, EOFError):
                if self._stop.is_set():
                    return
                continue
            except AuthenticationError:
                # a wrong client authkey raises INSIDE accept()'s
                # handshake; letting it unwind would kill this thread
                # and brick the server for every future client. Only
                # this exception — anything else should surface.
                continue
            with self._conns_lock:
                self._conns.add(conn)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True, name="rmt-client-conn").start()

    def _serve_conn(self, conn) -> None:
        send_lock = threading.Lock()
        job = _JobState(JobID.from_random().binary())
        self._rt.gcs.register_job(job.job_id, {"type": "client"})
        try:
            while not self._stop.is_set():
                try:
                    msg = conn.recv()
                except (EOFError, OSError):
                    return
                threading.Thread(
                    target=self._handle, args=(conn, send_lock, msg, job),
                    daemon=True).start()
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass
            self._reclaim_job(job)

    def _reclaim_job(self, job: _JobState) -> None:
        """Disconnect cleanup: kill the job's non-detached actors, remove
        its placement groups, free its put objects, finish its job row —
        the reference kills a driver's leases and actors on driver death
        the same way (gcs_job_manager.h:28 MarkJobFinished)."""
        rt = self._rt
        with job.mu:
            job.closed = True
            actors, pgs, puts = list(job.actors), list(job.pgs), \
                list(job.puts)
            job.actors.clear()
            job.pgs.clear()
            job.puts.clear()
            job.refs.clear()  # drop call_named returns: refcount frees them
        for aid in actors:
            self._reclaim_one("actors", aid)
        for pg_id in pgs:
            self._reclaim_one("pgs", pg_id)
        try:
            rt.free_objects(puts)
        except Exception:  # noqa: BLE001
            pass
        try:
            rt.gcs.set_job_state(job.job_id, "FINISHED")
        except Exception:  # noqa: BLE001
            pass

    def _reclaim_one(self, kind: str, value) -> None:
        rt = self._rt
        try:
            if kind == "actors":
                info = rt.actors.get(value)
                if info is not None and not info.spec.detached:
                    rt.kill_actor(value, no_restart=True)
            elif kind == "pgs":
                from ..core.placement_group import _manager

                _manager(rt).remove(value)
            elif kind == "puts":
                rt.free_objects([value])
        except Exception:  # noqa: BLE001 — reclaim is best-effort
            pass

    def _handle(self, conn, send_lock, msg: Dict[str, Any],
                job: _JobState) -> None:
        reply: Dict[str, Any] = {"req_id": msg.get("req_id"), "error": None}
        rt = self._rt

        def track(kind: str, value) -> None:
            with job.mu:
                if not job.closed:
                    getattr(job, kind).add(value)
                    return
            # the client vanished mid-request and reclaim already ran:
            # this straggler resource would leak forever — reclaim it now
            self._reclaim_one(kind, value)

        try:
            mtype = msg["type"]
            if mtype != "ping" and not job.proto_verified:
                raise ValueError(
                    f"request {mtype!r} before the wire-protocol "
                    "handshake: clients must ping (with their proto "
                    "version) first")
            if mtype == "submit_task":
                reply["return_ids"] = rt.submit_task(
                    msg["payload"], adopt_returns=False)
            elif mtype == "submit_actor_task":
                reply["return_ids"] = rt.submit_actor_task(
                    msg["payload"], adopt_returns=False)
            elif mtype == "create_actor":
                reply["actor_id"] = rt.create_actor(msg["payload"])
                track("actors", reply["actor_id"])
            elif mtype == "get_objects":
                values = rt.get_objects(msg["oids"], msg.get("timeout"))
                reply["values"] = [ser.dumps(v) for v in values]
            elif mtype == "put":
                reply["object_id"] = rt.put_object(ser.loads(msg["data"]))
                track("puts", reply["object_id"])
            elif mtype == "put_device":
                reply["object_id"] = rt.put_device_object(
                    ser.loads(msg["data"]))
                track("puts", reply["object_id"])
            elif mtype == "wait":
                ready, not_ready = rt.wait(
                    msg["oids"], msg["num_returns"], msg["timeout"])
                reply["ready"], reply["not_ready"] = ready, not_ready
            elif mtype == "kill_actor":
                rt.kill_actor(msg["actor_id"], msg["no_restart"])
            elif mtype == "cancel_task":
                rt.cancel(msg["object_id"], msg["force"])
            elif mtype == "get_named_actor":
                rec = rt.gcs.get_named_actor(msg["name"])
                if rec is None:
                    raise ValueError(f"no actor named {msg['name']!r}")
                reply["actor_id"] = rec.actor_id.binary()
            elif mtype == "cluster_resources":
                reply["resources"] = rt.scheduler.cluster_resources()
            elif mtype == "create_pg":
                from ..core.placement_group import _manager

                pg = _manager(rt).create(
                    msg["bundles"], msg["strategy"], msg.get("name", ""))
                reply["pg_id"] = pg.id
                track("pgs", pg.id)
            elif mtype == "pg_state":
                from ..core.placement_group import _manager

                reply["state"] = _manager(rt).state(msg["pg_id"])
            elif mtype == "wait_pg":
                from ..core.placement_group import _manager

                reply["created"] = _manager(rt).wait_created(
                    msg["pg_id"], msg["timeout"])
            elif mtype == "remove_pg":
                from ..core.placement_group import _manager

                _manager(rt).remove(msg["pg_id"])
            elif mtype == "list_named":
                reply["names"] = sorted(_named_functions)
            elif mtype == "call_named":
                from .. import api

                name = msg["name"]
                if name not in _named_functions:
                    raise KeyError(
                        f"no function registered as {name!r}; the cluster "
                        "side must call register_named_function first")
                entry = _named_functions[name]
                opts = {**entry["defaults"], **(msg.get("opts") or {})}
                # repr-keyed: option values may be dicts (resources={...})
                # which are unhashable; a repr collision is impossible for
                # these plain-literal option sets and a repr MISS is just
                # a cache rebuild
                key = repr(sorted(opts.items()))
                rf = entry["remote_cache"].get(key)
                if rf is None:
                    rf = api.remote(entry["fn"])
                    if opts:
                        rf = rf.options(**opts)
                    entry["remote_cache"][key] = rf
                refs = rf.remote(*[bytes(a) for a in msg.get("args", [])])
                refs = list(refs) if isinstance(refs, (list, tuple)) \
                    else [refs]
                with job.mu:
                    if not job.closed:
                        job.refs.extend(refs)
                reply["return_ids"] = [r.binary() for r in refs]
            elif mtype == "free_refs":
                # steady-state release for long-lived frontends: drop the
                # pinned call_named returns / put_bytes objects for these
                # ids so the store does not grow monotonically
                ids = {bytes(o) for o in msg["oids"]}
                with job.mu:
                    job.refs = [r for r in job.refs
                                if r.binary() not in ids]
                    puts = [o for o in ids if o in job.puts]
                    for o in puts:
                        job.puts.discard(o)
                if puts:
                    rt.free_objects(puts)
            elif mtype == "put_bytes":
                # raw-buffer puts for non-Python frontends: the value IS
                # the bytes (no pickle envelope crosses the wire)
                oid = rt.put_object(bytes(msg["data"]))
                track("puts", oid)
                reply["object_id"] = oid
            elif mtype == "get_bytes":
                values = rt.get_objects(msg["oids"], msg.get("timeout"))
                out = []
                for v in values:
                    if isinstance(v, (bytes, bytearray, memoryview)):
                        out.append(bytes(v))
                    else:
                        raise TypeError(
                            "get_bytes fetched a non-bytes value of type "
                            f"{type(v).__name__}; rich values need a "
                            "Python client")
                reply["values"] = out
            elif mtype == "ping":
                from ..config import WIRE_PROTOCOL_VERSION

                # strict: a MISSING proto is a pre-versioning peer, the
                # exact population the check exists to refuse
                proto = msg.get("proto")
                if proto != WIRE_PROTOCOL_VERSION:
                    raise ValueError(
                        "wire protocol mismatch: server speaks "
                        f"v{WIRE_PROTOCOL_VERSION}, client spoke "
                        f"v{proto} — upgrade the older side")
                job.proto_verified = True
                reply["pong"] = True
            else:
                raise ValueError(f"unknown client request {mtype!r}")
        except Exception as e:  # noqa: BLE001 — surfaces client-side
            try:
                reply = {"req_id": msg.get("req_id"), "error": ser.dumps(e)}
            except Exception:
                reply = {"req_id": msg.get("req_id"),
                         "error": ser.dumps(RuntimeError(str(e)))}
        try:
            with send_lock:
                conn.send(reply)
        except (OSError, BrokenPipeError):
            pass

    def close(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        # close live client connections so their pending requests fail
        # fast instead of hanging out the full request timeout
        with self._conns_lock:
            conns = list(self._conns)
            self._conns.clear()
        for conn in conns:
            try:
                conn.close()
            except OSError:
                pass
