"""TransformerLM: the flagship decoder-only language model (GPT/Llama family).

Net-new versus the reference (its model zoo lives in torch userland; SURVEY.md
§2.4-2.5): this is a TPU-first implementation —

  - params are plain pytrees with LAYER-STACKED weights ([L, ...]) consumed by
    ``lax.scan``, so compile time is O(1) in depth and XLA pipelines the
    layer loop;
  - compute in bf16 (MXU), params and reductions in fp32;
  - attention is pluggable: "flash" (Pallas kernel, ops/flash_attention.py),
    "ref" (jnp), "ring"/"ulysses" (sequence parallel, ops/ring_attention.py);
  - the architecture knobs cover GPT-2 (LayerNorm+GELU, learned positions
    approximated by RoPE here) and Llama (RMSNorm+SwiGLU+RoPE+GQA) presets.

Sharding rules for these parameter names live in parallel/sharding.py.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32_000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: Optional[int] = None  # < n_heads => GQA
    d_ff: Optional[int] = None        # default: SwiGLU 8/3 * d_model
    max_seq: int = 2048
    rope_theta: float = 10_000.0
    dtype: Any = jnp.bfloat16         # activation/compute dtype (MXU)
    param_dtype: Any = jnp.float32
    attention: str = "auto"           # auto|flash|ref|ring|ulysses
    remat: bool = False               # jax.checkpoint each block

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads or self.n_heads

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def ff_dim(self) -> int:
        if self.d_ff is not None:
            return self.d_ff
        # SwiGLU sizing, rounded to 128 for MXU tiling
        d = int(self.d_model * 8 / 3)
        return (d + 127) // 128 * 128


# presets (sizes match the commonly-published configs)
PRESETS: Dict[str, TransformerConfig] = {
    "test": TransformerConfig(vocab_size=512, d_model=64, n_layers=2,
                              n_heads=4, max_seq=128),
    "gpt2-small": TransformerConfig(vocab_size=50_304, d_model=768,
                                    n_layers=12, n_heads=12, max_seq=1024),
    "gpt2-medium": TransformerConfig(vocab_size=50_304, d_model=1024,
                                     n_layers=24, n_heads=16, max_seq=1024),
    "llama-1b": TransformerConfig(vocab_size=32_000, d_model=2048,
                                  n_layers=16, n_heads=32, n_kv_heads=8,
                                  max_seq=2048),
    "llama-7b": TransformerConfig(vocab_size=32_000, d_model=4096,
                                  n_layers=32, n_heads=32, max_seq=2048),
}


def init_params(key, cfg: TransformerConfig) -> Dict[str, Any]:
    """Layer-stacked parameter pytree."""
    L, D, F = cfg.n_layers, cfg.d_model, cfg.ff_dim
    H, Hkv, Dh = cfg.n_heads, cfg.kv_heads, cfg.head_dim
    keys = jax.random.split(key, 8)
    pd = cfg.param_dtype

    def dense(k, shape, fan_in):
        return (jax.random.normal(k, shape, pd) * (fan_in ** -0.5))

    return {
        "tok_embed": dense(keys[0], (cfg.vocab_size, D), D),
        "layers": {
            "ln1": jnp.ones((L, D), pd),
            "ln2": jnp.ones((L, D), pd),
            "wq": dense(keys[1], (L, D, H * Dh), D),
            "wk": dense(keys[2], (L, D, Hkv * Dh), D),
            "wv": dense(keys[3], (L, D, Hkv * Dh), D),
            "wo": dense(keys[4], (L, H * Dh, D), H * Dh),
            "w1": dense(keys[5], (L, D, F), D),
            "w3": dense(keys[6], (L, D, F), D),
            "w2": dense(keys[7], (L, F, D), F),
        },
        "final_ln": jnp.ones((D,), pd),
        "lm_head": dense(keys[0], (D, cfg.vocab_size), D),
    }


def _rmsnorm(x, scale):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * lax.rsqrt(var + 1e-6).astype(x.dtype)) * scale.astype(x.dtype)


def _rope(x, positions, theta: float):
    """Rotary embeddings over [..., S, H, Dh]."""
    Dh = x.shape[-1]
    half = Dh // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions.astype(jnp.float32)[..., None] * freqs  # [..., S, half]
    cos = jnp.cos(angles)[..., None, :].astype(x.dtype)  # [..., S, 1, half]
    sin = jnp.sin(angles)[..., None, :].astype(x.dtype)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], -1)


def _attention(q, k, v, cfg: TransformerConfig, mesh, sp_axis):
    """Dispatch on the configured attention implementation. q/k/v are
    [B, H, S, Dh] (kv possibly fewer heads — repeated here for GQA)."""
    if cfg.kv_heads != cfg.n_heads:
        rep = cfg.n_heads // cfg.kv_heads
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    mode = cfg.attention
    if mode in ("ring", "ulysses"):
        from ..ops import ring_attention, ulysses_attention

        fn = ring_attention if mode == "ring" else ulysses_attention
        return fn(q, k, v, mesh, axis=sp_axis or "sp", causal=True)
    from ..ops import flash_attention, reference_attention

    if mode == "ref":
        return reference_attention(q, k, v, causal=True)
    use = None if mode == "auto" else "on"
    return flash_attention(q, k, v, causal=True, use_pallas=use)


def apply_block(x, layer, cfg: TransformerConfig, mesh=None, sp_axis=None):
    """One transformer block: x [B, S, D] + per-layer weight dict -> [B, S, D].
    Shapes derive from ``x`` so the same block serves the full forward and
    the pipeline-parallel schedule (parallel/pipeline.py), where the batch
    dimension is a microbatch slice."""
    B, S = x.shape[0], x.shape[1]
    H, Hkv, Dh = cfg.n_heads, cfg.kv_heads, cfg.head_dim
    positions = jnp.arange(S)[None, :]
    h = _rmsnorm(x, layer["ln1"])
    q = (h @ layer["wq"].astype(cfg.dtype)).reshape(B, S, H, Dh)
    k = (h @ layer["wk"].astype(cfg.dtype)).reshape(B, S, Hkv, Dh)
    v = (h @ layer["wv"].astype(cfg.dtype)).reshape(B, S, Hkv, Dh)
    q = _rope(q, positions, cfg.rope_theta).transpose(0, 2, 1, 3)
    k = _rope(k, positions, cfg.rope_theta).transpose(0, 2, 1, 3)
    v = v.transpose(0, 2, 1, 3)
    o = _attention(q, k, v, cfg, mesh, sp_axis)
    o = o.transpose(0, 2, 1, 3).reshape(B, S, H * Dh)
    x = x + o @ layer["wo"].astype(cfg.dtype)
    h = _rmsnorm(x, layer["ln2"])
    gate = jax.nn.silu(h @ layer["w1"].astype(cfg.dtype))
    up = h @ layer["w3"].astype(cfg.dtype)
    x = x + (gate * up) @ layer["w2"].astype(cfg.dtype)
    return x


def forward(params, tokens, cfg: TransformerConfig, mesh=None, sp_axis=None):
    """tokens [B, S] -> logits [B, S, V] (fp32)."""
    x = params["tok_embed"][tokens].astype(cfg.dtype)

    def block(x, layer):
        return apply_block(x, layer, cfg, mesh, sp_axis)

    block_fn = jax.checkpoint(block) if cfg.remat else block

    def scan_body(x, layer):
        return block_fn(x, layer), None

    x, _ = lax.scan(scan_body, x, params["layers"])
    x = _rmsnorm(x, params["final_ln"])
    # bf16 operands on the MXU, fp32 accumulation/output — fp32 operands
    # would run the largest matmul in the model at a fraction of MXU rate
    logits = lax.dot_general(
        x, params["lm_head"].astype(cfg.dtype),
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return logits


def loss_fn(params, batch, cfg: TransformerConfig, mesh=None, sp_axis=None):
    """batch: {"tokens": [B, S], "targets": [B, S]} -> mean xent.

    Fused form: mean(logsumexp(logits) - logits[target]) — never
    materialises log_softmax's [B, S, V] residual, which is the difference
    between fitting batch 16 and OOMing on a 16 GB chip."""
    logits = forward(params, batch["tokens"], cfg, mesh, sp_axis)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    take = jnp.take_along_axis(logits, batch["targets"][..., None],
                               axis=-1)[..., 0]
    return jnp.mean(lse - take)


def count_params(params) -> int:
    return sum(x.size for x in jax.tree.leaves(params))


def generate(params, cfg: TransformerConfig, prompt, steps: int,
             temperature: float = 0.0, key=None):
    """Greedy/sampled decoding by full-prefix recompute (a KV-cached decode
    path is a serving-layer optimization, later round). prompt: [B, S0]."""
    tokens = prompt
    for _ in range(steps):
        logits = forward(params, tokens, cfg)[:, -1]
        if temperature > 0:
            key, sub = jax.random.split(key)
            nxt = jax.random.categorical(sub, logits / temperature)
        else:
            nxt = jnp.argmax(logits, axis=-1)
        tokens = jnp.concatenate([tokens, nxt[:, None]], axis=1)
    return tokens
