"""TransformerLM: the flagship decoder-only language model (GPT/Llama family).

Net-new versus the reference (its model zoo lives in torch userland; SURVEY.md
§2.4-2.5): this is a TPU-first implementation —

  - params are plain pytrees with LAYER-STACKED weights ([L, ...]) consumed by
    ``lax.scan``, so compile time is O(1) in depth and XLA pipelines the
    layer loop;
  - compute in bf16 (MXU), params and reductions in fp32;
  - attention is pluggable: "flash" (Pallas kernel, ops/flash_attention.py),
    "ref" (jnp), "ring"/"ulysses" (sequence parallel, ops/ring_attention.py);
  - the architecture knobs cover GPT-2 (LayerNorm+GELU, learned positions
    approximated by RoPE here) and Llama (RMSNorm+SwiGLU+RoPE+GQA) presets.

Sharding rules for these parameter names live in parallel/sharding.py.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32_000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: Optional[int] = None  # < n_heads => GQA
    d_ff: Optional[int] = None        # default: SwiGLU 8/3 * d_model
    max_seq: int = 2048
    rope_theta: float = 10_000.0
    dtype: Any = jnp.bfloat16         # activation/compute dtype (MXU)
    param_dtype: Any = jnp.float32
    attention: str = "auto"           # auto|flash|ref|ring|ulysses
    remat: bool = False               # jax.checkpoint each block
    # layer-scan unroll factor: 1 compiles O(1) in depth; n_layers trades
    # compile time for a few % step time (XLA drops the scan-carry
    # dynamic-update-slice traffic when the loop is unrolled)
    scan_unroll: int = 1
    # Mixture-of-Experts FFN (ops/moe.py); 0 = dense MLP. Net-new vs the
    # reference (SURVEY.md §2.4: EP absent there).
    n_experts: int = 0
    expert_top_k: int = 2
    expert_capacity_factor: float = 1.25
    expert_group_size: int = 256      # tokens per dispatch group (GShard G)
    moe_aux_weight: float = 0.01      # load-balancing loss weight

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads or self.n_heads

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def ff_dim(self) -> int:
        if self.d_ff is not None:
            return self.d_ff
        # SwiGLU sizing, rounded to 128 for MXU tiling
        d = int(self.d_model * 8 / 3)
        return (d + 127) // 128 * 128


# presets (sizes match the commonly-published configs)
PRESETS: Dict[str, TransformerConfig] = {
    "test": TransformerConfig(vocab_size=512, d_model=64, n_layers=2,
                              n_heads=4, max_seq=128),
    "test-moe": TransformerConfig(vocab_size=512, d_model=64, n_layers=2,
                                  n_heads=4, max_seq=128, n_experts=4,
                                  expert_top_k=2),
    "mixtral-tiny": TransformerConfig(vocab_size=32_000, d_model=1024,
                                      n_layers=8, n_heads=16, n_kv_heads=4,
                                      max_seq=2048, n_experts=8,
                                      expert_top_k=2),
    "gpt2-small": TransformerConfig(vocab_size=50_304, d_model=768,
                                    n_layers=12, n_heads=12, max_seq=1024),
    "gpt2-medium": TransformerConfig(vocab_size=50_304, d_model=1024,
                                     n_layers=24, n_heads=16, max_seq=1024),
    "llama-1b": TransformerConfig(vocab_size=32_000, d_model=2048,
                                  n_layers=16, n_heads=32, n_kv_heads=8,
                                  max_seq=2048),
    "llama-7b": TransformerConfig(vocab_size=32_000, d_model=4096,
                                  n_layers=32, n_heads=32, max_seq=2048),
}


def init_params(key, cfg: TransformerConfig) -> Dict[str, Any]:
    """Layer-stacked parameter pytree."""
    L, D, F = cfg.n_layers, cfg.d_model, cfg.ff_dim
    H, Hkv, Dh = cfg.n_heads, cfg.kv_heads, cfg.head_dim
    keys = jax.random.split(key, 8)
    pd = cfg.param_dtype

    def dense(k, shape, fan_in):
        return (jax.random.normal(k, shape, pd) * (fan_in ** -0.5))

    layers = {
        "ln1": jnp.ones((L, D), pd),
        "ln2": jnp.ones((L, D), pd),
        "wq": dense(keys[1], (L, D, H * Dh), D),
        "wk": dense(keys[2], (L, D, Hkv * Dh), D),
        "wv": dense(keys[3], (L, D, Hkv * Dh), D),
        "wo": dense(keys[4], (L, H * Dh, D), H * Dh),
    }
    if cfg.n_experts > 0:
        from ..ops import moe

        layers.update(moe.init_moe_params(keys[5], L, D, F, cfg.n_experts,
                                          pd))
    else:
        layers.update({
            "w1": dense(keys[5], (L, D, F), D),
            "w3": dense(keys[6], (L, D, F), D),
            "w2": dense(keys[7], (L, F, D), F),
        })
    return {
        "tok_embed": dense(keys[0], (cfg.vocab_size, D), D),
        "layers": layers,
        "final_ln": jnp.ones((D,), pd),
        "lm_head": dense(keys[0], (D, cfg.vocab_size), D),
    }


def _rmsnorm(x, scale):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * lax.rsqrt(var + 1e-6).astype(x.dtype)) * scale.astype(x.dtype)


def _rope(x, positions, theta: float):
    """Rotary embeddings over [..., S, H, Dh]."""
    Dh = x.shape[-1]
    half = Dh // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions.astype(jnp.float32)[..., None] * freqs  # [..., S, half]
    cos = jnp.cos(angles)[..., None, :].astype(x.dtype)  # [..., S, 1, half]
    sin = jnp.sin(angles)[..., None, :].astype(x.dtype)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], -1)


def _attention(q, k, v, cfg: TransformerConfig, mesh, sp_axis):
    """Dispatch on the configured attention implementation. q/k/v are
    [B, H, S, Dh] (kv possibly fewer heads — repeated here for GQA)."""
    if cfg.kv_heads != cfg.n_heads:
        rep = cfg.n_heads // cfg.kv_heads
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    mode = cfg.attention
    if mode in ("ring", "ulysses"):
        from ..ops import ring_attention, ulysses_attention

        fn = ring_attention if mode == "ring" else ulysses_attention
        return fn(q, k, v, mesh, axis=sp_axis or "sp", causal=True)
    from ..ops import flash_attention, reference_attention

    if mode == "ref":
        return reference_attention(q, k, v, causal=True)
    use = None if mode == "auto" else "on"
    return flash_attention(q, k, v, causal=True, use_pallas=use)


def apply_block_with_aux(x, layer, cfg: TransformerConfig, mesh=None,
                         sp_axis=None, attn_fn=None, positions=None):
    """One transformer block; returns (x, attn_aux, moe_aux).

    Shapes derive from ``x`` so the same block serves the full forward, the
    pipeline-parallel schedule (parallel/pipeline.py), and the KV-cached
    decode path. ``attn_fn``, if given, replaces the standard attention
    middle: it takes post-rope q/k/v as [B, S, H(kv), Dh] and returns
    (o [B, S, H, Dh], attn_aux) — the cached decode uses this hook to
    read/update its cache without duplicating the block math. The FFN is
    dense or MoE (ops/moe.py) per cfg.n_experts; moe_aux is the layer's
    load-balancing loss (0.0 when dense)."""
    B, S = x.shape[0], x.shape[1]
    H, Hkv, Dh = cfg.n_heads, cfg.kv_heads, cfg.head_dim
    if positions is None:
        positions = jnp.arange(S)[None, :]
    h = _rmsnorm(x, layer["ln1"])
    q = (h @ layer["wq"].astype(cfg.dtype)).reshape(B, S, H, Dh)
    k = (h @ layer["wk"].astype(cfg.dtype)).reshape(B, S, Hkv, Dh)
    v = (h @ layer["wv"].astype(cfg.dtype)).reshape(B, S, Hkv, Dh)
    q = _rope(q, positions, cfg.rope_theta)
    k = _rope(k, positions, cfg.rope_theta)
    attn_aux = None
    if attn_fn is not None:
        o, attn_aux = attn_fn(q, k, v)
    else:
        o = _attention(q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
                       v.transpose(0, 2, 1, 3), cfg, mesh, sp_axis)
        o = o.transpose(0, 2, 1, 3)
    x = x + o.reshape(B, S, H * Dh) @ layer["wo"].astype(cfg.dtype)
    h = _rmsnorm(x, layer["ln2"])
    if cfg.n_experts > 0:
        from ..ops import moe

        y, moe_aux = moe.moe_ffn(h, layer, cfg, mesh)
        x = x + y
    else:
        gate = jax.nn.silu(h @ layer["w1"].astype(cfg.dtype))
        up = h @ layer["w3"].astype(cfg.dtype)
        x = x + (gate * up) @ layer["w2"].astype(cfg.dtype)
        moe_aux = jnp.float32(0.0)
    return x, attn_aux, moe_aux


def apply_block(x, layer, cfg: TransformerConfig, mesh=None, sp_axis=None,
                attn_fn=None, positions=None):
    """apply_block_with_aux with the historical contract: returns x, or
    (x, attn_aux) when attn_fn is given. MoE aux is dropped here — callers
    that train MoE configs (forward/loss_fn) use the _with_aux variant."""
    x, attn_aux, _ = apply_block_with_aux(x, layer, cfg, mesh, sp_axis,
                                          attn_fn, positions)
    if attn_fn is not None:
        return x, attn_aux
    return x


def forward_with_aux(params, tokens, cfg: TransformerConfig, mesh=None,
                     sp_axis=None):
    """tokens [B, S] -> (logits [B, S, V] fp32, aux scalar): the mean
    per-layer MoE load-balancing loss (0.0 for dense configs)."""
    x = params["tok_embed"][tokens].astype(cfg.dtype)

    def block(x, layer):
        x, _, moe_aux = apply_block_with_aux(x, layer, cfg, mesh, sp_axis)
        return x, moe_aux

    block_fn = jax.checkpoint(block) if cfg.remat else block

    def scan_body(x, layer):
        return block_fn(x, layer)

    x, aux = lax.scan(scan_body, x, params["layers"],
                      unroll=min(cfg.scan_unroll, cfg.n_layers))
    x = _rmsnorm(x, params["final_ln"])
    # bf16 operands on the MXU, fp32 accumulation/output — fp32 operands
    # would run the largest matmul in the model at a fraction of MXU rate
    logits = lax.dot_general(
        x, params["lm_head"].astype(cfg.dtype),
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return logits, jnp.mean(aux)


def forward(params, tokens, cfg: TransformerConfig, mesh=None, sp_axis=None):
    """tokens [B, S] -> logits [B, S, V] (fp32)."""
    return forward_with_aux(params, tokens, cfg, mesh, sp_axis)[0]


def loss_fn(params, batch, cfg: TransformerConfig, mesh=None, sp_axis=None):
    """batch: {"tokens": [B, S], "targets": [B, S]} -> mean xent (+ the
    MoE load-balancing aux, weighted, for expert configs).

    Fused form: mean(logsumexp(logits) - logits[target]) — never
    materialises log_softmax's [B, S, V] residual, which is the difference
    between fitting batch 16 and OOMing on a 16 GB chip."""
    logits, aux = forward_with_aux(params, batch["tokens"], cfg, mesh,
                                   sp_axis)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    take = jnp.take_along_axis(logits, batch["targets"][..., None],
                               axis=-1)[..., 0]
    xent = jnp.mean(lse - take)
    if cfg.n_experts > 0:
        xent = xent + cfg.moe_aux_weight * aux
    return xent


def count_params(params) -> int:
    return sum(x.size for x in jax.tree.leaves(params))


# ------------------------------------------------------------ cached decode
def init_kv_cache(cfg: TransformerConfig, batch: int, max_len: int):
    """Static-shape per-layer KV cache: {"k","v"} of [L, B, Hkv, max_len, Dh].
    Cache dtype = activation dtype (bf16 on TPU: halves HBM traffic on the
    decode-bound attention reads)."""
    L, Hkv, Dh = cfg.n_layers, cfg.kv_heads, cfg.head_dim
    shape = (L, batch, Hkv, max_len, Dh)
    return {"k": jnp.zeros(shape, cfg.dtype), "v": jnp.zeros(shape, cfg.dtype)}


def forward_with_cache(params, tokens, cache, offset, cfg: TransformerConfig):
    """Incremental forward: run ``tokens`` [B, S] which occupy absolute
    positions [offset, offset+S), reading/writing the KV cache.

    Serves both prefill (S = prompt length, offset 0) and decode (S = 1)
    with STATIC shapes — ``offset`` is a traced scalar, so one compiled
    program covers every decode step (no per-position recompile, no O(S^2)
    prefix recompute per token — the weakness VERDICT r1 flagged in the
    old generate()). Returns (logits [B, S, V] fp32, updated cache).
    """
    B, S = tokens.shape
    H, Hkv, Dh = cfg.n_heads, cfg.kv_heads, cfg.head_dim
    T = cache["k"].shape[3]
    x = params["tok_embed"][tokens].astype(cfg.dtype)
    positions = offset + jnp.arange(S)[None, :]         # [1, S]
    key_pos = jnp.arange(T)                             # [T]
    # causal-vs-cache mask: query at absolute pos p sees key slots <= p
    mask = key_pos[None, :] <= positions[0][:, None]    # [S, T]

    def scan_body(x, layer_and_cache):
        layer, k_cache, v_cache = layer_and_cache

        def cached_attn(q, k, v):
            # write the new keys/values at [offset, offset+S), then attend
            # over the whole (masked) cache
            kc = lax.dynamic_update_slice(
                k_cache, k.transpose(0, 2, 1, 3), (0, 0, offset, 0))
            vc = lax.dynamic_update_slice(
                v_cache, v.transpose(0, 2, 1, 3), (0, 0, offset, 0))
            kk, vv = kc, vc                             # [B, Hkv, T, Dh]
            if Hkv != H:
                rep = H // Hkv
                kk = jnp.repeat(kk, rep, axis=1)
                vv = jnp.repeat(vv, rep, axis=1)
            qh = q.transpose(0, 2, 1, 3)                # [B, H, S, Dh]
            scores = jnp.einsum(
                "bhsd,bhtd->bhst", qh, kk,
                preferred_element_type=jnp.float32) * (Dh ** -0.5)
            scores = jnp.where(mask[None, None], scores, -jnp.inf)
            probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
            o = jnp.einsum("bhst,bhtd->bhsd", probs, vv)
            return o.transpose(0, 2, 1, 3), (kc, vc)

        x, (kc, vc) = apply_block(x, layer, cfg, attn_fn=cached_attn,
                                  positions=positions)
        return x, (kc, vc)

    x, (k_new, v_new) = lax.scan(
        scan_body, x, (params["layers"], cache["k"], cache["v"]))
    x = _rmsnorm(x, params["final_ln"])
    logits = lax.dot_general(
        x, params["lm_head"].astype(cfg.dtype),
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return logits, {"k": k_new, "v": v_new}


def forward_with_cache_rows(params, tokens, cache, offsets,
                            cfg: TransformerConfig):
    """Incremental forward with PER-ROW positions: row ``i`` of ``tokens``
    [B, S] occupies absolute positions [offsets[i], offsets[i]+S) of its
    cache row. This is the kernel continuous batching needs — rows of one
    decode batch sit at different sequence depths (one request is 900
    tokens in, its neighbor just prefilled) — and it is also the exact
    fix for the padded-batch approximation: each row attends only to its
    own true history (mask per row), with rope/positional phases taken
    from its own offset. Returns (logits [B, S, V] fp32, updated cache).
    """
    B, S = tokens.shape
    H, Hkv, Dh = cfg.n_heads, cfg.kv_heads, cfg.head_dim
    T = cache["k"].shape[3]
    x = params["tok_embed"][tokens].astype(cfg.dtype)
    positions = offsets[:, None] + jnp.arange(S)[None, :]     # [B, S]
    key_pos = jnp.arange(T)                                   # [T]
    # per-row causal-vs-cache mask: row i's query at absolute pos p sees
    # key slots <= p of row i's cache only
    mask = key_pos[None, None, :] <= positions[:, :, None]    # [B, S, T]

    def scan_body(x, layer_and_cache):
        layer, k_cache, v_cache = layer_and_cache

        def cached_attn(q, k, v):
            kt = k.transpose(0, 2, 1, 3)                      # [B,Hkv,S,Dh]
            vt = v.transpose(0, 2, 1, 3)
            write = jax.vmap(
                lambda c, u, o: lax.dynamic_update_slice(c, u, (0, o, 0)))
            kc = write(k_cache, kt, offsets)
            vc = write(v_cache, vt, offsets)
            kk, vv = kc, vc
            if Hkv != H:
                rep = H // Hkv
                kk = jnp.repeat(kk, rep, axis=1)
                vv = jnp.repeat(vv, rep, axis=1)
            qh = q.transpose(0, 2, 1, 3)                      # [B, H, S, Dh]
            scores = jnp.einsum(
                "bhsd,bhtd->bhst", qh, kk,
                preferred_element_type=jnp.float32) * (Dh ** -0.5)
            scores = jnp.where(mask[:, None], scores, -jnp.inf)
            probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
            o = jnp.einsum("bhst,bhtd->bhsd", probs, vv)
            return o.transpose(0, 2, 1, 3), (kc, vc)

        x, (kc, vc) = apply_block(x, layer, cfg, attn_fn=cached_attn,
                                  positions=positions)
        return x, (kc, vc)

    x, (k_new, v_new) = lax.scan(
        scan_body, x, (params["layers"], cache["k"], cache["v"]))
    x = _rmsnorm(x, params["final_ln"])
    logits = lax.dot_general(
        x, params["lm_head"].astype(cfg.dtype),
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return logits, {"k": k_new, "v": v_new}


import functools


@functools.lru_cache(maxsize=32)
def _decode_program(cfg: TransformerConfig, temperature: float, steps: int):
    """Compile-once decode program, cached per (cfg, temperature, steps) —
    a serving loop calling generate() per request must NOT re-trace (jit
    caches key on the callable, so a closure built inside generate() would
    recompile every call)."""

    def run(params, prompt, key):
        B, S0 = prompt.shape
        cache = init_kv_cache(cfg, B, S0 + steps)
        logits, cache = forward_with_cache(params, prompt, cache, 0, cfg)
        last = logits[:, -1]

        def pick(logits, k):
            if temperature > 0:
                return jax.random.categorical(k, logits / temperature)
            return jnp.argmax(logits, axis=-1)

        def step(carry, i):
            cache, last_logits, key = carry
            key, sub = jax.random.split(key)
            nxt = pick(last_logits, sub)
            logits, cache = forward_with_cache(
                params, nxt[:, None], cache, S0 + i, cfg)
            return (cache, logits[:, -1], key), nxt

        (_, _, _), toks = lax.scan(
            step, (cache, last, key), jnp.arange(steps))
        return toks.T  # [B, steps]

    return jax.jit(run)


def generate(params, cfg: TransformerConfig, prompt, steps: int,
             temperature: float = 0.0, key=None):
    """KV-cached decoding: one prefill pass over the prompt, then a
    ``lax.scan`` of single-token steps against the cache — O(S) attention
    per new token and ONE compiled program for the whole decode, reused
    across calls with the same shapes (serving-friendly).
    prompt: [B, S0] -> [B, S0+steps]."""
    if key is None:
        key = jax.random.PRNGKey(0)
    new_tokens = _decode_program(cfg, float(temperature), int(steps))(
        params, prompt, key)
    return jnp.concatenate([prompt, new_tokens], axis=1)
