"""ResNet-50 (flax.linen) — the vision benchmark model.

The reference's ResNet-50 story is "torchvision inside the user's Train loop"
(BASELINE.json: ResNet-50 DDP images/sec target). Here it is a first-class
jax model: bf16 conv compute (MXU), fp32 BatchNorm statistics, NHWC layout
(TPU-native), trained data-parallel via parallel/sharding.py presets.
"""

from __future__ import annotations

import functools
from typing import Any, Sequence, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn


class Bottleneck(nn.Module):
    features: int
    strides: Tuple[int, int] = (1, 1)
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = functools.partial(
            nn.BatchNorm, use_running_average=not train, momentum=0.9,
            epsilon=1e-5, dtype=jnp.float32,
        )
        residual = x
        y = conv(self.features, (1, 1))(x)
        y = nn.relu(norm()(y))
        y = conv(self.features, (3, 3), self.strides)(y)
        y = nn.relu(norm()(y))
        y = conv(self.features * 4, (1, 1))(y)
        y = norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(self.features * 4, (1, 1), self.strides,
                            name="proj")(residual)
            residual = norm(name="proj_bn")(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    stage_sizes: Sequence[int] = (3, 4, 6, 3)  # ResNet-50
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        x = nn.Conv(64, (7, 7), (2, 2), padding=[(3, 3), (3, 3)],
                    use_bias=False, dtype=self.dtype)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-5, dtype=jnp.float32)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = Bottleneck(64 * 2 ** i, strides, self.dtype)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x


def resnet50(num_classes: int = 1000) -> ResNet:
    return ResNet((3, 4, 6, 3), num_classes)


def resnet18_like(num_classes: int = 10) -> ResNet:
    """Small variant for tests."""
    return ResNet((1, 1, 1, 1), num_classes)


def init_resnet(model: ResNet, key, image_shape=(224, 224, 3)):
    variables = model.init(key, jnp.zeros((1, *image_shape), jnp.float32),
                           train=False)
    return variables["params"], variables["batch_stats"]


def resnet_loss_fn(model: ResNet, params, batch_stats, batch):
    """Cross-entropy over {"image": [B,H,W,C], "label": [B]}; returns
    (loss, new_batch_stats) — BatchNorm stats thread through as mutable
    state, the flax idiom."""
    logits, updates = model.apply(
        {"params": params, "batch_stats": batch_stats},
        batch["image"], train=True, mutable=["batch_stats"],
    )
    logp = jax.nn.log_softmax(logits)
    loss = -jnp.mean(
        jnp.take_along_axis(logp, batch["label"][:, None], axis=-1)
    )
    return loss, updates["batch_stats"]


def make_resnet_train_step(model: ResNet, optimizer, mesh=None):
    """DP train step; with a mesh, the batch shards over data axes and XLA
    cross-replica-sums BatchNorm grads like any other grad (per-shard BN
    statistics — the standard/fast choice, matching torch DDP defaults)."""
    from jax.sharding import NamedSharding

    from ..parallel.sharding import batch_pspec

    def step(params, batch_stats, opt_state, batch):
        if mesh is not None:
            batch = jax.lax.with_sharding_constraint(
                batch, NamedSharding(mesh, batch_pspec(mesh))
            )
        (loss, new_stats), grads = jax.value_and_grad(
            lambda p: resnet_loss_fn(model, p, batch_stats, batch),
            has_aux=True,
        )(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = jax.tree.map(lambda p, u: p + u, params, updates)
        return params, new_stats, opt_state, loss

    return jax.jit(step, donate_argnums=(0, 1, 2))
