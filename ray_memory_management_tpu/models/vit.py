"""Vision Transformer (flax.linen) — the second vision family.

The reference's vision story runs torchvision models inside user Train
loops; ViT here is first-class and TPU-shaped like resnet.py: bf16
matmul compute on the MXU with fp32 LayerNorm statistics and the fp32
classifier head, patchify as a single strided conv (one big matmul per
image rather than a gather), learned position embeddings, pre-norm
encoder blocks (Dosovitskiy et al. 2020). Attention here is
bidirectional over ~200 patch tokens, so the jnp path XLA fuses is the
right tool (the Pallas flash kernel in ops/ pays off at the long CAUSAL
sequences the LM path runs, not at S~200 dense).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    d_model: int = 384
    n_layers: int = 12
    n_heads: int = 6
    mlp_ratio: int = 4
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2


PRESETS: Dict[str, ViTConfig] = {
    # standard model card sizes (ViT-S/16, ViT-B/16)
    "vit-s16": ViTConfig(d_model=384, n_layers=12, n_heads=6),
    "vit-b16": ViTConfig(d_model=768, n_layers=12, n_heads=12),
    # CI-scale: 32x32 inputs, a few layers
    "vit-tiny-test": ViTConfig(image_size=32, patch_size=8, d_model=64,
                               n_layers=2, n_heads=4, num_classes=10),
}


class EncoderBlock(nn.Module):
    cfg: ViTConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        # pre-norm attention (fp32 LN stats, bf16 matmuls)
        y = nn.LayerNorm(dtype=jnp.float32)(x)
        y = nn.SelfAttention(
            num_heads=cfg.n_heads, dtype=cfg.dtype,
            deterministic=True)(y)
        x = x + y
        y = nn.LayerNorm(dtype=jnp.float32)(x)
        y = nn.Dense(cfg.d_model * cfg.mlp_ratio, dtype=cfg.dtype)(y)
        y = nn.gelu(y)
        y = nn.Dense(cfg.d_model, dtype=cfg.dtype)(y)
        return x + y


class ViT(nn.Module):
    cfg: ViTConfig

    @nn.compact
    def __call__(self, images):
        cfg = self.cfg
        B = images.shape[0]
        x = images.astype(cfg.dtype)
        # patchify = one strided conv: [B, H, W, C] -> [B, P, d_model]
        x = nn.Conv(cfg.d_model, (cfg.patch_size, cfg.patch_size),
                    strides=(cfg.patch_size, cfg.patch_size),
                    dtype=cfg.dtype, name="patch_embed")(x)
        x = x.reshape(B, -1, cfg.d_model)
        cls = self.param("cls_token", nn.initializers.zeros,
                         (1, 1, cfg.d_model))
        x = jnp.concatenate(
            [jnp.broadcast_to(cls, (B, 1, cfg.d_model)).astype(cfg.dtype),
             x], axis=1)
        pos = self.param("pos_embed",
                         nn.initializers.normal(stddev=0.02),
                         (1, cfg.n_patches + 1, cfg.d_model))
        x = x + pos.astype(cfg.dtype)
        for i in range(cfg.n_layers):
            x = EncoderBlock(cfg, name=f"block_{i}")(x)
        x = nn.LayerNorm(dtype=jnp.float32)(x)
        # classify from the CLS token; head stays fp32 for stable logits
        return nn.Dense(cfg.num_classes, dtype=jnp.float32,
                        name="head")(x[:, 0].astype(jnp.float32))


def init_vit(cfg: ViTConfig, key) -> Any:
    model = ViT(cfg)
    images = jnp.zeros((1, cfg.image_size, cfg.image_size, 3), jnp.float32)
    return model, model.init(key, images)["params"]


def vit_loss_fn(model: ViT, params, batch) -> jnp.ndarray:
    logits = model.apply({"params": params}, batch["image"])
    labels = jax.nn.one_hot(batch["label"], logits.shape[-1])
    return -jnp.mean(jnp.sum(labels * jax.nn.log_softmax(logits), axis=-1))


def make_vit_train_step(model: ViT, optimizer, mesh=None):
    """One jit'd fwd+bwd+update. With a mesh, the batch is constrained
    onto the data axes (parallel/sharding.py's batch_pspec — the resnet
    path's dp recipe); params/opt-state are donated so training state is
    updated in place rather than double-buffered."""
    import optax

    def step(params, opt_state, batch):
        if mesh is not None:
            from jax.sharding import NamedSharding

            from ..parallel.sharding import batch_pspec

            batch = jax.lax.with_sharding_constraint(
                batch, NamedSharding(mesh, batch_pspec(mesh)))
        loss, grads = jax.value_and_grad(
            lambda p: vit_loss_fn(model, p, batch))(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return jax.jit(step, donate_argnums=(0, 1))


# same tree-leaves sum the LM family exposes — one implementation
from .gpt import count_params  # noqa: E402,F401
