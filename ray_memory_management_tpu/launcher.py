"""Cluster launcher: ``rmt up / down / exec / submit`` over a cluster YAML.

The reference's launcher (python/ray/autoscaler/_private/commands.py behind
``ray up/down/attach/exec``, scripts.py:1165-1623) provisions cloud nodes,
then boots a head and workers over SSH. Here the same lifecycle targets a
TPU-pod-like fleet:

  - the HEAD is a detached ``rmt head`` process: an rmt runtime + thin-client
    server (client/server.py) + the node-agent TCP listener;
  - WORKERS are node agents (core/node_agent.py) joined to the head, one per
    host, launched through a NodeProvider;
  - providers: ``subprocess`` (this host — the fake_multi_node analog used
    by tests and single-host pods) and ``ssh`` (one agent per remote host,
    the reference's command-runner path; exercised in tests by overriding
    the ssh binary).

Cluster state (head pid, ports, worker pids) persists in
``~/.rmt/clusters/<name>.json`` so ``down``/``exec`` find the cluster the
way the reference keeps cluster state under ``~/.ray``.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional

STATE_DIR = os.path.expanduser("~/.rmt/clusters")

# the package's parent dir: launched daemons and exec'd client scripts must
# import this package regardless of their cwd/script dir (the reference gets
# this for free from pip-installed ray; here the checkout is the install)
_PKG_PARENT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _with_pkg_path(env: Dict[str, str]) -> Dict[str, str]:
    parts = [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p]
    if _PKG_PARENT not in parts:
        env["PYTHONPATH"] = os.pathsep.join([_PKG_PARENT] + parts)
    return env


# ------------------------------------------------------------------ config
def load_cluster_config(path: str) -> Dict[str, Any]:
    import yaml

    with open(path) as f:
        cfg = yaml.safe_load(f)
    cfg.setdefault("cluster_name", "default")
    cfg.setdefault("provider", {"type": "subprocess"})
    cfg.setdefault("head", {})
    cfg.setdefault("workers", [])
    return cfg


def _state_path(name: str) -> str:
    os.makedirs(STATE_DIR, exist_ok=True)
    return os.path.join(STATE_DIR, f"{name}.json")


def load_state(name: str) -> Optional[Dict[str, Any]]:
    try:
        with open(_state_path(name)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def save_state(name: str, state: Dict[str, Any]) -> None:
    """Persist cluster state. Worker records are live objects that
    provider threads mutate and may carry runtime-only fields (the GCE
    provider's "_mu" lock, which json.dump would crash on): persist a
    snapshot of the JSON-safe public fields only — ``down`` needs
    name/kind/pid, and terminate_worker treats a missing "_mu" as
    "loaded from disk"."""

    def _public(rec: Dict[str, Any]) -> Dict[str, Any]:
        mu = rec.get("_mu")
        if mu is not None:
            with mu:
                items = list(rec.items())
        else:
            items = list(rec.items())
        return {k: v for k, v in items
                if not k.startswith("_")
                and isinstance(v, (str, int, float, bool, type(None)))}

    snapshot = dict(state)
    if isinstance(snapshot.get("workers"), list):
        snapshot["workers"] = [
            _public(w) if isinstance(w, dict) else w
            for w in snapshot["workers"]]
    with open(_state_path(name), "w") as f:
        json.dump(snapshot, f, indent=2)


# ---------------------------------------------------------------- providers
class NodeProvider:
    """Launches one node agent per worker entry (the reference's
    NodeProvider + command-runner pair, autoscaler/_private/*)."""

    def launch_worker(self, spec: Dict[str, Any], head_addr: str,
                      authkey_hex: str) -> Dict[str, Any]:
        raise NotImplementedError

    def terminate_worker(self, record: Dict[str, Any]) -> None:
        raise NotImplementedError


class SubprocessProvider(NodeProvider):
    """Workers as local agent processes — the fake_multi_node analog
    (autoscaler/_private/fake_multi_node) and the single-host-pod case."""

    def __init__(self, log_dir: str = ""):
        self.log_dir = log_dir
        self._count = 0

    def launch_worker(self, spec, head_addr, authkey_hex):
        self._count += 1
        log = _daemon_log(self.log_dir, f"worker-{self._count}")
        proc = subprocess.Popen(
            [sys.executable, "-m",
             "ray_memory_management_tpu.core.node_agent",
             "--address", head_addr, "--authkey", authkey_hex,
             "--num-cpus", str(spec.get("num_cpus", 4)),
             "--num-tpus", str(spec.get("num_tpus", 0))],
            env=_with_pkg_path(dict(os.environ)), close_fds=True, **log,
        )
        return {"kind": "subprocess", "pid": proc.pid}

    def terminate_worker(self, record):
        try:
            os.kill(record["pid"], signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            pass


class SSHProvider(NodeProvider):
    """One agent per remote host over ssh (the command-runner path,
    autoscaler/_private/command_runner.py). The ssh binary is
    configurable so tests can substitute a local shim."""

    def __init__(self, provider_cfg: Dict[str, Any], log_dir: str = ""):
        self.ssh = provider_cfg.get("ssh_command", "ssh")
        self.user = provider_cfg.get("ssh_user", "")
        self.opts = provider_cfg.get("ssh_options",
                                     ["-o", "StrictHostKeyChecking=no"])
        self.python = provider_cfg.get("remote_python", "python3")
        self.log_dir = log_dir

    def launch_worker(self, spec, head_addr, authkey_hex):
        host = spec["host"]
        target = f"{self.user}@{host}" if self.user else host
        remote_cmd = (
            f"{self.python} -m ray_memory_management_tpu.core.node_agent "
            f"--address {head_addr} --authkey {authkey_hex} "
            f"--num-cpus {spec.get('num_cpus', 4)} "
            f"--num-tpus {spec.get('num_tpus', 0)}"
        )
        proc = subprocess.Popen([self.ssh, *self.opts, target, remote_cmd],
                                close_fds=True,
                                **_daemon_log(self.log_dir, f"ssh-{host}"))
        return {"kind": "ssh", "pid": proc.pid, "host": host}

    def terminate_worker(self, record):
        # killing the local ssh client drops the channel; the agent exits
        # on channel EOF (its run loop returns when the head/pipe is gone)
        try:
            os.kill(record["pid"], signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            pass


class GCETPUProvider(NodeProvider):
    """GCE TPU-VM nodes through the ``gcloud`` CLI (the reference's GCP
    node provider, autoscaler/_private/gcp/node_provider.py, recast for
    TPU VMs: ``gcloud compute tpus tpu-vm create/ssh/delete``).

    Worker spec fields: ``name`` (instance name; generated if absent),
    ``accelerator_type`` (e.g. v5litepod-8), ``version`` (runtime image),
    plus the usual num_cpus/num_tpus. Provider config: ``project``,
    ``zone``, optional ``gcloud_command`` (tests substitute a recording
    shim), ``remote_python``, ``bootstrap`` (shell prefix run before
    the agent, e.g. a pip install of this package), ``create_retries``
    (default 3) and ``create_retry_wait_s`` (default 30, doubled per
    attempt) for transient create failures. For multi-host pods
    the agent starts on EVERY host (``--worker=all``) — each host joins
    the head as its own node, which is exactly the one-agent-per-host
    model the multi-host plane expects."""

    # stderr markers of transient gcloud failures worth retrying: capacity
    # stockouts, quota/rate limiting, and service-side flakiness. Anything
    # else (auth, bad flags, permission) fails fast into record["error"].
    # Phrases, not bare substrings: operation ids / request URLs embed
    # arbitrary digits, so bare "429"/"503" would misclassify permanent
    # errors (HTTP codes are matched word-bounded in _retryable).
    _RETRYABLE = ("RESOURCE_EXHAUSTED", "ZONE_RESOURCE_POOL_EXHAUSTED",
                  "QUOTA EXCEEDED", "QUOTA_EXCEEDED", "RATE_LIMIT",
                  "RATE LIMIT", "UNAVAILABLE", "INTERNAL ERROR",
                  "DEADLINE_EXCEEDED", "TRY AGAIN")

    def __init__(self, provider_cfg: Dict[str, Any], log_dir: str = ""):
        import itertools

        self.gcloud = provider_cfg.get("gcloud_command", "gcloud")
        self.project = provider_cfg.get("project", "")
        self.zone = provider_cfg.get("zone", "")
        self.python = provider_cfg.get("remote_python", "python3")
        self.bootstrap = provider_cfg.get("bootstrap", "")
        self.create_retries = int(provider_cfg.get("create_retries", 3))
        self.create_retry_wait_s = float(
            provider_cfg.get("create_retry_wait_s", 30.0))
        self.log_dir = log_dir
        self._counter = itertools.count(1)  # thread-safe (CPython atomic)

    @classmethod
    def _retryable(cls, stderr: str) -> bool:
        import re

        up = stderr.upper()
        if any(marker in up for marker in cls._RETRYABLE):
            return True
        return re.search(r"\b(429|503)\b", up) is not None

    def _scope(self) -> List[str]:
        out = []
        if self.project:
            out += ["--project", self.project]
        if self.zone:
            out += ["--zone", self.zone]
        return out

    def _wait_ready(self, name: str, record,
                    timeout_s: float = 900.0) -> str:
        """Poll ``describe`` until the TPU VM reports READY (used when a
        create was adopted via ALREADY_EXISTS and the server-side
        operation may still be provisioning). Returns "ready",
        "cancelled" (terminate_worker ran — the caller must fall through
        to its cancelled-cleanup delete, not bail out before it), or
        "failed" (record["error"] set)."""
        deadline = time.monotonic() + timeout_s
        describe = [self.gcloud, "compute", "tpus", "tpu-vm", "describe",
                    name, *self._scope(), "--format", "value(state)"]
        consecutive_failures = 0
        while time.monotonic() < deadline:
            with record["_mu"]:
                if record["cancelled"]:
                    return "cancelled"
            try:
                rc = subprocess.run(describe, capture_output=True,
                                    text=True, timeout=120)
            except Exception:  # noqa: BLE001 - transient describe flake
                consecutive_failures += 1
                if consecutive_failures >= 6:
                    record["error"] = (f"describe {name} kept "
                                       "failing/hanging")
                    return "failed"
                time.sleep(10)
                continue
            if rc.returncode != 0:
                err = rc.stderr.strip()
                up = err.upper()
                # a gone VM or dead credentials will never turn READY:
                # fail fast instead of burning the full timeout
                if "NOT_FOUND" in up or "PERMISSION" in up or \
                        "UNAUTHENTICATED" in up:
                    record["error"] = (f"describe {name} failed: "
                                       + err[-400:])
                    return "failed"
                consecutive_failures += 1
                if consecutive_failures >= 6:
                    record["error"] = (f"describe {name} kept failing: "
                                       + err[-400:])
                    return "failed"
                time.sleep(10)
                continue
            consecutive_failures = 0
            state = rc.stdout.strip().upper()
            if state == "READY":
                return "ready"
            if state in ("TERMINATED", "PREEMPTED", "DELETING"):
                record["error"] = f"vm {name} entered state {state}"
                return "failed"
            time.sleep(10)
        record["error"] = (f"vm {name} not READY after {timeout_s:.0f}s "
                           "(adopted via ALREADY_EXISTS)")
        return "failed"

    def launch_worker(self, spec, head_addr, authkey_hex):
        import threading

        name = spec.get("name", f"rmt-worker-{next(self._counter)}")
        create = [
            self.gcloud, "compute", "tpus", "tpu-vm", "create", name,
            *self._scope(),
            "--accelerator-type", spec.get("accelerator_type",
                                           "v5litepod-8"),
            "--version", spec.get("version", "tpu-ubuntu2204-base"),
        ]
        agent_cmd = (
            f"{self.python} -m ray_memory_management_tpu.core.node_agent "
            f"--address {head_addr} --authkey {authkey_hex} "
            f"--num-cpus {spec.get('num_cpus', 4)} "
            f"--num-tpus {spec.get('num_tpus', 0)}"
        )
        if self.bootstrap:
            agent_cmd = f"{self.bootstrap} && {agent_cmd}"
        ssh = [
            self.gcloud, "compute", "tpus", "tpu-vm", "ssh", name,
            *self._scope(), "--worker=all", "--command", agent_cmd,
        ]
        # _mu makes terminate-vs-provision atomic: the delete can run while
        # the up-to-30-minute create is still in flight, and without the
        # cancelled check the late-finishing provision would spawn the ssh
        # agent anyway (pid was None at kill time), leaving an orphan agent
        # dialing the head against a deleted VM
        record = {"kind": "gce-tpu", "pid": None, "name": name,
                  "error": None, "cancelled": False,
                  "_mu": threading.Lock()}

        def provision():
            # create takes MINUTES per TPU VM: run it off the caller so a
            # multi-worker `up` provisions the whole pod concurrently
            # (nodes join the head as their agents come up). Transient
            # failures — capacity stockouts, quota/rate limits, service
            # flakiness, hung creates — retry with exponential backoff;
            # everything else fails fast into record["error"].
            for attempt in range(self.create_retries + 1):
                with record["_mu"]:
                    if record["cancelled"]:
                        return  # terminated before we created anything
                try:
                    rc = subprocess.run(create, capture_output=True,
                                        text=True, timeout=1800)
                except subprocess.TimeoutExpired:
                    # a hung create is the same transient condition as a
                    # server-reported timeout: retry it
                    if attempt < self.create_retries:
                        time.sleep(
                            self.create_retry_wait_s * (2 ** attempt))
                        continue
                    record["error"] = "create timed out after retries"
                    return
                except Exception as e:  # noqa: BLE001
                    record["error"] = f"create failed: {e!r}"
                    return
                if rc.returncode == 0:
                    break
                err = rc.stderr.strip()
                if attempt > 0 and "ALREADY_EXISTS" in err.upper():
                    # an earlier "failed" attempt actually landed server-
                    # side (the classic ambiguous 503-after-accept): the
                    # VM exists, so proceed to ssh — failing here would
                    # leave a billed VM running that nothing tracks or
                    # deletes. The server-side create may still be
                    # mid-provision (the timed-out attempt's operation
                    # keeps running), and ssh is one-shot: wait for READY
                    # first or the agent launch fails with no retry.
                    status = self._wait_ready(name, record)
                    if status == "failed":
                        # error recorded; the VM stays in cluster state so
                        # `rmt down` still deletes it
                        return
                    # "ready" falls through to ssh; "cancelled" falls
                    # through to the post-loop cancelled check, which
                    # skips ssh and runs the cleanup delete
                    break
                if attempt < self.create_retries and self._retryable(err):
                    time.sleep(self.create_retry_wait_s * (2 ** attempt))
                    continue
                record["error"] = err[-500:]
                return
            with record["_mu"]:
                cancelled = record["cancelled"]
                if not cancelled:
                    proc = subprocess.Popen(
                        ssh, close_fds=True,
                        **_daemon_log(self.log_dir, f"gce-{name}"))
                    record["pid"] = proc.pid
            if cancelled:
                # terminate_worker already ran — its delete hit a VM that
                # didn't exist yet, so the create we just finished made a
                # fresh (billed) VM nobody else will clean up: delete it
                # here, outside the lock, and RECORD any failure (a billed
                # VM silently leaking is the worst outcome)
                try:
                    rc = subprocess.run(
                        [self.gcloud, "compute", "tpus", "tpu-vm",
                         "delete", name, *self._scope(), "--quiet"],
                        capture_output=True, text=True, timeout=1800)
                    # terminate_worker may have won the race and deleted
                    # the VM itself — a not-found delete is a success, not
                    # a leak
                    if rc.returncode != 0 and "not found" not in \
                            rc.stderr.lower():
                        record["error"] = ("cleanup delete failed — VM "
                                           f"{name} may be leaked: "
                                           + rc.stderr.strip()[-400:])
                except Exception as e:  # noqa: BLE001
                    record["error"] = ("cleanup delete failed — VM "
                                       f"{name} may be leaked: {e!r}")

        threading.Thread(target=provision, daemon=True,
                         name=f"gce-up-{name}").start()
        return record

    def terminate_worker(self, record):
        mu = record.get("_mu")
        if mu is not None:
            with mu:
                record["cancelled"] = True
                pid = record.get("pid")
        else:
            pid = record.get("pid")
        if pid:
            try:
                os.kill(pid, signal.SIGTERM)  # drop the ssh channel
            except (ProcessLookupError, PermissionError):
                pass
        subprocess.run(
            [self.gcloud, "compute", "tpus", "tpu-vm", "delete",
             record["name"], *self._scope(), "--quiet"],
            capture_output=True, text=True, timeout=1800)


def _daemon_log(log_dir: str, tag: str) -> Dict[str, Any]:
    """Popen kwargs detaching a daemon's stdio from the caller: inheriting
    the CLI's pipes would keep e.g. ``subprocess.run(capture_output=True)``
    callers blocked on pipe EOF for as long as the daemon lives. Output
    goes to a log file when a log_dir is known (the reference keeps head /
    raylet logs under the session dir), else /dev/null."""
    if not log_dir:
        return {"stdin": subprocess.DEVNULL, "stdout": subprocess.DEVNULL,
                "stderr": subprocess.DEVNULL}
    os.makedirs(log_dir, exist_ok=True)
    f = open(os.path.join(log_dir, f"{tag}.log"), "ab")
    return {"stdin": subprocess.DEVNULL, "stdout": f, "stderr": f}


def make_provider(provider_cfg: Dict[str, Any],
                  log_dir: str = "") -> NodeProvider:
    kind = provider_cfg.get("type", "subprocess")
    if kind == "subprocess":
        return SubprocessProvider(log_dir)
    if kind == "ssh":
        return SSHProvider(provider_cfg, log_dir)
    if kind in ("gce", "gce-tpu"):
        return GCETPUProvider(provider_cfg, log_dir)
    raise ValueError(f"unknown provider type: {kind}")


# --------------------------------------------------------------- lifecycle
def up(config_path: str, wait_s: float = 60.0) -> Dict[str, Any]:
    """Boot the head process and all workers; returns the cluster state."""
    cfg = load_cluster_config(config_path)
    name = cfg["cluster_name"]
    existing = load_state(name)
    if existing and _pid_alive(existing.get("head_pid")):
        raise RuntimeError(f"cluster '{name}' is already up "
                           f"(head pid {existing['head_pid']})")

    info_path = _state_path(name) + ".head"
    try:
        os.unlink(info_path)
    except OSError:
        pass
    head_cfg = cfg["head"]
    env = dict(os.environ)
    env["RMT_HEAD_INFO_PATH"] = info_path
    env["RMT_HEAD_NUM_CPUS"] = str(head_cfg.get("num_cpus", 4))
    env["RMT_HEAD_NUM_TPUS"] = str(head_cfg.get("num_tpus", 0))
    env["RMT_HEAD_CLIENT_PORT"] = str(head_cfg.get("client_port", 0))
    log_dir = os.path.join(STATE_DIR, f"{name}.logs")
    head = subprocess.Popen(
        [sys.executable, "-m", "ray_memory_management_tpu.launcher"],
        env=_with_pkg_path(env), close_fds=True,
        **_daemon_log(log_dir, "head"),
    )
    deadline = time.monotonic() + wait_s
    info = None
    while time.monotonic() < deadline:
        if head.poll() is not None:
            raise RuntimeError(f"head exited rc={head.returncode}")
        try:
            with open(info_path) as f:
                info = json.load(f)
            break
        except (OSError, ValueError):
            time.sleep(0.2)
    if info is None:
        head.kill()
        raise TimeoutError("head did not come up in time")

    provider = make_provider(cfg["provider"], log_dir)
    workers = [provider.launch_worker(spec, info["node_listener"],
                                      info["authkey"])
               for spec in cfg["workers"]]
    # state is saved BEFORE the readiness wait so a slow/unreachable worker
    # leaves a cluster `rmt down` can still find and clean up
    state = {
        "cluster_name": name,
        "config_path": os.path.abspath(config_path),
        "head_pid": head.pid,
        "client_address": info["client_address"],
        "node_listener": info["node_listener"],
        "workers": workers,
        "provider": cfg["provider"],
    }
    save_state(name, state)
    # ray-up waits until workers are usable; here that means the agents
    # registered and the cluster's aggregate CPU covers every node
    want_cpus = (head_cfg.get("num_cpus", 4)
                 + sum(w.get("num_cpus", 4) for w in cfg["workers"]))
    _wait_for_cpus(info["client_address"], want_cpus,
                   deadline - time.monotonic() + wait_s)
    return state


def down(config_or_name: str) -> bool:
    """Tear the cluster down (``ray down`` analog)."""
    name = config_or_name
    if os.path.exists(config_or_name):
        name = load_cluster_config(config_or_name)["cluster_name"]
    state = load_state(name)
    if state is None:
        return False
    provider = make_provider(state.get("provider", {}))
    for rec in state.get("workers", []):
        provider.terminate_worker(rec)
    head_pid = state.get("head_pid")
    if _pid_alive(head_pid):
        _kill_quietly(head_pid, signal.SIGTERM)
        for attempt in range(100):
            _reap(head_pid)
            if not _pid_alive(head_pid):
                break
            if attempt == 50:  # graceful shutdown is taking too long
                _kill_quietly(head_pid, signal.SIGKILL)
            time.sleep(0.1)
    try:
        os.unlink(_state_path(name))
    except OSError:
        pass
    return True


def client_address(config_or_name: str) -> str:
    name = config_or_name
    if os.path.exists(config_or_name):
        name = load_cluster_config(config_or_name)["cluster_name"]
    state = load_state(name)
    if state is None:
        raise RuntimeError(f"cluster '{name}' is not up")
    return state["client_address"]


def exec_script(config_or_name: str, script: List[str]) -> int:
    """Run a command with RMT_CLIENT_ADDRESS pointing at the cluster
    (``ray exec``/``ray submit`` analog — the script connects via
    client.connect(os.environ['RMT_CLIENT_ADDRESS']))."""
    env = _with_pkg_path(dict(os.environ))
    env["RMT_CLIENT_ADDRESS"] = client_address(config_or_name)
    return subprocess.call(script, env=env)


def _wait_for_cpus(client_address: str, want_cpus: float,
                   timeout: float) -> None:
    """Poll the head's cluster_resources through the thin-client port
    until every launched node has registered its CPUs."""
    from multiprocessing.connection import Client as _Client

    from .config import WIRE_PROTOCOL_VERSION

    host, port = client_address.rsplit(":", 1)
    deadline = time.monotonic() + max(5.0, timeout)
    while time.monotonic() < deadline:
        try:
            conn = _Client((host, int(port)), authkey=b"rmt-client")
            try:
                # every verb is refused until the versioned ping lands
                # (the wire-protocol gate all frontends pass through)
                conn.send({"type": "ping", "req_id": 0,
                           "proto": WIRE_PROTOCOL_VERSION})
                conn.recv()
                conn.send({"type": "cluster_resources", "req_id": 1})
                reply = conn.recv()
            finally:
                conn.close()
            if reply.get("resources", {}).get("CPU", 0) >= want_cpus:
                return
        except (OSError, EOFError, ValueError):
            pass
        time.sleep(0.25)
    raise TimeoutError(
        f"workers did not register {want_cpus} CPUs in time")


def _kill_quietly(pid, sig) -> None:
    try:
        os.kill(pid, sig)
    except (ProcessLookupError, PermissionError):
        pass  # exited on its own between the liveness check and the kill


def _reap(pid) -> None:
    """Collect the exit status if ``pid`` is our zombie child (a SIGKILLed
    child stays kill-0-visible until waited, which would make _pid_alive
    lie forever)."""
    try:
        os.waitpid(pid, os.WNOHANG)
    except ChildProcessError:
        pass  # not our child (down() from another process) — init reaps it


def _pid_alive(pid) -> bool:
    if not pid:
        return False
    try:
        os.kill(pid, 0)
        return True
    except (ProcessLookupError, PermissionError):
        return False
    except OSError:
        return False


# ------------------------------------------------------------- head process
def _head_main() -> int:
    """Entry point of the detached head process (``rmt up`` spawns this):
    an rmt runtime serving thin clients + node agents until SIGTERM."""
    import threading

    import ray_memory_management_tpu as rmt
    from ray_memory_management_tpu.client import ClusterServer

    rt = rmt.init(
        num_cpus=int(os.environ.get("RMT_HEAD_NUM_CPUS", "4")),
        num_tpus=int(os.environ.get("RMT_HEAD_NUM_TPUS", "0")),
    )
    server = ClusterServer(port=int(os.environ.get("RMT_HEAD_CLIENT_PORT",
                                                   "0")))
    host, port = rt.node_listener_address
    info = {
        "client_address": f"127.0.0.1:{server.port}",
        "node_listener": f"{host}:{port}",
        "authkey": rt._authkey.hex(),
        "pid": os.getpid(),
    }
    info_path = os.environ["RMT_HEAD_INFO_PATH"]
    with open(info_path + ".tmp", "w") as f:
        json.dump(info, f)
    os.replace(info_path + ".tmp", info_path)

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    rmt.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(_head_main())
