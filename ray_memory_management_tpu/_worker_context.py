"""Process-global context: is this process a driver or a worker?

The reference keeps a global worker singleton with a mode flag
(python/ray/_private/worker.py global_worker). Here the public API consults
this module to route calls either to the in-process driver Runtime or to the
worker's pipe-backed proxy.
"""

from __future__ import annotations

_proxy = None
_runtime = None


def set_proxy(proxy) -> None:
    global _proxy
    _proxy = proxy


def get_proxy():
    return _proxy


def set_runtime(rt) -> None:
    global _runtime
    _runtime = rt


def get_runtime():
    return _runtime


def in_worker() -> bool:
    return _proxy is not None


def backend():
    """The submission backend for the current process (driver runtime or
    worker proxy). Raises if neither is initialized."""
    if _proxy is not None:
        return _proxy
    if _runtime is not None:
        return _runtime
    raise RuntimeError(
        "not initialized: call ray_memory_management_tpu.init() first"
    )


def get_trace_context():
    """The (trace_id, span_id, parent_span_id) context of the task this
    process is currently executing, or None outside a traced task. In a
    worker this is set around exec by the dispatcher; nested ``.remote()``
    submits read it so child tasks chain onto the parent's trace."""
    from .utils import tracing

    return tracing.get_current()


def set_trace_context(ctx):
    """Install a trace context for the current thread (returns the reset
    token — primarily for drivers that want several submits grouped
    under one hand-minted trace)."""
    from .utils import tracing

    return tracing.set_current(ctx)
