"""Public collective API.

Mirrors python/ray/util/collective/collective.py — the full surface:
init_collective_group(:120), create_collective_group(:151), declare/destroy,
get_rank, get_collective_group_size, allreduce(:258), barrier(:298),
reduce(:311), broadcast(:373), allgather(:423), reducescatter(:472),
send(:531)/recv(:594) — with backends re-targeted for TPU (types.py here):

  - ``xla``: collectives compile to XLA ICI programs over a jax mesh
    (mesh_group.py). Caller must be a process that owns devices (the
    host-process model); tensors are the stacked [world, ...] representation.
  - ``objstore``: cross-actor CPU collectives through the object plane with a
    named-actor rendezvous (coordinator.py), callable from any rank actor.

A GroupManager keyed by group name tracks membership per process, like the
reference's _group_mgr (collective.py:40).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from .types import Backend, ReduceOp


class _GroupManager:
    def __init__(self):
        self._groups: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def put(self, name: str, group) -> None:
        with self._lock:
            self._groups[name] = group

    def get(self, name: str):
        with self._lock:
            group = self._groups.get(name)
        if group is None:
            raise ValueError(
                f"collective group {name!r} is not initialized in this "
                f"process; call init_collective_group() first"
            )
        return group

    def pop(self, name: str):
        with self._lock:
            return self._groups.pop(name, None)


_group_mgr = _GroupManager()


def init_collective_group(
    world_size: int,
    rank: int,
    backend: str = Backend.XLA,
    group_name: str = "default",
    devices: Optional[list] = None,
    precision: Optional[str] = None,
):
    """Join (rank) this process/actor into a collective group
    (reference :120).

    ``precision`` sets the group default for the reduction collectives
    (allreduce/reduce/reducescatter): "f32" (bit-exact, the effective
    default), "bf16" or "int8" quantize each rank's contribution before
    the wire and accumulate at full precision. A per-call ``precision=``
    overrides it; None defers to config.collective_precision."""
    backend = Backend.resolve(backend)
    if backend == Backend.XLA:
        from .mesh_group import MeshCollectives

        group = MeshCollectives(devices, precision=precision)
        if world_size != group.world_size:
            raise ValueError(
                f"xla backend: world_size {world_size} != "
                f"{group.world_size} local devices; pass devices= explicitly"
            )
        group.rank = rank
        group.group_name = group_name
    else:
        from .coordinator import ObjstoreGroup, create_coordinator

        coord = create_coordinator(group_name, world_size)
        group = ObjstoreGroup(coord, world_size, rank, group_name,
                              precision=precision)
    _group_mgr.put(group_name, group)
    return group


def create_collective_group(
    actors: List[Any],
    world_size: int,
    ranks: List[int],
    backend: str = Backend.OBJSTORE,
    group_name: str = "default",
    precision: Optional[str] = None,
):
    """Declarative group over existing actors (reference :151): sends an
    ``init_collective_group`` call into every actor. Actor classes must
    provide the ``_rmt_init_collective`` hook — inherit
    :class:`CollectiveGroupMixin` (or define an equivalent method that calls
    ``init_collective_group`` locally). An actor without the hook fails with
    a remote AttributeError naming the missing method."""
    from .. import api

    if len(actors) != len(ranks):
        raise ValueError("actors and ranks must align")
    backend = Backend.resolve(backend)
    if backend == Backend.XLA:
        raise ValueError(
            "xla groups are per-process meshes; create them inside the actor "
            "with init_collective_group(backend='xla')"
        )
    from .coordinator import create_coordinator

    create_coordinator(group_name, world_size)  # pre-create, avoids races
    refs = []
    for actor, rank in zip(actors, ranks):
        if precision is None:
            # old positional shape: an actor class with a pre-precision
            # _rmt_init_collective hook keeps working
            refs.append(actor._rmt_init_collective.remote(
                world_size, rank, backend, group_name
            ))
        else:
            refs.append(actor._rmt_init_collective.remote(
                world_size, rank, backend, group_name, precision
            ))
    api.get(refs, timeout=120)


def destroy_collective_group(group_name: str = "default") -> None:
    """Drop the local group and kill the rendezvous coordinator (if this
    process can reach it) so re-forming the group starts from clean state."""
    group = _group_mgr.pop(group_name)
    if group is not None and hasattr(group, "_coord"):
        from .coordinator import destroy_coordinator

        try:
            destroy_coordinator(group_name)
        except Exception:
            pass  # driver gone / already dead


def get_rank(group_name: str = "default") -> int:
    return _group_mgr.get(group_name).rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _group_mgr.get(group_name).world_size


def is_group_initialized(group_name: str = "default") -> bool:
    try:
        _group_mgr.get(group_name)
        return True
    except ValueError:
        return False


# ---------------------------------------------------------------- operations
class _op_timer:
    """Times one collective op into rmt_collective_latency_seconds. These
    module functions are the single entry point for BOTH backends (xla
    mesh and objstore), so per-op latency lands here once."""

    __slots__ = ("_op", "_t0")

    def __init__(self, op: str):
        self._op = op
        self._t0 = 0.0

    def __enter__(self):
        import time as _time

        self._t0 = _time.monotonic()
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            import time as _time

            from ..core.metrics_defs import collective_latency_seconds

            collective_latency_seconds().observe(
                _time.monotonic() - self._t0, tags={"op": self._op})
        return False


def allreduce(tensor, group_name: str = "default", op: str = ReduceOp.SUM,
              precision: Optional[str] = None):
    """``precision="f32" | "bf16" | "int8"``: sub-f32 quantizes each
    rank's shard before the wire (bf16 halves the moved bytes, int8 with
    block-wise scales ~quarters them) and dequantizes+accumulates at
    full f32 — EQuARX-style lossy-aware comms. Omit (None) for the group
    default; f32 stays bit-exact."""
    with _op_timer("allreduce"):
        return _group_mgr.get(group_name).allreduce(
            tensor, op, precision=precision)


def reduce(tensor, dst_rank: int = 0, group_name: str = "default",
           op: str = ReduceOp.SUM, precision: Optional[str] = None):
    with _op_timer("reduce"):
        return _group_mgr.get(group_name).reduce(
            tensor, dst_rank, op, precision=precision)


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    with _op_timer("broadcast"):
        return _group_mgr.get(group_name).broadcast(tensor, src_rank)


def allgather(tensor, group_name: str = "default"):
    with _op_timer("allgather"):
        return _group_mgr.get(group_name).allgather(tensor)


def reducescatter(tensor, group_name: str = "default",
                  op: str = ReduceOp.SUM,
                  precision: Optional[str] = None):
    with _op_timer("reducescatter"):
        return _group_mgr.get(group_name).reducescatter(
            tensor, op, precision=precision)


def barrier(group_name: str = "default"):
    with _op_timer("barrier"):
        return _group_mgr.get(group_name).barrier()


def send(tensor, dst_rank: int, group_name: str = "default"):
    with _op_timer("send"):
        return _group_mgr.get(group_name).send(tensor, dst_rank)


def recv(src_rank: int, group_name: str = "default"):
    with _op_timer("recv"):
        return _group_mgr.get(group_name).recv(src_rank)


class CollectiveGroupMixin:
    """Mixin giving actor classes the conventional init hook used by
    create_collective_group."""

    def _rmt_init_collective(self, world_size: int, rank: int, backend: str,
                             group_name: str,
                             precision: Optional[str] = None) -> bool:
        init_collective_group(world_size, rank, backend, group_name,
                              precision=precision)
        return True
