"""Object-plane collective backend: rendezvous actor + derived collectives.

The CPU fallback (Gloo analog, gloo_collective_group.py) re-architected for
this runtime: instead of pygloo transports, ranks meet at a named coordinator
actor — the same named-actor rendezvous the reference uses to share the
NCCLUniqueID (nccl_collective_group.py:53-95) — and the data itself rides the
shared-memory object plane (small tensors inline, large ones zero-copy through
the store).

The coordinator implements one primitive, ``gather(seq, rank, value)``: block
until all ranks contributed, return the ordered list. Every collective is
derived client-side (allreduce = gather + local reduce; broadcast = gather +
pick root; ...). P2P send/recv uses per-destination mailboxes.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .types import ReduceOp


class _CoordinatorImpl:
    """Actor class (registered lazily so the decorator binds to the running
    API). async methods: contributions from different ranks interleave on the
    actor's asyncio loop (fiber.h-style concurrency)."""

    def __init__(self, world_size: int):
        self.world_size = world_size
        self._rounds: Dict[int, List[Any]] = {}
        self._events: Dict[int, "asyncio.Event"] = {}
        self._mailboxes: Dict[Tuple[int, int, int], Any] = {}
        self._mail_events: Dict[Tuple[int, int, int], "asyncio.Event"] = {}

    def world(self) -> int:
        return self.world_size

    def _event(self, table, key):
        import asyncio

        ev = table.get(key)
        if ev is None:
            table[key] = ev = asyncio.Event()
        return ev

    async def gather(self, seq: int, rank: int, value) -> List[Any]:
        round_ = self._rounds.setdefault(seq, [None] * self.world_size)
        round_[rank] = (True, value)
        ev = self._event(self._events, seq)
        if all(v is not None for v in round_):
            ev.set()
        else:
            await ev.wait()
        return [v[1] for v in self._rounds[seq]]

    def retire(self, seq: int) -> None:
        """Drop a completed round (called by rank 0 of the NEXT round so slow
        readers of round N are never raced)."""
        self._rounds.pop(seq - self.world_size * 4, None)
        self._events.pop(seq - self.world_size * 4, None)

    async def put_mail(self, seq: int, src: int, dst: int, value) -> None:
        key = (seq, src, dst)
        self._mailboxes[key] = value
        self._event(self._mail_events, key).set()

    async def take_mail(self, seq: int, src: int, dst: int):
        key = (seq, src, dst)
        ev = self._event(self._mail_events, key)
        await ev.wait()
        value = self._mailboxes.pop(key)
        self._mail_events.pop(key, None)
        return value


_NUMPY_REDUCERS = {
    ReduceOp.SUM: lambda parts: np.sum(parts, axis=0),
    ReduceOp.PRODUCT: lambda parts: np.prod(parts, axis=0),
    ReduceOp.MIN: lambda parts: np.min(parts, axis=0),
    ReduceOp.MAX: lambda parts: np.max(parts, axis=0),
}


def _reduce(values: List[Any], op: str):
    arrs = [np.asarray(v) for v in values]
    return _NUMPY_REDUCERS[op](np.stack(arrs))


class ObjstoreGroup:
    """Per-rank handle to an object-plane collective group."""

    def __init__(self, coordinator_handle, world_size: int, rank: int,
                 group_name: str, precision: Optional[str] = None):
        self._coord = coordinator_handle
        self.world_size = world_size
        self.rank = rank
        self.group_name = group_name
        # group-level default for the reduction collectives; a per-call
        # precision= wins, and None defers to config.collective_precision
        self.precision = precision
        # collectives and p2p keep separate sequence spaces: every rank runs
        # the same ordered list of collectives (SPMD discipline), while p2p
        # ordering is per (src, dst) pair
        self._coll_seq = 0
        self._p2p_seq: Dict[Tuple[int, int], int] = {}

    def _next_coll_seq(self) -> int:
        self._coll_seq += 1
        return self._coll_seq

    def _next_p2p_seq(self, src: int, dst: int) -> int:
        key = (src, dst)
        self._p2p_seq[key] = self._p2p_seq.get(key, 0) + 1
        return self._p2p_seq[key]

    def _gather(self, value) -> List[Any]:
        from .. import api

        seq = self._next_coll_seq()
        out = api.get(self._coord.gather.remote(seq, self.rank, value),
                      timeout=120)
        if self.rank == 0:
            self._coord.retire.remote(seq)
        return out

    def _quantized_gather(self, tensor, op_name: str,
                          precision: str) -> List[np.ndarray]:
        """Quantize this rank's contribution (core/codec.py kernels),
        gather the QUANTIZED payloads through the object plane — the
        wire genuinely carries ~2x (bf16) / ~4x (int8) fewer tensor
        bytes — and return every rank's dequantized f32 array for
        full-precision accumulation."""
        from ..core import codec

        payload = codec.quantize_array(np.asarray(tensor), precision)
        codec.count_quantized_op(op_name, precision)
        return [codec.dequantize_array(v) for v in self._gather(payload)]

    def _resolve(self, precision: Optional[str]) -> str:
        from .types import resolve_precision

        return resolve_precision(precision, self.precision)

    # -- the collective surface (collective.py:258-615 in the reference) ------
    def allreduce(self, tensor, op: str = ReduceOp.SUM,
                  precision: Optional[str] = None):
        p = self._resolve(precision)
        if p != "f32":
            return _reduce(self._quantized_gather(tensor, "allreduce", p),
                           op)
        return _reduce(self._gather(np.asarray(tensor)), op)

    def reduce(self, tensor, root_rank: int = 0, op: str = ReduceOp.SUM,
               precision: Optional[str] = None):
        p = self._resolve(precision)
        if p != "f32":
            values = self._quantized_gather(tensor, "reduce", p)
        else:
            values = self._gather(np.asarray(tensor))
        if self.rank == root_rank:
            return _reduce(values, op)
        return np.asarray(tensor)

    def broadcast(self, tensor, root_rank: int = 0):
        values = self._gather(
            np.asarray(tensor) if self.rank == root_rank else None
        )
        return np.asarray(values[root_rank])

    def allgather(self, tensor) -> List[Any]:
        return [np.asarray(v) for v in self._gather(np.asarray(tensor))]

    def reducescatter(self, tensor, op: str = ReduceOp.SUM,
                      precision: Optional[str] = None):
        p = self._resolve(precision)
        if p != "f32":
            reduced = _reduce(
                self._quantized_gather(tensor, "reducescatter", p), op)
        else:
            reduced = _reduce(self._gather(np.asarray(tensor)), op)
        chunks = np.array_split(reduced, self.world_size, axis=0)
        return chunks[self.rank]

    def barrier(self):
        self._gather(None)

    def send(self, tensor, dst_rank: int):
        from .. import api

        seq = self._next_p2p_seq(self.rank, dst_rank)
        api.get(self._coord.put_mail.remote(
            seq, self.rank, dst_rank, np.asarray(tensor)), timeout=120)

    def recv(self, src_rank: int):
        from .. import api

        seq = self._next_p2p_seq(src_rank, self.rank)
        return np.asarray(api.get(
            self._coord.take_mail.remote(seq, src_rank, self.rank),
            timeout=120,
        ))


def create_coordinator(group_name: str, world_size: int):
    """Create (or fetch) the named coordinator actor for a group; racing
    creators fall back to lookup (the reference's rank-0-creates /
    others-poll rendezvous, nccl_collective_group.py:53-95). A coordinator
    left over from a same-named group must match world_size — call
    destroy_collective_group() first to re-form a group with a different
    world (the reference has the same reuse rule for named NCCL groups)."""
    from .. import api

    name = f"__rmt_collective_{group_name}"

    def checked(handle):
        existing = api.get(handle.world.remote(), timeout=60)
        if existing != world_size:
            raise ValueError(
                f"collective group {group_name!r} already exists with "
                f"world_size={existing} (wanted {world_size}); call "
                f"destroy_collective_group({group_name!r}) first"
            )
        return handle

    try:
        return checked(api.get_actor(name))
    except ValueError as e:
        if "world_size" in str(e):
            raise
    actor_cls = api.remote(_CoordinatorImpl)
    try:
        return actor_cls.options(
            name=name, max_concurrency=max(world_size * 2, 8)
        ).remote(world_size)
    except ValueError:
        return checked(api.get_actor(name))  # lost the creation race


def destroy_coordinator(group_name: str) -> None:
    """Kill the named coordinator so the next group formation starts fresh
    (prevents stale rounds from leaking across re-inits)."""
    from .. import api

    try:
        handle = api.get_actor(f"__rmt_collective_{group_name}")
    except ValueError:
        return
    api.kill(handle)
