"""XLA-backend collectives: jitted mesh programs over ICI.

This is the TPU replacement for the reference's NCCL hot path
(nccl_collective_group.py:579-629 — comm/stream lookup then per-tensor NCCL
kernels). Here each collective is a jit-compiled ``shard_map`` program whose
body is a single XLA collective (lax.psum / all_gather / psum_scatter /
ppermute); XLA schedules it over the ICI links, which is strictly better than
hand-managed streams. Compiled programs are cached per (op, shape, dtype,
world) the way the reference caches comms per device set.

The "one tensor per rank" NCCL model maps to a stacked global array sharded on
its leading axis: rank i's tensor is shard i. On one host this runs over the
local chips; multi-host runs the same program under jax.distributed (the
driver's ``dryrun_multichip`` exercises it on a virtual mesh).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .types import ReduceOp

_AXIS = "ranks"


from ..utils.jax_compat import (  # noqa: F401 — HAS_SHARD_MAP re-exported
    HAS_SHARD_MAP,
    shard_map as _shard_map_compat,
)

_INT8_BLOCK = 256  # must match core/codec.py's block-wise scale grain


def _reduce_fn(op: str):
    def _product(t):
        # gather-then-multiply: exact for zeros/negatives/ints (an exp-of-
        # psum-of-logs trick would NaN on non-positive inputs)
        return jnp.prod(lax.all_gather(t, _AXIS, axis=0), axis=0)

    return {
        ReduceOp.SUM: lambda t: lax.psum(t, _AXIS),
        ReduceOp.MAX: lambda t: lax.pmax(t, _AXIS),
        ReduceOp.MIN: lambda t: lax.pmin(t, _AXIS),
        ReduceOp.PRODUCT: _product,
    }[op]


_STACK_REDUCERS = {
    ReduceOp.SUM: jnp.sum,
    ReduceOp.MAX: jnp.max,
    ReduceOp.MIN: jnp.min,
    ReduceOp.PRODUCT: jnp.prod,
}


def _dequant_stack(t, precision: str):
    """Inside a shard_map body: quantize this rank's shard, all_gather
    the QUANTIZED payload (what actually crosses ICI — half the bytes
    for bf16, ~quarter for int8+scales), and return the dequantized
    [world, ...local] float32 stack. The caller reduces over axis 0 at
    full precision — quantize-before-wire, f32 accumulation (EQuARX).
    The jnp twin of core/codec.py's numpy kernels; the block-wise int8
    scale math matches bit-for-bit so both backends report the same
    accuracy envelope."""
    if precision == "bf16":
        g = lax.all_gather(t.astype(jnp.bfloat16), _AXIS, axis=0)
        return g.astype(jnp.float32)
    # int8, block-wise absmax scales (shapes are static under jit, so
    # the padding below is compile-time)
    shape = t.shape
    flat = t.astype(jnp.float32).reshape(-1)
    pad = (-flat.size) % _INT8_BLOCK
    padded = jnp.pad(flat, (0, pad)) if pad else flat
    blocks = padded.reshape(-1, _INT8_BLOCK)
    scale = jnp.max(jnp.abs(blocks), axis=1, keepdims=True) / 127.0
    safe = jnp.where(scale == 0.0, 1.0, scale)
    q = jnp.clip(jnp.round(blocks / safe), -127, 127).astype(jnp.int8)
    gq = lax.all_gather(q, _AXIS, axis=0)        # [world, nblk, B] int8
    gs = lax.all_gather(scale, _AXIS, axis=0)    # [world, nblk, 1] f32
    deq = (gq.astype(jnp.float32) * gs).reshape(gq.shape[0], -1)
    return deq[:, :flat.size].reshape((gq.shape[0],) + shape)


def _count_quantized(op: str, precision: str) -> None:
    from ..core.codec import count_quantized_op

    count_quantized_op(op, precision)


class MeshCollectives:
    """Collectives over a 1-D mesh of devices (one 'rank' per device)."""

    def __init__(self, devices: Optional[list] = None,
                 precision: Optional[str] = None):
        devices = devices if devices is not None else jax.devices()
        self.mesh = Mesh(devices, (_AXIS,))
        self.world_size = len(devices)
        self._sharding = NamedSharding(self.mesh, P(_AXIS))
        # group-level default precision for the reduction collectives;
        # None defers to config.collective_precision, then "f32". A
        # per-call precision= always wins.
        self.precision = precision
        # per-instance program cache (an lru_cache on methods would pin the
        # instance and its compiled executables in a class-level cache
        # forever); dies with the group
        self._programs = {}

    def _cached(self, key, build):
        fn = self._programs.get(key)
        if fn is None:
            fn = self._programs[key] = build()
        return fn

    # -- helpers --------------------------------------------------------------
    def shard_ranks(self, stacked):
        """Place a [world, ...] array so shard i lives on device i."""
        return jax.device_put(stacked, self._sharding)

    def _smap(self, fn, out_spec=P(_AXIS)):
        # check disabled (check_vma on new jax, check_rep on old):
        # collective bodies intentionally produce values whose replication
        # XLA cannot infer statically (e.g. all_gather then replicated
        # output)
        if not HAS_SHARD_MAP:
            raise RuntimeError(
                "this jax installation provides no shard_map "
                "(neither jax.shard_map nor jax.experimental.shard_map); "
                "xla-backend collectives are unavailable")
        return _shard_map_compat(
            fn, mesh=self.mesh, in_specs=P(_AXIS), out_specs=out_spec)

    def _precision(self, precision):
        from .types import resolve_precision

        return resolve_precision(precision, self.precision)

    # -- collectives (each returns a jitted, cached program) ------------------
    def _allreduce_fn(self, op: str, precision: str = "f32"):
        if precision == "f32":
            # today's program, byte for byte — f32 stays bit-exact
            return self._cached(
                ("allreduce", op),
                lambda: jax.jit(self._smap(_reduce_fn(op))),
            )

        def build():
            red = _STACK_REDUCERS[op]

            def body(t):
                return red(_dequant_stack(t, precision), axis=0)

            return jax.jit(self._smap(body))

        return self._cached(("allreduce", op, precision), build)

    def allreduce(self, stacked, op: str = ReduceOp.SUM,
                  precision: Optional[str] = None):
        """[world, ...] -> [world, ...] with every rank-slice = reduction.

        ``precision``: "f32" (bit-exact default) | "bf16" | "int8" —
        sub-f32 runs quantize-on-wire with f32 accumulation; result
        dtype is float32 for quantized runs."""
        p = self._precision(precision)
        if p != "f32":
            _count_quantized("allreduce", p)
        return self._allreduce_fn(op, p)(self.shard_ranks(stacked))

    def _reducescatter_fn(self, op: str, precision: str = "f32"):
        key = (("reducescatter", op) if precision == "f32"
               else ("reducescatter", op, precision))
        return self._cached(
            key, lambda: self._build_reducescatter(op, precision))

    def _build_reducescatter(self, op: str, precision: str = "f32"):
        if precision != "f32":
            red = _STACK_REDUCERS[op]

            def qbody(t):
                full = red(_dequant_stack(t, precision), axis=0)
                rank = lax.axis_index(_AXIS)
                n = t.shape[1] // self.world_size
                return lax.dynamic_slice_in_dim(full, rank * n, n, axis=1)

            return jax.jit(self._smap(qbody))
        if op != ReduceOp.SUM:
            red = _reduce_fn(op)

            def body(t):
                full = red(t)  # replicate reduction, then slice
                rank = lax.axis_index(_AXIS)
                n = t.shape[1] // self.world_size
                return lax.dynamic_slice_in_dim(full, rank * n, n, axis=1)

            return jax.jit(self._smap(body))
        return jax.jit(self._smap(
            lambda t: lax.psum_scatter(t, _AXIS, scatter_dimension=1,
                                       tiled=True)
        ))

    def reducescatter(self, stacked, op: str = ReduceOp.SUM,
                      precision: Optional[str] = None):
        """[world, world*n] -> rank i holds sum-slice i ([world, n] global)."""
        p = self._precision(precision)
        if p != "f32":
            _count_quantized("reducescatter", p)
        return self._reducescatter_fn(op, p)(self.shard_ranks(stacked))

    def _allgather_fn(self):
        # out_spec P(): every rank computes the identical full stack, so the
        # global result is the replicated [world, ...] gather
        return self._cached(("allgather",), lambda: jax.jit(self._smap(
            lambda t: lax.all_gather(t[0], _AXIS, axis=0), out_spec=P()
        )))

    def allgather(self, stacked):
        """[world, ...] -> every rank holds the full stack (returned global
        shape [world, world, ...] collapses to one [world, ...] copy)."""
        out = self._allgather_fn()(self.shard_ranks(stacked))
        return out

    def _broadcast_fn(self, root: int):
        return self._cached(("broadcast", root),
                            lambda: self._build_broadcast(root))

    def _build_broadcast(self, root: int):
        # masked psum: every rank contributes zeros except the root, so the
        # reduction IS the root's slice. Moves O(bytes) per ICI link (the
        # ring allreduce schedule), not the O(world * bytes) of gathering
        # the whole stack to every rank. (jax's ppermute cannot express a
        # one-to-all fanout — sources must be unique — and a log-round tree
        # would be latency-optimal but more program for no bandwidth win.)
        def body(t):
            rank = lax.axis_index(_AXIS)
            contrib = jnp.where(rank == root, t, jnp.zeros_like(t))
            return lax.psum(contrib, _AXIS)

        return jax.jit(self._smap(body))

    def broadcast(self, stacked, root: int = 0):
        """Every rank-slice of the result equals root's input slice."""
        return self._broadcast_fn(root)(self.shard_ranks(stacked))

    def _ppermute_fn(self, perm: tuple):
        return self._cached(("ppermute", perm),
                            lambda: self._build_ppermute(perm))

    def _build_ppermute(self, perm: tuple):
        def body(t):
            return lax.ppermute(t, _AXIS, perm=list(perm))

        return jax.jit(self._smap(body))

    def ppermute(self, stacked, perm):
        """Neighbor exchange over ICI (the ring-attention building block)."""
        return self._ppermute_fn(tuple(map(tuple, perm)))(
            self.shard_ranks(stacked)
        )

    def send_recv(self, stacked, src: int, dst: int):
        """P2P as a degenerate collective-permute (reference send/recv,
        collective.py:531,594 — NCCL P2P maps to ppermute on ICI)."""
        return self.ppermute(stacked, [(src, dst)])

    def _reduce_rooted_fn(self, root: int, op: str,
                          precision: str = "f32"):
        def build():
            if precision != "f32":
                sred = _STACK_REDUCERS[op]

                def qbody(t):
                    out = sred(_dequant_stack(t, precision), axis=0)
                    rank = lax.axis_index(_AXIS)
                    return jnp.where(rank == root, out,
                                     t.astype(jnp.float32))

                return jax.jit(self._smap(qbody))
            red = _reduce_fn(op)

            def body(t):
                out = red(t)
                rank = lax.axis_index(_AXIS)
                # NCCL reduce semantics: only root's output is defined;
                # other ranks keep their input slice (cheap, and closer to
                # "unmodified buffer" than fabricated zeros)
                return jnp.where(rank == root, out, t)

            return jax.jit(self._smap(body))

        key = (("reduce", root, op) if precision == "f32"
               else ("reduce", root, op, precision))
        return self._cached(key, build)

    def reduce(self, stacked, root_rank: int = 0, op: str = ReduceOp.SUM,
               precision: Optional[str] = None):
        """Rooted reduce: root's slice of the result holds the reduction;
        other slices pass through unchanged. (On ICI the wire cost matches
        allreduce — the ring crosses every link either way — but the
        SEMANTICS are rooted, as in the reference's collective.reduce,
        util/collective/collective.py:311.)"""
        p = self._precision(precision)
        if p != "f32":
            _count_quantized("reduce", p)
        return self._reduce_rooted_fn(root_rank, op, p)(
            self.shard_ranks(stacked))

    def barrier(self):
        jax.block_until_ready(self.allreduce(
            jnp.zeros((self.world_size, 1), jnp.float32)
        ))
