"""Collective types: backends and reduce ops.

Mirrors python/ray/util/collective/types.py:29-34 — the reference enumerates
NCCL and GLOO; the TPU build replaces them with:
  - XLA: collectives lowered to XLA ICI programs over a jax device mesh
    (psum / all_gather / psum_scatter / ppermute), the NCCL analog;
  - OBJSTORE: a CPU fallback riding the shared-memory object plane with an
    actor-based rendezvous (the Gloo analog; the rendezvous-via-named-actor
    pattern follows nccl_collective_group.py:53-95).
"""

from __future__ import annotations

from dataclasses import dataclass


class Backend:
    XLA = "xla"
    OBJSTORE = "objstore"
    # Accept the reference's names as aliases so ported user code maps cleanly.
    _ALIASES = {"nccl": XLA, "gloo": OBJSTORE, "xla": XLA, "objstore": OBJSTORE}

    @classmethod
    def resolve(cls, name: str) -> str:
        try:
            return cls._ALIASES[name.lower()]
        except KeyError:
            raise ValueError(
                f"unknown collective backend {name!r}; "
                f"use one of {sorted(set(cls._ALIASES.values()))}"
            )


class ReduceOp:
    SUM = "sum"
    PRODUCT = "product"
    MIN = "min"
    MAX = "max"


#: wire precisions for the reduction collectives. f32 is bit-exact
#: (today's code path, byte for byte); bf16/int8 quantize each rank's
#: contribution BEFORE the wire and dequantize+accumulate at f32
#: (EQuARX-style — block-wise scale factors for int8). Strictly opt-in:
#: per-call ``precision=`` > group default > config.collective_precision
#: > "f32".
PRECISIONS = ("f32", "bf16", "int8")


def resolve_precision(call_precision, group_precision):
    """The precedence chain above, shared by both backends; raises on an
    unknown precision at the call site (not deep inside a jit trace)."""
    p = call_precision if call_precision is not None else group_precision
    if p is None:
        try:
            from ..config import global_config

            p = getattr(global_config(), "collective_precision", None)
        except Exception:  # noqa: BLE001 — config import cycles in tools
            p = None
    p = p or "f32"
    if p not in PRECISIONS:
        raise ValueError(
            f"unknown collective precision {p!r} (want one of "
            f"{PRECISIONS})")
    return p


@dataclass
class AllReduceOptions:
    reduceOp: str = ReduceOp.SUM
    timeout_ms: int = 30_000


@dataclass
class BarrierOptions:
    timeout_ms: int = 30_000


@dataclass
class ReduceOptions:
    reduceOp: str = ReduceOp.SUM
    root_rank: int = 0
    timeout_ms: int = 30_000


@dataclass
class BroadcastOptions:
    root_rank: int = 0
    timeout_ms: int = 30_000


@dataclass
class AllGatherOptions:
    timeout_ms: int = 30_000


@dataclass
class ReduceScatterOptions:
    reduceOp: str = ReduceOp.SUM
    timeout_ms: int = 30_000
