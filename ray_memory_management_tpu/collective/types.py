"""Collective types: backends and reduce ops.

Mirrors python/ray/util/collective/types.py:29-34 — the reference enumerates
NCCL and GLOO; the TPU build replaces them with:
  - XLA: collectives lowered to XLA ICI programs over a jax device mesh
    (psum / all_gather / psum_scatter / ppermute), the NCCL analog;
  - OBJSTORE: a CPU fallback riding the shared-memory object plane with an
    actor-based rendezvous (the Gloo analog; the rendezvous-via-named-actor
    pattern follows nccl_collective_group.py:53-95).
"""

from __future__ import annotations

from dataclasses import dataclass


class Backend:
    XLA = "xla"
    OBJSTORE = "objstore"
    # Accept the reference's names as aliases so ported user code maps cleanly.
    _ALIASES = {"nccl": XLA, "gloo": OBJSTORE, "xla": XLA, "objstore": OBJSTORE}

    @classmethod
    def resolve(cls, name: str) -> str:
        try:
            return cls._ALIASES[name.lower()]
        except KeyError:
            raise ValueError(
                f"unknown collective backend {name!r}; "
                f"use one of {sorted(set(cls._ALIASES.values()))}"
            )


class ReduceOp:
    SUM = "sum"
    PRODUCT = "product"
    MIN = "min"
    MAX = "max"


@dataclass
class AllReduceOptions:
    reduceOp: str = ReduceOp.SUM
    timeout_ms: int = 30_000


@dataclass
class BarrierOptions:
    timeout_ms: int = 30_000


@dataclass
class ReduceOptions:
    reduceOp: str = ReduceOp.SUM
    root_rank: int = 0
    timeout_ms: int = 30_000


@dataclass
class BroadcastOptions:
    root_rank: int = 0
    timeout_ms: int = 30_000


@dataclass
class AllGatherOptions:
    timeout_ms: int = 30_000


@dataclass
class ReduceScatterOptions:
    reduceOp: str = ReduceOp.SUM
    timeout_ms: int = 30_000
