"""Collective communication library (ray.util.collective analog, re-targeted
for TPU: XLA/ICI mesh collectives + an object-plane CPU fallback)."""

from .collective import (  # noqa: F401
    CollectiveGroupMixin,
    allgather,
    allreduce,
    barrier,
    broadcast,
    create_collective_group,
    destroy_collective_group,
    get_collective_group_size,
    get_rank,
    init_collective_group,
    is_group_initialized,
    recv,
    reduce,
    reducescatter,
    send,
)
from .mesh_group import HAS_SHARD_MAP, MeshCollectives  # noqa: F401
from .types import (  # noqa: F401
    PRECISIONS, Backend, ReduceOp, resolve_precision,
)
