"""Runtime environments: per-task/actor execution context.

The reference installs conda/pip/container/working_dir/py_modules
environments through the per-node agent and starts dedicated workers
inside them (python/ray/_private/runtime_env/{conda,pip,working_dir,
py_modules}.py, plugin.py; worker_pool.h:446 dedicated workers). The
host-process TPU model keeps one pooled worker per slot, so supported
fields apply at execution time and roll back afterwards:

  - ``env_vars``: exported around the call
  - ``working_dir``: a directory copied once into a per-env cache
    (URI-cache analog, uri_cache.py) and chdir'd into
  - ``py_modules``: local dirs/files prepended to sys.path
  - ``pip``: packages installed ONCE into a content-keyed virtualenv
    (the reference's pip.py + uri_cache.py); the env's site-packages is
    prepended to sys.path around the call. List form (``["pkg"]``) or
    dict form (``{"packages": [...], "extra_args": [...]}`` — extra_args
    is where offline installs pass ``--no-index --find-links ...``).

  - ``conda``: a NAMED or CREATED conda environment. Unlike the keys
    above, conda cannot apply inside a pooled worker (it is a different
    interpreter): tasks and actors carrying it run in DEDICATED
    cold-spawned workers whose process IS the env's python — the
    reference's dedicated-worker pattern for conda/container envs
    (worker_pool.h:446; _private/runtime_env/conda.py). Accepted forms:
    an env name or prefix path (str), a path to an environment.yml, or
    an env-spec dict (created once, content-keyed, via the ``conda``
    CLI — override the binary with RMT_CONDA_EXE). The env must contain
    this framework's dependencies (the reference likewise requires ray
    inside the conda env).

``container`` would need OS-level sandboxing; it raises a clear error
rather than silently half-working. The plugin hook mirrors plugin.py: a
callable ``setup(env_dict) -> context_manager`` registered by name.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import shutil
import subprocess
import sys
import tempfile
from typing import Any, Callable, Dict, List, Optional

_UNSUPPORTED = ("container",)
_plugins: Dict[str, Callable[[Any], Any]] = {}


def register_plugin(name: str, setup: Callable[[Any], Any]) -> None:
    """Register ``setup(value) -> context manager`` for a custom key."""
    _plugins[name] = setup


def validate(runtime_env: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    if not runtime_env:
        return {}
    for key in runtime_env:
        if key in _UNSUPPORTED:
            raise ValueError(
                f"runtime_env[{key!r}] needs process-level isolation that "
                "the pooled host-process worker model does not provide "
                "(use 'pip' for package installs)")
        if key not in ("env_vars", "working_dir", "py_modules", "pip",
                       "conda") and key not in _plugins:
            raise ValueError(f"unknown runtime_env key {key!r}")
    env_vars = runtime_env.get("env_vars")
    if env_vars is not None and not all(
            isinstance(k, str) and isinstance(v, str)
            for k, v in env_vars.items()):
        raise ValueError("env_vars must be Dict[str, str]")
    pip = runtime_env.get("pip")
    if pip is not None and not isinstance(pip, (list, dict)):
        raise ValueError(
            "pip must be a list of requirements or "
            "{'packages': [...], 'extra_args': [...]}")
    conda = runtime_env.get("conda")
    if conda is not None and not isinstance(conda, (str, dict)):
        raise ValueError(
            "conda must be an env name, a prefix path, a path to an "
            "environment.yml, or an env-spec dict")
    return dict(runtime_env)


_WD_CACHE = os.path.join(tempfile.gettempdir(), "rmt_runtime_env_wd")


def _dir_digest(src: str) -> str:
    """Content key: relative names + sizes + mtimes of every file, so an
    edited working_dir gets a fresh cache entry (uri_cache.py keys by
    content URI the same way)."""
    h = hashlib.sha256(os.path.abspath(src).encode())
    for root, dirs, files in sorted(os.walk(src)):
        dirs.sort()
        for name in sorted(files):
            full = os.path.join(root, name)
            try:
                st = os.stat(full)
            except OSError:
                continue
            rel = os.path.relpath(full, src)
            h.update(f"{rel}:{st.st_size}:{st.st_mtime_ns}".encode())
    return h.hexdigest()[:16]


def _materialize_working_dir(src: str) -> str:
    """Copy the working dir into a content-keyed cache once per host."""
    dest = os.path.join(_WD_CACHE, _dir_digest(src))
    if not os.path.isdir(dest):
        os.makedirs(_WD_CACHE, exist_ok=True)
        # private tmp dir per copier: concurrent materializers each copy
        # into their own staging area; rename is atomic, losers clean up
        tmp = tempfile.mkdtemp(dir=_WD_CACHE, prefix=".staging-")
        staged = os.path.join(tmp, "wd")
        shutil.copytree(src, staged)
        try:
            os.rename(staged, dest)
        except OSError:
            pass  # another process won the race
        shutil.rmtree(tmp, ignore_errors=True)
    return dest


_PIP_CACHE = os.path.join(tempfile.gettempdir(), "rmt_runtime_env_pip")


def _pip_spec(spec) -> tuple:
    if isinstance(spec, dict):
        return (list(spec.get("packages") or []),
                list(spec.get("extra_args") or []))
    return list(spec), []


def _pip_env_site_packages(spec) -> str:
    """Install the requested packages ONCE into a content-keyed target
    directory (``pip install --target``) and return it for sys.path. The
    cache key is the requirement list — the reference's pip.py builds an
    env per runtime_env hash under its URI cache the same way
    (python/ray/_private/runtime_env/pip.py, uri_cache.py). A --target
    dir (rather than a virtualenv) layers cleanly over a pooled worker's
    existing interpreter: the base environment stays visible and the env
    applies/rolls back as a single sys.path entry."""
    packages, extra_args = _pip_spec(spec)
    # content-key local source trees: a path-string key would serve stale
    # builds forever after the user edits the package (uri_cache.py keys
    # working_dir by content the same way)
    key_parts = []
    for pkg in sorted(packages):
        if os.path.isdir(pkg):
            key_parts.append(f"{pkg}@{_dir_digest(pkg)}")
        elif os.path.isfile(pkg):
            st = os.stat(pkg)
            key_parts.append(f"{pkg}@{st.st_size}:{st.st_mtime_ns}")
        else:
            key_parts.append(pkg)
    key = hashlib.sha256(
        json.dumps([key_parts, extra_args]).encode()).hexdigest()[:16]
    dest = os.path.join(_PIP_CACHE, key)
    marker = os.path.join(dest, ".rmt_ready")
    if not os.path.exists(marker):
        os.makedirs(_PIP_CACHE, exist_ok=True)
        tmp = tempfile.mkdtemp(dir=_PIP_CACHE, prefix=".staging-")
        try:
            target = os.path.join(tmp, "env")
            os.makedirs(target)
            if packages:
                proc = subprocess.run(
                    [sys.executable, "-m", "pip", "install", "--quiet",
                     "--disable-pip-version-check", "--target", target,
                     *extra_args, *packages],
                    capture_output=True, text=True)
                if proc.returncode != 0:
                    raise RuntimeError(
                        f"pip install {packages} failed:\n{proc.stderr}")
            with open(os.path.join(target, ".rmt_ready"), "w") as f:
                f.write("ok")
            try:
                os.rename(target, dest)
            except OSError:
                pass  # another materializer won the race
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
    return dest


_CONDA_CACHE = os.path.join(tempfile.gettempdir(), "rmt_runtime_env_conda")


def _conda_exe() -> str:
    """The conda binary: RMT_CONDA_EXE override (also how tests fake the
    CLI), else CONDA_EXE (set inside any activated conda), else PATH."""
    exe = os.environ.get("RMT_CONDA_EXE") or os.environ.get("CONDA_EXE") \
        or shutil.which("conda")
    if not exe:
        raise RuntimeError(
            "runtime_env['conda'] needs the conda CLI; none found "
            "(set RMT_CONDA_EXE to the binary)")
    return exe


def conda_env_key(spec) -> str:
    """Stable identity of a conda env request — the dispatch layer keys
    dedicated workers on this (one warm dedicated pool per env, the
    reference's runtime-env-hash worker key, worker_pool.h:446)."""
    if isinstance(spec, str):
        if os.path.isfile(spec):  # environment.yml: key by content
            st = os.stat(spec)
            raw = f"file:{os.path.abspath(spec)}:{st.st_size}:" \
                  f"{st.st_mtime_ns}"
        else:
            raw = f"name:{spec}"
    else:
        raw = "spec:" + json.dumps(spec, sort_keys=True)
    return hashlib.sha256(raw.encode()).hexdigest()[:16]


def conda_python(spec) -> str:
    """Resolve (creating once if needed) the env and return its python.

    - prefix path with ``bin/python`` -> used directly, no CLI needed
    - env NAME -> looked up via ``conda env list --json``
    - environment.yml path or spec dict -> ``conda env create`` into a
      content-keyed prefix under the host cache (created ONCE; the
      offline-cache analog of pip's content-keyed --target dir)
    """
    if isinstance(spec, str):
        cand = os.path.join(spec, "bin", "python")
        if os.path.isdir(spec) and os.path.exists(cand):
            return cand
        if os.path.isfile(spec):
            return _conda_create_keyed(yaml_path=spec)
        # named env: ask the CLI where it lives
        exe = _conda_exe()
        proc = subprocess.run([exe, "env", "list", "--json"],
                              capture_output=True, text=True)
        if proc.returncode == 0:
            for prefix in json.loads(proc.stdout).get("envs", []):
                if os.path.basename(prefix) == spec:
                    py = os.path.join(prefix, "bin", "python")
                    if os.path.exists(py):
                        return py
        raise RuntimeError(
            f"conda env {spec!r} not found (conda env list gave "
            f"rc={proc.returncode})")
    return _conda_create_keyed(spec_dict=spec)


def _conda_create_keyed(spec_dict: Optional[dict] = None,
                        yaml_path: Optional[str] = None) -> str:
    """Create the env ONCE under a content-keyed prefix. Unlike the
    pip/working_dir caches, conda envs are NOT relocatable (binaries and
    activation scripts embed the prefix), so stage-and-rename would
    corrupt them — creation happens IN PLACE at the final prefix, with
    an fcntl lock serializing concurrent creators and a ready-marker
    distinguishing a finished env from a half-created one (the
    reference's conda.py locks per-env the same way,
    _private/runtime_env/conda.py)."""
    import fcntl

    key = conda_env_key(spec_dict if spec_dict is not None else yaml_path)
    prefix = os.path.join(_CONDA_CACHE, key)
    py = os.path.join(prefix, "bin", "python")
    marker = os.path.join(prefix, ".rmt_ready")
    if os.path.exists(marker):
        return py
    os.makedirs(_CONDA_CACHE, exist_ok=True)
    exe = _conda_exe()
    with open(os.path.join(_CONDA_CACHE, f".{key}.lock"), "w") as lockf:
        fcntl.flock(lockf, fcntl.LOCK_EX)
        if os.path.exists(marker):  # another creator finished while we waited
            return py
        if os.path.isdir(prefix):
            # a previous creator died mid-create: start clean
            shutil.rmtree(prefix, ignore_errors=True)
        tmp = tempfile.mkdtemp(dir=_CONDA_CACHE, prefix=".spec-")
        try:
            if yaml_path is None:
                # JSON is a YAML subset: dump the dict spec to a file
                yaml_path = os.path.join(tmp, "environment.yml")
                with open(yaml_path, "w") as f:
                    json.dump(spec_dict, f)
            proc = subprocess.run(
                [exe, "env", "create", "-p", prefix, "-f", yaml_path,
                 "--quiet"],
                capture_output=True, text=True)
            if proc.returncode != 0 or not os.path.exists(py):
                shutil.rmtree(prefix, ignore_errors=True)
                raise RuntimeError(
                    f"conda env create failed (rc={proc.returncode}):\n"
                    f"{proc.stderr[-2000:]}")
            with open(marker, "w") as f:
                f.write("ok")
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
    return py


def apply_permanent(runtime_env: Optional[Dict[str, Any]]) -> None:
    """Apply an env for the remainder of this process — used for actors,
    whose worker process is dedicated to them (no rollback needed, and
    async methods see the env without any per-call bookkeeping)."""
    if not runtime_env:
        return
    for k, v in (runtime_env.get("env_vars") or {}).items():
        os.environ[k] = v
    wd = runtime_env.get("working_dir")
    if wd:
        target = _materialize_working_dir(wd)
        os.chdir(target)
        sys.path.insert(0, target)
    for mod in runtime_env.get("py_modules") or []:
        sys.path.insert(0, os.path.abspath(mod))
    pip = runtime_env.get("pip")
    if pip:
        sys.path.insert(0, _pip_env_site_packages(pip))
    for key, value in runtime_env.items():
        if key in _plugins:
            cm = _plugins[key](value)
            cm.__enter__()  # intentionally never exited


@contextlib.contextmanager
def applied(runtime_env: Optional[Dict[str, Any]]):
    """Apply a runtime env around one task execution; restore after.
    Used for PLAIN tasks only, which execute serially on the worker's
    single-thread task executor — the save/restore is race-free because
    no other task can interleave. Actors use apply_permanent()."""
    if not runtime_env:
        yield
        return
    saved_env: Dict[str, Optional[str]] = {}
    saved_cwd: Optional[str] = None
    saved_path_len = len(sys.path)
    pip_dir: Optional[str] = None
    stack = contextlib.ExitStack()
    try:
        for k, v in (runtime_env.get("env_vars") or {}).items():
            saved_env[k] = os.environ.get(k)
            os.environ[k] = v
        wd = runtime_env.get("working_dir")
        if wd:
            saved_cwd = os.getcwd()
            target = _materialize_working_dir(wd)
            os.chdir(target)
            sys.path.insert(0, target)
        for mod in runtime_env.get("py_modules") or []:
            sys.path.insert(0, os.path.abspath(mod))
        pip = runtime_env.get("pip")
        if pip:
            pip_dir = _pip_env_site_packages(pip)
            sys.path.insert(0, pip_dir)
            # a fresh import path must not serve stale negative caches
            import importlib

            importlib.invalidate_caches()
        for key, value in runtime_env.items():
            if key in _plugins:
                stack.enter_context(_plugins[key](value))
        yield
    finally:
        stack.close()
        if pip_dir is not None:
            # evict modules imported FROM the env so the next task (which
            # may not request this env) cannot see them through the
            # sys.modules cache; pure-python unload only — C extensions
            # stay mapped, which is why the reference dedicates workers
            # to pip envs instead
            prefix = pip_dir + os.sep
            for name, mod in list(sys.modules.items()):
                f = getattr(mod, "__file__", None) or ""
                if f.startswith(prefix):
                    del sys.modules[name]
        del sys.path[: max(0, len(sys.path) - saved_path_len)]
        if saved_cwd is not None:
            try:
                os.chdir(saved_cwd)
            except OSError:
                pass
        for k, old in saved_env.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old
