"""Runtime environments: per-task/actor execution context.

The reference installs conda/pip/container/working_dir/py_modules
environments through the per-node agent and starts dedicated workers
inside them (python/ray/_private/runtime_env/{conda,pip,working_dir,
py_modules}.py, plugin.py; worker_pool.h:446 dedicated workers). The
host-process TPU model keeps one pooled worker per slot, so supported
fields apply at execution time and roll back afterwards:

  - ``env_vars``: exported around the call
  - ``working_dir``: a directory copied once into a per-env cache
    (URI-cache analog, uri_cache.py) and chdir'd into
  - ``py_modules``: local dirs/files prepended to sys.path

``conda``/``pip``/``container`` would need process-level isolation; they
raise a clear error rather than silently half-working (this image also
forbids installs). The plugin hook mirrors plugin.py: a callable
``setup(env_dict) -> context_manager`` registered by name.
"""

from __future__ import annotations

import contextlib
import hashlib
import os
import shutil
import sys
import tempfile
from typing import Any, Callable, Dict, Optional

_UNSUPPORTED = ("conda", "pip", "container")
_plugins: Dict[str, Callable[[Any], Any]] = {}


def register_plugin(name: str, setup: Callable[[Any], Any]) -> None:
    """Register ``setup(value) -> context manager`` for a custom key."""
    _plugins[name] = setup


def validate(runtime_env: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    if not runtime_env:
        return {}
    for key in runtime_env:
        if key in _UNSUPPORTED:
            raise ValueError(
                f"runtime_env[{key!r}] needs process-level isolation that "
                "the pooled host-process worker model does not provide "
                "(and this environment forbids package installs)")
        if key not in ("env_vars", "working_dir", "py_modules") and \
                key not in _plugins:
            raise ValueError(f"unknown runtime_env key {key!r}")
    env_vars = runtime_env.get("env_vars")
    if env_vars is not None and not all(
            isinstance(k, str) and isinstance(v, str)
            for k, v in env_vars.items()):
        raise ValueError("env_vars must be Dict[str, str]")
    return dict(runtime_env)


_WD_CACHE = os.path.join(tempfile.gettempdir(), "rmt_runtime_env_wd")


def _dir_digest(src: str) -> str:
    """Content key: relative names + sizes + mtimes of every file, so an
    edited working_dir gets a fresh cache entry (uri_cache.py keys by
    content URI the same way)."""
    h = hashlib.sha256(os.path.abspath(src).encode())
    for root, dirs, files in sorted(os.walk(src)):
        dirs.sort()
        for name in sorted(files):
            full = os.path.join(root, name)
            try:
                st = os.stat(full)
            except OSError:
                continue
            rel = os.path.relpath(full, src)
            h.update(f"{rel}:{st.st_size}:{st.st_mtime_ns}".encode())
    return h.hexdigest()[:16]


def _materialize_working_dir(src: str) -> str:
    """Copy the working dir into a content-keyed cache once per host."""
    dest = os.path.join(_WD_CACHE, _dir_digest(src))
    if not os.path.isdir(dest):
        os.makedirs(_WD_CACHE, exist_ok=True)
        # private tmp dir per copier: concurrent materializers each copy
        # into their own staging area; rename is atomic, losers clean up
        tmp = tempfile.mkdtemp(dir=_WD_CACHE, prefix=".staging-")
        staged = os.path.join(tmp, "wd")
        shutil.copytree(src, staged)
        try:
            os.rename(staged, dest)
        except OSError:
            pass  # another process won the race
        shutil.rmtree(tmp, ignore_errors=True)
    return dest


def apply_permanent(runtime_env: Optional[Dict[str, Any]]) -> None:
    """Apply an env for the remainder of this process — used for actors,
    whose worker process is dedicated to them (no rollback needed, and
    async methods see the env without any per-call bookkeeping)."""
    if not runtime_env:
        return
    for k, v in (runtime_env.get("env_vars") or {}).items():
        os.environ[k] = v
    wd = runtime_env.get("working_dir")
    if wd:
        target = _materialize_working_dir(wd)
        os.chdir(target)
        sys.path.insert(0, target)
    for mod in runtime_env.get("py_modules") or []:
        sys.path.insert(0, os.path.abspath(mod))
    for key, value in runtime_env.items():
        if key in _plugins:
            cm = _plugins[key](value)
            cm.__enter__()  # intentionally never exited


@contextlib.contextmanager
def applied(runtime_env: Optional[Dict[str, Any]]):
    """Apply a runtime env around one task execution; restore after.
    Used for PLAIN tasks only, which execute serially on the worker's
    single-thread task executor — the save/restore is race-free because
    no other task can interleave. Actors use apply_permanent()."""
    if not runtime_env:
        yield
        return
    saved_env: Dict[str, Optional[str]] = {}
    saved_cwd: Optional[str] = None
    saved_path_len = len(sys.path)
    stack = contextlib.ExitStack()
    try:
        for k, v in (runtime_env.get("env_vars") or {}).items():
            saved_env[k] = os.environ.get(k)
            os.environ[k] = v
        wd = runtime_env.get("working_dir")
        if wd:
            saved_cwd = os.getcwd()
            target = _materialize_working_dir(wd)
            os.chdir(target)
            sys.path.insert(0, target)
        for mod in runtime_env.get("py_modules") or []:
            sys.path.insert(0, os.path.abspath(mod))
        for key, value in runtime_env.items():
            if key in _plugins:
                stack.enter_context(_plugins[key](value))
        yield
    finally:
        stack.close()
        del sys.path[: max(0, len(sys.path) - saved_path_len)]
        if saved_cwd is not None:
            try:
                os.chdir(saved_cwd)
            except OSError:
                pass
        for k, old in saved_env.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old
