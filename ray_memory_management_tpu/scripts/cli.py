"""``rmt`` CLI.

The reference's ``ray`` click CLI (python/ray/scripts/scripts.py:
status:1865, memory:1823, timeline:1758, microbenchmark:1744, plus the
job and workflow CLIs). argparse-based (no extra deps); subcommands that
need a cluster spin up an ephemeral in-process one.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional


def _ephemeral_runtime(num_nodes: int = 1):
    import ray_memory_management_tpu as rmt

    return rmt.init(num_nodes=num_nodes, ignore_reinit_error=True)


def cmd_agent(args) -> int:
    from ray_memory_management_tpu.core import node_agent

    return node_agent.main([
        "--address", args.address, "--authkey", args.authkey,
        "--num-cpus", str(args.num_cpus), "--num-tpus", str(args.num_tpus),
    ])


def cmd_up(args) -> int:
    from ray_memory_management_tpu import launcher

    state = launcher.up(args.config)
    print(f"cluster '{state['cluster_name']}' is up")
    print(f"  head pid:       {state['head_pid']}")
    print(f"  client address: {state['client_address']}")
    print(f"  node listener:  {state['node_listener']}")
    print(f"  workers:        {len(state['workers'])}")
    print("connect with: from ray_memory_management_tpu.client import "
          f"connect; connect(\"{state['client_address']}\")")
    return 0


def cmd_down(args) -> int:
    from ray_memory_management_tpu import launcher

    if launcher.down(args.config):
        print("cluster stopped")
        return 0
    print("no such cluster (already down?)")
    return 1


def cmd_exec(args) -> int:
    from ray_memory_management_tpu import launcher

    if not args.command:
        print("rmt exec: no command given "
              "(usage: rmt exec CONFIG -- CMD [ARGS...])", file=sys.stderr)
        return 2
    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        print("rmt exec: no command given", file=sys.stderr)
        return 2
    return launcher.exec_script(args.config, command)


def cmd_status(args) -> int:
    import ray_memory_management_tpu as rmt

    _ephemeral_runtime(args.num_nodes)
    total = rmt.cluster_resources()
    avail = rmt.available_resources()
    print("======== Cluster status ========")
    print(f"Nodes: {len(rmt.nodes())}")
    print("Resources")
    print("---------------------------------")
    for key in sorted(total):
        print(f"  {avail.get(key, 0):.1f}/{total[key]:.1f} {key}")
    rmt.shutdown()
    return 0


def cmd_check(args) -> int:
    """Static-analysis suite (lock discipline, metric/fault registry
    consistency, wire-protocol additivity, trace propagation). Exits
    non-zero with ``file:line: rule: message`` output on violations.
    ``--perf`` instead runs the perf-regression gate: the newest bench
    round's headline fields diffed against the previous round with
    per-field tolerance bands."""
    if args.perf:
        from ray_memory_management_tpu.analysis import check_perf

        return check_perf.main(
            root=args.root, baseline=args.baseline,
            current=args.current, as_json=args.json)
    from ray_memory_management_tpu.analysis.__main__ import main as check

    argv = []
    if args.json:
        argv.append("--json")
    if args.frozen:
        argv.append("--frozen")
    for r in args.rules or ():
        argv.extend(["--rule", r])
    if args.root:
        argv.extend(["--root", args.root])
    return check(argv)


def cmd_memory(args) -> int:
    """Object summary of the runtime in THIS process (meaningful when
    main() is invoked programmatically inside a driver; the runtime is
    in-process, so a fresh CLI process has nothing to attach to)."""
    from ray_memory_management_tpu import _worker_context, state

    if _worker_context.get_runtime() is None:
        print("no cluster is running in this process "
              "(call init() first, then rmt.scripts.cli.main(['memory']))",
              file=sys.stderr)
        return 1
    print(json.dumps(state.summarize_objects(), indent=2))
    return 0


def cmd_summary(args) -> int:
    """Task-state counts plus per-lifecycle-stage latency percentiles of
    the runtime in THIS process (the ``ray summary tasks`` analog). Like
    ``memory``, this reads the in-process runtime — call main(['summary'])
    from a driver."""
    from ray_memory_management_tpu import _worker_context, state

    if _worker_context.get_runtime() is None:
        print("no cluster is running in this process "
              "(call init() first, then rmt.scripts.cli.main(['summary']))",
              file=sys.stderr)
        return 1
    print(json.dumps({
        "tasks": state.summarize_tasks(),
        "latencies": state.summarize_task_latencies(),
    }, indent=2))
    return 0


def cmd_jobs(args) -> int:
    """Live job-plane view of the runtime in THIS process (like
    ``memory``/``summary``, reads the in-process runtime — call
    main(['jobs']) from a driver). One row per GCS job (driver + every
    thin-client connection) with its quota-ledger usage: bytes charged
    against object/device quotas, cpu slots in use vs parked, priority,
    and preemption/demotion counters."""
    from ray_memory_management_tpu import _worker_context, state

    if _worker_context.get_runtime() is None:
        print("no cluster is running in this process "
              "(call init() first, then rmt.scripts.cli.main(['jobs']))",
              file=sys.stderr)
        return 1
    rows = state.list_jobs()
    if args.json:
        print(json.dumps(rows, indent=2))
        return 0

    def _mb(n):
        return f"{(n or 0) / 1e6:8.1f}MB"

    print(f"{'job':16s} {'state':9s} {'prio':>4s} {'slots':>11s} "
          f"{'obj_bytes':>10s} {'dev_bytes':>10s} {'preempt':>7s}")
    for row in rows:
        u = row.get("usage") or {}
        q = u.get("quota") or {}
        slots = (f"{u.get('tasks_inflight', 0)}/"
                 f"{q.get('cpu_slots') or '∞'}"
                 + (f" (+{u['tasks_parked']}q)"
                    if u.get("tasks_parked") else ""))
        print(f"{row['job_id'][:16]:16s} {row.get('state', '?'):9s} "
              f"{u.get('priority', 1):>4d} {slots:>11s} "
              f"{_mb(u.get('object_bytes'))} {_mb(u.get('device_bytes'))} "
              f"{u.get('preempted', 0):>7d}")
    return 0


def cmd_trace(args) -> int:
    """Span tree + critical-path attribution for one trace of the
    runtime in THIS process (like ``summary``/``memory``, reads the
    in-process runtime — call main(['trace', ...]) from a driver). With
    no trace_id, lists the indexed trace ids newest-last."""
    from ray_memory_management_tpu import _worker_context, state

    rt = _worker_context.get_runtime()
    if rt is None:
        print("no cluster is running in this process "
              "(call init() first, then rmt.scripts.cli.main(['trace']))",
              file=sys.stderr)
        return 1
    if not args.trace_id:
        with rt._lock:
            trace_ids = list(rt._traces)
        print(json.dumps({"traces": trace_ids}, indent=2))
        return 0
    data = {
        "trace": state.get_trace(args.trace_id),
        "critical_path": state.summarize_critical_path(args.trace_id),
    }
    if args.output:
        with open(args.output, "w") as f:
            json.dump(data, f, indent=2)
        print(f"trace written to {args.output}")
    else:
        print(json.dumps(data, indent=2))
    return 0


def cmd_logs(args) -> int:
    """Structured log records of the runtime in THIS process (like
    ``trace``/``summary``, reads the in-process runtime — call
    main(['logs', ...]) from a driver). ``--follow`` poll-tails the
    head store, printing new records as workers ship them — the
    driver-live-tail analog of Ray's worker-output streaming."""
    import time

    from ray_memory_management_tpu import _worker_context, state
    from ray_memory_management_tpu.utils import structlog

    rt = _worker_context.get_runtime()
    if rt is None:
        print("no cluster is running in this process "
              "(call init() first, then rmt.scripts.cli.main(['logs']))",
              file=sys.stderr)
        return 1

    def fetch(since_seq: int):
        recs = state.get_logs(task_id=args.task_id,
                              trace_id=args.trace_id,
                              node_id=args.node_id,
                              level=args.level,
                              limit=args.limit)
        return [r for r in recs if r.get("seq", 0) > since_seq]

    last_seq = 0
    deadline = (time.monotonic() + args.duration
                if args.duration is not None else None)
    try:
        while True:
            for rec in fetch(last_seq):
                print(structlog.format_record(rec))
                last_seq = max(last_seq, rec.get("seq", 0))
            if not args.follow:
                return 0
            if deadline is not None and time.monotonic() >= deadline:
                return 0
            time.sleep(args.poll_interval)
    except KeyboardInterrupt:
        return 0


def cmd_profile(args) -> int:
    """Folded stack samples from the cluster profiling plane (like
    ``logs``/``trace``, reads the in-process runtime — call
    main(['profile', ...]) from a driver). ``--duration`` waits that
    long first so the continuous samplers accumulate more cluster-wide
    samples (and, with ``--hz``, additionally burst-samples THIS process
    at that rate while waiting). ``-o FILE`` writes collapsed-stack
    lines (``stack count``) ready for flamegraph.pl / Speedscope."""
    import time

    from ray_memory_management_tpu import _worker_context, state
    from ray_memory_management_tpu.utils import profiler

    rt = _worker_context.get_runtime()
    if rt is None:
        print("no cluster is running in this process "
              "(call init() first, then rmt.scripts.cli.main(['profile']))",
              file=sys.stderr)
        return 1
    if args.duration:
        if args.hz:
            profiler.burst(args.duration, args.hz)
        else:
            time.sleep(args.duration)
    folded = state.get_profile(node_id=args.node_id,
                               task_id=args.task_id,
                               trace_id=args.trace_id,
                               limit=args.limit, fold=True)
    lines = [f"{r['stack']} {r['count']}" for r in folded]
    if args.output:
        with open(args.output, "w") as f:
            f.write("\n".join(lines) + ("\n" if lines else ""))
        print(f"{len(lines)} folded stacks written to {args.output}")
    else:
        for line in lines:
            print(line)
    return 0


def cmd_doctor(args) -> int:
    """Ranked cluster diagnosis from the health plane (like ``logs``/
    ``trace``, reads the in-process runtime — call main(['doctor'])
    from a driver): re-evaluates the SLO rule pack against the tsdb,
    runs the static probes (dead nodes, stuck leases, unsealed creates,
    degraded spill, quota-starved jobs), and prints firing alerts first
    — severity-ranked, each with its evidence window and (when
    attributable) the exemplar trace id to pivot into ``rmt trace``/
    ``rmt logs``/``rmt profile``."""
    from ray_memory_management_tpu import _worker_context, state
    from ray_memory_management_tpu.core import health as _health

    rt = _worker_context.get_runtime()
    if rt is None:
        print("no cluster is running in this process "
              "(call init() first, then rmt.scripts.cli.main(['doctor']))",
              file=sys.stderr)
        return 1
    engine = getattr(rt, "health", None)
    store = getattr(rt, "tsdb", None)
    if engine is None or store is None:
        print("health plane unavailable on this runtime", file=sys.stderr)
        return 1
    engine.evaluate()  # fresh pass so the diagnosis isn't one tick stale
    alerts = state.get_alerts()
    probes = _health.run_probes(rt, store)
    # rule-pack point-in-time values round out the diagnosis (a rule
    # under threshold still shows what it measured)
    rules = []
    for rule in engine.rules:
        try:
            value = engine.eval_expr(rule)
        except Exception:
            value = None
        rules.append({"rule": rule.name, "expr": rule.describe_expr(),
                      "value": value, "threshold": rule.threshold,
                      "severity": rule.severity})
    firing = [a for a in alerts if a["state"] == "firing"]
    healthy = not firing and not probes
    if args.json:
        print(json.dumps({"healthy": healthy, "alerts": alerts,
                          "probes": probes, "rules": rules}, indent=2))
        return 0 if healthy else 1

    def _fmt_val(v):
        return "n/a" if v is None else f"{v:g}"

    print("======== rmt doctor ========")
    if healthy:
        print("healthy: no firing alerts, no probe findings")
    for i, a in enumerate(firing, 1):
        print(f"{i}. [{a['severity']}] {a['rule']}: {a['expr']} = "
              f"{_fmt_val(a['value'])} (threshold {a['threshold']:g}, "
              f"held {a['for_duration_s']:g}s)")
        if a.get("description"):
            print(f"   {a['description']}")
        ev = a.get("evidence") or []
        if ev:
            pts = ", ".join(f"{v:g}" for _, v in ev)
            print(f"   evidence ({len(ev)} samples over "
                  f"{ev[-1][0] - ev[0][0]:.1f}s): {pts}")
        ex = a.get("exemplar") or {}
        if ex.get("trace_id"):
            print(f"   pivot: rmt trace {ex['trace_id']}"
                  + (f"  (task {ex['task_id']})" if ex.get("task_id")
                     else ""))
    for f in probes:
        print(f"-- [{f['severity']}] {f['probe']}: {f['summary']}")
    print("---- rule pack ----")
    for r in rules:
        print(f"   {r['rule']:20s} {r['expr']:45s} "
              f"{_fmt_val(r['value']):>12s} / {r['threshold']:g}")
    return 0 if healthy else 1


def cmd_microbenchmark(args) -> int:
    import ray_memory_management_tpu as rmt
    from ray_memory_management_tpu.utils.microbenchmark import (
        run_microbenchmark,
    )

    _ephemeral_runtime()
    results = run_microbenchmark(scale=args.scale)
    for name, value in results.items():
        unit = "GB/s" if "gigabytes" in name else "ops/s"
        print(f"{name}: {value:,.1f} {unit}")
    rmt.shutdown()
    return 0


def cmd_timeline(args) -> int:
    import ray_memory_management_tpu as rmt

    _ephemeral_runtime()
    path = rmt.timeline(args.output)
    print(f"trace written to {path}")
    rmt.shutdown()
    return 0


# ------------------------------------------------------------------- jobs
def cmd_job_submit(args) -> int:
    from ray_memory_management_tpu.job_submission import JobSubmissionClient

    import shlex

    client = JobSubmissionClient(args.job_dir)
    entrypoint = list(args.entrypoint)
    if entrypoint and entrypoint[0] == "--":
        entrypoint = entrypoint[1:]
    if not entrypoint:
        print("error: no entrypoint command given", file=sys.stderr)
        return 2
    # shlex.join preserves each argv element's quoting through the
    # shell=True re-parse (plain ' '.join corrupts args with spaces)
    job_id = client.submit_job(
        entrypoint=shlex.join(entrypoint),
        submission_id=args.submission_id)
    print(job_id)
    if args.wait:
        for chunk in client.tail_job_logs(job_id, timeout_s=args.timeout):
            sys.stdout.write(chunk)
        status = client.get_job_status(job_id)
        print(f"\njob {job_id} finished: {status}")
        return 0 if status == "SUCCEEDED" else 1
    return 0


def cmd_job_list(args) -> int:
    from ray_memory_management_tpu.job_submission import JobSubmissionClient

    for meta in JobSubmissionClient(args.job_dir).list_jobs():
        print(f"{meta['job_id']}  {meta['status']:10s}  "
              f"{meta['entrypoint']}")
    return 0


def cmd_job_status(args) -> int:
    from ray_memory_management_tpu.job_submission import JobSubmissionClient

    print(JobSubmissionClient(args.job_dir).get_job_status(args.job_id))
    return 0


def cmd_job_logs(args) -> int:
    from ray_memory_management_tpu.job_submission import JobSubmissionClient

    sys.stdout.write(
        JobSubmissionClient(args.job_dir).get_job_logs(args.job_id))
    return 0


def cmd_job_stop(args) -> int:
    from ray_memory_management_tpu.job_submission import JobSubmissionClient

    ok = JobSubmissionClient(args.job_dir).stop_job(args.job_id)
    print("stopped" if ok else "not running")
    return 0


# --------------------------------------------------------------- workflow
def cmd_workflow_list(args) -> int:
    from ray_memory_management_tpu import workflow

    for wid, status in workflow.list_all():
        print(f"{wid}  {status}")
    return 0


def cmd_workflow_status(args) -> int:
    from ray_memory_management_tpu import workflow

    print(workflow.get_status(args.workflow_id))
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="rmt", description="TPU-native distributed runtime CLI")
    sub = p.add_subparsers(dest="command", required=True)

    s = sub.add_parser("status", help="show cluster resources")
    s.add_argument("--num-nodes", type=int, default=1)
    s.set_defaults(fn=cmd_status)

    s = sub.add_parser(
        "up",
        help="boot a cluster from a YAML config: a detached head serving "
             "thin clients plus one node agent per worker entry "
             "('ray up' analog)")
    s.add_argument("config", help="cluster YAML path")
    s.set_defaults(fn=cmd_up)

    s = sub.add_parser("down", help="tear a cluster down ('ray down')")
    s.add_argument("config", help="cluster YAML path or cluster name")
    s.set_defaults(fn=cmd_down)

    s = sub.add_parser(
        "exec",
        help="run a command against a running cluster: RMT_CLIENT_ADDRESS "
             "is set for the child ('ray exec'/'ray submit' analog)")
    s.add_argument("config", help="cluster YAML path or cluster name")
    s.add_argument("command", nargs=argparse.REMAINDER,
                   help="command (and args) to run")
    s.set_defaults(fn=cmd_exec)

    s = sub.add_parser(
        "agent",
        help="join this host to a head as a worker node (the reference's "
             "'ray start --address' analog; runs a node agent that tunnels "
             "workers + objects to the head over TCP)")
    s.add_argument("--address", required=True, help="head HOST:PORT")
    s.add_argument("--authkey", required=True, help="hex cluster authkey")
    s.add_argument("--num-cpus", type=int, default=4)
    s.add_argument("--num-tpus", type=int, default=0)
    s.set_defaults(fn=cmd_agent)

    s = sub.add_parser(
        "check",
        help="run the rmtcheck static-analysis suite (exit non-zero on "
             "violations)")
    s.add_argument("--json", action="store_true",
                   help="machine-readable JSON report")
    s.add_argument("--frozen", action="store_true",
                   help="fail on new wire-protocol keys instead of "
                        "auto-registering (CI mode)")
    s.add_argument("--rule", action="append", dest="rules", metavar="RULE",
                   help="run only this rule (repeatable)")
    s.add_argument("--root", default=None, help="repo root to analyze")
    s.add_argument("--perf", action="store_true",
                   help="run the perf-regression gate over the "
                        "BENCH_r*.json history instead of the static "
                        "rules (exit 1 on a regression past tolerance)")
    s.add_argument("--baseline", default=None, metavar="ROUND",
                   help="with --perf: baseline round (e.g. 5 or "
                        "BENCH_r05.json; default: previous parseable "
                        "round)")
    s.add_argument("--current", default=None, metavar="ROUND",
                   help="with --perf: round under test (default: newest "
                        "parseable round)")
    s.set_defaults(fn=cmd_check)

    s = sub.add_parser("memory", help="object store summary")
    s.set_defaults(fn=cmd_memory)

    s = sub.add_parser(
        "summary",
        help="task-state counts + per-stage latency p50/p95/p99")
    s.set_defaults(fn=cmd_summary)

    s = sub.add_parser(
        "jobs",
        help="live jobs (driver + thin-client connections) with "
             "quota-ledger usage: bytes, slots, priority, preemptions")
    s.add_argument("--json", action="store_true",
                   help="machine-readable JSON rows")
    s.set_defaults(fn=cmd_jobs)

    s = sub.add_parser(
        "trace",
        help="span tree + critical-path breakdown for one trace "
             "(no trace_id: list known trace ids)")
    s.add_argument("trace_id", nargs="?", default=None)
    s.add_argument("--output", default=None,
                   help="write JSON here instead of stdout")
    s.set_defaults(fn=cmd_trace)

    s = sub.add_parser(
        "logs",
        help="query the cluster log plane (print/logging output of every "
             "worker, trace-correlated); --follow live-tails it")
    s.add_argument("--task", dest="task_id", default=None,
                   help="filter: task id (hex)")
    s.add_argument("--trace", dest="trace_id", default=None,
                   help="filter: trace id (hex)")
    s.add_argument("--node", dest="node_id", default=None,
                   help="filter: node id (hex)")
    s.add_argument("--level", default=None,
                   help="minimum severity (DEBUG/INFO/WARNING/ERROR/"
                        "CRITICAL)")
    s.add_argument("--limit", type=int, default=1000,
                   help="newest N records per poll (default 1000)")
    s.add_argument("--follow", action="store_true",
                   help="poll for new records until interrupted")
    s.add_argument("--duration", type=float, default=None,
                   help="with --follow: stop after this many seconds")
    s.add_argument("--poll-interval", type=float, default=0.5,
                   help="follow poll period in seconds (default 0.5)")
    s.set_defaults(fn=cmd_logs)

    s = sub.add_parser(
        "profile",
        help="query the cluster profiling plane (folded stack samples "
             "from every process, task/trace-correlated); -o writes "
             "flamegraph.pl-ready collapsed stacks")
    s.add_argument("--task", dest="task_id", default=None,
                   help="filter: task id (hex)")
    s.add_argument("--trace", dest="trace_id", default=None,
                   help="filter: trace id (hex)")
    s.add_argument("--node", dest="node_id", default=None,
                   help="filter: node id (hex)")
    s.add_argument("--duration", type=float, default=None,
                   help="accumulate samples for this many seconds "
                        "before querying")
    s.add_argument("--hz", type=float, default=None,
                   help="with --duration: burst-sample this process at "
                        "this rate while waiting")
    s.add_argument("--limit", type=int, default=10000,
                   help="newest N samples to merge (default 10000)")
    s.add_argument("-o", "--output", default=None,
                   help="write folded 'stack count' lines here instead "
                        "of stdout")
    s.set_defaults(fn=cmd_profile)

    s = sub.add_parser(
        "doctor",
        help="ranked cluster diagnosis: run the health rule pack + "
             "static probes and print firing alerts with evidence "
             "(exit 1 when anything fires)")
    s.add_argument("--json", action="store_true",
                   help="machine-readable JSON diagnosis")
    s.set_defaults(fn=cmd_doctor)

    s = sub.add_parser("microbenchmark",
                       help="run the core microbenchmark suite")
    s.add_argument("--scale", type=float, default=1.0)
    s.set_defaults(fn=cmd_microbenchmark)

    s = sub.add_parser("timeline", help="dump a chrome trace")
    s.add_argument("--output", default="timeline.json")
    s.set_defaults(fn=cmd_timeline)

    job = sub.add_parser("job", help="job submission")
    jsub = job.add_subparsers(dest="job_command", required=True)
    s = jsub.add_parser("submit")
    s.add_argument("--job-dir", default=None)
    s.add_argument("--submission-id", default=None)
    s.add_argument("--wait", action="store_true")
    s.add_argument("--timeout", type=float, default=3600.0)
    s.add_argument("entrypoint", nargs=argparse.REMAINDER)
    s.set_defaults(fn=cmd_job_submit)
    for name, fn, extra in (
        ("list", cmd_job_list, ()),
        ("status", cmd_job_status, ("job_id",)),
        ("logs", cmd_job_logs, ("job_id",)),
        ("stop", cmd_job_stop, ("job_id",)),
    ):
        s = jsub.add_parser(name)
        s.add_argument("--job-dir", default=None)
        for a in extra:
            s.add_argument(a)
        s.set_defaults(fn=fn)

    wf = sub.add_parser("workflow", help="workflow management")
    wsub = wf.add_subparsers(dest="workflow_command", required=True)
    s = wsub.add_parser("list")
    s.set_defaults(fn=cmd_workflow_list)
    s = wsub.add_parser("status")
    s.add_argument("workflow_id")
    s.set_defaults(fn=cmd_workflow_status)

    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
