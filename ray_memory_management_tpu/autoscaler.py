"""Autoscaler: reconcile node count against resource demand.

The reference's StandardAutoscaler.update loop (autoscaler/_private/
autoscaler.py:154,345) driven by the Monitor (monitor.py:125,333) reading
load from GCS, with pluggable NodeProviders (AWS/GCP/.../fake_multi_node).
Here: demand = tasks the scheduler could not place (the runtime's pending
queue) plus per-node queue backlog; the provider contract is create/
terminate; ``VirtualNodeProvider`` adds in-process nodes (the
fake_multi_node analog used for tests), and a TPU-pod provider slots in
by implementing the same two methods over real hosts.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from . import _worker_context

# -- explicit demand (autoscaler sdk request_resources analog) ----------------
# The elastic trainer pins a demand floor here so the Monitor replaces a
# dead training node even while no tasks are queued (a gang that lost a
# member holds its survivors and queues NOTHING — invisible to the
# pending/backlog signals below).
_request_mu = threading.Lock()
_requested_bundles: List[Dict[str, float]] = []


def request_resources(bundles: Optional[List[Dict[str, float]]] = None
                      ) -> None:
    """Pin a resource-demand floor (ray.autoscaler.sdk.request_resources
    analog): the autoscaler scales until the cluster's TOTAL capacity can
    hold every requested bundle. Replaces any previous request; ``None``
    or ``[]`` clears it."""
    with _request_mu:
        _requested_bundles[:] = [dict(b) for b in (bundles or [])]


def requested_bundles() -> List[Dict[str, float]]:
    with _request_mu:
        return [dict(b) for b in _requested_bundles]


class NodeProvider:
    """Provider contract (autoscaler/node_provider.py): create/terminate
    nodes and enumerate the ones this autoscaler manages."""

    def create_node(self, node_config: Dict[str, Any]) -> Any:
        raise NotImplementedError

    def terminate_node(self, node_id: Any) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[Any]:
        raise NotImplementedError


class _RuntimeNodeProvider(NodeProvider):
    """Shared bookkeeping for providers that add nodes to the local
    runtime: tracks managed node ids and filters on cluster liveness;
    subclasses supply the create/terminate mechanism."""

    def __init__(self, runtime=None):
        self._rt = runtime or _worker_context.get_runtime()
        self._managed: List[Any] = []

    def _create(self, node_config: Dict[str, Any]) -> Any:
        raise NotImplementedError

    def _terminate(self, node_id: Any) -> None:
        raise NotImplementedError

    def create_node(self, node_config: Dict[str, Any]) -> Any:
        node_id = self._create(node_config)
        self._managed.append(node_id)
        return node_id

    def terminate_node(self, node_id: Any) -> None:
        if node_id in self._managed:
            self._managed.remove(node_id)
        self._terminate(node_id)

    def non_terminated_nodes(self) -> List[Any]:
        return [n for n in self._managed
                if self._rt.nodes.get(n) and self._rt.nodes[n].alive]


class VirtualNodeProvider(_RuntimeNodeProvider):
    """Adds/removes virtual nodes on the in-process runtime — the
    fake_multi_node provider analog for tests and laptops."""

    def _create(self, node_config: Dict[str, Any]) -> Any:
        return self._rt.add_node(dict(node_config))

    def _terminate(self, node_id: Any) -> None:
        self._rt.remove_node(node_id)


class ProcessNodeProvider(_RuntimeNodeProvider):
    """Scales real node-agent PROCESSES joined to the head over TCP (the
    multi-host plane, core/node_agent.py) — each node shares nothing with
    the head but the channel, so this is the faithful stand-in for a
    cloud/TPU-pod provider on one box; a real pod provider implements the
    same two methods with GCE create/delete calls."""

    def _create(self, node_config: Dict[str, Any]) -> Any:
        return self._rt.add_remote_node_process(
            num_cpus=node_config.get("num_cpus", 4),
            num_tpus=node_config.get("num_tpus", 0))

    def _terminate(self, node_id: Any) -> None:
        self._rt.stop_remote_node(node_id)


class StandardAutoscaler:
    """One reconciliation pass per ``update()`` (autoscaler.py:345):
    scale up while unplaceable demand exists and below max_workers;
    scale down nodes idle longer than idle_timeout_s."""

    def __init__(self, provider: NodeProvider,
                 node_config: Optional[Dict[str, Any]] = None,
                 min_workers: int = 0, max_workers: int = 4,
                 idle_timeout_s: float = 30.0,
                 upscaling_speed: float = 1.0,
                 runtime=None):
        self.provider = provider
        self.node_config = dict(node_config or {"num_cpus": 4})
        self.min_workers = min_workers
        self.max_workers = max_workers
        self.idle_timeout_s = idle_timeout_s
        self.upscaling_speed = max(upscaling_speed, 0.1)
        self._rt = runtime or _worker_context.get_runtime()
        self._idle_since: Dict[Any, float] = {}
        self.num_launches = 0
        self.num_terminations = 0

    # -- demand signals -------------------------------------------------------
    def pending_demand(self) -> int:
        """Tasks with nowhere to go right now (the load-metrics 'pending'
        the reference monitor reads from GCS)."""
        rt = self._rt
        with rt._lock:  # nodes dict mutates under this same lock
            pending = len(rt._pending_schedule)
            node_managers = list(rt.nodes.values())
        backlog = sum(nm.backlog() for nm in node_managers if nm.alive)
        return pending + backlog + self._unmet_requests(node_managers)

    def _unmet_requests(self, node_managers) -> int:
        """Requested bundles (request_resources) that the cluster's TOTAL
        capacity cannot hold — charged against totals, not availability,
        so a running gang does not read as perpetual demand."""
        req = requested_bundles()
        if not req:
            return 0
        from .core.resources import Resources

        totals = [Resources.from_fixed(nm.resources.total.fixed())
                  for nm in node_managers if nm.alive]
        unmet = 0
        for b in req:
            r = Resources(b)
            for i, free in enumerate(totals):
                if r.fits_in(free):
                    totals[i] = free - r
                    break
            else:
                unmet += 1
        return unmet

    def _node_busy(self, node_id) -> bool:
        nm = self._rt.nodes.get(node_id)
        if nm is None or not nm.alive:
            return False
        if nm.queue:
            return True
        return any(h.inflight or h.actor_id is not None
                   for h in nm.workers.values())

    # -- reconciliation -------------------------------------------------------
    def update(self) -> None:
        managed = self.provider.non_terminated_nodes()
        demand = self.pending_demand()

        # scale up: below min, or unplaceable work exists
        want = 0
        if len(managed) < self.min_workers:
            want = self.min_workers - len(managed)
        elif demand > 0 and len(managed) < self.max_workers:
            want = max(1, int(len(managed) * self.upscaling_speed) or 1)
        want = min(want, self.max_workers - len(managed))
        for _ in range(max(want, 0)):
            self.provider.create_node(self.node_config)
            self.num_launches += 1

        # scale down: idle past the timeout, but never below min_workers
        now = time.monotonic()
        managed = self.provider.non_terminated_nodes()
        for node_id in list(managed):
            if self._node_busy(node_id):
                self._idle_since.pop(node_id, None)
                continue
            since = self._idle_since.setdefault(node_id, now)
            if (now - since >= self.idle_timeout_s
                    and len(self.provider.non_terminated_nodes())
                    > self.min_workers):
                self.provider.terminate_node(node_id)
                self._idle_since.pop(node_id, None)
                self.num_terminations += 1


class Monitor:
    """Background loop driving the autoscaler (monitor.py:333)."""

    def __init__(self, autoscaler: StandardAutoscaler,
                 update_interval_s: float = 1.0):
        self.autoscaler = autoscaler
        self.update_interval_s = update_interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="rmt-autoscaler")
        self._thread.start()

    def _loop(self) -> None:
        import logging

        log = logging.getLogger(__name__)
        while not self._stop.is_set():
            try:
                self.autoscaler.update()
            except Exception:
                # keep reconciling, but a failing provider must be visible
                log.exception("autoscaler update failed")
            self._stop.wait(self.update_interval_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
