"""Typed, env-overridable configuration flags.

Mirrors the reference's ``RAY_CONFIG(type, name, default)`` macro system
(src/ray/common/ray_config.h:46-58, defaults in src/ray/common/ray_config_def.h):
every flag has a type, a default, and an environment override spelled
``RMT_<NAME>``. Unlike the reference's C++ singleton, this is a plain Python
dataclass-like registry so tests can construct scoped configs.
"""

from __future__ import annotations

import os
from typing import Any, Dict

_FLAG_DEFS: Dict[str, tuple] = {}


def _flag(name: str, typ, default, doc: str = ""):
    _FLAG_DEFS[name] = (typ, default, doc)
    return default


# --- object store / data plane (reference: ray_config_def.h) -----------------
_flag("max_direct_call_object_size", int, 100 * 1024,
      "Objects <= this are inlined in task replies / the in-process memory "
      "store instead of the shared-memory store (ray_config_def.h:181).")
_flag("task_rpc_inlined_bytes_limit", int, 10 * 1024 * 1024,
      "Total bytes of args inlined into a task submission (ray_config_def.h:424).")
_flag("object_store_memory", int, 512 * 1024 * 1024,
      "Per-node shared-memory store capacity in bytes.")
_flag("object_store_fallback_directory", str, "/tmp/rmt_spill",
      "Directory for spilled objects (external storage).")
_flag("min_spilling_size", int, 1 * 1024 * 1024,
      "Spill batches of at least this many bytes (ray_config_def.h:495; the "
      "reference default is 100 MiB, scaled down for single-host stores).")
_flag("object_spilling_threshold", float, 0.8,
      "Start spilling when the store passes this fraction full "
      "(ray_config_def.h:499).")
_flag("object_store_full_timeout_s", float, 5.0,
      "How long an allocation waits for reader refs / pins to drain when "
      "nothing is spillable before raising ObjectStoreFullError (the plasma "
      "CreateRequestQueue blocks clients the same way, "
      "create_request_queue.h:32).")
_flag("push_pressure_retry_s", float, 30.0,
      "Total budget a pressured push to a remote store keeps retrying "
      "(with backoff) while the sender holds its read ref. The receiver "
      "nacks 'retryable' when transiently full instead of failing the "
      "transfer — pressure causes slowness, never object loss (the "
      "reference's pull-manager admission control + queued plasma "
      "creates, pull_manager.h:47, create_request_queue.h:32).")
_flag("max_io_workers", int, 2,
      "Concurrent spill/restore IO threads (ray_config_def.h:489; default 4).")
_flag("object_manager_chunk_size", int, 5 * 1024 * 1024,
      "Chunk size for inter-node object push/pull (ray_config_def.h:300).")
_flag("transfer_max_conns", int, 32,
      "Concurrent serving REQUESTS per TransferServer (the PullManager "
      "in-flight cap analog, pull_manager.h:47). Must comfortably exceed "
      "transfer_stripe_count: one striped peer alone opens that many "
      "parallel range requests.")
_flag("transfer_stripe_threshold", int, 8 * 1024 * 1024,
      "Objects >= this many bytes are pulled as parallel stripes over "
      "multiple connections; smaller objects use one stream (the v2 "
      "range-request wire protocol).")
_flag("transfer_stripe_count", int, 0,
      "Parallel connections per striped pull; each stripe receives a "
      "disjoint range of the same destination allocation. 0 = auto "
      "(min(4, cpu_count)): on a single-core host parallel stripes only "
      "add GIL/context-switch overhead (measured 1.16 -> 0.75 GB/s at 4 "
      "stripes), so auto degrades to one stream there.")
_flag("transfer_pool_size", int, 8,
      "Idle authenticated connections kept per (host, port) peer by the "
      "transfer-plane connection pool, amortizing the challenge/response "
      "handshake across pulls. 0 disables pooling.")
_flag("transfer_idle_timeout_s", float, 30.0,
      "Server-side idle timeout on a pooled transfer connection: a "
      "connection with no request for this long is closed (the client "
      "pool transparently re-dials on next use).")
_flag("transfer_broadcast_fanout", int, 2,
      "Max concurrent pulls of ONE object per holding node during a "
      "multi-destination distribution. Later fetchers wait for an "
      "in-flight copy to land and pull from the new holder, turning an "
      "n-destination broadcast from source-bottlenecked O(n*size) into a "
      "pipelined O(size*log n) tree. 0 disables the gate.")

# --- device (HBM) object tier ------------------------------------------------
_flag("device_store_capacity_bytes", int, 0,
      "HBM budget for the per-process device object store; putting past "
      "it demotes least-recently-used UNPINNED device objects to the "
      "host shm tier (which spills below itself as usual). 0 = auto: "
      "60% of jax.local_devices() memory stats when the backend reports "
      "them, else a 1 GiB fallback for CPU-backed arrays. Negative "
      "disables eviction entirely (unbounded pinning).")
_flag("device_demote_precision", str, "f32",
      "Dtype-aware downcast applied when a float32 device object is "
      "demoted to host: 'f32' keeps the exact bytes; 'bf16' writes the "
      "PR 7 quantize envelope (half the host/spill bytes, values "
      "round-tripped through bf16 truncation — rel err <= 2^-8). "
      "Non-f32 payloads always demote exact.")
_flag("device_promote_on_read", bool, True,
      "Re-promote a demoted device object back into the device store on "
      "its next device-side read (LRU re-entry; it can be demoted "
      "again under pressure). Off leaves demoted objects host-resident.")
_flag("device_ici_transfer", bool, True,
      "Move device objects device-to-device with a jitted transfer "
      "(compiled per shape/dtype/src/dst) when producer and consumer "
      "sit on the same mesh, instead of bouncing through host "
      "serialization; cross-mesh readers always fall back to the "
      "striped host wire path.")

# --- scheduling --------------------------------------------------------------
_flag("scheduler_spread_threshold", float, 0.5,
      "Hybrid policy: pack onto the local/low-index nodes until utilization "
      "passes this, then spread (hybrid_scheduling_policy.h:48).")
_flag("scheduler_locality_weight", float, 1.0,
      "Soft data-locality score weight: among fitting nodes, prefer the "
      "holder of the most argument bytes, traded off against utilization "
      "and dispatch-queue depth (the owner-side locality-aware lease "
      "policy, locality_aware_scheduling in the direct task transport). "
      "0 disables locality scoring entirely. Always subordinate to hard "
      "NodeAffinity / placement-group strategies and to spillback when "
      "the holder is saturated.")
_flag("locality_min_bytes", int, 256 * 1024,
      "Locality scoring engages only when some fitting node holds at "
      "least this many argument bytes — tiny args are cheaper to move "
      "than a placement distortion is to absorb (inlined args never "
      "count: they ship in the exec message).")
_flag("argument_prefetch", bool, True,
      "Pipelined argument prestage: when placement lands on a non-holder, "
      "submit the task to the node's dispatch queue immediately and pull "
      "its args concurrently, overlapping the transfer with queue wait "
      "instead of serializing it in front of execution. Prestaged pulls "
      "ride the broadcast-gate admission; a worker that wins the race "
      "simply blocks on its arg get until the same copy lands "
      "(create_or_wait dedupes). Off restores transfer-then-submit.")
_flag("worker_prestart_count", int, 2,
      "Workers to prestart per node at startup (worker_pool.h prestart).")
_flag("max_workers_per_node", int, 8,
      "Upper bound on pooled workers per node.")
_flag("worker_lease_timeout_s", float, 30.0,
      "How long a task waits for a worker lease before erroring.")
_flag("log_to_driver", bool, True,
      "Stream worker stdout/stderr to the driver, prefixed with the worker "
      "identity (the reference's log monitor tails worker logs to the "
      "driver, services.py:1126; here the lines ride the worker pipe).")
_flag("max_tasks_in_flight_per_worker", int, 10,
      "Pipelining depth: tasks whose resource request matches a busy "
      "worker's held lease queue on its pipe instead of waiting for the "
      "owner round trip (the reference's small-task pipelining knob, "
      "max_tasks_in_flight_per_worker in the direct task transport).")
_flag("worker_fork_server", bool, True,
      "Fork CPU-platform workers from a pre-warmed zygote process (ms "
      "spawns) instead of exec'ing a fresh interpreter (the reference's "
      "WorkerPool prestart/reuse economics, worker_pool.h:104,349,427). "
      "TPU-platform workers always cold-spawn.")
_flag("cpu_worker_env_drop", str, "PALLAS_AXON_POOL_IPS",
      "Comma-separated env vars dropped when spawning CPU-platform workers "
      "— accelerator-bootstrap triggers (sitecustomize TPU plugin init) "
      "that would cost seconds of spawn latency a CPU worker never needs.")

# --- multi-host plane --------------------------------------------------------
_flag("enable_node_listener", bool, True,
      "Listen for node agents joining over TCP (the head side of the "
      "multi-host plane; node_agent.py is the raylet-process analog).")
_flag("node_listener_host", str, "127.0.0.1",
      "Interface the node listener binds. Use 0.0.0.0 to accept agents "
      "from other hosts.")
_flag("node_listener_port", int, 0,
      "Node listener port; 0 picks an ephemeral port.")

_flag("gcs_storage_path", str, "",
      "Durable GCS table storage (sqlite file). Empty = in-memory tables "
      "that die with the driver; set a path and detached actors + cluster "
      "KV survive head restarts (the Redis-FT analog, "
      "redis_store_client.h:28).")

# --- decentralized control plane ---------------------------------------------
_flag("gcs_directory_shards", int, 0,
      "Lock-striped shards for the GCS object directory (locations / "
      "sizes / tiers) and the head's refcount tables, keyed by object id "
      "so directory updates and free batches from different nodes never "
      "contend on one lock (the reference shards its GCS tables the same "
      "way, gcs_table_storage.h). 0 = auto (cpu_count, clamped to "
      "[4, gcs_directory_shards_max]).")
_flag("gcs_directory_shards_max", int, 64,
      "Upper clamp for AUTO directory-shard resolution. 64 shards stop "
      "paying off around 8 virtual nodes; pod-scale runs (64-256 node "
      "memberships) raise this so add/locate traffic from hundreds of "
      "agent channels keeps striping instead of re-serializing.")
_flag("gcs_directory_hot_max_rows", int, 1_000_000,
      "Hot-row budget for the GCS object directory, split evenly across "
      "shards. Rows beyond the per-shard share spill COLD (LRU within "
      "shard): holder set / size / tier map serialize in batches to the "
      "gcs_storage blob surface and fault back in transparently on "
      "locate, so head RSS stays bounded at millions of rows instead of "
      "growing ~1KB per live object. <=0 disables spilling (every row "
      "stays RAM-resident).")
_flag("gcs_directory_cold_s", float, 5.0,
      "A directory row is a spill candidate once it has not been "
      "located, renewed, or mutated for this long. The hard hot-row cap "
      "wins over recency: an over-budget shard spills its LRU tail even "
      "if some of it is younger than this.")
_flag("leaf_lease_batch", int, 64,
      "Max leaf-lease grants coalesced into one lease_batch frame per "
      "node per scheduling pass. The leaf fast path buffers grants "
      "head-side and flushes one frame per node instead of one frame "
      "per task, so per-node control ingress is O(flushes), not "
      "O(tasks). 1 disables coalescing (every grant ships alone, the "
      "pre-batching wire behavior).")
_flag("leaf_lease_slots", int, 0,
      "Execution-lease credits granted in bulk per node for LEAF tasks "
      "(no placement group / affinity / runtime_env, <=1 CPU, no TPU): "
      "the head places these round-robin without consulting the cluster "
      "scheduler, and node agents dispatch them onto their own workers, "
      "spilling back to the head router only when saturated (the raylet "
      "two-level lease protocol, raylet_client.h:398). 0 = auto "
      "(2x the node's CPU count); negative disables leaf leasing.")
# --- multi-tenant job plane --------------------------------------------------
_flag("job_watchdog_interval_s", float, 0.5,
      "Cadence of the cluster server's job watchdog: jobs whose client "
      "connection closed but whose disconnect notification was dropped "
      "(the job.detach fault site) are found and swept at this interval. "
      "<=0 disables the watchdog (dropped detaches then leak until "
      "shutdown — chaos-test territory only).")
_flag("job_sweep_retry_s", float, 1.0,
      "Delay before a job-death sweep that hit an error (the job.sweep "
      "fault site, or a transient runtime error mid-step) is re-run by "
      "the heartbeat loop. Sweeps are idempotent; retrying is always "
      "safe.")
_flag("reply_flush_window_s", float, 0.001,
      "Adaptive coalescing window for worker->head done replies: after "
      "the first queued reply the drain thread waits up to this long for "
      "more completions before writing one batch frame (flushes early on "
      "reply_flush_max or an urgent frame). 0 restores write-asap.")
_flag("reply_flush_max", int, 32,
      "Flush the worker reply batch as soon as it reaches this many "
      "frames, regardless of the adaptive window.")
_flag("sealed_wal_max_bytes", int, 32 * 1024,
      "With durable gcs_storage_path set, sealed object values up to "
      "this size are written to a sealed-object WAL so a head restart "
      "loses no sealed small objects (larger values stay recoverable "
      "through lineage / spill as before). 0 disables the WAL.")

# --- cloud storage credentials -----------------------------------------------
_flag("cloud_storage_access_key", str, "",
      "Access key id for the s3:// external-storage backend. Resolution "
      "order: this flag (incl. RMT_cloud_storage_access_key), then the "
      "AWS_ACCESS_KEY_ID environment variable, then the SDK default "
      "chain (instance profile, ~/.aws).")
_flag("cloud_storage_secret_key", str, "",
      "Secret access key paired with cloud_storage_access_key.")
_flag("cloud_storage_endpoint", str, "",
      "Endpoint URL override for the s3:// backend (minio, GCS interop "
      "mode). Empty uses the SDK default endpoint; also honors "
      "AWS_ENDPOINT_URL.")
_flag("cloud_storage_region", str, "",
      "Region for the s3:// backend; falls back to AWS_DEFAULT_REGION "
      "then the SDK default.")
_flag("cloud_storage_credentials_file", str, "",
      "Service-account JSON for the gs:// backend; falls back to "
      "GOOGLE_APPLICATION_CREDENTIALS then the SDK default chain.")

# --- fault tolerance ---------------------------------------------------------
_flag("fault_injection_spec", str, "",
      "Deterministic fault-injection plane spec (utils/faults.py): "
      "';'-separated 'site:mode[:p=P][:after=N][:max=N][:stall=S]' rules "
      "over the registered sites (transfer.send/recv/dial, spill.write/"
      "read, control.dispatch, worker.exec). Empty disables injection. "
      "Propagates to node agents and workers via RMT_fault_injection_spec.")
_flag("fault_injection_seed", int, 0,
      "Seed for the fault plane's per-site RNG streams: same seed + spec "
      "=> the same injection schedule, replayable across runs.")
_flag("transfer_retry_attempts", int, 3,
      "Max attempts per transfer-plane operation (dial, fetch) under the "
      "unified RetryPolicy before the failure is surfaced.")
_flag("transfer_retry_backoff_s", float, 0.05,
      "Base exponential backoff between transfer retries (jittered).")
_flag("transfer_stripe_deadline_s", float, 30.0,
      "Per-stripe progress deadline on a striped pull: a stripe that "
      "stalls past this re-resolves live holders and re-pulls its range "
      "from an alternate source (mid-pull holder failover) instead of "
      "hanging the whole fetch.")
_flag("transfer_verify_checksum", bool, True,
      "Verify the CRC32 carried in transfer replies / spill metadata at "
      "every materialization boundary (stripe completion, restore). A "
      "mismatch is treated as object loss — re-pull or reconstruct — "
      "never silent corruption.")
_flag("transfer_compression", str, "off",
      "Wire compression for the transfer plane (fetches, broadcast "
      "tree, spill write/restore). 'off' sends raw bytes (today's "
      "path, and what a codec-unaware v2 peer always gets); 'auto' "
      "negotiates the best codec both ends support (lz4 when "
      "available, else zlib); or name one codec ('zlib', 'lz4') to "
      "pin it. Negotiation is additive inside wire protocol v2 — a "
      "peer without the feature simply ignores the request key and "
      "replies raw.")
_flag("transfer_compress_min_bytes", int, 64 * 1024,
      "Payloads below this many bytes are never compressed (the "
      "syscall+CRC already dominates small pulls). Above it, a "
      "trial-block probe still skips encoding for incompressible "
      "payloads so the worst case stays within ~2% of the raw path.")
_flag("transfer_compress_level", int, 1,
      "zlib compression level for the wire codec (1 = fastest; the "
      "wire wants throughput, not archival ratio).")
_flag("collective_precision", str, "f32",
      "Default precision for quantized collectives when neither the "
      "op call nor the group names one: f32 (bit-exact, the default "
      "— quantization is strictly opt-in), bf16 (half the wire "
      "bytes), or int8 (block-wise scales, ~quarter the wire bytes); "
      "dequantize+accumulate always happens at f32 (EQuARX-style).")
_flag("spill_retry_attempts", int, 3,
      "Max attempts per spill/restore IO operation under the RetryPolicy.")
_flag("spill_retry_backoff_s", float, 0.1,
      "Base exponential backoff between spill IO retries (jittered).")
_flag("spill_degraded_backoff_s", float, 30.0,
      "After spill IO exhausts its retries, the store degrades to keeping "
      "objects in memory under backpressure (loud SPILL_DEGRADED event, "
      "not a crash) and re-probes the storage backend at this period.")
_flag("unsealed_create_deadline_s", float, 300.0,
      "Unsealed creates older than this are swept and aborted (the "
      "fetching process died mid-pull and leaked the allocation). Must "
      "comfortably exceed every bounded transfer timeout so a live "
      "in-flight pull is never swept out from under its writer.")
_flag("num_heartbeats_timeout", int, 30,
      "Missed heartbeats before a node is declared dead "
      "(gcs_heartbeat_manager.cc:29).")
_flag("heartbeat_interval_s", float, 0.5, "Node heartbeat period.")
_flag("task_max_retries", int, 4,
      "Default retries for normal tasks (remote_function.py:161-166).")
_flag("actor_max_restarts", int, 0, "Default actor restarts.")

# --- tpu / accelerator -------------------------------------------------------
_flag("tpu_chips_per_host", int, 4,
      "Chips exposed per host-process (v4/v5 host has 4; the worker is a "
      "host-process — SURVEY.md §7 design stance).")
_flag("tpu_visible_chips_env", str, "TPU_VISIBLE_CHIPS",
      "Env var used to scope chips to a leased worker, the TPU analog of "
      "CUDA_VISIBLE_DEVICES handling (_raylet.pyx:563, _private/utils.py:349).")

# --- serve data plane --------------------------------------------------------
_flag("serve_backpressure_timeout_s", float, 60.0,
      "How long a Router.assign call waits for a replica slot to drain "
      "before shedding the request (raises BackpressureTimeout and bumps "
      "rmt_serve_shed_total{reason=backpressure_timeout}).")
_flag("kv_page_tokens", int, 64,
      "KV-cache page size in tokens for the serve engine's paged "
      "device cache: a slot's KV rows grow in pages of this many "
      "positions instead of reserving max_seq up front, so HBM held by "
      "a replica scales with live tokens.")
_flag("serve_kv_pool_bytes", int, 0,
      "Per-replica KV page-pool budget in bytes. 0 sizes the pool to "
      "the monolithic slab's footprint (max_slots x max_seq), so the "
      "paged engine can never hold more HBM than the slab it replaced; "
      "exhaustion causes admission backpressure, never an allocation "
      "failure.")
_flag("serve_shed_queue_factor", float, 2.0,
      "HTTP proxy load-shed threshold as a multiple of the deployment's "
      "total capacity (replicas x max_concurrent_queries): when the "
      "known queue depth exceeds it the proxy answers 429 instead of "
      "queueing the request.")

# --- misc --------------------------------------------------------------------
_flag("memory_monitor_interval_s", float, 0.0,
      "Node OOM-monitor check period (memory_monitor.h analog). 0 "
      "disables it; when enabled, host memory above the threshold kills "
      "the newest running task's worker (it retries under its budget).")
_flag("memory_usage_threshold", float, 0.95,
      "Fraction of host memory use that triggers the OOM kill "
      "(ray_config_def.h memory_usage_threshold analog).")
_flag("event_stats", bool, True,
      "Collect per-handler event-loop stats (src/ray/common/event_stats.cc).")

# --- observability: profiling plane ------------------------------------------
_flag("profile_hz", float, 11.0,
      "Continuous wall-clock stack-sampling rate (samples/s) for the "
      "profiling plane's always-on sampler in every process (worker, "
      "agent, head). Low by design: the acceptance contract is <= 5% "
      "tasks/s overhead on the chatty fan-out. 0 disables the continuous "
      "sampler (burst capture stays available); RMT_PROFILE=0 disables "
      "the whole plane.")
_flag("profile_burst_hz", float, 97.0,
      "Sampling rate for on-demand burst captures (rmt profile --hz "
      "default, and the RMT_WORKER_PROFILE deprecation alias). Bursts "
      "are short and opt-in, so this trades overhead for resolution.")

# --- observability: health plane ---------------------------------------------
_flag("metrics_max_series_per_name", int, 256,
      "Cardinality guard: max distinct tag-value combinations a single "
      "metric name may hold in the registry. The first write past the "
      "cap folds into an all-__other__ overflow series (counted by "
      "rmt_metrics_series_overflow_total{metric}) so an unbounded "
      "job_id/deployment tag space cannot grow the registry or the "
      "Prometheus exposition forever. 0 disables the cap.")
_flag("tsdb_raw_points", int, 600,
      "Per-series raw ring size in the head's time-series store. At the "
      "0.5s heartbeat tick, 600 points ~= 5 minutes of tick-resolution "
      "history; the ring is a fixed-size deque, so head RSS is bounded "
      "by construction.")
_flag("tsdb_downsample_every", int, 10,
      "Every N raw samples the tsdb folds them into one aggregate "
      "(min/max/last/count) point in the downsampled ring, trading "
      "resolution for horizon (10 ticks at 0.5s = one 5s point).")
_flag("tsdb_downsample_points", int, 720,
      "Per-series downsampled ring size: 720 aggregate points at one "
      "per 5s ~= 1 hour of coarse history behind the raw window.")
_flag("tsdb_max_series_per_name", int, 64,
      "Per-name series cap inside the tsdb (tighter than the registry "
      "guard: the store keeps history per series, not one float). "
      "Samples for tag combos past the cap fold into an all-__other__ "
      "bucket and are counted by rmt_tsdb_dropped_total{reason}. "
      "0 disables the cap.")


def _coerce(typ, raw: str):
    if typ is bool:
        return raw.lower() in ("1", "true", "yes", "on")
    return typ(raw)


# Version of every cross-process wire schema (node registration, thin-client
# requests, transfer-plane fetches — the reference versions its protobuf
# schemas the same way, src/ray/protobuf/). Strict equality: a mixed-version
# cluster fails LOUDLY at the handshake with both versions named, instead of
# mis-parsing a frame mid-run. Bump on ANY incompatible message change.
# v2: transfer-plane range requests ({oid, offset, length}) + per-connection
# request loops (connection reuse) replaced v1's one-full-object-per-
# connection fetch.
WIRE_PROTOCOL_VERSION = 2


class Config:
    """A scoped snapshot of all flags, with ``RMT_<NAME>`` env overrides
    applied at construction time (the reference reads ``RAY_<name>`` once at
    process start, ray_config.h:58)."""

    def __init__(self, **overrides: Any):
        for name, (typ, default, _doc) in _FLAG_DEFS.items():
            env = os.environ.get(f"RMT_{name}")
            value = _coerce(typ, env) if env is not None else default
            setattr(self, name, value)
        for k, v in overrides.items():
            if k not in _FLAG_DEFS:
                raise ValueError(f"unknown config flag: {k}")
            setattr(self, k, v)

    def to_dict(self) -> Dict[str, Any]:
        return {name: getattr(self, name) for name in _FLAG_DEFS}

    @staticmethod
    def flag_docs() -> Dict[str, str]:
        return {name: doc for name, (_t, _d, doc) in _FLAG_DEFS.items()}


_global_config: Config | None = None


def global_config() -> Config:
    global _global_config
    if _global_config is None:
        _global_config = Config()
    return _global_config


def set_global_config(cfg: Config) -> None:
    global _global_config
    _global_config = cfg
