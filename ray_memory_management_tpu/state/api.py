"""State API: structured views over live cluster state.

The reference serves these from the dashboard's state head backed by GCS
(experimental/state/api.py + state_aggregator); the single-process
runtime answers them directly from the owner runtime + GCS tables. Every
function returns plain list-of-dicts (the reference's .to_dict() rows)
and supports the same filters=[(key, "=", value)] shape.
"""

from __future__ import annotations

from collections import Counter
from typing import Any, Dict, List, Optional, Tuple

from .. import _worker_context


def _runtime():
    rt = _worker_context.get_runtime()
    if rt is None:
        raise RuntimeError("state API requires an initialized runtime "
                           "(call init() first)")
    return rt


def _apply_filters(rows: List[Dict[str, Any]],
                   filters: Optional[List[Tuple[str, str, Any]]]):
    if not filters:
        return rows
    out = []
    for row in rows:
        ok = True
        for key, op, value in filters:
            have = row.get(key)
            if op in ("=", "=="):
                ok = have == value
            elif op == "!=":
                ok = have != value
            else:
                raise ValueError(f"unsupported filter op {op!r}")
            if not ok:
                break
        if ok:
            out.append(row)
    return out


def list_nodes(filters=None, limit: int = 10000) -> List[Dict[str, Any]]:
    rt = _runtime()
    rows = []
    with rt.gcs._lock:  # snapshot: registrations mutate this concurrently
        infos = list(rt.gcs.nodes.values())
    for info in infos:
        rows.append({
            "node_id": info.node_id.hex(),
            "state": "ALIVE" if info.alive else "DEAD",
            "resources_total": info.resources.total.to_dict(),
            "labels": info.labels,
            "store": info.store_name,
        })
    return _apply_filters(rows, filters)[:limit]


def list_actors(filters=None, limit: int = 10000) -> List[Dict[str, Any]]:
    rt = _runtime()
    rows = []
    with rt.gcs._lock:
        records = list(rt.gcs.actors.values())
    for rec in records:
        rows.append({
            "actor_id": rec.actor_id.hex(),
            "class_name": getattr(rec.spec, "name", "Actor"),
            "state": rec.state,
            "node_id": rec.node_id.hex() if rec.node_id else None,
            "name": getattr(rec.spec, "registered_name", None),
            "num_restarts": rec.num_restarts,
            "death_cause": rec.death_cause,
        })
    return _apply_filters(rows, filters)[:limit]


def list_tasks(filters=None, limit: int = 10000) -> List[Dict[str, Any]]:
    rt = _runtime()
    with rt._lock:
        records = list(rt.tasks.items())
        # GC'd tasks stay observable through the bounded history
        # (runtime.task_history; the reference's GcsTaskManager log) —
        # stored as raw tuples on the completion hot path, rendered here
        history = list(rt.task_history)
    from ..core.runtime import stage_durations

    rows = [{
        "task_id": tid.hex(), "name": name, "state": state,
        "num_returns": nret, "retries_left": retries,
        "is_actor_task": is_actor,
        "durations": stage_durations(ts),
    } for tid, name, state, nret, retries, is_actor, ts in history]
    for task_id, rec in records:
        rows.append({
            "task_id": task_id.hex(),
            "name": rec.spec.name,
            "state": rec.state,
            "num_returns": rec.spec.num_returns,
            "retries_left": rec.retries_left,
            "is_actor_task": rec.spec.is_actor_task,
            "durations": stage_durations(rec.ts),
        })
    return _apply_filters(rows, filters)[:limit]


def list_objects(filters=None, limit: int = 10000) -> List[Dict[str, Any]]:
    rt = _runtime()
    rows = []
    with rt._lock:
        mem = {oid: len(data) for oid, data in rt.memory_store.items()}
    for oid, size in mem.items():
        rows.append({
            "object_id": oid.hex(),
            "size_bytes": size,
            "where": "memory_store",
            "node_id": None,
        })
    with rt.gcs._lock:
        locations = {oid: list(nodes) for oid, nodes
                     in rt.gcs.object_locations.items()}
    for oid, nodes in locations.items():
        for node_id in nodes:
            with rt._lock:
                nm = rt.nodes.get(node_id)
            size = None
            where = "store"
            if nm is not None and nm.alive:
                try:
                    # read shm directly: store.get() would RESTORE spilled
                    # objects (disk read + shm fill) just to measure them
                    view = nm.store.shm.get(oid)
                    if view is not None:
                        size = view.nbytes
                        nm.store.shm.release(oid)
                    elif nm.store.contains(oid):
                        where = "spilled"
                except Exception:
                    size = None
            rows.append({
                "object_id": oid.hex(),
                "size_bytes": size,
                "where": where,
                "node_id": node_id.hex(),
            })
    return _apply_filters(rows, filters)[:limit]


def list_jobs(filters=None, limit: int = 10000) -> List[Dict[str, Any]]:
    """Job table rows (the driver plus every thin-client connection; the
    reference's list_jobs over the GcsJobManager table,
    gcs_job_manager.h:28)."""
    rt = _runtime()
    return _apply_filters(rt.gcs.list_jobs(), filters)[:limit]


def list_workers(filters=None, limit: int = 10000) -> List[Dict[str, Any]]:
    rt = _runtime()
    rows = []
    with rt._lock:
        node_managers = list(rt.nodes.values())
    for nm in node_managers:
        for handle in list(nm.workers.values()):
            rows.append({
                "worker_id": handle.worker_id.hex(),
                "node_id": nm.node_id.hex(),
                "pid": handle.proc.pid if handle.proc else None,
                "alive": handle.alive(),
                "is_actor_worker": handle.actor_id is not None,
            })
    return _apply_filters(rows, filters)[:limit]


def list_placement_groups(filters=None,
                          limit: int = 10000) -> List[Dict[str, Any]]:
    rt = _runtime()
    if rt.pg_manager is None:
        return []
    from ..core.placement_group import placement_group_table

    rows = list(placement_group_table().values())
    return _apply_filters(rows, filters)[:limit]


def list_cluster_events(filters=None,
                        limit: int = 10000) -> List[Dict[str, Any]]:
    """Structured cluster events (the dashboard event module analog —
    NODE_ADDED/NODE_DEAD/TASK_RETRY/ACTOR_RESTARTING/WORKER_OOM_KILLED/
    OBJECT_SPILLED, utils/events.py). Accepts both this module's
    [(key, op, value)] filter tuples and events.list_events' {key: value}
    dict form, so it composes like every sibling list_* API."""
    from ..utils import events

    if isinstance(filters, dict):
        return events.list_events(filters, limit)
    # filter BEFORE limiting (like every sibling list_* API) and return the
    # newest matches (like events.list_events does for the dict form)
    rows = _apply_filters(events.list_events(None, limit=1 << 62), filters)
    return rows[-limit:] if limit > 0 else []


# ------------------------------------------------------------- summaries
def summarize_tasks() -> Dict[str, Any]:
    counts = Counter(r["state"] for r in list_tasks())
    by_name = Counter(r["name"] for r in list_tasks())
    return {"by_state": dict(counts),
            "by_name": dict(by_name.most_common(20)),
            "total": sum(counts.values())}


def summarize_actors() -> Dict[str, Any]:
    counts = Counter(r["state"] for r in list_actors())
    return {"by_state": dict(counts), "total": sum(counts.values())}


def summarize_objects() -> Dict[str, Any]:
    rows = list_objects()
    total_bytes = sum(r["size_bytes"] or 0 for r in rows)
    return {"count": len(rows), "total_bytes": total_bytes}


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile over a pre-sorted sample."""
    if not sorted_vals:
        return 0.0
    idx = max(0, min(len(sorted_vals) - 1,
                     int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def summarize_task_latencies() -> Dict[str, Dict[str, float]]:
    """Per-lifecycle-stage latency summary (count / mean / p50 / p95 /
    p99, milliseconds) over the runtime's bounded stage-duration samples
    — the ``ray summary tasks`` timing breakdown analog. Exact
    percentiles from raw samples, not bucket interpolation (the
    rmt_task_stage_seconds histogram serves the monitoring view)."""
    rt = _runtime()
    out: Dict[str, Dict[str, float]] = {}
    for stage, buf in list(rt.task_latencies.items()):
        vals = sorted(buf)
        if not vals:
            continue
        out[stage] = {
            "count": len(vals),
            "mean_ms": (sum(vals) / len(vals)) * 1e3,
            "p50_ms": _percentile(vals, 0.50) * 1e3,
            "p95_ms": _percentile(vals, 0.95) * 1e3,
            "p99_ms": _percentile(vals, 0.99) * 1e3,
        }
    return out
