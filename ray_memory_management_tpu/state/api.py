"""State API: structured views over live cluster state.

The reference serves these from the dashboard's state head backed by GCS
(experimental/state/api.py + state_aggregator); the single-process
runtime answers them directly from the owner runtime + GCS tables. Every
function returns plain list-of-dicts (the reference's .to_dict() rows)
and supports the same filters=[(key, "=", value)] shape.
"""

from __future__ import annotations

from collections import Counter
from typing import Any, Dict, List, Optional, Tuple

from .. import _worker_context


def _runtime():
    rt = _worker_context.get_runtime()
    if rt is None:
        raise RuntimeError("state API requires an initialized runtime "
                           "(call init() first)")
    return rt


def _apply_filters(rows: List[Dict[str, Any]],
                   filters: Optional[List[Tuple[str, str, Any]]]):
    if not filters:
        return rows
    out = []
    for row in rows:
        ok = True
        for key, op, value in filters:
            have = row.get(key)
            if op in ("=", "=="):
                ok = have == value
            elif op == "!=":
                ok = have != value
            else:
                raise ValueError(f"unsupported filter op {op!r}")
            if not ok:
                break
        if ok:
            out.append(row)
    return out


def _job_task_prefix(job_id: str) -> str:
    """Task ids (and their return-object ids) embed the owning job's
    first 4 id bytes (ids.TaskID.for_task), so an 8-hex-char prefix match
    attributes rows whose full job tag was pruned — task-history tuples,
    log records, profile samples."""
    return job_id.lower()[:8]


def list_nodes(filters=None, limit: int = 10000) -> List[Dict[str, Any]]:
    rt = _runtime()
    rows = []
    with rt.gcs._lock:  # snapshot: registrations mutate this concurrently
        infos = list(rt.gcs.nodes.values())
    for info in infos:
        rows.append({
            "node_id": info.node_id.hex(),
            "state": "ALIVE" if info.alive else "DEAD",
            "resources_total": info.resources.total.to_dict(),
            "labels": info.labels,
            "store": info.store_name,
        })
    return _apply_filters(rows, filters)[:limit]


def list_actors(filters=None, limit: int = 10000) -> List[Dict[str, Any]]:
    rt = _runtime()
    rows = []
    with rt.gcs._lock:
        records = list(rt.gcs.actors.values())
    for rec in records:
        rows.append({
            "actor_id": rec.actor_id.hex(),
            "class_name": getattr(rec.spec, "name", "Actor"),
            "state": rec.state,
            "node_id": rec.node_id.hex() if rec.node_id else None,
            "name": getattr(rec.spec, "registered_name", None),
            "num_restarts": rec.num_restarts,
            "death_cause": rec.death_cause,
        })
    return _apply_filters(rows, filters)[:limit]


def list_tasks(filters=None, limit: int = 10000,
               job_id: Optional[str] = None) -> List[Dict[str, Any]]:
    rt = _runtime()
    with rt._lock:
        records = list(rt.tasks.items())
        # GC'd tasks stay observable through the bounded history
        # (runtime.task_history; the reference's GcsTaskManager log) —
        # stored as raw tuples on the completion hot path, rendered here
        history = list(rt.task_history)
    from ..core.runtime import stage_durations

    rows = [{
        "task_id": tid.hex(), "name": name, "state": state,
        "num_returns": nret, "retries_left": retries,
        "is_actor_task": is_actor,
        "durations": stage_durations(ts),
        "trace_id": trace_ctx[0] if trace_ctx else None,
        "span_id": trace_ctx[1] if trace_ctx else None,
        "parent_span_id": trace_ctx[2] if trace_ctx else None,
        "cpu_s": rusage.get("cpu_s") if rusage else None,
        "peak_rss": rusage.get("peak_rss") if rusage else None,
        "hbm_bytes": rusage.get("hbm_bytes") if rusage else None,
        "job_id": None,  # pruned to the id prefix; see _job_task_prefix
    } for tid, name, state, nret, retries, is_actor, ts, trace_ctx, rusage
        in history]
    for task_id, rec in records:
        tctx = rec.spec.trace_ctx
        ru = rec.rusage
        jid = getattr(rec.spec, "job_id", None)
        rows.append({
            "task_id": task_id.hex(),
            "name": rec.spec.name,
            "state": rec.state,
            "num_returns": rec.spec.num_returns,
            "retries_left": rec.retries_left,
            "is_actor_task": rec.spec.is_actor_task,
            "durations": stage_durations(rec.ts),
            "trace_id": tctx[0] if tctx else None,
            "span_id": tctx[1] if tctx else None,
            "parent_span_id": tctx[2] if tctx else None,
            "cpu_s": ru.get("cpu_s") if ru else None,
            "peak_rss": ru.get("peak_rss") if ru else None,
            "hbm_bytes": ru.get("hbm_bytes") if ru else None,
            "job_id": jid.hex() if jid else None,
        })
    if job_id is not None:
        want, pref = job_id.lower(), _job_task_prefix(job_id)
        rows = [r for r in rows
                if (r["job_id"] == want if r["job_id"] is not None
                    else r["task_id"].startswith(pref))]
    return _apply_filters(rows, filters)[:limit]


def list_objects(filters=None, limit: int = 10000,
                 job_id: Optional[str] = None) -> List[Dict[str, Any]]:
    rt = _runtime()
    rows = []
    with rt._lock:
        mem = {oid: len(data) for oid, data in rt.memory_store.items()}
    # job attribution: quota ledgers know every byte a client job charged
    # (including inline memory_store puts the directory never sees); the
    # directory's jobs table tags store/device rows. An oid in neither is
    # driver-owned and reports job_id=None.
    owner_by_oid: Dict[bytes, str] = {}
    for jid, led in list(getattr(rt, "_job_ledgers", {}).items()):
        with led.lock:
            for o in led.object_sizes:
                owner_by_oid[o] = jid.hex()
            for o in led.device_sizes:
                owner_by_oid[o] = jid.hex()
    for oid, size in mem.items():
        rows.append({
            "object_id": oid.hex(),
            "size_bytes": size,
            "where": "memory_store",
            "node_id": None,
            "job_id": owner_by_oid.get(oid),
        })
    oids = rt.gcs.directory_keys()
    # one batched directory read replaces the old per-(object, node) shm
    # get/release round-trips — for remote stores each of those was an
    # IPC, making the listing O(objects * nodes) remote calls
    located = rt.gcs.locate_objects(oids)
    with rt._lock:
        node_managers = dict(rt.nodes)
    # spill metadata is only visible for in-process stores; a remote
    # node's spilled set would cost the very round-trips we're avoiding
    spilled_by_node: Dict[Any, set] = {}
    for node_id, nm in node_managers.items():
        store = getattr(nm, "store", None)
        lock = getattr(store, "_spill_lock", None)
        if lock is None:
            continue
        try:
            with lock:
                spilled_by_node[node_id] = set(store._spilled)
        except Exception:
            continue
    for oid, (size, holders, tiers) in located.items():
        for node_id in holders:
            tier = tiers.get(node_id, "shm")
            if tier == "hbm":
                where = "device"  # live HBM pin (process-local)
            elif oid in spilled_by_node.get(node_id, ()):
                where = "spilled"
            else:
                where = "store"
            tag = rt.gcs.object_job(oid)
            rows.append({
                "object_id": oid.hex(),
                "size_bytes": size or None,
                "where": where,
                "tier": tier,
                "node_id": node_id.hex(),
                "job_id": tag.hex() if tag else owner_by_oid.get(oid),
            })
    if job_id is not None:
        # explicit tag wins; untagged rows (task returns) match through
        # the job prefix their minting task id embeds
        want, pref = job_id.lower(), _job_task_prefix(job_id)
        rows = [r for r in rows
                if r["job_id"] == want
                or (r["job_id"] is None and r["object_id"].startswith(pref))]
    return _apply_filters(rows, filters)[:limit]


def list_jobs(filters=None, limit: int = 10000) -> List[Dict[str, Any]]:
    """Job table rows (the driver plus every thin-client connection; the
    reference's list_jobs over the GcsJobManager table,
    gcs_job_manager.h:28). Live jobs carry their quota-ledger ``usage``
    snapshot (bytes charged, slots, preemption/demotion counters)."""
    rt = _runtime()
    rows = rt.gcs.list_jobs()
    usage = rt.job_usage() if hasattr(rt, "job_usage") else {}
    for row in rows:
        row["usage"] = usage.get(row.get("job_id"))
    return _apply_filters(rows, filters)[:limit]


def list_workers(filters=None, limit: int = 10000) -> List[Dict[str, Any]]:
    rt = _runtime()
    rows = []
    with rt._lock:
        node_managers = list(rt.nodes.values())
    for nm in node_managers:
        for handle in list(nm.workers.values()):
            rows.append({
                "worker_id": handle.worker_id.hex(),
                "node_id": nm.node_id.hex(),
                "pid": handle.proc.pid if handle.proc else None,
                "alive": handle.alive(),
                "is_actor_worker": handle.actor_id is not None,
            })
    return _apply_filters(rows, filters)[:limit]


def list_placement_groups(filters=None,
                          limit: int = 10000) -> List[Dict[str, Any]]:
    rt = _runtime()
    if rt.pg_manager is None:
        return []
    from ..core.placement_group import placement_group_table

    rows = list(placement_group_table().values())
    return _apply_filters(rows, filters)[:limit]


def list_cluster_events(filters=None,
                        limit: int = 10000) -> List[Dict[str, Any]]:
    """Structured cluster events (the dashboard event module analog —
    NODE_ADDED/NODE_DEAD/TASK_RETRY/ACTOR_RESTARTING/WORKER_OOM_KILLED/
    OBJECT_SPILLED, utils/events.py). Accepts both this module's
    [(key, op, value)] filter tuples and events.list_events' {key: value}
    dict form, so it composes like every sibling list_* API."""
    from ..utils import events

    if isinstance(filters, dict):
        return events.list_events(filters, limit)
    # filter BEFORE limiting (like every sibling list_* API) and return the
    # newest matches (like events.list_events does for the dict form)
    rows = _apply_filters(events.list_events(None, limit=1 << 62), filters)
    return rows[-limit:] if limit > 0 else []


# ------------------------------------------------------------- summaries
def summarize_tasks() -> Dict[str, Any]:
    counts = Counter(r["state"] for r in list_tasks())
    by_name = Counter(r["name"] for r in list_tasks())
    return {"by_state": dict(counts),
            "by_name": dict(by_name.most_common(20)),
            "total": sum(counts.values())}


def summarize_actors() -> Dict[str, Any]:
    counts = Counter(r["state"] for r in list_actors())
    return {"by_state": dict(counts), "total": sum(counts.values())}


def summarize_objects() -> Dict[str, Any]:
    rows = list_objects()
    total_bytes = sum(r["size_bytes"] or 0 for r in rows)
    return {"count": len(rows), "total_bytes": total_bytes}


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile over a pre-sorted sample."""
    if not sorted_vals:
        return 0.0
    idx = max(0, min(len(sorted_vals) - 1,
                     int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


# --------------------------------------------------------------- tracing
def _trace_task_rows(trace_id: str) -> List[Dict[str, Any]]:
    """All tasks indexed under one trace, from live records first and the
    bounded history for anything already pruned. Rows keep the raw
    transition-stamp dict (``ts``) so the critical-path sweep can build
    intervals without re-deriving them from durations."""
    rt = _runtime()
    with rt._lock:
        task_ids = list(rt._traces.get(trace_id, ()))
        found: Dict[bytes, tuple] = {}
        for tid in task_ids:
            rec = rt.tasks.get(tid)
            if rec is not None:
                found[tid] = (rec.spec.name, rec.state,
                              rec.spec.trace_ctx, dict(rec.ts))
        missing = [t for t in task_ids if t not in found]
        history = list(rt.task_history) if missing else []
    if missing:
        want = set(missing)
        for tid, name, state, _n, _r, _a, ts, tctx, _ru in history:
            if tid in want and tctx:
                found[tid] = (name, state, tctx, dict(ts))
    rows = []
    for tid in task_ids:
        got = found.get(tid)
        if got is None:
            continue
        name, state, tctx, ts = got
        rows.append({
            "task_id": tid.hex(),
            "name": name,
            "state": state,
            "span_id": tctx[1] if tctx else None,
            "parent_span_id": tctx[2] if tctx else None,
            "ts": ts,
        })
    return rows


def get_trace(trace_id: str) -> Dict[str, Any]:
    """Span tree for one trace: every task whose submit minted a span
    under ``trace_id``, linked parent→child the way nested ``.remote()``
    calls chained their contexts. ``roots``/``children`` reference spans
    by span_id (flat ``spans`` list holds the payload), so the result
    JSON-serializes without recursion."""
    from ..core.runtime import stage_durations

    rows = _trace_task_rows(trace_id)
    spans = []
    for r in rows:
        ts = r["ts"]
        stamps = [v for v in ts.values() if v is not None]
        spans.append({
            "span_id": r["span_id"],
            "parent_span_id": r["parent_span_id"],
            "task_id": r["task_id"],
            "name": r["name"],
            "state": r["state"],
            "start_ts": min(stamps) if stamps else None,
            "end_ts": max(stamps) if stamps else None,
            "durations": stage_durations(ts),
            "children": [],
        })
    by_span = {s["span_id"]: s for s in spans if s["span_id"]}
    roots = []
    for s in spans:
        parent = s["parent_span_id"]
        if parent and parent in by_span:
            by_span[parent]["children"].append(s["span_id"])
        else:
            roots.append(s["span_id"])
    return {"trace_id": trace_id, "num_spans": len(spans),
            "roots": roots, "spans": spans}


def get_logs(task_id: Optional[str] = None,
             trace_id: Optional[str] = None,
             node_id: Optional[str] = None,
             level: Optional[str] = None,
             since: Optional[float] = None,
             limit: int = 1000,
             job_id: Optional[str] = None) -> List[Dict[str, Any]]:
    """Query the cluster's structured log plane (utils/structlog.py):
    every record a worker/agent/driver process captured — package-logger
    lines, user ``logging`` calls, and teed task ``print()`` output —
    stamped with node/pid/role/task/actor/trace/span identity. Filters
    are ANDed; ``level`` is a MINIMUM severity (``"WARNING"`` returns
    WARNING and above), ``since`` an exclusive ts lower bound; the
    newest ``limit`` records return oldest-first. Id filters take hex
    strings (the ids list_tasks/get_trace rows carry); ``job_id``
    matches records through the job prefix their task id embeds."""
    rt = _runtime()
    store = getattr(rt, "log_store", None)
    if store is None:
        return []
    if job_id is None:
        return store.query(task_id=task_id, trace_id=trace_id,
                           node_id=node_id, level=level, since=since,
                           limit=limit)
    # job filter is applied here (the store doesn't index jobs): fetch
    # unbounded so the newest-``limit`` cut happens AFTER narrowing
    pref = _job_task_prefix(job_id)
    rows = [r for r in store.query(task_id=task_id, trace_id=trace_id,
                                   node_id=node_id, level=level,
                                   since=since, limit=None)
            if (r.get("task_id") or "").startswith(pref)]
    return rows[-limit:] if limit > 0 else []


def get_profile(node_id: Optional[str] = None,
                task_id: Optional[str] = None,
                trace_id: Optional[str] = None,
                since: Optional[float] = None,
                limit: int = 10000,
                fold: bool = True,
                job_id: Optional[str] = None):
    """Query the cluster's profiling plane (utils/profiler.py): stack
    samples every worker/agent/driver process captured, stamped with
    node/pid/role/thread/task/trace identity. Filters are ANDed; id
    filters take hex strings (the ids list_tasks/get_trace rows carry).

    With ``fold=True`` (default) matching samples merge into collapsed
    form: ``[{"stack": "root;child;leaf", "count": n}, ...]``, heaviest
    first — one ``"\\n".join(f"{r['stack']} {r['count']}")`` away from
    flamegraph.pl / Speedscope input. ``fold=False`` returns the raw
    sample records (newest ``limit``, oldest-first)."""
    rt = _runtime()
    store = getattr(rt, "profile_store", None)
    if store is None:
        return []
    if job_id is None:
        samples = store.query(task_id=task_id, trace_id=trace_id,
                              node_id=node_id, since=since, limit=limit)
    else:
        # narrow by the job prefix task ids embed, THEN cut to newest
        # ``limit`` — same post-filter shape as get_logs(job_id=)
        pref = _job_task_prefix(job_id)
        samples = [s for s in store.query(task_id=task_id,
                                          trace_id=trace_id,
                                          node_id=node_id, since=since,
                                          limit=None)
                   if (s.get("task_id") or "").startswith(pref)]
        samples = samples[-limit:] if limit > 0 else []
    if not fold:
        return samples
    from ..utils import profiler as _profiler

    folded = _profiler.fold(samples)
    return [{"stack": stack, "count": count} for stack, count in
            sorted(folded.items(), key=lambda kv: (-kv[1], kv[0]))]


def query_series(name: str,
                 tags: Optional[Dict[str, str]] = None,
                 since: Optional[float] = None,
                 window: float = 60.0,
                 rate: bool = False,
                 delta: bool = False,
                 quantile: Optional[float] = None) -> Dict[str, Any]:
    """Query the health plane's time-series store (utils/tsdb.py): the
    heartbeat-tick history of one ``rmt_*`` metric. ``series`` holds
    per-tag-combo point lists ``[[ts, value], ...]`` (coarse downsampled
    history first, then the raw tick-resolution ring; ``tags`` is a
    subset match, ``since`` a ts lower bound). ``rate=True`` /
    ``delta=True`` / ``quantile=q`` additionally evaluate the named
    aggregate over the trailing ``window`` seconds — ``delta`` is the
    exact counted increments between the window's first and last
    samples, and ``rate * span_s == delta`` by construction. Empty
    under ``RMT_HEALTH=0`` (the store never filled)."""
    rt = _runtime()
    store = getattr(rt, "tsdb", None)
    if store is None:
        return {"name": name, "series": []}
    out: Dict[str, Any] = {
        "name": name,
        "series": store.range(name, tags=tags, since=since),
    }
    if rate or delta:
        out["span_s"] = store.span(name, window, tags=tags)
    if rate:
        out["rate"] = store.rate(name, window, tags=tags)
    if delta:
        out["delta"] = store.delta(name, window, tags=tags)
    if quantile is not None:
        out["quantile"] = store.quantile_over_time(
            name, quantile, window, tags=tags)
    return out


def get_alerts(state: Optional[str] = None,
               limit: int = 100) -> List[Dict[str, Any]]:
    """Query the SLO rules engine (core/health.py): currently-firing
    alerts plus the bounded resolved history, most severe first. Each
    row carries the rule, its expr/threshold/observed value, the
    evidence samples (``[[ts, value], ...]`` of the offending series),
    and — when the runtime could attribute one — an exemplar
    task_id/trace_id that pivots into get_trace/get_logs/get_profile.
    ``state`` filters to ``"firing"`` or ``"resolved"``."""
    rt = _runtime()
    engine = getattr(rt, "health", None)
    if engine is None:
        return []
    return engine.alerts(state=state, limit=limit)


# Critical-path attribution: stage -> transition-stamp intervals, listed
# in PRIORITY order. A wall-clock instant covered by several overlapping
# intervals (a sibling executing while another waits in queue) is charged
# to the highest-priority stage only — exec beats transfer beats queue
# beats schedule-wait — so the stage seconds sum to at most the wall time
# and the uncovered remainder is, by construction, runtime overhead.
_CP_STAGES = (
    ("exec", (("RUNNING", "WORKER_DONE"),)),
    ("transfer", (("PREFETCH_START", "PREFETCH_DONE"),
                  ("WORKER_DONE", "FINISHED"))),
    ("queue", (("DISPATCHED", "RUNNING"),)),
    ("schedule_wait", (("SUBMITTED", "SCHEDULED"),)),
)


def summarize_critical_path(trace_id: str) -> Dict[str, Any]:
    """Attribute a trace's wall time (first submit stamp → last stamp of
    any of its spans) to named stages via a priority interval sweep.
    Every second lands somewhere: ``stages`` + ``overhead_s`` equals
    ``wall_time_s`` exactly; ``coverage`` is the fraction explained by
    the named (non-overhead) stages."""
    rows = _trace_task_rows(trace_id)
    empty = {"trace_id": trace_id, "tasks": len(rows),
             "wall_time_s": 0.0, "stages": {}, "overhead_s": 0.0,
             "coverage": 0.0}
    if not rows:
        return empty
    intervals: List[Tuple[float, float, int, str]] = []
    t_min, t_max = float("inf"), float("-inf")
    for r in rows:
        ts = r["ts"]
        for v in ts.values():
            if v is not None:
                t_min = min(t_min, v)
                t_max = max(t_max, v)
        for prio, (stage, edges) in enumerate(_CP_STAGES):
            for a, b in edges:
                ta, tb = ts.get(a), ts.get(b)
                if ta is not None and tb is not None and tb > ta:
                    intervals.append((ta, tb, prio, stage))
    if t_max <= t_min:
        return empty
    wall = t_max - t_min
    # boundary sweep: between consecutive stamp boundaries exactly one
    # stage (or none) wins, so each segment is charged exactly once
    points = sorted({t_min, t_max,
                     *(p for iv in intervals for p in iv[:2])})
    stages: Dict[str, float] = {}
    overhead = 0.0
    for lo, hi in zip(points, points[1:]):
        seg = hi - lo
        if seg <= 0:
            continue
        best = None
        for ta, tb, prio, stage in intervals:
            if ta <= lo and tb >= hi and (best is None or prio < best[0]):
                best = (prio, stage)
        if best is None:
            overhead += seg
        else:
            stages[best[1]] = stages.get(best[1], 0.0) + seg
    return {"trace_id": trace_id, "tasks": len(rows),
            "wall_time_s": wall,
            "stages": stages,
            "overhead_s": overhead,
            "coverage": (wall - overhead) / wall}


def summarize_task_latencies() -> Dict[str, Dict[str, float]]:
    """Per-lifecycle-stage latency summary (count / mean / p50 / p95 /
    p99, milliseconds) over the runtime's bounded stage-duration samples
    — the ``ray summary tasks`` timing breakdown analog. Exact
    percentiles from raw samples, not bucket interpolation (the
    rmt_task_stage_seconds histogram serves the monitoring view).

    When finished tasks carried rusage deltas (the profiling plane's
    per-task attribution), a ``resources`` stage reports cpu_s /
    peak_rss / hbm_bytes percentiles in native units (seconds / bytes),
    keyed ``<resource>_{count,mean,p50,p95,p99}``."""
    rt = _runtime()
    out: Dict[str, Dict[str, float]] = {}
    for stage, buf in list(rt.task_latencies.items()):
        vals = sorted(buf)
        if not vals:
            continue
        out[stage] = {
            "count": len(vals),
            "mean_ms": (sum(vals) / len(vals)) * 1e3,
            "p50_ms": _percentile(vals, 0.50) * 1e3,
            "p95_ms": _percentile(vals, 0.95) * 1e3,
            "p99_ms": _percentile(vals, 0.99) * 1e3,
        }
    resources: Dict[str, float] = {}
    for key, buf in list(getattr(rt, "task_resources", {}).items()):
        vals = sorted(buf)
        if not vals:
            continue
        resources.update({
            f"{key}_count": len(vals),
            f"{key}_mean": sum(vals) / len(vals),
            f"{key}_p50": _percentile(vals, 0.50),
            f"{key}_p95": _percentile(vals, 0.95),
            f"{key}_p99": _percentile(vals, 0.99),
        })
    if resources:
        out["resources"] = resources
    return out
