"""State observability API.

The reference's state API (python/ray/experimental/state/api.py —
list_actors:719, list_tasks:942, list_objects:986, summaries :1233-1297)
plus the GCS global-state reads in ray._private.state.
"""

from .api import (  # noqa: F401
    get_alerts,
    get_logs,
    get_profile,
    get_trace,
    list_actors,
    list_cluster_events,
    list_jobs,
    list_nodes,
    list_objects,
    list_placement_groups,
    list_tasks,
    list_workers,
    query_series,
    summarize_actors,
    summarize_critical_path,
    summarize_objects,
    summarize_task_latencies,
    summarize_tasks,
)
