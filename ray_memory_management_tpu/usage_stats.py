"""Usage stats: opt-out telemetry collection (DISABLED by default here).

The reference collects opt-out usage reports through the dashboard
(dashboard/modules/usage_stats, CLI toggles scripts.py:1688,1702). This
build ships the same surface but records ONLY to a local JSON file and
never performs network IO (this environment has no egress; a real
deployment would point ``report()`` at a collector).
"""

from __future__ import annotations

import json
import os
import tempfile
import time
from typing import Any, Dict

_ENV_FLAG = "RMT_USAGE_STATS_ENABLED"
_DEFAULT_PATH = os.path.join(tempfile.gettempdir(), "rmt_usage_stats.json")


def usage_stats_enabled() -> bool:
    return os.environ.get(_ENV_FLAG, "0") == "1"


def enable() -> None:
    os.environ[_ENV_FLAG] = "1"


def disable() -> None:
    os.environ[_ENV_FLAG] = "0"


def collect() -> Dict[str, Any]:
    """The reference's payload shape: versions, cluster shape, library
    usage — no user data."""
    from . import __version__, _worker_context

    rt = _worker_context.get_runtime()
    payload = {
        "schema_version": "0.1",
        "timestamp": time.time(),
        "library_version": __version__,
        "num_nodes": sum(1 for nm in rt.nodes.values() if nm.alive)
        if rt else 0,
        "total_resources": (
            rt.scheduler.cluster_resources() if rt else {}),
    }
    return payload


def report(path: str = _DEFAULT_PATH) -> str | None:
    """Write one usage record locally if enabled; returns the path."""
    if not usage_stats_enabled():
        return None
    payload = collect()
    with open(path, "a") as f:
        f.write(json.dumps(payload) + "\n")
    return path
