"""Public core API: init/remote/get/put/wait/actors.

Mirrors the reference's public surface (python/ray/_private/worker.py —
ray.init:1022, ray.get:2205, ray.put:2305, ray.wait:2360, ray.remote:2780;
python/ray/remote_function.py:161 RemoteFunction._remote; python/ray/actor.py:657
ActorClass._remote) with the same defaults: tasks take 1 CPU and 4 retries,
actors take 0 lifetime CPUs and 0 restarts, ``num_returns=1``.

Accelerators: ``num_tpus`` is the first-class resource (the reference's
``num_gpus`` analog, _private/resource_spec.py:88-101); fractional values
time-share a chip, integral values get ``TPU_VISIBLE_CHIPS`` isolation.
"""

from __future__ import annotations

import functools
import os
import threading
import uuid
from typing import Any, Dict, List, Optional, Sequence, Union

from . import _worker_context
from . import serialization as ser
from .config import Config
from .core.object_ref import ObjectRef
from .exceptions import RmtError

__all__ = [
    "init", "shutdown", "is_initialized", "remote", "get", "put", "wait",
    "kill", "cancel", "get_actor", "method", "ObjectRef", "nodes",
    "cluster_resources", "available_resources", "timeline", "cpp_function",
    "cpp_functions",
]

_INLINE_LIMIT_DEFAULT = 100 * 1024


def _backend():
    return _worker_context.backend()


def _inline_limit() -> int:
    rt = _worker_context.get_runtime()
    if rt is not None:
        return rt.config.max_direct_call_object_size
    proxy = _worker_context.get_proxy()
    if proxy is not None:  # worker proxy or thin client, both expose it
        return proxy.inline_limit
    return _INLINE_LIMIT_DEFAULT


def _encode_arg(value: Any):
    """Encode one call argument: refs stay refs; small values inline; large
    values are promoted to store objects (the reference inlines args up to
    100 KiB and puts the rest in plasma, serialization.py:363,411)."""
    if isinstance(value, ObjectRef):
        return ("ref", value.binary())
    data = ser.serialize(value)
    if data.total_size <= _inline_limit():
        return ("v", data.to_bytes())
    return ("ref", _backend().put_serialized_arg(data))


def _encode_call(args, kwargs):
    return (
        [_encode_arg(a) for a in args],
        {k: _encode_arg(v) for k, v in kwargs.items()},
    )


# ----------------------------------------------------------------- functions
class RemoteFunction:
    def __init__(self, fn, **options):
        self._fn = fn
        self._options = options
        self._fn_id = uuid.uuid4().bytes
        self._fn_blob: Optional[bytes] = None
        self._blob_lock = threading.Lock()
        # everything but args/kwargs is fixed per RemoteFunction; building
        # (and validating) it once keeps .remote() off the hot path's back
        self._payload_template: Optional[dict] = None
        functools.update_wrapper(self, fn)

    def options(self, **options) -> "RemoteFunction":
        merged = {**self._options, **options}
        clone = RemoteFunction(self._fn, **merged)
        return clone

    def _blob(self) -> bytes:
        with self._blob_lock:
            if self._fn_blob is None:
                self._fn_blob = ser.dumps_function(self._fn)
            return self._fn_blob

    def _template(self) -> dict:
        tmpl = self._payload_template
        if tmpl is None:
            opts = self._options
            resources: Dict[str, float] = dict(opts.get("resources") or {})
            resources["CPU"] = opts.get("num_cpus", 1.0)
            if opts.get("num_tpus"):
                resources["TPU"] = opts["num_tpus"]
            if opts.get("memory"):
                resources["memory"] = opts["memory"]
            tmpl = {
                "name": opts.get("name",
                                 getattr(self._fn, "__name__", "task")),
                "fn_id": self._fn_id,
                "fn_blob": self._blob(),
                "num_returns": opts.get("num_returns", 1),
                "resources": resources,
                "strategy": _resolve_strategy(opts),
                "max_retries": opts.get("max_retries", 4),
                "retry_exceptions": bool(opts.get("retry_exceptions",
                                                  False)),
                "runtime_env": _validated_runtime_env(opts),
            }
            self._payload_template = tmpl
        return tmpl

    def remote(self, *args, **kwargs):
        enc_args, enc_kwargs = _encode_call(args, kwargs)
        payload = dict(self._template())
        payload["args"] = enc_args
        payload["kwargs"] = enc_kwargs
        return_ids = _backend().submit_task(payload)
        # adopt: submit pre-registered one handle ref per return id
        refs = [ObjectRef(oid, _owner(), adopt=_owner() is not None)
                for oid in return_ids]
        return refs[0] if len(refs) == 1 else refs

    def __call__(self, *args, **kwargs):
        raise TypeError(
            "remote functions must be called with .remote() "
            f"(use {self.__name__}.remote(...))"
        )

    def __reduce__(self):
        # Remote functions are captured in other tasks' closures; rebuild with
        # the same fn_id so worker-side function caches stay warm.
        return (_rebuild_remote_function,
                (self._fn, self._options, self._fn_id))


def _rebuild_remote_function(fn, options, fn_id):
    rf = RemoteFunction(fn, **options)
    rf._fn_id = fn_id
    return rf


def _validated_runtime_env(opts) -> Optional[dict]:
    env = opts.get("runtime_env")
    if not env:
        return None
    from .runtime_env import validate

    return validate(env)


def _resolve_strategy(opts) -> Any:
    strategy = opts.get("scheduling_strategy")
    pg = opts.get("placement_group")
    if pg is not None:
        from .core.scheduling_strategies import PlacementGroupSchedulingStrategy

        return PlacementGroupSchedulingStrategy(
            pg, opts.get("placement_group_bundle_index", -1)
        )
    return strategy


def _owner():
    """Driver-side refs participate in refcounting; worker-side are bare."""
    return _worker_context.get_runtime()


# --------------------------------------------------------- C++ task plane
class CppFunction:
    """Handle to a function implemented by a connected C++ executor
    process (the worker-side C++ API — reference: cpp/include/ray/api.h
    ``ray::Task(fn).Remote()``; here the executor registers its function
    names over the client protocol and long-polls for work).

    Args are raw ``bytes`` (the cross-language boundary moves opaque
    buffers); results come back as ``bytes`` through ordinary
    ObjectRefs — ``rmt.get`` works unchanged."""

    def __init__(self, name: str, num_returns: int = 1):
        self._name = name
        self._num_returns = num_returns

    def options(self, num_returns: int = 1) -> "CppFunction":
        return CppFunction(self._name, num_returns)

    def remote(self, *args) -> Union[ObjectRef, List[ObjectRef]]:
        from .client.server import submit_cpp_task

        owner = _owner()
        if owner is None:
            raise RmtError("cpp_function requires the in-process driver "
                           "(thin clients use the call_cpp verb)")
        oids = submit_cpp_task(
            self._name, [bytes(a) for a in args],
            num_returns=self._num_returns, adopt=True)
        refs = [ObjectRef(oid, owner, adopt=True) for oid in oids]
        return refs[0] if len(refs) == 1 else refs


def cpp_function(name: str) -> CppFunction:
    """A handle that dispatches to a registered C++ executor function."""
    return CppFunction(name)


def cpp_functions() -> List[str]:
    """Names currently served by connected C++ executors."""
    from .client.server import cpp_function_names

    return cpp_function_names()


# ------------------------------------------------------------------- actors
class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str,
                 num_returns: int = 1):
        self._handle = handle
        self._name = name
        self._num_returns = num_returns

    def options(self, num_returns: int = 1) -> "ActorMethod":
        return ActorMethod(self._handle, self._name, num_returns)

    def remote(self, *args, **kwargs):
        enc_args, enc_kwargs = _encode_call(args, kwargs)
        payload = {
            "actor_id": self._handle._actor_id,
            "method": self._name,
            "args": enc_args,
            "kwargs": enc_kwargs,
            "num_returns": self._num_returns,
        }
        return_ids = _backend().submit_actor_task(payload)
        # adopt: submit pre-registered one handle ref per return id
        refs = [ObjectRef(oid, _owner(), adopt=_owner() is not None)
                for oid in return_ids]
        return refs[0] if len(refs) == 1 else refs


class ActorHandle:
    def __init__(self, actor_id: bytes, class_name: str = "Actor"):
        self._actor_id = actor_id
        self._class_name = class_name

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("__"):  # dunder lookups are never actor methods
            raise AttributeError(name)
        return ActorMethod(self, name)

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id.hex()[:8]})"

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._class_name))

    @property
    def _actor_id_hex(self) -> str:
        return self._actor_id.hex()


class ActorClass:
    def __init__(self, cls, **options):
        self._cls = cls
        self._options = options
        self._cls_id = uuid.uuid4().bytes
        self._cls_blob: Optional[bytes] = None
        self._blob_lock = threading.Lock()
        functools.update_wrapper(self, cls, updated=[])

    def options(self, **options) -> "ActorClass":
        merged = {**self._options, **options}
        clone = ActorClass(self._cls, **merged)
        clone._cls_id = self._cls_id  # same code; workers can reuse the cache
        clone._cls_blob = self._cls_blob
        return clone

    def _blob(self) -> bytes:
        with self._blob_lock:
            if self._cls_blob is None:
                self._cls_blob = ser.dumps_function(self._cls)
            return self._cls_blob

    def remote(self, *args, **kwargs) -> ActorHandle:
        opts = self._options
        enc_args, enc_kwargs = _encode_call(args, kwargs)
        resources: Dict[str, float] = dict(opts.get("resources") or {})
        # Actors hold 0 CPUs by default while alive (actor.py option
        # handling): many lightweight actors can share a node.
        if opts.get("num_cpus") is not None:
            resources["CPU"] = opts["num_cpus"]
        if opts.get("num_tpus"):
            resources["TPU"] = opts["num_tpus"]
        payload = {
            "name": opts.get("name", self._cls.__name__),
            "cls_id": self._cls_id,
            "cls_blob": self._blob(),
            "args": enc_args,
            "kwargs": enc_kwargs,
            "resources": resources,
            "strategy": _resolve_strategy(opts),
            "max_restarts": opts.get("max_restarts", 0),
            "max_task_retries": opts.get("max_task_retries", 0),
            "max_concurrency": opts.get("max_concurrency", 1),
            "detached": opts.get("lifetime") == "detached",
            "registered_name": opts.get("name"),
            "placement": opts.get("placement"),
            "runtime_env": _validated_runtime_env(opts),
        }
        pg = opts.get("placement_group")
        if pg is not None:
            payload["placement"] = (
                pg.id, opts.get("placement_group_bundle_index", -1)
            )
        actor_id = _backend().create_actor(payload)
        return ActorHandle(actor_id, self._cls.__name__)

    def __call__(self, *args, **kwargs):
        raise TypeError("actor classes must be instantiated with .remote()")

    def __reduce__(self):
        return (_rebuild_actor_class,
                (self._cls, self._options, self._cls_id))


def _rebuild_actor_class(cls, options, cls_id):
    ac = ActorClass(cls, **options)
    ac._cls_id = cls_id
    return ac


# ---------------------------------------------------------------- decorator
def remote(*args, **options):
    """``@remote`` / ``@remote(num_cpus=..., num_tpus=..., ...)`` for
    functions and classes (worker.py:2780 in the reference)."""

    def decorate(obj):
        if isinstance(obj, type):
            return ActorClass(obj, **options)
        return RemoteFunction(obj, **options)

    if len(args) == 1 and callable(args[0]) and not options:
        return decorate(args[0])
    if args:
        raise TypeError("remote() takes keyword options only")
    return decorate


def method(num_returns: int = 1):
    """Decorator recording per-method defaults (reference @ray.method)."""

    def wrap(fn):
        fn.__rmt_num_returns__ = num_returns
        return fn

    return wrap


# ------------------------------------------------------------------ objects
def put(value: Any, *, device: bool = False) -> ObjectRef:
    """Store a value and return its ref. ``device=True`` pins a
    jax.Array in the calling process's device store — the ref points at
    live HBM, same-process gets are zero-copy, and remote readers pull a
    host copy materialized on demand (SURVEY.md §7; net-new vs the
    reference's host-only plasma)."""
    if isinstance(value, ObjectRef):
        raise TypeError("put() of an ObjectRef is not allowed")
    if device:
        oid = _backend().put_device_object(value)
    else:
        oid = _backend().put_object(value)
    # in a worker the proxy IS the reference counter for its own puts
    # (creator-owns, reference_count.h:39); on the driver _owner() is the
    # runtime as before
    owner = _owner()
    if owner is None and not device:
        b = _backend()
        if hasattr(b, "add_local_ref"):
            owner = b
    return ObjectRef(oid, owner)


def get(refs: Union[ObjectRef, Sequence[ObjectRef]],
        *, timeout: Optional[float] = None, consume: bool = False):
    """``consume=True`` is the device-tier donation read: the caller
    asserts it is the LAST reader of a device object, the store drops
    its pin and hands over the live buffer so the caller can donate it
    into a pjit computation (``donate_argnums``) without a copy. The
    ref is dead for device reads afterwards; non-device objects ignore
    the flag."""
    single = isinstance(refs, ObjectRef)
    if not single and not isinstance(refs, (list, tuple)):
        raise TypeError(
            f"get() expects an ObjectRef or a list of them, got {type(refs)}"
        )
    ref_list = [refs] if single else list(refs)
    for r in ref_list:
        if not isinstance(r, ObjectRef):
            raise TypeError(f"get() expects ObjectRefs, got {type(r)}")
    ids = [r.binary() for r in ref_list]
    if consume:
        values = _backend().get_objects(ids, timeout, consume=True)
    else:
        values = _backend().get_objects(ids, timeout)
    return values[0] if single else values


def wait(refs: Sequence[ObjectRef], *, num_returns: int = 1,
         timeout: Optional[float] = None, fetch_local: bool = True):
    ids = [r.binary() for r in refs]
    by_id = {r.binary(): r for r in refs}
    ready, not_ready = _backend().wait(ids, num_returns, timeout, fetch_local)
    ready_set = set(ready[:num_returns])
    ready_refs = [by_id[i] for i in ready[:num_returns]]
    rest = [by_id[i] for i in ids if i not in ready_set]
    return ready_refs, rest


def kill(actor: ActorHandle, *, no_restart: bool = True) -> None:
    _backend().kill_actor(actor._actor_id, no_restart)


def cancel(ref: ObjectRef, *, force: bool = False) -> None:
    _backend().cancel_task(ref.binary(), force)


def get_actor(name: str) -> ActorHandle:
    rt = _worker_context.get_runtime()
    if rt is not None:
        rec = rt.gcs.get_named_actor(name)
        if rec is None:
            raise ValueError(f"no actor named {name!r}")
        return ActorHandle(rec.actor_id.binary(), rec.spec.name)
    proxy = _worker_context.get_proxy()
    if proxy is None:
        raise RmtError("not initialized")
    actor_id = proxy.get_named_actor(name)
    return ActorHandle(actor_id, name)


# -------------------------------------------------------------------- init
_init_lock = threading.Lock()


def init(
    num_cpus: Optional[int] = None,
    num_tpus: Optional[int] = None,
    num_nodes: int = 1,
    object_store_memory: Optional[int] = None,
    resources: Optional[Dict[str, float]] = None,
    namespace: Optional[str] = None,
    ignore_reinit_error: bool = False,
    _config: Optional[Config] = None,
):
    """Start an in-process cluster with ``num_nodes`` virtual nodes (each a
    NodeManager + store + worker pool). The multi-node shape exists for
    scheduling/FT semantics and tests (cluster_utils.py analog); production
    multi-host wiring rides jax.distributed + the DCN object plane."""
    from .core.runtime import Runtime

    with _init_lock:
        if _worker_context.get_runtime() is not None:
            if ignore_reinit_error:
                return _worker_context.get_runtime()
            raise RmtError("already initialized (use shutdown() first)")
        cfg = _config or Config()
        if object_store_memory:
            cfg.object_store_memory = object_store_memory
        if num_cpus is None:
            num_cpus = max(4, os.cpu_count() or 4)
        if num_tpus is None:
            num_tpus = _detect_tpu_chips()
        node_spec = {
            "num_cpus": num_cpus,
            "num_tpus": num_tpus,
            "resources": resources,
        }
        rt = Runtime(cfg, [dict(node_spec) for _ in range(num_nodes)],
                     namespace=namespace)
        _worker_context.set_runtime(rt)
        return rt


def _detect_tpu_chips() -> int:
    """TPU autodetection analog of GPU autodetect (_private/resource_spec.py:273):
    honor TPU_VISIBLE_CHIPS, else count devices of an ALREADY-INITIALIZED
    accelerator backend. Never import jax or trigger backend creation here —
    that would claim the chips (and can block on a busy TPU) just because the
    scheduler asked how many exist."""
    env = os.environ.get("TPU_VISIBLE_CHIPS")
    if env:
        return len([c for c in env.split(",") if c != ""])
    import sys

    jax = sys.modules.get("jax")
    if jax is None:
        return 0
    try:
        from jax._src import xla_bridge

        initialized = getattr(xla_bridge, "_backends", {})
        count = 0
        for platform, backend in initialized.items():
            if platform != "cpu":
                # local count only: on a multi-host slice device_count() is
                # the global chip count, which would oversubscribe this node
                count += backend.local_device_count()
        return count
    except Exception:
        return 0


def shutdown() -> None:
    rt = _worker_context.get_runtime()
    if rt is not None:
        rt.shutdown()
        _worker_context.set_runtime(None)


def is_initialized() -> bool:
    return _worker_context.get_runtime() is not None


def nodes() -> List[dict]:
    rt = _worker_context.get_runtime()
    if rt is None:
        return []
    return [
        {
            "NodeID": info.node_id.hex(),
            "Alive": info.alive,
            "Resources": info.resources.total.to_dict(),
            "StoreName": info.store_name,
            "Labels": info.labels,
        }
        for info in rt.gcs.nodes.values()
    ]


def cluster_resources() -> Dict[str, float]:
    return _worker_context.get_runtime().scheduler.cluster_resources()


def available_resources() -> Dict[str, float]:
    return _worker_context.get_runtime().scheduler.available_resources()


def timeline(filename: Optional[str] = None):
    from .utils.timeline import dump_timeline

    return dump_timeline(filename)
