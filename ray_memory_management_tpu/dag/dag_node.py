"""DAG node types and execution.

Mirrors the reference's node taxonomy (python/ray/dag/: DAGNode base
dag_node.py:23, FunctionNode, ClassMethodNode, InputNode/InputAttributeNode
input_node.py, MultiOutputNode output_node.py) re-founded on this runtime's
task/actor API. Execution is owner-side: one pass over the graph submits
every task with parent ObjectRefs as arguments — the runtime's dependency
resolution provides the actual topological scheduling, so independent
branches run concurrently without any DAG-level orchestration.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple


class DAGNode:
    """A node in a static task graph. Immutable once constructed."""

    def __init__(self, args: Tuple, kwargs: Dict[str, Any]):
        self._bound_args = args
        self._bound_kwargs = kwargs

    # -- traversal ------------------------------------------------------------
    def _children(self) -> List["DAGNode"]:
        out = []
        for a in list(self._bound_args) + list(self._bound_kwargs.values()):
            if isinstance(a, DAGNode):
                out.append(a)
        return out

    def _resolve_args(self, memo: Dict[int, Any], input_value) -> Tuple:
        args = [
            a._execute_impl(memo, input_value) if isinstance(a, DAGNode)
            else a
            for a in self._bound_args
        ]
        kwargs = {
            k: (v._execute_impl(memo, input_value) if isinstance(v, DAGNode)
                else v)
            for k, v in self._bound_kwargs.items()
        }
        return args, kwargs

    # -- execution ------------------------------------------------------------
    def execute(self, *input_args, **input_kwargs):
        """Run the whole graph once; returns ObjectRef(s) for this node.

        ``input_args``/``input_kwargs`` feed the graph's InputNode (one
        positional value, or several accessed via InputAttributeNode).
        """
        if len(input_args) == 1 and not input_kwargs:
            input_value = input_args[0]
        elif not input_args and not input_kwargs:
            input_value = None
        else:
            input_value = _DAGInput(input_args, input_kwargs)
        memo: Dict[int, Any] = {}
        return self._execute_impl(memo, input_value)

    def _execute_impl(self, memo: Dict[int, Any], input_value):
        key = id(self)
        if key not in memo:
            memo[key] = self._submit(memo, input_value)
        return memo[key]

    def _submit(self, memo, input_value):
        raise NotImplementedError


class _DAGInput:
    """Multi-arg input bundle, unpacked by InputAttributeNode."""

    def __init__(self, args: Tuple, kwargs: Dict[str, Any]):
        self.args = args
        self.kwargs = kwargs


class InputNode(DAGNode):
    """Placeholder for the value passed to ``dag.execute(value)``
    (input_node.py InputNode). Usable as a context manager, matching the
    reference's ``with InputNode() as inp:`` idiom."""

    _local = threading.local()

    def __init__(self):
        super().__init__((), {})

    def __enter__(self) -> "InputNode":
        return self

    def __exit__(self, *exc) -> None:
        pass

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return InputAttributeNode(self, name)

    def __getitem__(self, key) -> "InputAttributeNode":
        return InputAttributeNode(self, key)

    def _submit(self, memo, input_value):
        return input_value


class InputAttributeNode(DAGNode):
    """``inp.x`` / ``inp[0]`` — one field of a multi-arg execute() call."""

    def __init__(self, parent: InputNode, key):
        super().__init__((parent,), {})
        self._key = key

    def _submit(self, memo, input_value):
        value = self._bound_args[0]._execute_impl(memo, input_value)
        if isinstance(value, _DAGInput):
            if isinstance(self._key, int):
                return value.args[self._key]
            if self._key in value.kwargs:
                return value.kwargs[self._key]
            return value.args[self._key]
        if isinstance(self._key, int):
            return value[self._key]
        return getattr(value, self._key, value[self._key])


class FunctionNode(DAGNode):
    """``fn.bind(...)`` over a remote function (function_node.py)."""

    def __init__(self, remote_fn, args: Tuple, kwargs: Dict[str, Any],
                 options: Optional[dict] = None):
        super().__init__(args, kwargs)
        self._remote_fn = remote_fn
        self._options = options or {}

    def options(self, **opts) -> "FunctionNode":
        return FunctionNode(self._remote_fn, self._bound_args,
                            self._bound_kwargs, {**self._options, **opts})

    def _submit(self, memo, input_value):
        args, kwargs = self._resolve_args(memo, input_value)
        fn = self._remote_fn
        if self._options:
            fn = fn.options(**self._options)
        return fn.remote(*args, **kwargs)


class ClassMethodNode(DAGNode):
    """``actor.method.bind(...)`` over a live actor handle
    (class_node.py ClassMethodNode)."""

    def __init__(self, actor_method, args: Tuple, kwargs: Dict[str, Any]):
        super().__init__(args, kwargs)
        self._method = actor_method

    def _submit(self, memo, input_value):
        args, kwargs = self._resolve_args(memo, input_value)
        return self._method.remote(*args, **kwargs)


class MultiOutputNode(DAGNode):
    """Bundle several leaves as the DAG output (output_node.py):
    ``MultiOutputNode([a, b]).execute(x)`` -> [ref_a, ref_b]."""

    def __init__(self, outputs: List[DAGNode]):
        super().__init__(tuple(outputs), {})

    def _submit(self, memo, input_value):
        return [n._execute_impl(memo, input_value)
                for n in self._bound_args]
