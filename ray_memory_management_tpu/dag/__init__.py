"""Static task graphs (the ``ray.dag`` analog).

The reference builds lazy DAGs of tasks/actor calls with ``.bind()``
(python/ray/dag/dag_node.py:23; function/class/method nodes in
function_node.py, class_node.py) and executes them with ``dag.execute()``;
Serve deployment graphs compile onto it. Here the same surface:

    @rmt.remote
    def add(a, b): return a + b

    with InputNode() as inp:
        dag = add.bind(inp, add.bind(inp, 1))
    assert rmt.get(dag.execute(2)) == 5

Nodes are immutable descriptions; ``execute`` walks the graph bottom-up,
memoizing each node into ONE task submission per execution (diamond
dependencies execute once) and wiring parent results as ObjectRefs so the
scheduler overlaps independent branches.
"""

from .dag_node import (  # noqa: F401
    ClassMethodNode,
    DAGNode,
    FunctionNode,
    InputAttributeNode,
    InputNode,
    MultiOutputNode,
)

__all__ = [
    "DAGNode",
    "FunctionNode",
    "ClassMethodNode",
    "InputNode",
    "InputAttributeNode",
    "MultiOutputNode",
]
