"""Unique identifiers for tasks, objects, actors, nodes, jobs and placement groups.

Design follows the reference's 128-bit binary IDs with embedded provenance
(reference: src/ray/design_docs/id_specification.md, src/ray/common/id.h):

- A ``TaskID`` embeds the job; an ``ObjectID`` of a task return embeds the
  producing ``TaskID`` plus a return index, so lineage can be recovered from the
  ID alone (the owner resubmits the producing task on loss — reference
  src/ray/core_worker/object_recovery_manager.h:41).
- IDs are fixed-size ``bytes`` wrapped in typed classes; hashing/equality is by
  value so they can key dicts and travel through pickle cheaply.
"""

from __future__ import annotations

import os
import threading

_ID_SIZE = 16  # 128-bit, as in the reference (id_specification.md)


class _EntropyPool:
    """Buffered os.urandom: one syscall refills 4 KiB instead of one
    syscall per ID (ID minting sits on the task-submission hot path)."""

    __slots__ = ("_buf", "_pos", "_lock")

    def __init__(self):
        self._buf = b""
        self._pos = 0
        self._lock = threading.Lock()

    def take(self, n: int) -> bytes:
        with self._lock:
            if self._pos + n > len(self._buf):
                self._buf = os.urandom(4096)
                self._pos = 0
            out = self._buf[self._pos : self._pos + n]
            self._pos += n
            return out

    def reset(self) -> None:
        with self._lock:
            self._buf = b""
            self._pos = 0


_entropy = _EntropyPool()
# A forked child inheriting the buffer would mint the parent's exact IDs;
# os.urandom had no such hazard, so restore it at fork time.
if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=_entropy.reset)

# Number of trailing bytes of an ObjectID that encode the return index. The
# reference packs the index into the ObjectID the same way
# (src/ray/common/id.h ObjectID::FromIndex).
_INDEX_BYTES = 4


class BaseID:
    """Value-typed 128-bit identifier."""

    __slots__ = ("_binary", "_hash")

    def __init__(self, binary: bytes):
        if not isinstance(binary, bytes) or len(binary) != _ID_SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {_ID_SIZE} bytes, got {binary!r}"
            )
        self._binary = binary
        self._hash = hash(binary)

    @classmethod
    def _wrap(cls, binary: bytes):
        """Construct from bytes KNOWN to be a valid 16-byte ID (minted by
        this module). Skips __init__'s validation — ID minting runs twice
        per task on the submission hot path, where the isinstance/length
        checks are pure overhead."""
        self = object.__new__(cls)
        self._binary = binary
        self._hash = hash(binary)
        return self

    @classmethod
    def from_random(cls):
        return cls._wrap(_entropy.take(_ID_SIZE))

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def nil(cls):
        return cls(b"\x00" * _ID_SIZE)

    def binary(self) -> bytes:
        return self._binary

    def hex(self) -> str:
        return self._binary.hex()

    def is_nil(self) -> bool:
        return self._binary == b"\x00" * _ID_SIZE

    def __hash__(self):
        return self._hash

    def __eq__(self, other):
        return type(other) is type(self) and other._binary == self._binary

    def __lt__(self, other):
        return self._binary < other._binary

    def __repr__(self):
        return f"{type(self).__name__}({self._binary.hex()[:16]})"

    def __reduce__(self):
        return (type(self), (self._binary,))


class JobID(BaseID):
    pass


class NodeID(BaseID):
    pass


class WorkerID(BaseID):
    pass


class ActorID(BaseID):
    pass


class PlacementGroupID(BaseID):
    pass


class TaskID(BaseID):
    @classmethod
    def for_task(cls, job_id: JobID) -> "TaskID":
        """Fresh task id carrying the job in its first 4 bytes.

        The trailing ``_INDEX_BYTES`` are zero so that return-object IDs can
        embed a return index there and still map back to this task via
        :meth:`ObjectID.task_id`.
        """
        return cls._wrap(
            job_id.binary()[:4]
            + _entropy.take(_ID_SIZE - 4 - _INDEX_BYTES)
            + b"\x00" * _INDEX_BYTES
        )


class ObjectID(BaseID):
    @classmethod
    def for_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        """Deterministic ID of the ``index``-th return of ``task_id``.

        Mirrors ObjectID::FromIndex in the reference: lineage reconstruction
        re-derives the same IDs when the task is re-executed.
        """
        prefix = task_id.binary()[: _ID_SIZE - _INDEX_BYTES]
        return cls._wrap(prefix + index.to_bytes(_INDEX_BYTES, "little"))

    @classmethod
    def for_put(cls) -> "ObjectID":
        """Random ID for a driver/worker ``put`` (no lineage)."""
        return cls._wrap(_entropy.take(_ID_SIZE))

    def task_id(self) -> TaskID:
        """The producing task's ID prefix (valid only for return objects)."""
        return TaskID(self._binary[: _ID_SIZE - _INDEX_BYTES] + b"\x00" * _INDEX_BYTES)

    def return_index(self) -> int:
        return int.from_bytes(self._binary[_ID_SIZE - _INDEX_BYTES :], "little")


# Trace-plane identifiers: plain hex strings rather than BaseID — they only
# ever travel inside timeline args / wire-message dicts, never key runtime
# tables, so the typed-wrapper machinery would be pure overhead on the
# submit hot path. 128-bit trace ids (collision-free per cluster lifetime),
# 64-bit span ids (per-trace scope), both from the buffered entropy pool.

def new_trace_id() -> str:
    return _entropy.take(_ID_SIZE).hex()


def new_span_id() -> str:
    return _entropy.take(8).hex()
