"""Exception hierarchy, mirroring the reference's python/ray/exceptions.py."""

from __future__ import annotations

import traceback


class RmtError(Exception):
    """Base class for all framework errors."""


class TaskError(RmtError):
    """A task raised an exception; re-raised at ``get()`` on the caller.

    Mirrors RayTaskError (python/ray/exceptions.py): carries the remote
    traceback string so the driver sees where the task failed.
    """

    def __init__(self, function_name: str, cause: BaseException | None = None,
                 remote_tb: str | None = None):
        self.function_name = function_name
        self.cause = cause
        self.remote_tb = remote_tb or (
            "".join(traceback.format_exception(cause)) if cause else ""
        )
        super().__init__(
            f"task {function_name} failed:\n{self.remote_tb}"
        )


class ActorError(RmtError):
    """Raised when calling a dead/unreachable actor (RayActorError)."""


class ActorDiedError(ActorError):
    pass


class WorkerCrashedError(RmtError):
    """The worker process executing the task died (WorkerCrashedError)."""


class ObjectLostError(RmtError):
    """Object value unavailable and lineage reconstruction failed
    (ObjectLostError / ObjectReconstructionFailedError)."""

    def __init__(self, object_id_hex: str, msg: str = ""):
        self.object_id_hex = object_id_hex
        super().__init__(f"object {object_id_hex} lost. {msg}")


class ObjectStoreFullError(RmtError):
    """Store full and spilling could not make room (ObjectStoreFullError)."""


class NodeDeadError(RmtError):
    """A task or transfer was handed to a node already marked dead. The
    operation is not retryable ON THIS NODE — the caller must re-place
    it on a live one (the dead node's queue is drained exactly once by
    its death handler and never again)."""


class QuotaExceededError(RmtError):
    """A job exceeded its admission quota (``JobQuota``). Raised at the
    admission edge — submit / put / device-pin — never as a side effect
    of another job's activity. Carries enough context for the caller to
    decide between backoff, demotion, and giving up."""

    def __init__(self, job_id_hex: str, resource: str,
                 requested: float, limit: float, used: float):
        self.job_id_hex = job_id_hex
        self.resource = resource
        self.requested = requested
        self.limit = limit
        self.used = used
        super().__init__(
            f"job {job_id_hex[:8]} over {resource} quota: "
            f"requested {requested:g} with {used:g}/{limit:g} used"
        )


class GetTimeoutError(RmtError, TimeoutError):
    """``get(timeout=...)`` expired (python/ray/exceptions.py GetTimeoutError)."""


class RuntimeEnvSetupError(RmtError):
    pass


class PlacementGroupError(RmtError):
    pass
