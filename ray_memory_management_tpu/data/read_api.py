"""Dataset creation: ranges, items, arrays, files.

The reference's read API (python/ray/data/read_api.py — range, from_items,
from_numpy/pandas/arrow, read_csv/json/parquet/text/binary_files via
datasources, data/datasource/). File reads are one task per file; ranges
and items are partitioned driver-side into ``parallelism`` blocks.
"""

from __future__ import annotations

import glob as _glob
import os
from typing import Any, List, Optional

import numpy as np

from .. import api
from .block import BlockAccessor
from .dataset import Dataset
from .plan import BlockList, ExecutionPlan


_py_range = range  # the builtin, shadowed by the public range() below


def _make_dataset(blocks: BlockList) -> Dataset:
    return Dataset(ExecutionPlan(blocks))


def _put_block(block) -> tuple:
    meta = BlockAccessor.for_block(block).get_metadata()
    return api.put(block), meta


def range(n: int, *, parallelism: int = 8) -> Dataset:  # noqa: A001
    """Integers [0, n) as simple rows (reference read_api.range)."""
    parallelism = max(1, min(parallelism, n or 1))
    bounds = np.linspace(0, n, parallelism + 1).astype(int)
    blocks = [_put_block(list(_py_range(int(lo), int(hi))))
              for lo, hi in zip(bounds[:-1], bounds[1:])]
    return _make_dataset(blocks)


def range_tensor(n: int, *, shape=(1,), parallelism: int = 8) -> Dataset:
    """ndarray blocks of shape [rows, *shape] (read_api.range_tensor) —
    rows are tensors, stored contiguously."""
    parallelism = max(1, min(parallelism, n or 1))
    bounds = np.linspace(0, n, parallelism + 1).astype(int)
    blocks = []
    for lo, hi in zip(bounds[:-1], bounds[1:]):
        base = np.arange(int(lo), int(hi), dtype=np.int64)
        arr = np.broadcast_to(
            base.reshape((-1,) + (1,) * len(shape)),
            (len(base),) + tuple(shape)).copy()
        blocks.append(_put_block(arr))
    return _make_dataset(blocks)


def from_items(items: List[Any], *, parallelism: int = 8) -> Dataset:
    parallelism = max(1, min(parallelism, len(items) or 1))
    bounds = np.linspace(0, len(items), parallelism + 1).astype(int)
    blocks = [_put_block(list(items[int(lo):int(hi)]))
              for lo, hi in zip(bounds[:-1], bounds[1:])]
    return _make_dataset(blocks)


def from_numpy(arrays) -> Dataset:
    if isinstance(arrays, np.ndarray):
        arrays = [arrays]
    return _make_dataset([_put_block(np.asarray(a)) for a in arrays])


def from_pandas(dfs) -> Dataset:
    if not isinstance(dfs, list):
        dfs = [dfs]
    return _make_dataset([_put_block(df) for df in dfs])


def from_arrow(tables) -> Dataset:
    if not isinstance(tables, list):
        tables = [tables]
    return _make_dataset([_put_block(t) for t in tables])


def _expand_paths(paths) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(
                os.path.join(p, f) for f in os.listdir(p)
                if not f.startswith(".")))
        elif any(c in p for c in "*?["):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
    if not out:
        raise ValueError(f"no input files found for {paths}")
    return out


def _read_files(paths, reader_fn) -> Dataset:
    files = _expand_paths(paths)
    out_refs = [_read_file_task.options(num_returns=2).remote(f, reader_fn)
                for f in files]
    blocks = [(b, api.get(m)) for b, m in out_refs]
    return _make_dataset(blocks)


@api.remote
def _read_file_task(path: str, reader_fn):
    block = reader_fn(path)
    meta = BlockAccessor.for_block(block).get_metadata(input_files=[path])
    return block, meta


def read_csv(paths, **kwargs) -> Dataset:
    def reader(path):
        import pandas as pd

        return pd.read_csv(path, **kwargs)

    return _read_files(paths, reader)


def read_json(paths, *, lines: bool = True, **kwargs) -> Dataset:
    def reader(path):
        import pandas as pd

        return pd.read_json(path, lines=lines, **kwargs)

    return _read_files(paths, reader)


def read_parquet(paths, *, columns: Optional[List[str]] = None) -> Dataset:
    def reader(path):
        import pyarrow.parquet as pq

        return pq.read_table(path, columns=columns)

    return _read_files(paths, reader)


def read_text(paths, *, encoding: str = "utf-8") -> Dataset:
    def reader(path):
        with open(path, encoding=encoding) as f:
            return [line.rstrip("\n") for line in f]

    return _read_files(paths, reader)


def read_binary_files(paths) -> Dataset:
    def reader(path):
        with open(path, "rb") as f:
            return [f.read()]

    return _read_files(paths, reader)


def read_numpy(paths) -> Dataset:
    def reader(path):
        return np.load(path)

    return _read_files(paths, reader)
